"""Tier-1 gates for the compilation-stability sanitizer.

Three layers, matching the sanitizer's halves (registry: ``dbsp_tpu.
retrace``; static pass: ``tools/check_retrace.py``; runtime sentinel:
``dbsp_tpu.testing.retrace``):

* **q1-q8 steady state at zero.** Every Nexmark query's compiled
  steady-state loop — post-warmup, post-presize, the growth protocol
  bench.py measures under — runs inside a sentinel session: zero
  UNDECLARED recompiles (every ``step_fn``/``_scan_body`` compile is
  ledgered to a declared cause) and zero IMPLICIT host<->device
  transfers (``jax.transfer_guard("disallow")`` armed over the jitted
  dispatch — a violation raises at the dispatch site, so mere completion
  is the proof).
* **Seeded non-vacuity, runtime.** A jitted step with a python-value
  branch on its tick (the per-value retrace anti-pattern) must be
  caught across several seeds; the control (one distinct value) must
  stay silent — the sentinel neither rots nor cries wolf.
* **Seeded non-vacuity, static.** The REAL checkpoint decoder's owning
  ``jnp.array`` copy is load-bearing: flipping it to ``jnp.asarray`` in
  the real source must raise exactly one D001 (zero-copy view escaping
  into donated state), and a ``# retrace: ok`` waiver must suppress it.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from dbsp_tpu.testing import retrace as sentinel  # noqa: E402

QUERIES = ("q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8")


def _compiled_query(qname, per_tick=60, seed=7):
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.nexmark import (GeneratorConfig, build_inputs, device_gen,
                                  queries)

    cfg = GeneratorConfig(seed=seed)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, getattr(queries, qname)(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(1, build)
    hp, ha, hb = handles

    def gen_fn(tick):
        p, a, b = device_gen.generate_tick(cfg, tick * per_tick, per_tick)
        return {hp: p, ha: a, hb: b}

    return compile_circuit(handle, gen_fn=gen_fn), out


@pytest.mark.parametrize("qname", QUERIES)
def test_compiled_steady_state_is_recompile_and_transfer_free(qname):
    """The acceptance gate: q1-q8's compiled steady state shows zero
    undeclared recompiles AND zero implicit transfers, under the same
    warmup -> presize -> measure protocol bench.py runs."""
    ch, out = _compiled_query(qname)
    warm = 3
    ch.run_ticks(0, warm, validate_every=1, project_ratio=4.0)
    ch.presize(1.0, interval=1)
    # one post-presize tick so the steady region starts on a compiled
    # program (any presize-driven rebuild compiles here, outside the gate)
    ch.run_ticks(warm, 1, validate_every=1, project_ratio=4.0)
    with sentinel.session(ch) as report:
        ch.run_ticks(warm + 1, 4, validate_every=2, project_ratio=4.0)
        ch.block()
    assert report.undeclared() == [], report.summary()
    summary = report.summary()
    assert summary["transfer_guard"] == "disallow"
    # the gate must not be vacuous: the sentinel set is being tracked
    assert any(p.endswith((".step_fn", "._scan_body"))
               for p in summary["programs"])


def test_steady_state_scan_path_is_clean():
    """The lax.scan chunk path (TPU dispatch amortization) under the
    sentinel: chunked steady ticks stay at zero undeclared."""
    ch, out = _compiled_query("q4")
    ch.run_ticks(0, 3, validate_every=1, project_ratio=4.0)
    ch.presize(1.0, interval=2)
    ch.run_ticks(3, 1, validate_every=1, project_ratio=4.0)
    with sentinel.session(ch) as report:
        ch.run_ticks(4, 4, validate_every=2, scan=True, project_ratio=4.0)
        ch.block()
    assert report.undeclared() == [], report.summary()


@pytest.mark.parametrize("seed", (0, 1, 2))
def test_sentinel_catches_seeded_per_value_retrace(seed):
    """A python-value branch on a static tick recompiles per distinct
    value; one declared construction cannot cover three compiles — the
    sentinel must flag it (NOT waivable at runtime)."""

    def step_fn(state, tick):
        if tick % 2 == 0:  # python branch burned in per static value
            return state + 1
        return state - 1

    seeded = jax.jit(step_fn, static_argnums=(1,))
    with sentinel.session() as report:
        sentinel.note_construction("step_fn")
        st = jnp.zeros((), jnp.int64)
        for t in range(3):
            st = seeded(st, seed * 10 + t)
    bad = report.undeclared()
    assert bad and "step_fn" in bad[0], bad
    # the ledger persists past session exit (reset happens on the NEXT
    # enter), so the raising entry point sees the same imbalance
    from dbsp_tpu.retrace import RetraceError

    with pytest.raises(RetraceError, match="undeclared recompile"):
        sentinel.check()
    sentinel.reset()


def test_sentinel_control_stays_silent():
    """The control: one distinct static value, one declared construction
    — at most one compile, the ledger balances, no false positive."""

    def step_fn(state, tick):
        if tick % 2 == 0:
            return state + 1
        return state - 1

    ctl = jax.jit(step_fn, static_argnums=(1,))
    with sentinel.session() as report:
        sentinel.note_construction("step_fn")
        st = jnp.zeros((), jnp.int64)
        for _ in range(3):
            st = ctl(st, 4)  # same static value every call
    assert report.undeclared() == []
    assert report.compiles.get("step_fn", 0) <= 1


def test_sentinel_session_restores_loggers_and_handle():
    """session() leaves no residue: logger levels/propagation restored,
    the handle's builder shadows removed, the guard disarmed."""
    import logging

    ch, out = _compiled_query("q1", per_tick=20)
    before = {n: (logging.getLogger(n).level, logging.getLogger(n).propagate)
              for n in sentinel._COMPILE_LOGGERS}
    with sentinel.session(ch):
        assert ch._steady_guard == "disallow"
        assert "_make_step" in ch.__dict__  # instance shadow installed
    assert ch._steady_guard is None
    assert "_make_step" not in ch.__dict__
    after = {n: (logging.getLogger(n).level, logging.getLogger(n).propagate)
             for n in sentinel._COMPILE_LOGGERS}
    assert after == before
    assert not sentinel.enabled()


# ---------------------------------------------------------------------------
# static half, seeded against REAL sources: the decoder's owning copy
# ---------------------------------------------------------------------------

_CHECKPOINT = os.path.join(_ROOT, "dbsp_tpu", "checkpoint.py")


def _d001(findings):
    return [f for f in findings if "D001:" in f]


def test_decoder_owning_copy_is_load_bearing_for_d001():
    """The real checkpoint decoder is D001-clean BECAUSE ``_Decoder._arr``
    copies (``jnp.array``); re-introducing the historical zero-copy bug
    (``jnp.asarray`` — XLA frees the decoder's buffer after donation)
    in the real source yields exactly one D001."""
    from tools.check_retrace import check_source

    with open(_CHECKPOINT) as f:
        src = f.read()
    rel = "dbsp_tpu/checkpoint.py"
    assert check_source(src, rel) == []

    needle = "return jnp.array(self.load(name))"
    assert needle in src  # the owning copy the registry's invariant names
    seeded = src.replace(needle, "return jnp.asarray(self.load(name))")
    findings = check_source(seeded, rel)
    assert len(_d001(findings)) == 1, findings
    assert "_Decoder._arr" in _d001(findings)[0]
    assert "zero-copy view" in _d001(findings)[0]

    waived = src.replace(
        needle, "return jnp.asarray(self.load(name))  # retrace: ok seeded")
    findings_w = check_source(waived, rel)
    assert _d001(findings_w) == []
    # a USED waiver is not stale — the audit stays quiet too
    assert not any("W001:" in f for f in findings_w)


def test_np_decoder_numpy_view_would_also_be_caught():
    """The host-tier decoder variant copies too (``np.array``); an
    ``np.asarray`` view there is the same class of bug only if the
    qualname is a declared producer — prove the walk keys on the
    registry, not on luck, by declaring it and seeding the view."""
    from tools.check_retrace import check_source

    with open(_CHECKPOINT) as f:
        src = f.read()
    rel = "dbsp_tpu/checkpoint.py"
    needle = "return np.array(self.load(name))"
    assert needle in src
    seeded = src.replace(needle, "return np.asarray(self.load(name))")
    # undeclared qualname: the walk does not fire (not a donation feeder)
    assert _d001(check_source(seeded, rel)) == []
    extra = {(rel, "_NpDecoder._arr"): "test: host tier feeds donation"}
    assert len(_d001(check_source(seeded, rel,
                                  extra_producers=extra))) == 1
