"""SQL conformance: a few hundred generated queries checked against sqlite.

The reference's SQL frontend is validated by ~7M SQL Logic Tests
(SURVEY.md L5); this is the same idea at in-tree scale — an SLT-style
runner whose oracle is sqlite3 (stdlib), over the dialect subset the
planner supports. All queries register as views on ONE circuit (sharing
table traces), step once over the data, and compare result multisets.

Semantics notes encoded here:
* integer '/' truncates toward zero in both engines;
* AVG: ours is truncating integer average — compare via sqlite's
  CAST(SUM/COUNT) with matching truncation;
* LEFT JOIN NULLs: ours pads with iinfo.min (planner.NULL_INT) — sqlite's
  None maps to that marker;
* ORDER BY/LIMIT: compared as top-K multisets; generated data keeps order
  keys unique so both engines agree on the boundary.
"""

import itertools
import random
import sqlite3

import jax.numpy as jnp
import numpy as np
import pytest

from dbsp_tpu.circuit import Runtime
from dbsp_tpu.operators import add_input_zset
from dbsp_tpu.sql.planner import NULL_INT, SqlContext, SqlError

TABLES = {
    "t1": ["a", "b", "c"],
    "t2": ["x", "y"],
}


def _data(rng):
    rows1 = [(rng.randrange(8), rng.randrange(-20, 20), rng.randrange(1, 50))
             for _ in range(40)]
    rows2 = [(rng.randrange(8), rng.randrange(0, 30)) for _ in range(15)]
    # unique 'c' values for ORDER BY determinism at the LIMIT boundary
    rows1 = [(a, b, 100 * i + c) for i, (a, b, c) in enumerate(rows1)]
    return {"t1": rows1, "t2": rows2}


def _cases():
    qs = []
    # projections / arithmetic / where
    for pred in ["a > 3", "b < 0 and c > 500", "not (a = 2 or a = 5)",
                 "b + a > 0", "c % 7 = 1", "b between -5 and 5",
                 "a <> 4 and b >= -10"]:
        qs.append(f"SELECT a, b, c FROM t1 WHERE {pred}")
        qs.append(f"SELECT a + b AS s, c - 1 FROM t1 WHERE {pred}")
        qs.append(f"SELECT DISTINCT a FROM t1 WHERE {pred}")
    for expr in ["a + b * 2", "c / 4", "b / 3", "c % 5 + a", "0 - b"]:
        qs.append(f"SELECT {expr} AS e FROM t1")
        qs.append(f"SELECT {expr} AS e FROM t1 WHERE a < 6")
    # aggregates / group by / having
    for agg in ["count(*)", "sum(b)", "min(c)", "max(b)", "avg(c)",
                "sum(a + b)"]:
        qs.append(f"SELECT a, {agg} AS v FROM t1 GROUP BY a")
        qs.append(f"SELECT a, {agg} AS v FROM t1 WHERE c > 300 GROUP BY a")
    for having in ["count(*) > 3", "sum(c) > 2000", "min(b) < 0",
                   "count(*) = 1 or max(c) > 3000"]:
        qs.append(f"SELECT a, count(*) AS n FROM t1 GROUP BY a "
                  f"HAVING {having}")
        qs.append(f"SELECT a, sum(c) AS s FROM t1 GROUP BY a "
                  f"HAVING {having}")
    # joins
    qs.append("SELECT t1.a, t1.b, t2.y FROM t1 JOIN t2 ON t1.a = t2.x")
    qs.append("SELECT t1.a, t2.y FROM t1 JOIN t2 ON t1.a = t2.x "
              "WHERE t2.y > 10")
    qs.append("SELECT t1.a, t1.b, t2.y FROM t1 LEFT JOIN t2 "
              "ON t1.a = t2.x WHERE t1.b > 5")
    qs.append("SELECT t1.a, t2.x, t2.y FROM t1 JOIN t2 "
              "ON t2.x BETWEEN t1.a - 1 AND t1.a + 1")
    qs.append("SELECT t1.a, t2.y FROM t1 JOIN t2 "
              "ON t2.y BETWEEN t1.c - 200 AND t1.c + 200 WHERE t1.a = 3")
    # order by / limit
    qs.append("SELECT a, b, c FROM t1 ORDER BY c LIMIT 5")
    qs.append("SELECT a, b, c FROM t1 ORDER BY c DESC LIMIT 7")
    qs.append("SELECT a, c FROM t1 WHERE b > 0 ORDER BY c LIMIT 3")
    qs.append("SELECT a, count(*) AS n FROM t1 GROUP BY a "
              "ORDER BY a LIMIT 4")
    # star projections must hide internal plumbing columns
    qs.append("SELECT * FROM t1 WHERE a = 2")
    qs.append("SELECT * FROM t1 JOIN t2 ON t1.a = t2.x WHERE t2.y > 5")
    qs.append("SELECT * FROM t2 WHERE y > (SELECT min(y) FROM t2)")
    # scalar subqueries
    qs.append("SELECT a, b FROM t1 WHERE b > (SELECT min(b) FROM t1)")
    qs.append("SELECT a, c FROM t1 WHERE c > (SELECT avg(c) FROM t1)")
    qs.append("SELECT a FROM t1 WHERE a = (SELECT max(x) FROM t2)")
    # grouped variants across both group columns
    for g, agg in itertools.product(["a", "b"], ["count(*)", "sum(c)"]):
        qs.append(f"SELECT {g}, {agg} AS v FROM t1 GROUP BY {g}")
    # parameterized sweep for volume: every (pred x agg) grouped query
    preds = ["a > 1", "a <= 5", "b < 10", "c > 800", "b % 2 = 0",
             "a + 1 < 7", "not b > 0"]
    aggs = ["count(*)", "sum(b)", "max(c)", "min(c)", "sum(a)"]
    for p, ag in itertools.product(preds, aggs):
        qs.append(f"SELECT a, {ag} AS v FROM t1 WHERE {p} GROUP BY a")
    for p in preds:
        qs.append(f"SELECT a, b FROM t1 WHERE {p}")
        qs.append(f"SELECT DISTINCT a, b FROM t1 WHERE {p}")
        qs.append(f"SELECT t1.a, t2.y FROM t1 JOIN t2 ON t1.a = t2.x "
                  f"WHERE {p}")
    return qs


def _sqlite_expected(conn, sql):
    cur = conn.execute(sql)
    rows = cur.fetchall()
    out = {}
    for r in rows:
        key = tuple(NULL_INT(np.int64) if v is None else int(v) for v in r)
        out[key] = out.get(key, 0) + 1
    return out


def _to_sqlite(sql: str) -> str:
    """Translate dialect: our truncating AVG -> sqlite expression."""
    import re

    return re.sub(r"avg\(([^)]*)\)",
                  r"CAST(TOTAL(\1) / (ABS(COUNT(\1)) + 0.0) AS INT)", sql,
                  flags=re.IGNORECASE)


def test_slt_conformance():
    rng = random.Random(99)
    data = _data(rng)
    queries = _cases()
    assert len(queries) > 100

    conn = sqlite3.connect(":memory:")
    for t, cols in TABLES.items():
        conn.execute(f"CREATE TABLE {t} ({', '.join(cols)})")
        conn.executemany(
            f"INSERT INTO {t} VALUES ({', '.join('?' * len(cols))})",
            data[t])

    def build(c):
        ctx = SqlContext(c)
        handles = {}
        for t, cols in TABLES.items():
            s, h = add_input_zset(c, (jnp.int64,),
                                  (jnp.int64,) * (len(cols) - 1))
            ctx.register_table(t, s, cols)
            handles[t] = h
        outs = []
        for q in queries:
            outs.append(ctx.query(q).output())
        return handles, outs

    handle, (handles, outs) = Runtime.init_circuit(1, build)
    for t, rows in data.items():
        handles[t].extend([(r, 1) for r in rows])
    handle.step()

    failures = []
    for q, out in zip(queries, outs):
        got = out.to_dict()
        want = _sqlite_expected(conn, _to_sqlite(q))
        if got != want:
            failures.append((q, got, want))
    assert not failures, (
        f"{len(failures)}/{len(queries)} queries diverge; first: "
        f"{failures[0]}")
