"""SQL conformance: a few hundred generated queries checked against sqlite.

The reference's SQL frontend is validated by ~7M SQL Logic Tests
(SURVEY.md L5); this is the same idea at in-tree scale — an SLT-style
runner whose oracle is sqlite3 (stdlib), over the dialect subset the
planner supports. All queries register as views on ONE circuit (sharing
table traces), step once over the data, and compare result multisets.

Semantics notes encoded here:
* integer '/' truncates toward zero in both engines;
* AVG: ours is truncating integer average — compare via sqlite's
  CAST(SUM/COUNT) with matching truncation;
* LEFT JOIN NULLs: ours pads with iinfo.min (planner.NULL_INT) — sqlite's
  None maps to that marker;
* ORDER BY/LIMIT: compared as top-K multisets; generated data keeps order
  keys unique so both engines agree on the boundary.
"""

import itertools
import json
import os
import random
import sqlite3

import jax.numpy as jnp
import numpy as np
import pytest

from dbsp_tpu.circuit import Runtime
from dbsp_tpu.operators import add_input_zset
from dbsp_tpu.sql.planner import NULL_INT, SqlContext, SqlError

pytestmark = pytest.mark.slow  # excluded from the -m fast pre-commit tier

TABLES = {
    "t1": ["a", "b", "c"],
    "t2": ["x", "y"],
    "t3": ["p", "q"],
    # string + nullable columns: d INT NOT NULL, s VARCHAR NULL, m INT NULL
    "t4": ["d", "s", "m"],
}
STRING_COLS = {"t4": ("s",)}
NULLABLE_COLS = {"t4": ("s", "m")}
WORDS = ["apple", "apricot", "banana", "berry", "cherry", "date", "fig",
         "grape", None]


def _data(rng):
    rows1 = [(rng.randrange(8), rng.randrange(-20, 20), rng.randrange(1, 50))
             for _ in range(40)]
    rows2 = [(rng.randrange(8), rng.randrange(0, 30)) for _ in range(15)]
    rows3 = [(rng.randrange(0, 30), rng.randrange(50, 99)) for _ in range(10)]
    # unique 'c' values for ORDER BY determinism at the LIMIT boundary
    rows1 = [(a, b, 100 * i + c) for i, (a, b, c) in enumerate(rows1)]
    rows4 = [(rng.randrange(10), rng.choice(WORDS),
              rng.choice([None, *range(-5, 15)])) for _ in range(30)]
    return {"t1": rows1, "t2": rows2, "t3": rows3, "t4": rows4}


def _cases():
    qs = []
    # projections / arithmetic / where
    for pred in ["a > 3", "b < 0 and c > 500", "not (a = 2 or a = 5)",
                 "b + a > 0", "c % 7 = 1", "b between -5 and 5",
                 "a <> 4 and b >= -10"]:
        qs.append(f"SELECT a, b, c FROM t1 WHERE {pred}")
        qs.append(f"SELECT a + b AS s, c - 1 FROM t1 WHERE {pred}")
        qs.append(f"SELECT DISTINCT a FROM t1 WHERE {pred}")
    for expr in ["a + b * 2", "c / 4", "b / 3", "c % 5 + a", "0 - b"]:
        qs.append(f"SELECT {expr} AS e FROM t1")
        qs.append(f"SELECT {expr} AS e FROM t1 WHERE a < 6")
    # aggregates / group by / having
    for agg in ["count(*)", "sum(b)", "min(c)", "max(b)", "avg(c)",
                "sum(a + b)"]:
        qs.append(f"SELECT a, {agg} AS v FROM t1 GROUP BY a")
        qs.append(f"SELECT a, {agg} AS v FROM t1 WHERE c > 300 GROUP BY a")
    for having in ["count(*) > 3", "sum(c) > 2000", "min(b) < 0",
                   "count(*) = 1 or max(c) > 3000"]:
        qs.append(f"SELECT a, count(*) AS n FROM t1 GROUP BY a "
                  f"HAVING {having}")
        qs.append(f"SELECT a, sum(c) AS s FROM t1 GROUP BY a "
                  f"HAVING {having}")
    # joins
    qs.append("SELECT t1.a, t1.b, t2.y FROM t1 JOIN t2 ON t1.a = t2.x")
    qs.append("SELECT t1.a, t2.y FROM t1 JOIN t2 ON t1.a = t2.x "
              "WHERE t2.y > 10")
    qs.append("SELECT t1.a, t1.b, t2.y FROM t1 LEFT JOIN t2 "
              "ON t1.a = t2.x WHERE t1.b > 5")
    qs.append("SELECT t1.a, t2.x, t2.y FROM t1 JOIN t2 "
              "ON t2.x BETWEEN t1.a - 1 AND t1.a + 1")
    qs.append("SELECT t1.a, t2.y FROM t1 JOIN t2 "
              "ON t2.y BETWEEN t1.c - 200 AND t1.c + 200 WHERE t1.a = 3")
    # order by / limit
    qs.append("SELECT a, b, c FROM t1 ORDER BY c LIMIT 5")
    qs.append("SELECT a, b, c FROM t1 ORDER BY c DESC LIMIT 7")
    qs.append("SELECT a, c FROM t1 WHERE b > 0 ORDER BY c LIMIT 3")
    qs.append("SELECT a, count(*) AS n FROM t1 GROUP BY a "
              "ORDER BY a LIMIT 4")
    # star projections must hide internal plumbing columns
    qs.append("SELECT * FROM t1 WHERE a = 2")
    qs.append("SELECT * FROM t1 JOIN t2 ON t1.a = t2.x WHERE t2.y > 5")
    qs.append("SELECT * FROM t2 WHERE y > (SELECT min(y) FROM t2)")
    # scalar subqueries
    qs.append("SELECT a, b FROM t1 WHERE b > (SELECT min(b) FROM t1)")
    qs.append("SELECT a, c FROM t1 WHERE c > (SELECT avg(c) FROM t1)")
    qs.append("SELECT a FROM t1 WHERE a = (SELECT max(x) FROM t2)")
    # grouped variants across both group columns
    for g, agg in itertools.product(["a", "b"], ["count(*)", "sum(c)"]):
        qs.append(f"SELECT {g}, {agg} AS v FROM t1 GROUP BY {g}")
    # parameterized sweep for volume: every (pred x agg) grouped query
    preds = ["a > 1", "a <= 5", "b < 10", "c > 800", "b % 2 = 0",
             "a + 1 < 7", "not b > 0"]
    aggs = ["count(*)", "sum(b)", "max(c)", "min(c)", "sum(a)"]
    for p, ag in itertools.product(preds, aggs):
        qs.append(f"SELECT a, {ag} AS v FROM t1 WHERE {p} GROUP BY a")
    for p in preds:
        qs.append(f"SELECT a, b FROM t1 WHERE {p}")
        qs.append(f"SELECT DISTINCT a, b FROM t1 WHERE {p}")
        qs.append(f"SELECT t1.a, t2.y FROM t1 JOIN t2 ON t1.a = t2.x "
                  f"WHERE {p}")
    return qs


PREDS1 = ["a > 3", "b < 0", "c % 7 = 1", "not (a = 2 or a = 5)",
          "b between -5 and 5", "a + 1 < 6", "b >= -10"]
PREDS2 = ["x > 2", "y < 15", "x % 2 = 0", "y between 5 and 25", "not x = 3"]
AGGS = ["count(*)", "sum(b)", "min(c)", "max(b)", "avg(c)"]

# join-chain FROM variants with the columns visible in each
JOIN_FROMS = {
    "t1only": ("t1", ["a", "b", "c"]),
    "equi": ("t1 JOIN t2 ON t1.a = t2.x",
             ["t1.a", "t1.b", "t1.c", "t2.x", "t2.y"]),
    "left": ("t1 LEFT JOIN t2 ON t1.a = t2.x",
             ["t1.a", "t1.b", "t1.c", "t2.y"]),
    "chain3": ("t1 JOIN t2 ON t1.a = t2.x JOIN t3 ON t2.y = t3.p",
               ["t1.a", "t1.b", "t2.y", "t3.p", "t3.q"]),
}


def _extended_cases():
    """The generated pairwise corpus (reference bar: the Calcite frontend's
    ~7M SLTs, doc/vldb23/implementation.tex:38-52 — environmentally scaled):
    every planner feature pair (set ops x predicates, join chains x
    predicates x projections, FROM-subqueries x aggregates, join kind x
    distinct x aggregation x having) appears, >=2000 cases total with the
    core corpus."""
    qs = []
    # set operations x left/right predicates x arity (4 x 7 x 5 x 2 = 280)
    for op in ("UNION", "UNION ALL", "EXCEPT", "INTERSECT"):
        for p1 in PREDS1:
            for p2 in PREDS2:
                qs.append(f"SELECT a FROM t1 WHERE {p1} {op} "
                          f"SELECT x FROM t2 WHERE {p2}")
                qs.append(f"SELECT a, b FROM t1 WHERE {p1} {op} "
                          f"SELECT x, y FROM t2 WHERE {p2}")
    # set-op chains: unparenthesized chains are left-associative with equal
    # precedence in BOTH engines; grouping uses the FROM-subquery form
    # (sqlite's grammar rejects parenthesized compound-select operands)
    for p1 in PREDS1[:4]:
        qs.append(f"SELECT a FROM t1 WHERE {p1} UNION SELECT x FROM t2 "
                  "EXCEPT SELECT p FROM t3")
        qs.append(f"SELECT a FROM t1 WHERE {p1} UNION ALL SELECT x FROM t2 "
                  "INTERSECT SELECT a FROM t1")
        qs.append("SELECT * FROM (SELECT a FROM t1 WHERE "
                  f"{p1} UNION SELECT x FROM t2) u "
                  "EXCEPT SELECT p FROM t3")
        qs.append(f"SELECT a FROM t1 WHERE {p1} UNION ALL "
                  "SELECT * FROM (SELECT x FROM t2 "
                  "INTERSECT SELECT a FROM t1) v")
    # join chains x predicates x projections
    for p in PREDS1:
        qs.append("SELECT t1.a, t2.y, t3.q FROM t1 JOIN t2 ON t1.a = t2.x "
                  f"JOIN t3 ON t2.y = t3.p WHERE {p}")
        qs.append("SELECT t1.a, t3.q FROM t1 JOIN t2 ON t1.a = t2.x "
                  "JOIN t3 ON t2.y = t3.p")
        qs.append("SELECT t1.b, t2.x, t3.p FROM t1 JOIN t2 ON t1.a = t2.x "
                  f"JOIN t3 ON t2.y = t3.p WHERE {p}")
    # FROM-subqueries: grouped inner x outer predicate; subquery join table
    for agg in AGGS:
        for p in PREDS1[:4]:
            qs.append(f"SELECT s.a, s.v FROM (SELECT a, {agg} AS v FROM t1 "
                      f"WHERE {p} GROUP BY a) s WHERE s.v > 2")
            qs.append(f"SELECT s.v FROM (SELECT a, {agg} AS v FROM t1 "
                      f"GROUP BY a) s WHERE s.a > 2 AND {'s.v < 1000'}")
    for p in PREDS1[:5]:
        qs.append(f"SELECT s.a, t2.y FROM (SELECT a, b, c FROM t1 WHERE {p})"
                  " s JOIN t2 ON s.a = t2.x")
    for outer in ("s.n > 1", "s.n = 2", "s.a + s.n > 4", "not s.n > 3"):
        qs.append("SELECT s.a, s.n FROM (SELECT a, count(*) AS n FROM t1 "
                  f"GROUP BY a) s WHERE {outer}")
    # pairwise mega-sweep: join kind x predicate x distinct x projection
    # (a/b/c resolve unqualified in both engines — unique across tables)
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        for p in PREDS1:
            for dist in ("", "DISTINCT "):
                qs.append(f"SELECT {dist}{', '.join(cols[:2])} FROM {frm} "
                          f"WHERE {p}")
                qs.append(f"SELECT {dist}{cols[0]} FROM {frm} WHERE {p}")
            qs.append(f"SELECT {', '.join(cols)} FROM {frm} WHERE {p}")
    # join kind x aggregation x group col x having
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        gcol = cols[0]
        acol = cols[1] if jk == "t1only" else cols[-1]
        for agg in ("count(*)", f"sum({acol})", f"min({acol})",
                    f"max({acol})", f"avg({acol})"):
            qs.append(f"SELECT {gcol}, {agg} AS v FROM {frm} "
                      f"GROUP BY {gcol}")
            qs.append(f"SELECT {gcol}, {agg} AS v FROM {frm} "
                      f"GROUP BY {gcol} HAVING count(*) > 1")
            qs.append(f"SELECT {gcol}, {agg} AS v FROM {frm} "
                      f"GROUP BY {gcol} HAVING {agg} > 3")
    # arithmetic-expression projections x predicates (pairwise over ops)
    exprs = ["a + b", "c - b", "a * 2 + b", "c / 3", "c % 5", "0 - b",
             "a * b - c", "(a + b) * 2", "c / 4 + a % 3"]
    for e in exprs:
        for p in PREDS1:
            qs.append(f"SELECT {e} AS e FROM t1 WHERE {p}")
            qs.append(f"SELECT a, {e} AS e FROM t1 WHERE {p}")
    # scalar subqueries x outer predicates, incl. set-op subqueries
    for p in PREDS1:
        qs.append(f"SELECT a, b FROM t1 WHERE {p} "
                  "AND b > (SELECT min(b) FROM t1)")
        qs.append(f"SELECT a, c FROM t1 WHERE {p} "
                  "OR c > (SELECT avg(c) FROM t1)")
    # order by / limit x predicates (t1 only: unique order keys)
    for p in PREDS1:
        for lim, desc in ((3, ""), (5, " DESC"), (8, "")):
            qs.append(f"SELECT a, b, c FROM t1 WHERE {p} "
                      f"ORDER BY c{desc} LIMIT {lim}")
    # union of aggregates (set op over grouped subplans)
    for agg in AGGS[:4]:
        qs.append(f"SELECT a, {agg} AS v FROM t1 GROUP BY a UNION "
                  "SELECT x, count(*) AS v FROM t2 GROUP BY x")
    # --- volume sweeps: the full pairwise crosses -------------------------
    PREDS3 = ["p > 5", "q < 80", "p % 3 = 0"]
    # compound WHERE (AND/OR pairs) x join kind x projection
    pairs = list(itertools.combinations(PREDS1, 2))  # 21
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        for p1, p2 in pairs:
            for comb in ("and", "or"):
                qs.append(f"SELECT {cols[0]} FROM {frm} "
                          f"WHERE ({p1}) {comb} ({p2})")
                qs.append(f"SELECT {', '.join(cols[:2])} FROM {frm} "
                          f"WHERE ({p1}) {comb} ({p2})")
                qs.append(f"SELECT DISTINCT {cols[0]} FROM {frm} "
                          f"WHERE ({p1}) {comb} ({p2})")
    # set ops with expression projections and with t3 operands
    for op in ("UNION", "UNION ALL", "EXCEPT", "INTERSECT"):
        for p1 in PREDS1:
            for p2 in PREDS2:
                qs.append(f"SELECT a + b FROM t1 WHERE {p1} {op} "
                          f"SELECT x + y FROM t2 WHERE {p2}")
            for p3 in PREDS3:
                qs.append(f"SELECT a FROM t1 WHERE {p1} {op} "
                          f"SELECT p FROM t3 WHERE {p3}")
                qs.append(f"SELECT c FROM t1 WHERE {p1} {op} "
                          f"SELECT q FROM t3 WHERE {p3}")
    # set ops over grouped operands
    for op in ("UNION", "EXCEPT", "INTERSECT"):
        for agg in AGGS:
            for p in PREDS1[:3]:
                qs.append(f"SELECT a, {agg} AS v FROM t1 WHERE {p} "
                          f"GROUP BY a {op} "
                          "SELECT x, count(*) AS v FROM t2 GROUP BY x")
    # aggregation x join kind x WHERE predicate
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        gcol = cols[0]
        for agg in AGGS:
            for p in PREDS1:
                qs.append(f"SELECT {gcol}, {agg} AS v FROM {frm} "
                          f"WHERE {p} GROUP BY {gcol}")
    # HAVING forms x join kind x aggregate
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        gcol = cols[0]
        for agg in AGGS:
            for hv in ("count(*) > 2", "sum(c) > 1000 or count(*) = 1",
                       f"min({cols[1]}) < 10", "not count(*) = 2"):
                qs.append(f"SELECT {gcol}, {agg} AS v FROM {frm} "
                          f"GROUP BY {gcol} HAVING {hv}")
    # expression pairs x predicates
    for (e1, e2) in itertools.combinations(
            ["a + b", "c - b", "c / 3", "c % 5", "a * b"], 2):
        for p in PREDS1:
            qs.append(f"SELECT {e1} AS u, {e2} AS w FROM t1 WHERE {p}")
    # scalar subqueries x comparison operators
    for cmp_ in ("=", "<>", "<", "<=", ">", ">="):
        for p in PREDS1:
            qs.append(f"SELECT a, b FROM t1 WHERE {p} "
                      f"AND a {cmp_} (SELECT max(x) FROM t2)")
    # range joins x widths x predicates
    for width in (0, 1, 2, 5, 10):
        for p in PREDS1[:4]:
            qs.append("SELECT t1.a, t2.x, t2.y FROM t1 JOIN t2 "
                      f"ON t2.x BETWEEN t1.a - {width} AND t1.a + {width} "
                      f"WHERE {p}")
    # limit sweep
    for lim in (1, 2, 4, 6, 9, 12):
        for p in PREDS1:
            qs.append(f"SELECT a, b, c FROM t1 WHERE {p} "
                      f"ORDER BY c LIMIT {lim}")
    # 3-way predicate combinations over t1
    for p1, p2, p3 in itertools.combinations(PREDS1, 3):
        qs.append(f"SELECT a, c FROM t1 WHERE ({p1}) and (({p2}) or ({p3}))")
    # NULL-aware aggregation over outer-join padding — direct, through
    # expression arguments (NULL must propagate through arithmetic), and
    # through FROM-subqueries (nullability crosses the subquery boundary)
    for agg in ("sum", "avg", "min", "max", "count"):
        qs.append(f"SELECT t1.a, {agg}(t2.y) AS v FROM t1 "
                  "LEFT JOIN t2 ON t1.a = t2.x GROUP BY t1.a")
        qs.append(f"SELECT t1.a, {agg}(t2.y + 1) AS v FROM t1 "
                  "LEFT JOIN t2 ON t1.a = t2.x GROUP BY t1.a")
        qs.append(f"SELECT s.k, {agg}(s.v) AS w FROM "
                  "(SELECT t1.a AS k, t2.y AS v FROM t1 "
                  "LEFT JOIN t2 ON t1.a = t2.x) s GROUP BY s.k")
    return qs




def _qual(pred: str, **cols) -> str:
    """Qualify bare column names in a generated predicate (word-boundary
    safe: a naive str.replace of 'd ' corrupts 'and')."""
    import re as _re

    for col, repl in cols.items():
        pred = _re.sub(rf"\b{col}\b", repl, pred)
    return pred


def _null_str_cases():
    """String / NULL / set-membership corpus (round-5 planner features):
    three-valued predicates and projections over declared-nullable
    columns, dictionary-string equality + IN + LIKE, LEFT-JOIN pads under
    predicates, and IN (SELECT)/EXISTS conjuncts — pairwise-crossed for
    volume, sqlite as the oracle throughout."""
    qs = []
    NPRED = ["m > 3", "m IS NULL", "m IS NOT NULL", "m + 1 > 2",
             "not m > 2", "m IS NOT NULL and m < 8", "m > 0 or d > 5",
             "m between 0 and 6"]
    SPRED = ["s = 'apple'", "s <> 'banana'", "s IN ('apple', 'berry')",
             "s NOT IN ('apple', 'berry')", "s LIKE 'a%'",
             "s LIKE '%rr%'", "s NOT LIKE 'b%'", "s IS NULL",
             "s IS NOT NULL"]
    PROJ = ["d", "d, m", "d, m + 1", "d, s", "s, m"]
    # nullable/string predicates x projections (+ DISTINCT variants)
    for p in NPRED + SPRED:
        for proj in PROJ:
            qs.append(f"SELECT {proj} FROM t4 WHERE {p}")
        qs.append(f"SELECT DISTINCT d FROM t4 WHERE {p}")
    # Kleene combinations: nullable x string predicate pairs
    for p1 in NPRED[:6]:
        for p2 in SPRED[:6]:
            for comb in ("and", "or"):
                qs.append(f"SELECT d, m FROM t4 WHERE ({p1}) {comb} ({p2})")
    # string GROUP BY + NULL-aware aggregates over nullable args
    for agg in ("count(*)", "count(m)", "sum(m)", "min(m)", "max(m)",
                "avg(m)"):
        qs.append(f"SELECT s, {agg} AS v FROM t4 GROUP BY s")
        qs.append(f"SELECT d, {agg} AS v FROM t4 GROUP BY d")
        for p in NPRED[:4] + SPRED[:4]:
            qs.append(f"SELECT d, {agg} AS v FROM t4 WHERE {p} GROUP BY d")
    # HAVING over nullable aggregates
    for hv in ("count(m) > 1", "sum(m) > 4", "min(m) < 2",
               "count(*) > 2 and max(m) > 3"):
        qs.append(f"SELECT d, count(*) AS n FROM t4 GROUP BY d "
                  f"HAVING {hv}")
    # LEFT JOIN pads under predicates/projections (t1 x t4 on a = d)
    for p in ("t4.m IS NULL", "t4.m > 2", "t4.s = 'apple'",
              "t4.s IS NULL", "t4.m + 1 > 3", "t4.d IS NOT NULL",
              "not t4.m > 4"):
        qs.append("SELECT t1.a, t4.m FROM t1 LEFT JOIN t4 "
                  f"ON t1.a = t4.d WHERE {p}")
        qs.append("SELECT t1.a, t4.m + 1 FROM t1 LEFT JOIN t4 "
                  f"ON t1.a = t4.d WHERE {p}")
    for agg in ("count(t4.m)", "sum(t4.m)", "max(t4.m)", "avg(t4.m)"):
        qs.append(f"SELECT t1.a, {agg} AS v FROM t1 LEFT JOIN t4 "
                  "ON t1.a = t4.d GROUP BY t1.a")
    # joins on string columns (equality on dictionary codes)
    qs.append("SELECT u.d, v.d FROM t4 u JOIN t4 v ON u.s = v.s "
              "WHERE u.d < v.d")
    qs.append("SELECT u.d, v.m FROM t4 u JOIN t4 v ON u.s = v.s "
              "WHERE u.m IS NULL")
    # IN (SELECT) / EXISTS / NOT EXISTS x outer predicates x sub predicates
    for p1 in PREDS1:
        for p2 in PREDS2[:4]:
            qs.append(f"SELECT a FROM t1 WHERE {p1} AND a IN "
                      f"(SELECT x FROM t2 WHERE {p2})")
            qs.append(f"SELECT a, b FROM t1 WHERE {p1} AND a NOT IN "
                      f"(SELECT x FROM t2 WHERE {p2})")
            qs.append(f"SELECT a FROM t1 WHERE {p1} AND EXISTS "
                      f"(SELECT x FROM t2 WHERE t2.x = t1.a AND {p2})")
            qs.append(f"SELECT a FROM t1 WHERE {p1} AND NOT EXISTS "
                      f"(SELECT x FROM t2 WHERE t2.x = t1.a AND {p2})")
    # membership over t4/t3 and uncorrelated EXISTS
    for p in NPRED[:5]:
        qs.append(f"SELECT d FROM t4 WHERE {p} AND d IN "
                  "(SELECT a FROM t1 WHERE a < 6)")
        qs.append(f"SELECT d, m FROM t4 WHERE ({p}) AND EXISTS "
                  "(SELECT p FROM t3 WHERE q > 60)")
        qs.append(f"SELECT d FROM t4 WHERE {p} AND m IN "
                  "(SELECT y FROM t2 WHERE y IS NOT NULL)")
    # IN-list over ints x predicates (incl. NULL literal member)
    for p in PREDS1[:5]:
        qs.append(f"SELECT a FROM t1 WHERE {p} AND a IN (1, 3, 5, 7)")
        qs.append(f"SELECT a FROM t1 WHERE {p} AND a NOT IN (2, 4)")
        qs.append(f"SELECT a, b FROM t1 WHERE {p} AND b IN (0, NULL, 5)")
    # membership x join kind
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        for sub in ("t1.a IN (SELECT x FROM t2)",
                    "EXISTS (SELECT p FROM t3 WHERE t3.p = t1.a)",
                    "t1.a NOT IN (SELECT p FROM t3)"):
            if "t1.a" in " ".join(cols) or jk == "t1only":
                qs.append(f"SELECT {cols[0]} FROM {frm} WHERE {sub}")
    # strings through FROM-subqueries and set ops
    for p in SPRED[:5]:
        qs.append("SELECT u.s, u.m FROM (SELECT s, m FROM t4 "
                  f"WHERE {p}) u WHERE u.m IS NOT NULL")
        qs.append(f"SELECT s FROM t4 WHERE {p} UNION "
                  "SELECT s FROM t4 WHERE s LIKE 'c%'")
        qs.append(f"SELECT s FROM t4 WHERE {p} EXCEPT "
                  "SELECT s FROM t4 WHERE m IS NULL")
    # volume: 3-way Kleene over nullable preds
    for p1, p2, p3 in itertools.combinations(NPRED[:6], 3):
        qs.append(f"SELECT d FROM t4 WHERE ({p1}) and (({p2}) or ({p3}))")
        qs.append(f"SELECT d, m FROM t4 WHERE (({p1}) or ({p2})) "
                  f"and not ({p3})")
    # volume: string pred x nullable pred x projection
    for p1 in SPRED:
        for p2 in NPRED:
            qs.append(f"SELECT d, s, m FROM t4 WHERE ({p1}) and ({p2})")
            qs.append(f"SELECT DISTINCT s FROM t4 WHERE ({p1}) or ({p2})")
    # volume: membership x scalar subquery x predicate
    for p in PREDS1:
        for cmp_ in ("<", ">="):
            qs.append(f"SELECT a FROM t1 WHERE {p} AND a IN "
                      "(SELECT x FROM t2) AND b "
                      f"{cmp_} (SELECT min(b) FROM t1)")
    # --- volume sweeps (the 5k-corpus pairwise crosses) -------------------
    ALLP = SPRED + NPRED
    # every string/nullable predicate pair x AND/OR x three projections
    for p1, p2 in itertools.combinations(ALLP, 2):
        for comb in ("and", "or"):
            qs.append(f"SELECT d FROM t4 WHERE ({p1}) {comb} ({p2})")
            qs.append(f"SELECT d, m FROM t4 WHERE ({p1}) {comb} ({p2})")
            qs.append(f"SELECT DISTINCT s FROM t4 "
                      f"WHERE ({p1}) {comb} ({p2})")
    # 3-way Kleene over a mixed sample
    for p1, p2, p3 in itertools.combinations(ALLP[::2], 3):
        qs.append(f"SELECT d FROM t4 WHERE ({p1}) and (({p2}) or ({p3}))")
        qs.append(f"SELECT d, s FROM t4 WHERE (({p1}) or ({p2})) "
                  f"and not ({p3})")
    # nullable arithmetic projections x predicates
    for e in ("m + 1", "m * 2", "m - d", "m + d", "0 - m", "m / 2",
              "m % 3", "m * m"):
        for p in ALLP:
            qs.append(f"SELECT d, {e} AS e FROM t4 WHERE {p}")
    # IN-lists x predicates x projections
    for lst in ("(1, 2, 3)", "(0, 5, 9)", "(2, NULL)", "(7)",
                "(1, 3, 5, 7, 9)", "(-1, 0, 1)"):
        for p in ALLP[:10]:
            qs.append(f"SELECT d FROM t4 WHERE {p} AND m IN {lst}")
            qs.append(f"SELECT d, m FROM t4 WHERE {p} AND d IN {lst}")
    # LIKE pattern sweep x nullable predicates
    for pat in ("a%", "%e", "%an%", "_pple", "%a%", "c%", "%y"):
        for p in NPRED:
            qs.append(f"SELECT d, s FROM t4 WHERE s LIKE '{pat}' "
                      f"AND {p}")
            qs.append(f"SELECT d FROM t4 WHERE s NOT LIKE '{pat}' "
                      f"OR {p}")
    # membership x join kind x aggregate
    for (jk, (frm, cols)) in JOIN_FROMS.items():
        for agg in AGGS:
            for sub in ("t1.a IN (SELECT x FROM t2)",
                        "EXISTS (SELECT p FROM t3 WHERE t3.p = t1.a)",
                        "t1.a NOT IN (SELECT p FROM t3)"):
                qs.append(f"SELECT {cols[0]}, {agg} AS v FROM {frm} "
                          f"WHERE {sub} GROUP BY {cols[0]}")
    # scalar subqueries against t4 x nullable predicates
    for p in NPRED[:6]:
        for cmp_ in ("<", ">", "<=", ">="):
            qs.append(f"SELECT d, m FROM t4 WHERE {p} "
                      f"AND d {cmp_} (SELECT avg(a) FROM t1)")
    # t4 self-join on string key x predicate pairs
    for p1 in SPRED[:6]:
        for p2 in NPRED[:6]:
            qs.append("SELECT u.d, v.d FROM t4 u JOIN t4 v "
                      f"ON u.s = v.s WHERE ({_qual(p1, s='u.s')})"
                      f" and ({_qual(p2, m='v.m', d='v.d')})")
    # string GROUP BY x HAVING x aggregate
    for agg in ("count(*)", "count(m)", "sum(m)", "max(m)"):
        for hv in ("count(*) > 1", "count(m) > 1", "sum(m) > 3",
                   "min(m) < 4", "max(m) >= 5", "not count(*) = 1"):
            qs.append(f"SELECT s, {agg} AS v FROM t4 GROUP BY s "
                      f"HAVING {hv}")
    # ORDER BY/LIMIT over t4's unique-ish d with predicates
    for p in ALLP[:12]:
        qs.append(f"SELECT d, m FROM t4 WHERE {p} ORDER BY d LIMIT 5")
    # membership nesting through FROM-subqueries
    for p in PREDS1[:5]:
        qs.append("SELECT u.a FROM (SELECT a, b FROM t1 WHERE a IN "
                  f"(SELECT x FROM t2)) u WHERE {_qual(p, a='u.a', b='u.b')}")
    # inner join t1 x t4 (int key) x int predicate x nullable predicate
    for p1 in PREDS1:
        for p2 in NPRED:
            qs.append("SELECT t1.a, t4.m FROM t1 JOIN t4 ON t1.a = t4.d "
                      f"WHERE ({p1}) and ({_qual(p2, m='t4.m', d='t4.d')})")
            qs.append("SELECT t1.b, t4.s FROM t1 JOIN t4 ON t1.a = t4.d "
                      f"WHERE ({p1}) or ({_qual(p2, m='t4.m', d='t4.d')})")
    # LEFT JOIN pad predicate pairs (both sides of the Kleene table)
    pads = ["t4.m IS NULL", "t4.m > 2", "t4.s = 'apple'", "t4.s IS NULL",
            "t4.m + 1 > 3", "not t4.m > 4", "t4.m IS NOT NULL"]
    for p1 in pads:
        for p2 in PREDS1:
            qs.append("SELECT t1.a, t4.m FROM t1 LEFT JOIN t4 "
                      f"ON t1.a = t4.d WHERE ({p1}) and ({p2})")
            qs.append("SELECT t1.a FROM t1 LEFT JOIN t4 "
                      f"ON t1.a = t4.d WHERE ({p1}) or ({p2})")
    # nullable expression pairs x predicates
    for (e1, e2) in itertools.combinations(
            ["m + 1", "m - d", "m * 2", "0 - m", "m % 3", "m / 2",
             "m + d", "d - m"], 2):
        for p in ALLP[:10]:
            qs.append(f"SELECT {e1} AS u, {e2} AS w FROM t4 WHERE {p}")
    # AND NOT pairs (the Kleene table's third column)
    for p1, p2 in itertools.combinations(ALLP, 2):
        qs.append(f"SELECT d, m FROM t4 WHERE ({p1}) and not ({p2})")
    # membership over t4 x every string/nullable predicate
    for p in ALLP:
        qs.append(f"SELECT d FROM t4 WHERE ({p}) AND d IN "
                  "(SELECT x FROM t2)")
        qs.append(f"SELECT d, s FROM t4 WHERE ({p}) AND NOT EXISTS "
                  "(SELECT x FROM t2 WHERE t2.x = t4.d)")
    # full PREDS2 sweep for correlated EXISTS (completes the [:4] slice)
    for p1 in PREDS1:
        qs.append(f"SELECT a FROM t1 WHERE {p1} AND EXISTS "
                  f"(SELECT x FROM t2 WHERE t2.x = t1.a AND {PREDS2[4]})")
        qs.append(f"SELECT a FROM t1 WHERE {p1} AND a IN "
                  f"(SELECT x FROM t2 WHERE {PREDS2[4]})")
    # IS NULL over projections of every nullable expression
    for e in ("m + 1", "m - d", "m * 2", "0 - m", "m % 3", "m / 2"):
        for p in ALLP[:6]:
            qs.append(f"SELECT d FROM t4 WHERE ({e} IS NULL) and ({p})")
            qs.append(f"SELECT d FROM t4 WHERE {e} IS NOT NULL and ({p})")
    return qs


def _sqlite_expected(conn, sql):
    cur = conn.execute(sql)
    rows = cur.fetchall()
    out = {}
    for r in rows:
        # native cells: strings stay strings, NULL stays None (our side
        # decodes through SqlContext.decode_output to the same shape)
        key = tuple(v if v is None or isinstance(v, str) else int(v)
                    for v in r)
        out[key] = out.get(key, 0) + 1
    return out


def _to_sqlite(sql: str) -> str:
    """Translate dialect: our truncating AVG -> sqlite expression."""
    import re

    return re.sub(r"avg\(([^)]*)\)",
                  r"CAST(TOTAL(\1) / (ABS(COUNT(\1)) + 0.0) AS INT)", sql,
                  flags=re.IGNORECASE)


def _run_chunk(queries):
    """Plan + step one chunk of queries on one circuit, compare every view
    against sqlite. Returns [(query, got, want), ...] divergences."""
    rng = random.Random(99)
    data = _data(rng)
    conn = sqlite3.connect(":memory:")
    for t, cols in TABLES.items():
        conn.execute(f"CREATE TABLE {t} ({', '.join(cols)})")
        conn.executemany(
            f"INSERT INTO {t} VALUES ({', '.join('?' * len(cols))})",
            data[t])

    def build(c):
        ctx = SqlContext(c)
        handles = {}
        for t, cols in TABLES.items():
            s, h = add_input_zset(c, (jnp.int64,),
                                  (jnp.int64,) * (len(cols) - 1))
            ctx.register_table(t, s, cols,
                               string_cols=STRING_COLS.get(t, ()),
                               nullable_cols=NULLABLE_COLS.get(t, ()))
            handles[t] = h
        views = [ctx.query(q) for q in queries]
        return ctx, handles, views, [v.output() for v in views]

    handle, (ctx, handles, views, outs) = Runtime.init_circuit(1, build)
    for t, rows in data.items():
        handles[t].extend([(ctx.encode_row(t, r), 1) for r in rows])
    handle.step()
    failures = []
    for q, view, out in zip(queries, views, outs):
        got = ctx.decode_output(view, out.to_dict())
        want = _sqlite_expected(conn, _to_sqlite(q))
        if got != want:
            failures.append((q, got, want))
    return failures


def _run_cases(queries, batch: int = 120):
    """Run chunks in SUBPROCESSES: beyond ~2k live compiled executables
    XLA:CPU's compile-and-load segfaults (observed on this corpus; same
    crash conftest bounds per-module), and in-process jax.clear_caches()
    between chunks is not isolation enough. A fresh process per chunk is;
    the persistent compile cache keeps re-JITs cheap."""
    import subprocess
    import sys
    import tempfile

    failures = []
    for start in range(0, len(queries), batch):
        chunk = queries[start:start + batch]
        with tempfile.TemporaryDirectory() as td:
            qf = os.path.join(td, "queries.json")
            rf = os.path.join(td, "failures.json")
            with open(qf, "w") as f:
                json.dump(chunk, f)
            root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=root + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), qf, rf],
                env=env, timeout=1800, capture_output=True, text=True)
            if r.returncode != 0 or not os.path.exists(rf):
                failures.append((f"chunk@{start} crashed rc={r.returncode}: "
                                 f"{r.stderr[-400:]}", {}, {}))
                continue
            with open(rf) as f:
                failures.extend(tuple(x) for x in json.load(f))
    return failures


def test_slt_conformance():
    queries = _cases()
    assert len(queries) > 100
    failures = _run_cases(queries, batch=len(queries))
    assert not failures, (
        f"{len(failures)}/{len(queries)} queries diverge; first: "
        f"{failures[0]}")


def test_slt_null_str_membership():
    """The round-5 feature corpus: three-valued NULL logic, dictionary
    strings (=/IN/LIKE/GROUP BY/joins), LEFT-JOIN pads under predicates,
    and IN (SELECT)/EXISTS lowering — a few hundred cases vs sqlite."""
    queries = _null_str_cases()
    assert len(queries) >= 500, len(queries)
    failures = _run_cases(queries[:300], batch=300)
    assert not failures, (
        f"{len(failures)} queries diverge; first 3: {failures[:3]}")


def test_slt_full_corpus():
    """The >=5000-case pairwise corpus (core + generated + the round-5
    string/NULL/membership families) vs sqlite — set ops, join chains,
    FROM-subqueries, feature cross-sweeps, three-valued predicates."""
    queries = _cases() + _extended_cases() + _null_str_cases()
    assert len(queries) >= 5000, len(queries)
    failures = _run_cases(queries)
    assert not failures, (
        f"{len(failures)}/{len(queries)} queries diverge; first 3: "
        f"{failures[:3]}")


if __name__ == "__main__":
    # subprocess chunk runner (see _run_cases): argv = queries.json out.json
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")  # env alone is too late
    with open(sys.argv[1]) as f:
        _chunk = json.load(f)
    _fails = _run_chunk(_chunk)
    with open(sys.argv[2], "w") as f:
        json.dump([[q, repr(g), repr(w)] for q, g, w in _fails], f)
