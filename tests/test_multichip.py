"""Multi-worker bit-identity: W workers == 1 worker, end to end.

The reference's contract (shard.rs:35-88): the same circuit over any
worker count produces identical output. This PR makes W-worker execution
first-class — recursive (fixedpoint) children and the rolling radix-tree
path evaluate per worker key-slice instead of collapsing to one worker —
so the matrix here covers exactly the shapes that used to force a
mid-circuit unshard, plus the Nexmark q1-q8 set on both engines.

Tier-1 runs a representative subset; the full W ∈ {2, 4, 8} x q1-q8
matrix rides the slow marker (the acceptance sweep).
"""

import pytest
import jax
import jax.numpy as jnp

from dbsp_tpu.circuit import Runtime
from dbsp_tpu.nexmark import (GeneratorConfig, NexmarkGenerator,
                              build_inputs, queries)
from dbsp_tpu.operators import add_input_zset
from dbsp_tpu.operators.aggregate import Max

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices (conftest)")

QUERIES = ["q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8"]
TICKS = 2
EPT = 600  # events per tick — small: per-shape jit compiles dominate


# ---------------------------------------------------------------------------
# Harnesses (W=1 results memoized per module — each worker count reruns
# the same circuit; comparing against the cached single-worker run keeps
# the matrix at one extra build per W instead of two)
# ---------------------------------------------------------------------------

_host_memo = {}


def run_host_query(qname: str, workers: int):
    key = (qname, workers)
    if key in _host_memo:
        return _host_memo[key]
    gen = NexmarkGenerator(GeneratorConfig(seed=11))

    def build(c):
        streams, handles = build_inputs(c)
        return handles, getattr(queries, qname)(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(workers, build)
    per_tick = []
    n = 0
    for _ in range(TICKS):
        gen.feed(handles, n, n + EPT)
        handle.step()
        b = out.take()
        per_tick.append({} if b is None else b.to_dict())
        n += EPT
    _host_memo[key] = per_tick
    return per_tick


def run_compiled_query(qname: str, workers: int, ticks: int = 3,
                       ept: int = 20):
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.nexmark import device_gen

    cfg = GeneratorConfig(seed=11)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, getattr(queries, qname)(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(workers, build)
    hp, ha, hb = handles

    def gen_fn(tick):
        p, a, b = device_gen.generate_tick(cfg, tick * ept, ept)
        return {hp: p, ha: a, hb: b}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    per_tick = {}

    def capture(next_tick):
        b = ch.output(out)
        per_tick[next_tick - 1] = {} if b is None else b.to_dict()

    ch.run_ticks(0, ticks, validate_every=1, on_validated=capture)
    return [per_tick[t] for t in range(ticks)], ch


def run_closure(workers: int, epochs):
    """Transitive closure via recursive() — the fixedpoint shape that
    previously forced an unconditional unshard."""

    def build(c):
        edges, h = add_input_zset(c, [jnp.int64], [jnp.int64])
        closure = edges.recurse(
            lambda child, r: r.join_index(
                child.import_stream(edges).index_by(
                    lambda k, v: (v[0],), (jnp.int64,),
                    val_fn=lambda k, v: (k[0],), val_dtypes=(jnp.int64,),
                    name="rev"),
                lambda k, lv, rv: ((rv[0],), (lv[0],)),
                [jnp.int64], [jnp.int64], name="step"))
        return h, closure.output()

    handle, (h, out) = Runtime.init_circuit(workers, build)
    results = []
    for rows in epochs:
        for r, w in rows:
            h.push(r, w)
        handle.step()
        b = out.take()
        results.append({} if b is None else b.to_dict())
    return results


CLOSURE_EPOCHS = [
    [((i, i + 1), 1) for i in range(6)] + [((10, 11), 1), ((11, 3), 1)],
    [((2, 3), -1)],           # deletion must propagate through the
    [((20, 0), 1)],           # fixedpoint (nested distinct corners)
]


def run_rolling(workers: int, use_tree: bool = True):
    """Partitioned rolling Max over [t-100, t] — the radix-tree shape that
    previously dropped to the O(window) recompute path under a mesh."""

    def build(c):
        s, h = add_input_zset(c, [jnp.int64, jnp.int64], [jnp.int64])
        out = s.partitioned_rolling_aggregate(Max(0), 100,
                                              use_tree=use_tree)
        return h, out.output()

    handle, (h, out) = Runtime.init_circuit(workers, build)
    eps = [
        [((p, t * 7, p * 91 + (t * 13) % 50), 1)
         for p in range(5) for t in range(12)],
        [((p, 40 + p, 999 - p), 1) for p in range(5)],
        [((1, 7, 1 * 91 + 13 % 50), -1)],  # late retraction
    ]
    results = []
    for rows in eps:
        for r, w in rows:
            h.push(r, w)
        handle.step()
        b = out.take()
        results.append({} if b is None else b.to_dict())
    # surface the operator so tests can assert which path ran
    op = next(n.operator for n in handle.circuit.nodes
              if type(n.operator).__name__ == "RollingAggregateOp")
    return results, op


# ---------------------------------------------------------------------------
# Tier-1 subset
# ---------------------------------------------------------------------------


def test_recursive_closure_w4_bit_identical():
    want = run_closure(1, CLOSURE_EPOCHS)
    assert any(want), "vacuous comparison"
    got = run_closure(4, CLOSURE_EPOCHS)
    assert got == want


def test_rolling_radix_w4_bit_identical_and_tree_engaged():
    want, op1 = run_rolling(1, use_tree=True)
    oracle, _ = run_rolling(1, use_tree=False)
    assert want == oracle  # tree fast path == O(window) recompute
    got, op4 = run_rolling(4, use_tree=True)
    assert got == want
    # the sharded run must actually have used the per-worker trees (a
    # silent fallback to window recompute would pass bit-identity)
    assert op4.tree is not None
    assert op4.tree.query_rows_gathered > 0
    assert any(len(s.batches) for s in op4.tree.levels)


def test_host_q4_w8_bit_identical():
    want = run_host_query("q4", 1)
    got = run_host_query("q4", 8)
    assert sum(len(d) for d in want) > 0
    assert got == want


def test_compiled_q4_w4_bit_identical():
    want, _ = run_compiled_query("q4", 1)
    got, _ = run_compiled_query("q4", 4)
    assert got == want
    assert sum(len(d) for d in want) > 0


def test_compiled_exchange_overflow_replays_not_drops():
    """Shrink a compiled exchange's static per-worker bucket so a routed
    tick overflows it: the requirement check must trigger the replay
    machinery (grow + re-run), count the event, and the final output must
    still be bit-identical to the unconstrained run — rows are never
    silently dropped off the bucket slice."""
    from dbsp_tpu.compiled import cnodes
    from dbsp_tpu.parallel.exchange import EXCHANGE_OVERFLOW_COUNTS

    want, _ = run_compiled_query("q3", 1, ticks=2, ept=40)
    got, ch = run_compiled_query("q3", 4, ticks=2, ept=40)
    assert got == want

    before = dict(EXCHANGE_OVERFLOW_COUNTS)
    exchanges = [cn for cn in ch.cnodes
                 if isinstance(cn, cnodes.CExchange)]
    assert exchanges, "q3 at W=4 must carry at least one exchange"

    # fresh driver with a sabotaged exchange bucket
    got2, ch2 = None, None

    def run_sabotaged():
        from dbsp_tpu.compiled import compile_circuit
        from dbsp_tpu.nexmark import device_gen

        cfg = GeneratorConfig(seed=11)

        def build(c):
            streams, handles = build_inputs(c)
            return handles, queries.q3(*streams).output()

        handle, (handles, out) = Runtime.init_circuit(4, build)
        hp, ha, hb = handles

        def gen_fn(tick):
            p, a, b = device_gen.generate_tick(cfg, tick * 40, 40)
            return {hp: p, ha: a, hb: b}

        ch = compile_circuit(handle, gen_fn=gen_fn)
        # run one tick to let caps self-initialize, then shrink the
        # exchange bucket below its observed requirement and replay
        per_tick = {}

        def capture(next_tick):
            b = ch.output(out)
            per_tick[next_tick - 1] = {} if b is None else b.to_dict()

        ch.run_ticks(0, 1, validate_every=1, on_validated=capture)
        shrunk = 0
        for cn in ch.cnodes:
            if isinstance(cn, cnodes.CExchange) and cn.last_required >= 2:
                # below the observed requirement: the next routed tick
                # MUST overflow the bucket
                cn.caps["exchange"] = max(1, cn.last_required // 2)
                shrunk += 1
        assert shrunk, "no exchange carried enough rows to sabotage"
        ch._step_jit = None
        ch._scan_jits = {}
        ch._req = None
        ch.run_ticks(1, 1, validate_every=1, on_validated=capture)
        return [per_tick[t] for t in range(2)], ch

    got2, ch2 = run_sabotaged()
    assert got2 == want  # replay repaired the overflow: no data loss
    assert ch2.exchange_overflows >= 1
    after = EXCHANGE_OVERFLOW_COUNTS.get("exchange", 0)
    assert after > before.get("exchange", 0)


def test_host_exchange_skew_observables():
    """obs-enabled host exchanges report per-worker occupancy and a
    max/mean skew ratio; the registry exports both gauges."""
    from dbsp_tpu.obs.instrument import CircuitInstrumentation
    from dbsp_tpu.obs.registry import MetricsRegistry

    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int64])
        # force one real exchange: re-key (away from the source hash) and
        # aggregate, whose sugar re-shards
        rek = s.index_by(lambda k, v: (v[0],), (jnp.int64,),
                         val_fn=lambda k, v: (k[0],),
                         val_dtypes=(jnp.int64,), name="rekey")
        from dbsp_tpu.operators.aggregate_linear import LinearCount

        return h, rek.aggregate(LinearCount()).output()

    handle, (h, out) = Runtime.init_circuit(4, build)
    reg = MetricsRegistry()
    CircuitInstrumentation(handle.circuit, reg)
    for i in range(64):
        h.push((i, i % 7), 1)
    handle.step()
    out.take()
    ops = [n.operator for n in handle.circuit.nodes
           if n.operator.name == "shard"]
    assert ops
    op = next(o for o in ops if getattr(o, "last_occupancy", None)
              and len(o.last_occupancy) > 1)
    assert sum(op.last_occupancy) > 0
    assert op.skew_ratio >= 1.0
    from dbsp_tpu.obs.export import prometheus_text

    text = prometheus_text(reg)
    assert "dbsp_tpu_exchange_worker_occupancy_rows" in text
    assert "dbsp_tpu_exchange_skew_ratio" in text
    assert "dbsp_tpu_exchange_overflow_total" in text


def test_p003_strict_shard_escalation_and_waiver():
    from dbsp_tpu.analysis import ERROR, WARN, analyze
    from dbsp_tpu.circuit.builder import RootCircuit, Stream
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp

    def build_defect():
        c = RootCircuit()
        s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
        u = c.add_unary_operator(UnshardOp(), s)
        u.schema = s.schema
        c.add_unary_operator(ExchangeOp(4), u).output()
        return c, u

    c, _ = build_defect()
    f = [x for x in analyze(c, workers=4) if x.rule_id == "P003"]
    assert len(f) == 1 and f[0].severity == WARN
    f = [x for x in analyze(c, workers=4, strict_shard=True)
         if x.rule_id == "P003"]
    assert len(f) == 1 and f[0].severity == ERROR
    # workers=1: the invariant is vacuous
    assert not [x for x in analyze(c, workers=1, strict_shard=True)
                if x.rule_id == "P003"]
    # waiver: Stream.waive_lint silences it (the graph-level '# ok')
    c2, u2 = build_defect()
    Stream(c2, u2.node_index).waive_lint("P003")
    assert not [x for x in analyze(c2, workers=4, strict_shard=True)
                if x.rule_id == "P003"]


def test_nexmark_queries_p003_clean_at_w8():
    """Zero-unshard invariant over the full query set: no P003 (and no
    ERROR of any kind) on the REAL 8-worker builds under strict-shard.
    Building under the runtime matters — a 1-worker build elides
    unshard() to intent metadata P003 cannot see."""
    from dbsp_tpu.analysis import ERROR, analyze
    from dbsp_tpu.circuit.builder import RootCircuit

    prev = Runtime._swap(Runtime(8, build_only=True))
    try:
        for qname in QUERIES:
            def build(c, _q=qname):
                streams, handles = build_inputs(c)
                getattr(queries, _q)(*streams).output()
                return None

            circuit, _ = RootCircuit.build(build)
            findings = analyze(circuit, workers=8, strict_shard=True)
            bad = [f for f in findings
                   if f.rule_id == "P003" or f.severity == ERROR]
            assert not bad, (qname, [f.render() for f in bad])
    finally:
        Runtime._swap(prev)


def test_p003_catches_reintroduced_recursive_unshard():
    """Enforcement canary: re-introducing the pre-lift shape — a collapsed
    stream imported into a recursive child — must FIRE P003 on a
    multi-worker build (this is exactly the regression the strict sweep
    exists to block; it must not be vacuous)."""
    from dbsp_tpu.analysis import analyze
    from dbsp_tpu.circuit.builder import RootCircuit

    prev = Runtime._swap(Runtime(4, build_only=True))
    try:
        def build(c):
            edges, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
            collapsed = edges.unshard()  # the pre-lift mistake
            collapsed.recurse(
                lambda child, r: r.join_index(
                    child.import_stream(collapsed),
                    lambda k, lv, rv: ((lv[0],), (rv[0],)),
                    [jnp.int64], [jnp.int64], name="step"))
            return None

        circuit, _ = RootCircuit.build(build)
        hits = [f for f in analyze(circuit, workers=4, strict_shard=True)
                if f.rule_id == "P003"]
        assert hits and all(f.severity == "error" for f in hits)
    finally:
        Runtime._swap(prev)


# ---------------------------------------------------------------------------
# Full matrix (slow tier — the acceptance sweep)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("workers", [2, 4, 8])
@pytest.mark.parametrize("qname", QUERIES)
def test_host_query_matrix_bit_identical(qname, workers):
    want = run_host_query(qname, 1)
    got = run_host_query(qname, workers)
    assert got == want


@pytest.mark.slow
@pytest.mark.parametrize("workers", [2, 8])
@pytest.mark.parametrize("qname", ["q1", "q2", "q3", "q4", "q8"])
def test_compiled_query_matrix_bit_identical(qname, workers):
    try:
        want, _ = run_compiled_query(qname, 1)
    except NotImplementedError as e:
        pytest.skip(f"{qname} not compiled: {e}")
    got, _ = run_compiled_query(qname, workers)
    assert got == want


@pytest.mark.slow
@pytest.mark.parametrize("workers", [2, 8])
def test_recursive_closure_matrix(workers):
    want = run_closure(1, CLOSURE_EPOCHS)
    assert run_closure(workers, CLOSURE_EPOCHS) == want


@pytest.mark.slow
@pytest.mark.parametrize("workers", [2, 8])
def test_rolling_matrix(workers):
    want, _ = run_rolling(1, use_tree=True)
    got, _ = run_rolling(workers, use_tree=True)
    assert got == want


def test_import_stream_default_zero_follows_value_placement():
    """The default import zero copies the imported VALUE's placement: an
    unsharded (host-resident, P003-waived shape) parent import at W>1 must
    emit unsharded zeros on later child ticks — [W, cap] zeros against 1-D
    parent batches is a mixed-placement merge downstream."""
    from dbsp_tpu.circuit.builder import RootCircuit
    from dbsp_tpu.circuit.nested import subcircuit
    from dbsp_tpu.zset.batch import Batch

    prev = Runtime._swap(Runtime(4, build_only=True))
    try:
        box = {}

        def build(c):
            s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])

            def f(child):
                child.import_stream(s)
                box["op"] = child.imports[-1][1]

            subcircuit(c, f)

        RootCircuit.build(build)
        op = box["op"]
        unsharded = Batch.empty((jnp.int64,), (jnp.int64,), cap=4)
        op.import_value(unsharded)
        assert op.eval() is unsharded        # first child tick: the value
        z = op.eval()                        # later ticks: the default zero
        assert not z.sharded
        sharded = Batch.empty((jnp.int64,), (jnp.int64,), cap=4, lead=(4,))
        op.import_value(sharded)
        op.eval()
        z = op.eval()
        assert z.sharded and z.weights.shape[0] == 4
    finally:
        Runtime._swap(prev)


def test_delay_zero_follows_unshard_placement():
    """delay()/integrate() default zeros are placement-aware at build time
    (Z1 emits its zero at clock_start, before any value is seen): a stream
    explicitly collapsed to the host via unshard() gets 1-D zeros even on
    a W>1 mesh, a sharded stream gets [W, cap] zeros."""
    from dbsp_tpu.circuit.builder import RootCircuit

    prev = Runtime._swap(Runtime(4, build_only=True))
    try:
        def z1_zero(build):
            circuit, _ = RootCircuit.build(build)
            op = next(n.operator for n in circuit.nodes
                      if getattr(n.operator, "name", "") == "z1")
            return op.zero_factory()

        def host_resident(c):
            s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
            s.unshard().waive_lint("P003").delay()

        def sharded(c):
            s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
            s.shard().delay()

        assert not z1_zero(host_resident).sharded
        z = z1_zero(sharded)
        assert z.sharded and z.weights.shape[0] == 4
    finally:
        Runtime._swap(prev)


def test_p003_fires_through_placement_preserving_ops():
    """The zero-unshard invariant is transitive: a map between the
    collapse and the re-shard still collapses the circuit to one worker
    mid-graph (unshard -> map -> shard)."""
    from dbsp_tpu.analysis import ERROR, analyze
    from dbsp_tpu.circuit.builder import RootCircuit

    prev = Runtime._swap(Runtime(4, build_only=True))
    try:
        def build(c):
            s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
            m = s.unshard().map_rows(lambda k, v: (k, v), (jnp.int64,),
                                     (jnp.int64,))
            m.shard().output()

        circuit, _ = RootCircuit.build(build)
    finally:
        Runtime._swap(prev)
    f = [x for x in analyze(circuit, workers=4, strict_shard=True)
         if x.rule_id == "P003"]
    assert len(f) == 1 and f[0].severity == ERROR


def test_delay_zero_walks_through_placement_preserving_ops():
    """_schema_zero's backward walk crosses map/filter: the zero for
    unshard().map_rows(...).delay() stays 1-D on a W>1 mesh."""
    from dbsp_tpu.circuit.builder import RootCircuit

    prev = Runtime._swap(Runtime(4, build_only=True))
    try:
        def build(c):
            s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
            m = s.unshard().waive_lint("P003").map_rows(
                lambda k, v: (k, v), (jnp.int64,), (jnp.int64,))
            m.delay()

        circuit, _ = RootCircuit.build(build)
        z1 = next(n.operator for n in circuit.nodes
                  if getattr(n.operator, "name", "") == "z1")
        assert not z1.zero_factory().sharded
    finally:
        Runtime._swap(prev)
