"""Unified observability subsystem (dbsp_tpu.obs): registry primitives,
Prometheus exposition round-trip, Chrome-trace spans, host/compiled/manager
instrumentation, the exactly-once on_validated fix, the compiled-fallback
counter, the sharded spine-budget semantics, and the metrics naming lint.

ISSUE 1 acceptance: a single GET /metrics on a running manager pipeline
returns per-operator eval-latency histogram buckets, spine residency
gauges, exchange row counters, and step-latency quantile summaries; /trace
returns perfetto-loadable Chrome-trace JSON with balanced spans.
"""

import json
import re

import pytest
import jax.numpy as jnp

from dbsp_tpu.obs import (CircuitInstrumentation, MetricNameError,
                          MetricsRegistry, PipelineObs, SpanRecorder,
                          legacy_controller_lines, prometheus_text,
                          prometheus_text_many, validate_metric_name)

# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------


def test_counter_inc_labels_and_monotonicity():
    r = MetricsRegistry()
    c = r.counter("dbsp_tpu_io_steps_total", "steps")
    c.inc()
    c.inc(4)
    assert c.value == 5
    lc = r.counter("dbsp_tpu_io_input_records_total", "rows",
                   labels=("endpoint",))
    lc.labels(endpoint="a").inc(3)
    lc.labels(endpoint="b").inc(7)
    assert r.value("dbsp_tpu_io_input_records_total", endpoint="a") == 3
    assert r.value("dbsp_tpu_io_input_records_total", endpoint="b") == 7
    with pytest.raises(ValueError):
        c.inc(-1)
    # collector mirror API never regresses
    c.set_total(3)
    assert c.value == 5
    c.set_total(9)
    assert c.value == 9
    # get-or-create returns the same object; a type change is an error
    assert r.counter("dbsp_tpu_io_steps_total") is c
    with pytest.raises(ValueError):
        r.gauge("dbsp_tpu_io_steps_total")


def test_gauge_set_inc_dec():
    r = MetricsRegistry()
    g = r.gauge("dbsp_tpu_trace_level_count", "levels", labels=("node",))
    g.labels(node="3").set(5)
    g.labels(node="3").inc()
    g.labels(node="3").dec(2)
    assert r.value("dbsp_tpu_trace_level_count", node="3") == 4


def test_histogram_buckets_count_sum_quantile():
    r = MetricsRegistry()
    h = r.histogram("dbsp_tpu_circuit_operator_eval_seconds", "lat",
                    buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.05, 2.0):
        h.observe(v)
    child = h._default
    assert child.count == 5
    assert child.buckets == [1, 2, 1, 0, 1]  # last = +Inf overflow
    assert abs(child.sum - 2.0605) < 1e-9
    q50 = h.quantile(0.5)
    assert 0.001 <= q50 <= 0.01  # the two 5ms observations
    text = prometheus_text(r)
    # cumulative buckets + +Inf == count
    assert re.search(r'_bucket\{le="0\.001"\} 1\b', text)
    assert re.search(r'_bucket\{le="\+Inf"\} 5\b', text)
    assert "dbsp_tpu_circuit_operator_eval_seconds_count 5" in text
    assert "# TYPE dbsp_tpu_circuit_operator_eval_seconds histogram" in text


def test_summary_quantile_exposition():
    r = MetricsRegistry()
    s = r.summary("dbsp_tpu_circuit_step_seconds", "step lat")
    for v in (0.001, 0.002, 0.004, 0.1):
        s.observe(v)
    text = prometheus_text(r)
    assert "# TYPE dbsp_tpu_circuit_step_seconds summary" in text
    for q in ("0.5", "0.95", "0.99"):
        assert f'dbsp_tpu_circuit_step_seconds{{quantile="{q}"}}' in text
    assert "dbsp_tpu_circuit_step_seconds_count 4" in text


def test_summary_empty_child_scrape_does_not_crash():
    """labels() creates a child with zero observations; its quantiles are
    NaN and must render as 'NaN', not raise mid-scrape."""
    r = MetricsRegistry()
    r.summary("dbsp_tpu_circuit_step_seconds", "lat",
              labels=("w",)).labels(w="0")
    text = prometheus_text(r)
    assert 'dbsp_tpu_circuit_step_seconds{w="0",quantile="0.5"} NaN' in text
    assert 'dbsp_tpu_circuit_step_seconds_count{w="0"} 0' in text


def test_metric_name_validation():
    validate_metric_name("dbsp_tpu_trace_device_resident_rows")
    validate_metric_name("dbsp_tpu_io_steps_total", "counter")
    for bad, kind in [
        ("steps_total", "counter"),              # missing prefix
        ("dbsp_tpu_steps", None),                # bad unit
        ("dbsp_tpu_io_steps", "counter"),        # counter without _total
        ("dbsp_tpu_io_latency_total", "summary"),  # _total non-counter
        ("dbsp_tpu_Io_steps_total", "counter"),  # uppercase
    ]:
        with pytest.raises(MetricNameError):
            validate_metric_name(bad, kind)
    r = MetricsRegistry()
    with pytest.raises(MetricNameError):
        r.counter("dbsp_tpu_bad_unit_frobs")
    with pytest.raises(MetricNameError):
        r.gauge("dbsp_tpu_trace_rows", labels=("Bad-Label",))


def test_prometheus_text_round_trip():
    """Parse the exposition back and recover every scalar sample."""
    r = MetricsRegistry()
    r.counter("dbsp_tpu_io_steps_total", "steps").inc(12)
    g = r.gauge("dbsp_tpu_trace_device_resident_rows", "rows",
                labels=("node",))
    g.labels(node="0.3").set(4096)
    g.labels(node="7").set(128)
    text = prometheus_text(r)
    samples = {}
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        m = re.match(r'^([a-z0-9_]+)(\{[^}]*\})? ([0-9.eE+-]+|\+Inf)$', line)
        assert m, f"unparsable exposition line: {line!r}"
        samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
    assert samples[("dbsp_tpu_io_steps_total", "")] == 12
    assert samples[("dbsp_tpu_trace_device_resident_rows",
                    '{node="0.3"}')] == 4096
    assert samples[("dbsp_tpu_trace_device_resident_rows",
                    '{node="7"}')] == 128
    # headers present once per family
    assert text.count("# TYPE dbsp_tpu_trace_device_resident_rows gauge") == 1


def test_prometheus_text_many_merges_families():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.counter("dbsp_tpu_io_steps_total", "steps").inc(1)
    rb.counter("dbsp_tpu_io_steps_total", "steps").inc(2)
    text = prometheus_text_many([({"pipeline": "a"}, ra),
                                 ({"pipeline": "b"}, rb)])
    assert text.count("# TYPE dbsp_tpu_io_steps_total counter") == 1
    assert 'dbsp_tpu_io_steps_total{pipeline="a"} 1' in text
    assert 'dbsp_tpu_io_steps_total{pipeline="b"} 2' in text


def test_collector_runs_at_exposition():
    r = MetricsRegistry()
    g = r.gauge("dbsp_tpu_trace_level_count", "levels")
    state = {"levels": 3}
    r.register_collector(lambda: g.set(state["levels"]))
    assert "dbsp_tpu_trace_level_count 3" in prometheus_text(r)
    state["levels"] = 8
    assert "dbsp_tpu_trace_level_count 8" in prometheus_text(r)


def test_legacy_controller_lines():
    stats = {"steps": 4,
             "inputs": {"in1": {"total_records": 10, "total_bytes": 99,
                                "buffered_records": 2}},
             "outputs": {"out1": {"total_records": 7, "total_bytes": 50}}}
    lines = legacy_controller_lines(stats)
    assert "dbsp_steps 4" in lines
    assert 'dbsp_input_records{endpoint="in1"} 10' in lines
    assert 'dbsp_input_buffered{endpoint="in1"} 2' in lines
    assert 'dbsp_output_records{endpoint="out1"} 7' in lines


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------


def _assert_balanced(events):
    stack = []
    for ev in events:
        if ev["ph"] == "B":
            stack.append(ev["name"])
        elif ev["ph"] == "E":
            assert stack, f"E without B: {ev}"
            assert stack.pop() == ev["name"], ev
    assert not stack, f"unclosed spans: {stack}"


def test_span_recorder_nesting_window_and_json():
    rec = SpanRecorder(max_steps=2)
    for t in range(4):
        with rec.span(f"step{t}", "step"):
            with rec.span("join[0.1]"):
                pass
            with rec.span("shard[0.2]", "exchange"):
                pass
    doc = json.loads(rec.to_json())  # valid JSON by construction
    evs = doc["traceEvents"]
    _assert_balanced(evs)
    # bounded window: only the last 2 steps retained
    names = {e["name"] for e in evs if e["ph"] == "B"}
    assert names == {"step2", "step3", "join[0.1]", "shard[0.2]"}
    assert rec.dropped_steps == 2
    assert doc["otherData"]["dropped_steps"] == 2
    cats = {e["name"]: e.get("cat") for e in evs if e["ph"] == "B"}
    assert cats["shard[0.2]"] == "exchange"
    # timestamps are microseconds, monotone within a step
    b = [e for e in evs if e["name"] == "step2"]
    assert b[0]["ts"] <= b[-1]["ts"]


def test_span_recorder_tolerates_unbalanced_end():
    rec = SpanRecorder()
    rec.end("phantom")  # attached mid-step: must not corrupt state
    with rec.span("step", "step"):
        pass
    _assert_balanced(rec.events())


# ---------------------------------------------------------------------------
# instrumentation: host circuit (no HTTP)
# ---------------------------------------------------------------------------


def _join_agg_build(c):
    from dbsp_tpu.operators import add_input_zset
    from dbsp_tpu.operators.aggregate import Max

    a, ha = add_input_zset(c, (jnp.int64,), (jnp.int64,))
    b, hb = add_input_zset(c, (jnp.int64,), (jnp.int64,))
    j = a.join_index(b, lambda k, av, bv: (av[0], (bv[0],)),
                     (jnp.int64,), (jnp.int64,))
    return (ha, hb), j.aggregate(Max(0)).integrate().output()


def test_circuit_instrumentation_host_path():
    from dbsp_tpu.circuit import Runtime

    handle, ((ha, hb), out) = Runtime.init_circuit(1, _join_agg_build)
    obs = PipelineObs(name="t")
    obs.attach_circuit(handle.circuit)
    for t in range(3):
        ha.extend([((t * 10 + i, i % 5), 1) for i in range(10)])
        hb.extend([((t * 10 + i, i % 3), 1) for i in range(10)])
        handle.step()
    assert obs.registry.value("dbsp_tpu_circuit_steps_total") == 3
    text = prometheus_text(obs.registry)
    assert "dbsp_tpu_circuit_operator_eval_seconds_bucket" in text
    assert 'operator="join"' in text
    assert 'dbsp_tpu_circuit_step_seconds{quantile="0.5"}' in text
    # spine gauges from the graph walk (join/aggregate build traces)
    assert "dbsp_tpu_trace_device_resident_rows{" in text
    assert "dbsp_tpu_trace_level_count{" in text
    hist = obs.registry.get("dbsp_tpu_circuit_operator_eval_seconds")
    assert all(c.count == 3 for _, c in hist.samples())
    # spans: balanced, step spans wrap operator spans
    evs = obs.spans.events()
    _assert_balanced(evs)
    assert sum(1 for e in evs if e["ph"] == "B" and e["name"] == "step") == 3
    assert any(e.get("cat") == "operator" for e in evs)
    json.loads(obs.spans.to_json())


def test_circuit_instrumentation_sharded_exchange_counters():
    from dbsp_tpu.circuit import Runtime

    handle, ((ha, hb), out) = Runtime.init_circuit(2, _join_agg_build)
    obs = PipelineObs(name="t2")
    obs.attach_circuit(handle.circuit)
    ha.extend([((i, i % 7), 1) for i in range(50)])
    hb.extend([((i, (i * 3) % 11), 1) for i in range(50)])
    handle.step()
    text = prometheus_text(obs.registry)
    rows = {m.group(1): float(m.group(2)) for m in re.finditer(
        r'dbsp_tpu_exchange_rows_total\{node="([^"]+)"\} ([0-9.]+)', text)}
    assert rows and any(v > 0 for v in rows.values()), text
    assert "dbsp_tpu_exchange_bytes_total{" in text


# ---------------------------------------------------------------------------
# compiled path: exactly-once on_validated + overflow counter
# ---------------------------------------------------------------------------


def test_run_ticks_on_validated_exactly_once_across_replay():
    """ADVICE #5: with snapshot_every > 1, an overflow replay re-runs
    validated intervals; on_validated must NOT re-fire for ticks already
    reported (accumulating callbacks would double-count)."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.zset.batch import Batch

    def build(c):
        from dbsp_tpu.operators import add_input_zset

        s, h = add_input_zset(c, (jnp.int64,), ())
        return h, s.distinct().integrate().output()

    handle, (h, out) = Runtime.init_circuit(1, build)
    C = 512  # rows per tick: trace level 0 (init cap < 12*C) must overflow

    def gen_fn(tick):
        keys = tick * C + jnp.arange(C, dtype=jnp.int64)
        return {h: Batch((keys,), (),
                         jnp.ones((C,), jnp.int64))}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    reported = []
    ch.run_ticks(0, 12, validate_every=1, snapshot_every=4,
                 on_validated=reported.append)
    assert ch.overflow_replays >= 1, "test vacuous: no overflow happened"
    assert reported == sorted(set(reported)), reported
    assert reported[-1] == 12
    # every validated interval reported exactly once despite the replays
    assert reported == list(range(1, 13))


def test_try_compiled_driver_catches_any_compile_failure(monkeypatch):
    """ADVICE #1: AssertionError (or anything) raised while building the
    compiled driver must fall back to host mode — counted with a reason."""
    from dbsp_tpu.compiled import driver as driver_mod

    def boom(self, handle, compiled=None):
        raise AssertionError("compiled z^-1 supports Batch-valued only")

    monkeypatch.setattr(driver_mod.CompiledCircuitDriver, "__init__", boom)
    reg = MetricsRegistry()
    assert driver_mod.try_compiled_driver(object(), registry=reg) is None
    assert reg.value("dbsp_tpu_compiled_fallback_total",
                     reason="AssertionError") == 1

    def boom2(self, handle, compiled=None):
        raise NotImplementedError("no compiled equivalent")

    monkeypatch.setattr(driver_mod.CompiledCircuitDriver, "__init__", boom2)
    assert driver_mod.try_compiled_driver(object(), registry=reg) is None
    assert reg.value("dbsp_tpu_compiled_fallback_total",
                     reason="NotImplementedError") == 1
    # no registry attached: still falls back silently
    assert driver_mod.try_compiled_driver(object()) is None


# ---------------------------------------------------------------------------
# spine budget vs residency gauge agreement (ADVICE #2)
# ---------------------------------------------------------------------------


def test_spine_budget_counts_sharded_batches():
    """Sharded batches count toward the enforced resident total (and the
    gauge), but only unsharded levels are offload candidates."""
    from dbsp_tpu.trace.spine import Spine, _is_cold
    from dbsp_tpu.zset.batch import Batch

    s = Spine((jnp.int64,), (), device_budget_rows=1024)
    sharded = Batch.empty((jnp.int64,), (), cap=1024, lead=(2,))
    unsharded = Batch.from_tuples([((i,), 1) for i in range(512)],
                                  (jnp.int64,))
    assert sharded.sharded and not unsharded.sharded
    s.batches = [sharded, unsharded]
    assert s.device_resident_rows() == 1024 + unsharded.cap
    s._enforce_budget()
    # the sharded level alone saturates the budget -> the unsharded level
    # was offloaded; the gauge and the enforcement agree on what's resident
    kinds = [(b.sharded, _is_cold(b)) for b in s.batches]
    assert (True, False) in kinds  # sharded stays on device
    assert (False, True) in kinds  # unsharded went cold
    assert s.device_resident_rows() == 1024
    assert s.host_offloaded_rows() == unsharded.cap


# ---------------------------------------------------------------------------
# watermark lag semantics (the gauge must carry signal, not equal lateness)
# ---------------------------------------------------------------------------


def test_watermark_lag_tracks_out_of_order_arrival():
    """frontier - latest_batch_max: 0 for in-order data, >0 when a batch
    arrives event-time-late. (frontier - watermark would be identically
    the configured lateness — no signal.)"""
    from dbsp_tpu.timeseries.watermark import WatermarkMonotonic
    from dbsp_tpu.zset.batch import Batch

    op = WatermarkMonotonic(lambda k, v: k[0], lateness=5)
    op.eval(Batch.from_tuples([((100,), 1)], (jnp.int64,)))
    md = op.metadata()
    assert md["max_event_time"] == 100 and md["last_batch_max"] == 100
    op.eval(Batch.from_tuples([((40,), 1)], (jnp.int64,)))  # late batch
    md = op.metadata()
    assert md["watermark"] == 95          # never regresses
    assert md["max_event_time"] == 100    # frontier holds
    assert md["last_batch_max"] == 40     # lag gauge reads 60
    # restored checkpoints have no last batch: collector must skip the lag
    op.load_state_dict(op.state_dict())
    assert op.metadata()["last_batch_max"] is None


# ---------------------------------------------------------------------------
# end-to-end: manager pipeline scrape (ISSUE acceptance)
# ---------------------------------------------------------------------------

TABLES = {
    "bids": {"columns": ["auction", "bidder", "price"],
             "dtypes": ["int64", "int64", "int64"], "key_columns": 1},
    "auctions": {"columns": ["id", "category"],
                 "dtypes": ["int64", "int64"], "key_columns": 1},
}
SQL = {"cat_stats":
       "SELECT auctions.category, COUNT(*) AS n, MAX(bids.price) AS hi "
       "FROM bids JOIN auctions ON bids.auction = auctions.id "
       "GROUP BY auctions.category"}


@pytest.fixture()
def manager():
    from dbsp_tpu.manager import PipelineManager

    m = PipelineManager()
    m.start()
    yield m
    m.stop()


def _feed(pipe):
    pipe.push("auctions", [[1, 7], [2, 9], [3, 9]])
    pipe.push("bids", [[1, 10, 100], [2, 11, 250], [3, 12, 50]])
    pipe.step()
    pipe.step()


def test_manager_metrics_scrape_host_mode(manager, monkeypatch):
    """One GET /metrics answers: operator latency histograms, spine
    residency, exchange counters (sharded deploy), step quantiles, IO
    counters, legacy names — and /trace is perfetto-loadable."""
    from dbsp_tpu.client import Connection

    monkeypatch.setenv("DBSP_TPU_MANAGER_COMPILED", "0")
    conn = Connection(port=manager.port)
    conn.create_program("prog", TABLES, SQL)
    pipe = conn.start_pipeline("p1", "prog", config={"workers": 2})
    assert [p for p in conn.pipelines()
            if p["name"] == "p1"][0]["mode"] == "host"
    _feed(pipe)
    assert pipe.read("cat_stats") == {(7, 1, 100): 1, (9, 2, 250): 1}
    text = pipe.metrics()
    assert re.search(
        r'dbsp_tpu_circuit_operator_eval_seconds_bucket\{[^}]*le="', text)
    assert "dbsp_tpu_trace_device_resident_rows{" in text
    rows = [float(m) for m in re.findall(
        r'dbsp_tpu_exchange_rows_total\{[^}]*\} ([0-9.]+)', text)]
    assert rows and any(v > 0 for v in rows)
    assert 'dbsp_tpu_circuit_step_seconds{quantile="0.5"}' in text
    assert "dbsp_tpu_io_pushed_records_total 6" in text
    steps = re.search(r"dbsp_tpu_io_steps_total (\d+)", text)
    assert steps and int(steps.group(1)) >= 2
    # legacy surface intact (pre-registry scrapers)
    assert "dbsp_steps" in text
    # Chrome-trace export: valid JSON, balanced, nested operator spans
    doc = pipe.trace()
    evs = doc["traceEvents"]
    _assert_balanced(evs)
    assert any(e["ph"] == "B" and e["name"] == "step" for e in evs)
    assert any(e.get("cat") == "operator" for e in evs)
    # fleet-wide aggregate on the manager port
    fleet = conn.metrics()
    assert 'pipeline="p1"' in fleet
    assert "dbsp_tpu_circuit_operator_eval_seconds_bucket" in fleet
    assert fleet.count(
        "# TYPE dbsp_tpu_circuit_steps_total counter") == 1


def test_manager_metrics_scrape_compiled_mode(manager):
    from dbsp_tpu.client import Connection

    conn = Connection(port=manager.port)
    conn.create_program("prog", TABLES, SQL)
    pipe = conn.start_pipeline("pc", "prog")
    assert [p for p in conn.pipelines()
            if p["name"] == "pc"][0]["mode"] == "compiled"
    _feed(pipe)
    text = pipe.metrics()
    ticks = re.search(r"dbsp_tpu_compiled_ticks_total (\d+)", text)
    assert ticks and int(ticks.group(1)) >= 2
    assert 'dbsp_tpu_compiled_tick_seconds{quantile="0.5"}' in text
    assert "dbsp_tpu_trace_device_resident_rows{" in text
    assert "dbsp_tpu_compiled_overflow_replays_total" in text
    doc = pipe.trace()
    evs = doc["traceEvents"]
    _assert_balanced(evs)
    assert any(e["ph"] == "B" and e["name"].startswith("tick[")
               for e in evs)
    assert any(e["ph"] == "B" and e["name"] == "compiled_step"
               for e in evs)


# ---------------------------------------------------------------------------
# metrics lint (tools/check_metrics.py) as a tier-1 gate
# ---------------------------------------------------------------------------


def test_metrics_lint_tree_is_clean():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.check_metrics import check_tree

    root = os.path.join(os.path.dirname(__file__), os.pardir, "dbsp_tpu")
    assert check_tree(os.path.abspath(root)) == []


def test_metrics_lint_catches_violations(tmp_path):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.check_metrics import check_tree

    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "rogue.py").write_text(
        'TEXT = "# TYPE my_metric counter"\n'
        'LINE = f\'dbsp_steps{{endpoint="{0}"}} 1\'\n'
        'NAME = "dbsp_tpu_foo_frobs"\n'
        'reg.counter("dbsp_tpu_io_records")\n'
        'reg.gauge("dbsp_tpu_trace_level_count", "x", labels=("tick_id",))\n')
    got = check_tree(str(bad))
    # line 1 (# TYPE header), line 2 (f-string label rendering — the ast
    # constant holds ONE brace after {{ unescaping), line 3 (bad unit),
    # line 4 twice (counter-kind _total rule + bare-literal unit rule),
    # line 5 (label name outside the closed allowlist — cardinality lint)
    assert len(got) == 6, got
    assert sum("exposition formatting" in v for v in got) == 2
    assert any("unit suffix" in v for v in got)
    assert any("_total" in v for v in got)
    assert any("allowlist" in v for v in got)


def test_metrics_lint_label_allowlist_positional(tmp_path):
    """The cardinality lint also sees positional labels args, and
    allowlisted labels pass."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.check_metrics import check_tree

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text(
        'reg.counter("dbsp_tpu_slo_breaches_total", "x", ("slo",))\n')
    assert check_tree(str(pkg)) == []
    (pkg / "bad.py").write_text(
        'reg.counter("dbsp_tpu_io_rows_total", "x", ("row_key",))\n')
    got = check_tree(str(pkg))
    assert len(got) == 1 and "allowlist" in got[0], got
