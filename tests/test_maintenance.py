"""Amortized (budgeted) LSM maintenance — bit-identity + slice bounds.

The maintain budget (``DBSP_TPU_MAINTAIN_BUDGET_ROWS``) bounds the rows a
single maintenance call may move/merge, so a multi-level drain cascade
spreads over several ticks instead of landing in one (the 8.3x p99/p50
tail of BENCH r05). These tests force a cascade in both engines and prove
the amortization changes WHEN compaction happens, never any result:

* compiled engine (``CompiledHandle.maintain``): per-tick outputs under a
  tight budget are bit-identical to the unbounded run, and no call moves
  more than the budget (``maintain_stats``/``maintain_pending``);
* host engine (``trace/spine.py::Spine``): content after every insert is
  identical to an unbounded spine's, and no insert's compaction slice
  exceeds the budget.
"""

import jax.numpy as jnp
import pytest

from dbsp_tpu.zset.batch import Batch

pytestmark = pytest.mark.fast


# ---------------------------------------------------------------------------
# host engine: Spine
# ---------------------------------------------------------------------------


def _rows(tick: int, n: int = 24):
    # distinct keys per tick so levels actually accumulate
    return [((tick * n + i, i), 1) for i in range(n)]


def test_spine_budgeted_maintenance_bit_identical():
    from dbsp_tpu.trace.spine import Spine

    free = Spine([jnp.int64], [jnp.int64], maintain_budget_rows=0)
    # budget below the full carry-chain cascade at this run's power-of-two
    # boundary (1984 rows at t=31) but above any single pair's cost (1024),
    # so the cascade splits while the anti-stall force never engages
    budget = 1280
    tight = Spine([jnp.int64], [jnp.int64], maintain_budget_rows=budget)
    deferred = False
    for t in range(40):
        batch = Batch.from_tuples(_rows(t), [jnp.int64], [jnp.int64])
        free.insert(batch)
        tight.insert(batch)
        # identical CONTENT at every point (compaction may differ)
        assert tight.to_dict() == free.to_dict()
        assert tight.last_slice_rows <= budget
        deferred = deferred or tight.pending_compaction
    # the cascade actually deferred work at least once...
    assert deferred
    assert tight.maintain_stats["max_slice_rows"] <= budget
    assert tight.maintain_stats["forced_merges"] == 0
    assert len(tight.batches) >= len(free.batches)
    # ...and probes agree with the canonical consolidation
    assert tight.consolidated().to_dict() == free.consolidated().to_dict()
    # pumping maintenance to completion converges the structures' content
    for _ in range(64):
        if not tight.maintain(budget_rows=0):
            break
    assert tight.to_dict() == free.to_dict()
    assert not tight.pending_compaction


def test_spine_anti_stall_forces_oversized_pairs():
    """A budget below ONE pair's cost must degrade to late compaction,
    never to unbounded batch accumulation: once a bucket holds more than
    two batches, the merge is forced (and counted)."""
    from dbsp_tpu.trace.spine import Spine

    sp = Spine([jnp.int64], maintain_budget_rows=1)
    for t in range(12):
        sp.insert(Batch.from_tuples([((t * 16 + i,), 1) for i in range(16)],
                                    [jnp.int64]))
        # never more than 2 batches per capacity bucket
        caps = [b.cap for b in sp.batches]
        assert all(caps.count(c) <= 2 for c in set(caps))
    assert sp.maintain_stats["forced_merges"] > 0


# ---------------------------------------------------------------------------
# compiled engine: CompiledHandle.maintain
# ---------------------------------------------------------------------------


def _run_compiled(monkeypatch, budget):
    """Drive a leveled-trace circuit (aggregate over an integrated trace)
    tick by tick at the given maintain budget; returns (per-tick output
    dicts, handle)."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import cnodes, compile_circuit
    from dbsp_tpu.compiled.compiler import CompiledOverflow
    from dbsp_tpu.operators import Max, add_input_zset

    # a small ladder so a 30-tick run cascades through every level
    monkeypatch.setattr(cnodes, "TRACE_LEVELS", 3)
    monkeypatch.setattr(cnodes, "LEVEL0_CAP", 64)

    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int64])
        return h, s.aggregate(Max()).output()

    handle, (h, out) = Runtime.init_circuit(1, build)
    ch = compile_circuit(handle)

    def feed(t):
        # 24 rows/tick, keys cycling over 48 groups, values varying —
        # inserts AND implicit retractions through the Max aggregate
        return Batch.from_tuples(
            [((i % 48, t * 31 + i), 1) for i in range(24)],
            [jnp.int64], [jnp.int64])

    outs = []
    for t in range(30):
        snap = ch.snapshot()
        while True:
            ch.step(tick=t, feeds={h: feed(t)})
            try:
                ch.validate()
                break
            except CompiledOverflow as e:
                ch.grow(e)
                ch.restore(snap)
        outs.append(ch.output(out).to_dict())
        ch.maintain(budget_rows=budget)
    return outs, ch


def test_compiled_budgeted_maintenance_bit_identical(monkeypatch):
    free_outs, free_ch = _run_compiled(monkeypatch, budget=0)  # unbounded
    budget = 96
    tight_outs, tight_ch = _run_compiled(monkeypatch, budget=budget)
    # (a) every tick's output delta is bit-identical to the unbounded run
    assert tight_outs == free_outs
    # (b) the budget bound held: no budgeted (deep-compaction) slice moved
    # more rows than the budget, and the only drains allowed past it are
    # level 0's exempt ones — whose slices are bounded by l0's capacity
    # (one interval's inflow; deferring l0 would trade a bounded drain for
    # an overflow replay + program retrace)
    stats = tight_ch.maintain_stats
    assert stats["max_budgeted_slice_rows"] <= budget
    from dbsp_tpu.compiled import cnodes
    l0_cap_bound = max(
        cn.caps[cn.level_keys[0]] for cn in tight_ch.cnodes
        if isinstance(cn, cnodes._Leveled))
    assert stats["max_slice_rows"] <= max(budget, l0_cap_bound)
    # the cascade really was split: partial drains happened and at least
    # one call left work pending for a later tick
    assert stats["partial_drains"] > 0
    assert stats["rows_moved"] > 0
    # the unbounded run was never forced to slice
    assert free_ch.maintain_stats["partial_drains"] == 0


def test_compiled_budget_defers_then_converges(monkeypatch):
    """Pending maintenance drains on later calls; the trace content (the
    union of levels) matches the unbounded engine's at the end."""
    _, free_ch = _run_compiled(monkeypatch, budget=0)
    _, tight_ch = _run_compiled(monkeypatch, budget=96)
    while tight_ch.maintain(budget_rows=96):
        pass
    for _ in range(8):
        tight_ch.maintain(budget_rows=0)
        if not tight_ch.maintain_pending:
            break

    def trace_content(ch):
        out = {}
        for cn in ch.cnodes:
            st = ch.states.get(str(cn.node.index))
            if st is None or not isinstance(st, tuple) or \
                    not isinstance(st[0], tuple):
                continue
            merged = {}
            for lvl in st[0]:
                for row, w in lvl.to_dict().items():
                    nw = merged.get(row, 0) + w
                    if nw:
                        merged[row] = nw
                    else:
                        merged.pop(row, None)
            out[str(cn.node.index)] = merged
        return out

    assert trace_content(tight_ch) == trace_content(free_ch)
