"""Bit-identity of the fused trace cursors and the consolidation regimes.

The perf tentpole (fused ladder probes + sortedness propagation) is only
legal because every new regime produces the IDENTICAL batches as the code
it replaced:

* ``cursor.join_ladder`` / ``cursor.gather_ladder`` /
  ``cursor.old_weights_ladder`` vs the per-level kernel loops, on
  adversarial ladders (duplicate rows across levels, sentinel tails,
  zero-net weights, dead query rows);
* ``Batch.consolidate()``'s rank-merge fold (sorted-run metadata) vs the
  full sort path;
* run-metadata propagation invariants under every tagging operator;
* ``kernels.searchsorted1`` with queries WIDER than the table dtype
  (the silent-narrowing regression);
* the same checks per worker slice on the 8-way virtual mesh
  (the dryrun_multichip path) via the sharded host join.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dbsp_tpu.zset import cursor, kernels
from dbsp_tpu.zset.batch import Batch, concat_batches

pytestmark = pytest.mark.fast


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _consolidated(rng, n_live, cap, nk=2, nv=1, key_range=40,
                  allow_neg=True):
    """A consolidated Batch with ``n_live`` random rows at capacity ``cap``
    (duplicates collapse, so live count may come out lower)."""
    lo = -3 if allow_neg else 1
    rows = []
    for _ in range(n_live):
        key = tuple(int(rng.integers(0, key_range)) for _ in range(nk + nv))
        w = int(rng.integers(lo, 4)) or 1
        rows.append((key, w))
    cols = [np.array([r[0][i] for r in rows], dtype=np.int64)
            for i in range(nk + nv)]
    ws = np.array([r[1] for r in rows], dtype=np.int64)
    return Batch.from_columns(cols[:nk], cols[nk:], ws, cap=cap)


def _batch_arrays(b: Batch):
    return tuple(np.asarray(c) for c in (*b.cols, b.weights))


def assert_batches_bitequal(a: Batch, b: Batch, msg=""):
    for x, y in zip(_batch_arrays(a), _batch_arrays(b)):
        np.testing.assert_array_equal(x, y, err_msg=msg)


def check_runs(b: Batch, context: str = "") -> None:
    """Verify the sorted-run metadata invariant: each tagged segment is a
    consolidated slice (sorted lex, unique live rows, live-packed, dead
    rows sentinel-keyed at weight 0)."""
    if b.runs is None:
        return
    assert sum(b.runs) == b.cap, f"{context}: runs {b.runs} != cap {b.cap}"
    cols = [np.asarray(c).reshape(-1, np.asarray(c).shape[-1])
            for c in b.cols]
    ws = np.asarray(b.weights).reshape(-1, np.asarray(b.weights).shape[-1])
    for wslice in range(ws.shape[0]):  # per worker slice, if sharded
        off = 0
        for r in b.runs:
            w = ws[wslice, off:off + r]
            seg = [c[wslice, off:off + r] for c in cols]
            live = w != 0
            nlive = int(live.sum())
            assert live[:nlive].all(), \
                f"{context}: run at {off} not live-packed"
            rows = list(zip(*[c[:nlive].tolist() for c in seg])) \
                if seg else [()] * nlive
            assert rows == sorted(rows), f"{context}: run at {off} unsorted"
            assert len(set(rows)) == len(rows), \
                f"{context}: duplicate live rows in run at {off}"
            for c in seg:
                dead = c[nlive:]
                if dead.size:
                    sent = np.asarray(kernels.sentinel_for(c.dtype))
                    assert (dead == sent).all(), \
                        f"{context}: dead rows not sentinel in run at {off}"
            off += r


def _ladder(rng, caps=(256, 64, 32, 16), **kw):
    """Adversarial spine ladder: overlapping key ranges so rows repeat
    across levels (some with cancelling weights)."""
    return tuple(_consolidated(rng, max(2, c // 3), c, **kw) for c in caps)


# ---------------------------------------------------------------------------
# searchsorted1 regression (satellite): wide query vs narrow table
# ---------------------------------------------------------------------------


def test_searchsorted1_wide_query_not_truncated():
    table = jnp.asarray(np.array([10, 20, 30, 40], np.int32))
    # 2^33 + 5 truncates to 5 under an int32 cast -> would insert at 0
    q = jnp.asarray(np.array([(1 << 33) + 5, -(1 << 33), 25], np.int64))
    got = np.asarray(kernels.searchsorted1(table, q))
    np.testing.assert_array_equal(got, [4, 0, 2])
    # and the common-dtype widening keeps the narrow fast path exact
    qs = jnp.asarray(np.array([5, 25, 45], np.int32))
    np.testing.assert_array_equal(
        np.asarray(kernels.searchsorted1(table, qs)), [0, 2, 4])


# ---------------------------------------------------------------------------
# fused ladder probes vs per-level loops
# ---------------------------------------------------------------------------


def test_lex_probe_ladder_matches_per_level():
    rng = np.random.default_rng(0)
    levels = _ladder(rng)
    delta = _consolidated(rng, 20, 32)
    for side in ("left", "right"):
        fused = np.asarray(cursor.lex_probe_ladder(
            [lvl.keys for lvl in levels], delta.keys, side))
        for k, lvl in enumerate(levels):
            ref = np.asarray(kernels.lex_probe(lvl.keys, delta.keys, side))
            np.testing.assert_array_equal(fused[k], ref, err_msg=side)


def test_join_ladder_matches_per_level_loop():
    from dbsp_tpu.operators.join import _join_level_impl

    rng = np.random.default_rng(1)
    fn = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    for trial in range(5):
        levels = _ladder(rng, allow_neg=trial % 2 == 0)
        delta = _consolidated(rng, 10 + trial * 7, 64)
        out_cap = 2048
        fused, total = cursor.join_ladder(delta, levels, 2, fn, out_cap)
        ref_parts, ref_total = [], 0
        for lvl in levels:
            part, t = _join_level_impl(delta, lvl, 2, fn, out_cap)
            ref_parts.append(part)
            ref_total += int(t)
        assert int(total) == ref_total
        assert ref_total <= out_cap, "test shapes must not overflow"
        assert_batches_bitequal(
            fused.consolidate(),
            concat_batches(ref_parts).consolidate().with_cap(out_cap),
            "fused join != per-level join")


def test_gather_ladder_matches_per_level_loop():
    from dbsp_tpu.operators.aggregate import _gather_level_impl

    rng = np.random.default_rng(2)
    levels = _ladder(rng)
    delta = _consolidated(rng, 24, 32)
    qkeys = delta.keys
    qlive = np.asarray(delta.weights) != 0
    qlive[-3:] = False  # some dead query rows
    qlive = jnp.asarray(qlive)
    out_cap = 2048
    (qrow, vals, w), total = cursor.gather_ladder(qkeys, qlive, levels,
                                                  out_cap)
    ref_rows, ref_total = [], 0
    for lvl in levels:
        rq, rv, rw, t = _gather_level_impl(qkeys, qlive, lvl, out_cap)
        ref_total += int(t)
        for i in range(out_cap):
            if int(rw[i]) != 0 or int(rq[i]) < qlive.shape[0]:
                if int(rq[i]) < qlive.shape[0]:
                    ref_rows.append((int(rq[i]),
                                     tuple(int(c[i]) for c in rv),
                                     int(rw[i])))
    got_rows = [(int(qrow[i]), tuple(int(c[i]) for c in vals), int(w[i]))
                for i in range(out_cap) if int(qrow[i]) < qlive.shape[0]]
    assert int(total) == ref_total
    assert sorted(got_rows) == sorted(ref_rows)


def test_old_weights_ladder_matches_per_level_sum():
    from dbsp_tpu.operators.distinct import _old_weights_level_impl

    rng = np.random.default_rng(3)
    levels = _ladder(rng, nk=1, nv=1)
    delta = _consolidated(rng, 16, 32, nk=1, nv=1)
    fused = np.asarray(cursor.old_weights_ladder(delta, levels))
    ref = sum(np.asarray(_old_weights_level_impl(delta, lvl))
              for lvl in levels)
    np.testing.assert_array_equal(fused, ref)


# ---------------------------------------------------------------------------
# consolidation regimes
# ---------------------------------------------------------------------------


def test_rank_fold_bitidentical_to_sort():
    rng = np.random.default_rng(4)
    for nruns in (2, 3, 5, 8):
        parts = [_consolidated(rng, 12, 32, key_range=10) for _ in
                 range(nruns)]
        # adversarial: a part that exactly cancels another
        parts.append(parts[0].neg())
        cat = concat_batches(parts)
        assert cat.sorted_runs == nruns + 1
        folded = cat.consolidate()
        sorted_ref = cat.tagged(None).consolidate()
        assert folded.sorted_runs == 1
        assert_batches_bitequal(folded, sorted_ref,
                                f"rank fold != sort ({nruns} runs)")
        check_runs(folded, "rank fold output")


def test_consolidate_skip_is_noop():
    rng = np.random.default_rng(5)
    b = _consolidated(rng, 20, 32)
    assert b.sorted_runs == 1
    assert b.consolidate() is b  # free by construction


def test_consolidate_counts_paths():
    rng = np.random.default_rng(6)
    before = dict(kernels.CONSOLIDATE_COUNTS)
    b = _consolidated(rng, 20, 32)
    b.consolidate()  # skipped
    concat_batches([b, b.neg()]).consolidate()  # rank fold
    concat_batches([b, b]).tagged(None).consolidate()  # sort or native
    delta = {k: v - before.get(k, 0)
             for k, v in kernels.CONSOLIDATE_COUNTS.items()}
    assert delta["skipped"] >= 1
    assert delta["rank"] >= 1
    assert delta["native"] + delta["sort"] >= 1


def test_runs_metadata_invariants_under_operators():
    rng = np.random.default_rng(7)
    b = _consolidated(rng, 24, 64)
    check_runs(b, "consolidated")
    assert b.sorted_runs == 1

    # weight ops preserve; scale drops (documented conservative choice)
    check_runs(b.neg(), "neg")
    assert b.neg().sorted_runs == 1
    assert b.scale(2).sorted_runs == 0

    # compaction preserves one run
    keep = jnp.asarray(rng.integers(0, 2, b.cap).astype(bool))
    c = b.compacted(keep & (b.weights != 0))
    assert c.sorted_runs == 1
    check_runs(c, "compacted")

    # masked: scalar cond preserves, per-row cond drops
    assert b.masked(jnp.asarray(True)).sorted_runs == 1
    assert b.masked(jnp.asarray(False)).sorted_runs == 1
    check_runs(b.masked(jnp.asarray(False)), "masked-false")
    assert b.masked(b.weights > 0).sorted_runs == 0

    # with_cap: grow extends the tail run, shrink keeps a single run
    g = b.with_cap(128)
    assert g.sorted_runs == 1
    check_runs(g, "grown")
    s = b.consolidate().shrink_to_fit()
    assert s.sorted_runs == 1
    check_runs(s, "shrunk")

    # concat accumulates runs; unknown input poisons
    cat = concat_batches([b, c])
    assert cat.runs == (b.cap, c.cap)
    check_runs(cat, "concat")
    assert concat_batches([b, b.scale(2)]).sorted_runs == 0

    # merge emits one canonical run
    m = b.merge_with(c)
    assert m.sorted_runs == 1
    check_runs(m, "merged")


def test_operator_kernels_tag_outputs():
    """Filter / map / stream-distinct outputs carry (and honor) run tags."""
    from dbsp_tpu.operators.distinct import StreamDistinct
    from dbsp_tpu.operators.filter_map import FilterOp, MapOp

    rng = np.random.default_rng(8)
    b = _consolidated(rng, 24, 64, allow_neg=True)
    f = FilterOp(lambda k, v: k[0] % 2 == 0)._inner(b)
    assert f.sorted_runs == 1
    check_runs(f, "filter")
    m = MapOp(lambda k, v: ((k[0] // 3,), (v[0],)))._inner(b)
    assert m.sorted_runs == 1
    check_runs(m, "map")
    d = StreamDistinct._kernel(b)
    assert d.sorted_runs == 1
    check_runs(d, "stream_distinct")
    # raw (deferred) map: unordered, but canonicalizes to the same Z-set
    raw = MapOp(lambda k, v: ((k[0] // 3,), (v[0],)))._inner_raw(b)
    assert raw.sorted_runs == 0
    assert raw.consolidate().to_dict() == m.to_dict()


# ---------------------------------------------------------------------------
# compiled placement pass
# ---------------------------------------------------------------------------


def test_placement_pass_defers_join_before_canonicalizing_consumers():
    """join -> filter -> map -> output: the join's consolidation leaves the
    program (deferred); outputs stay identical to the host path."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import cnodes, compile_circuit
    from dbsp_tpu.nexmark import GeneratorConfig, NexmarkGenerator, \
        build_inputs, device_gen, queries

    cfg = GeneratorConfig(seed=5)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, queries.q4(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(1, build)
    hp, ha, hb = handles

    def gen_fn(tick):
        p, a, b = device_gen.generate_tick(cfg, tick * 20, 20)
        return {hp: p, ha: a, hb: b}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    assert ch.deferred_consolidations >= 1
    joins = [cn for cn in ch.cnodes if isinstance(cn, cnodes.CJoin)]
    assert joins and all(getattr(cn, "defer_consolidate", False)
                         for cn in joins)

    outs = {}

    def capture(next_tick):
        b = ch.output(out)
        outs[next_tick - 1] = b.to_dict() if b is not None else {}

    ch.run_ticks(0, 3, validate_every=1, on_validated=capture)

    gen = NexmarkGenerator(cfg)
    handle2, (handles2, out2) = Runtime.init_circuit(1, build)
    n = 0
    for t in range(3):
        gen.feed(handles2, n, n + 1000)
        handle2.step()
        b = out2.take()
        assert outs[t] == (b.to_dict() if b is not None else {}), \
            f"tick {t} diverged under deferred consolidation"
        n += 1000


def test_placement_pass_keeps_consolidation_before_stateful_consumers():
    """join -> distinct (via trace): the join output feeds a spine insert,
    so its consolidation must NOT defer (q8 shape)."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import cnodes, compile_circuit
    from dbsp_tpu.nexmark import GeneratorConfig, build_inputs, device_gen, \
        queries

    cfg = GeneratorConfig(seed=6)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, queries.q8(*streams).output()

    handle, _ = Runtime.init_circuit(1, build)
    h = compile_circuit(handle, gen_fn=None)
    joins = [cn for cn in h.cnodes if isinstance(cn, cnodes.CJoin)]
    assert joins and not any(getattr(cn, "defer_consolidate", False)
                             for cn in joins)


def test_slotted_l0_survives_varying_delta_capacity():
    """Regression: the slotted level-0 geometry is PINNED per trace. A tick
    whose delta capacity differs from the pin (feeds mode buckets each
    tick's rows independently) must not reinterpret existing slots at a
    new slot size — distinct would silently re-emit rows already present."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.operators import add_input_zset

    def run(pad_tick2: int):
        def build(c):
            s, h = add_input_zset(c, (jnp.int64,), ())
            return h, s.distinct().output()

        handle, (t, out) = Runtime.init_circuit(1, build)
        ch = compile_circuit(handle)
        feeds = [
            [((k,), 1) for k in range(10, 16)],           # cap 8
            [((k,), 1) for k in range(0, 6)],             # cap 8
            # tick 2 re-feeds 10..15 among enough rows to force a BIGGER
            # delta capacity (retrace) — distinct must emit only the new
            [((k,), 1) for k in range(100, 100 + pad_tick2)] +
            [((k,), 1) for k in range(10, 16)],
        ]
        outs = []
        for tick, rows in enumerate(feeds):
            b = Batch.from_tuples(rows, [jnp.int64], [])
            ch.step(tick=tick, feeds={t: b})
            ch.validate()
            ch.maintain()
            o = ch.output(out)
            outs.append(o.to_dict() if o is not None else {})
        return outs

    grown = run(pad_tick2=20)    # tick-2 cap 32 != pinned slot 8
    stable = run(pad_tick2=2)    # tick-2 cap 8 == pinned slot
    for k in range(10, 16):
        assert (k,) not in grown[2], \
            f"distinct re-emitted {(k,)} after a delta-capacity change"
        assert (k,) not in stable[2]
    assert all((k,) in grown[2] for k in range(100, 120))


# ---------------------------------------------------------------------------
# 8-way mesh (the dryrun_multichip path): fused cursors per worker slice
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sharded_host_join_fused_ladder_8_equals_1():
    """The sharded host join (lifted fused ladder) over 8 virtual workers
    equals the single-worker evaluation — exchange + per-worker fused
    probes + output union, through the public Stream API."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.nexmark import GeneratorConfig, NexmarkGenerator, \
        build_inputs, queries

    def run(workers):
        gen = NexmarkGenerator(GeneratorConfig(seed=9))

        def build(c):
            streams, handles = build_inputs(c)
            return handles, queries.q4(*streams).output()

        handle, (handles, out) = Runtime.init_circuit(workers, build)
        integral = {}
        n = 0
        for _ in range(2):
            gen.feed(handles, n, n + 1200)
            handle.step()
            b = out.take()
            if b is not None:
                for r, w in b.to_dict().items():
                    integral[r] = integral.get(r, 0) + w
                    if integral[r] == 0:
                        del integral[r]
            n += 1200
        return integral

    want = run(1)
    assert want and run(8) == want
