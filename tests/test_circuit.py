"""Circuit core tests: scheduling, feedback, integrate/differentiate, handles.

Pattern follows the reference's engine tests (``circuit/circuit_builder.rs``
tests and ``circuit/dbsp_handle.rs:313-422``): build a small circuit with
Generator sources, step it, assert captured outputs.
"""

import pytest
import jax.numpy as jnp

from dbsp_tpu.circuit import RootCircuit, Runtime
from dbsp_tpu.circuit.scheduler import CircuitGraphError
from dbsp_tpu.operators import Generator, add_input_zset
from dbsp_tpu.zset import Batch


def test_scalar_integrate():
    got = []

    def build(c):
        s = c.add_source(Generator(list(range(1, 6)), default=0))
        s.integrate(zero_factory=lambda: 0).inspect(got.append)

    circuit, _ = RootCircuit.build(build)
    for _ in range(5):
        circuit.step()
    assert got == [1, 3, 6, 10, 15]


def test_scalar_differentiate_inverts_integrate():
    got = []

    def build(c):
        s = c.add_source(Generator([3, 1, 4, 1, 5], default=0))
        s.integrate(zero_factory=lambda: 0) \
         .differentiate(zero_factory=lambda: 0).inspect(got.append)

    circuit, _ = RootCircuit.build(build)
    for _ in range(5):
        circuit.step()
    assert got == [3, 1, 4, 1, 5]


def test_delay_shifts_by_one():
    got = []

    def build(c):
        s = c.add_source(Generator([10, 20, 30], default=0))
        s.delay(zero_factory=lambda: 0).inspect(got.append)

    circuit, _ = RootCircuit.build(build)
    for _ in range(4):
        circuit.step()
    assert got == [0, 10, 20, 30]


def test_zset_integrate_via_handles():
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [])
        return h, s.integrate().output()

    circuit, (h, out) = RootCircuit.build(build)
    h.push((1,), 1)
    h.push((2,), 2)
    circuit.step()
    assert out.to_dict() == {(1,): 1, (2,): 2}
    h.push((1,), -1)
    h.push((3,), 5)
    circuit.step()
    assert out.to_dict() == {(2,): 2, (3,): 5}
    circuit.step()  # no input: integral unchanged
    assert out.to_dict() == {(2,): 2, (3,): 5}


def test_zset_differentiate_recovers_deltas():
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [])
        integ = s.integrate()
        return h, integ.differentiate().output()

    circuit, (h, out) = RootCircuit.build(build)
    h.push((7,), 3)
    circuit.step()
    assert out.to_dict() == {(7,): 3}
    h.push((8,), 1)
    circuit.step()
    assert out.to_dict() == {(8,): 1}
    circuit.step()
    assert out.to_dict() == {}


def test_plus_minus_neg_sum():
    def build(c):
        a, ha = add_input_zset(c, [jnp.int64], [])
        b, hb = add_input_zset(c, [jnp.int64], [])
        d, hd = add_input_zset(c, [jnp.int64], [])
        return ha, hb, hd, a.plus(b).output(), a.minus(b).output(), \
            a.neg().output(), a.sum_with([b, d]).output()

    circuit, (ha, hb, hd, plus_o, minus_o, neg_o, sum_o) = \
        RootCircuit.build(build)
    ha.extend([((1,), 2), ((2,), 1)])
    hb.extend([((1,), -2), ((3,), 4)])
    hd.extend([((9,), 1)])
    circuit.step()
    assert plus_o.to_dict() == {(2,): 1, (3,): 4}
    assert minus_o.to_dict() == {(1,): 4, (2,): 1, (3,): -4}
    assert neg_o.to_dict() == {(1,): -2, (2,): -1}
    assert sum_o.to_dict() == {(2,): 1, (3,): 4, (9,): 1}


def test_nonstrict_cycle_rejected():
    # a cycle that does not pass through a strict (z^-1) node must be rejected
    from dbsp_tpu.operators.basic import Plus

    c = RootCircuit()
    s = c.add_source(Generator([1], default=0))
    n = c._add_node(Plus(), "binary", [s.node_index])
    n.inputs.append(n.index)  # self-loop
    with pytest.raises(CircuitGraphError):
        c.step()


def test_runtime_init_circuit_and_step_latency():
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [])
        return h, s.integrate().output()

    handle, (h, out) = Runtime.init_circuit(1, build)
    h.push((1,), 1)
    handle.step()
    assert out.to_dict() == {(1,): 1}
    assert len(handle.step_times_ns) == 1 and handle.step_times_ns[0] > 0


def test_scheduler_events_fire():
    events = []

    def build(c):
        c.register_scheduler_event_handler(lambda e: events.append(e.kind))
        s, h = add_input_zset(c, [jnp.int64], [])
        return h, s.output()

    circuit, _ = RootCircuit.build(build)
    assert events == ["clock_start"]  # fired when the root clock started
    circuit.step()
    assert events[1] == "step_start" and events[-1] == "step_end"
    assert "eval_start" in events and "eval_end" in events
