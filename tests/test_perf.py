"""Perf regression gate (`-m perf`): mini q3/q4/q8 runs against recorded
throughput bands.

Round 3 shipped a 10x q4 regression because no test measured anything;
this tier makes that a red test. Bands are intentionally loose (factor
PERF_BAND, default 2.5x) so single-core noise and contending processes
don't flake the gate, while an order-of-magnitude regression cannot pass.

The recorded values live in tests/perf_baseline.json and are updated
DELIBERATELY with the change that moves them:

    python tools/record_perf.py        # reruns the minis, rewrites json

Run the gate:  python -m pytest -m perf -q   (~2-3 min on a quiet core)
"""

import json
import os
import time

import pytest

pytestmark = [pytest.mark.perf, pytest.mark.slow]

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "perf_baseline.json")
PERF_BAND = float(os.environ.get("PERF_BAND", 2.5))

MINI = {"batch": 7_500, "warm": 3, "meas": 16}


def measure_query(qname: str, batch: int = MINI["batch"],
                  warm: int = MINI["warm"], meas: int = MINI["meas"]):
    """Steady-state events/s + p50 tick ms for one query, compiled mode,
    same protocol shape as bench.py at reduced length."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import cnodes, compile_circuit
    from dbsp_tpu.nexmark import (GeneratorConfig, build_inputs, device_gen,
                                  queries)

    query = getattr(queries, qname)
    batch = max(batch // 50, 1) * 50
    ept = batch // 50
    ticks = warm + 1 + meas
    cnodes.TRACE_LEVELS = cnodes.levels_for_run(ticks)
    cfg = GeneratorConfig(seed=1)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, query(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(1, build)
    hp, ha, hb = handles

    def gen_fn(tick):
        p, a, b = device_gen.generate_tick(cfg, tick * ept, ept)
        return {hp: p, ha: a, hb: b}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    ch.run_ticks(0, warm, validate_every=1, project_ratio=4.0)
    ch.presize(ticks / warm, interval=2)
    ch.run_ticks(warm, 1, validate_every=1, project_ratio=4.0)
    ch.step_times_ns.clear()
    t0 = time.perf_counter()
    ch.run_ticks(warm + 1, meas, validate_every=2, block_each=True,
                 project_ratio=4.0, snapshot_every=4)
    ch.block()
    elapsed = time.perf_counter() - t0
    ts = sorted(ch.step_times_ns)
    p50_ms = ts[len(ts) // 2] / 1e6
    return {
        "events_per_s": round(meas * batch / elapsed, 1),
        "steady_events_per_s": round(batch / (p50_ms / 1e3), 1),
        "p50_tick_ms": round(p50_ms, 2),
    }


def _baseline():
    assert os.path.exists(BASELINE_PATH), (
        "tests/perf_baseline.json missing — record it with "
        "`python tools/record_perf.py`")
    with open(BASELINE_PATH) as f:
        return json.load(f)


@pytest.mark.parametrize("qname", ["q3", "q4", "q8"])
def test_throughput_within_band(qname):
    base = _baseline()[qname]
    got = measure_query(qname)
    floor = base["steady_events_per_s"] / PERF_BAND
    assert got["steady_events_per_s"] >= floor, (
        f"{qname} regressed: {got['steady_events_per_s']:.0f} ev/s "
        f"steady vs recorded {base['steady_events_per_s']:.0f} "
        f"(band {PERF_BAND}x => floor {floor:.0f}); p50 "
        f"{got['p50_tick_ms']}ms vs {base['p50_tick_ms']}ms. If this "
        "change deliberately trades this throughput, re-record with "
        "tools/record_perf.py and say so in the commit.")
    # ELAPSED wall-clock floor (VERDICT r5 weak #2): steady_events_per_s
    # derives from p50 alone and is blind to between-tick host work
    # (validate fetches, maintain drains, snapshot copies) — q3's elapsed
    # regressed 2.32M -> 1.65M ev/s while its p50 IMPROVED. The reference's
    # metric is elapsed wall-clock (nexmark/benches/nexmark/main.rs:276),
    # so regressions there must fail tier-1 too.
    efloor = base["events_per_s"] / PERF_BAND
    assert got["events_per_s"] >= efloor, (
        f"{qname} ELAPSED wall-clock regressed: {got['events_per_s']:.0f} "
        f"ev/s vs recorded {base['events_per_s']:.0f} (band {PERF_BAND}x "
        f"=> floor {efloor:.0f}) while steady-state held "
        f"{got['steady_events_per_s']:.0f} — the regression is in "
        "BETWEEN-tick host work (validate/maintain/snapshot), which p50 "
        "cannot see. If deliberate, re-record with tools/record_perf.py "
        "and say so in the commit.")


# Per-kernel floor band: wider than the query band — single kernels at
# microbench shapes have more scheduler/cache jitter than a 16-tick run.
KERNEL_BAND = float(os.environ.get("PERF_KERNEL_BAND", 2 * PERF_BAND))

# Every kernel path the microbench must keep floors for — grows with the
# kernel substrate; a recording that silently drops one is a red test,
# not a silent coverage hole.
EXPECTED_KERNELS = {
    "consolidate", "rank_fold", "lex_probe", "lex_probe_ladder",
    "merge_sorted_cols", "expand_ranges", "compact", "gather_ladder",
    "join_ladder", "join_sorted", "segment_reduce", "agg_ladder",
    "flight_record",
}


def test_kernel_microbench_floor():
    """Coarse per-kernel floor (tools/microbench_kernels.py): a kernel that
    got KERNEL_BAND-times slower than its recorded baseline fails here
    with the kernel named — a query-level regression then starts from a
    suspect instead of a bisect. Recorded by tools/record_perf.py."""
    base = _baseline().get("kernels")
    if not base:
        pytest.skip("perf_baseline.json has no kernels section — record "
                    "with `python tools/record_perf.py`")
    missing = EXPECTED_KERNELS - set(base)
    assert not missing, (
        f"perf_baseline.json kernels section is missing {sorted(missing)} "
        "— re-record with `python tools/record_perf.py` so the new "
        "kernel paths are floor-gated")
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import microbench_kernels

    got = microbench_kernels.run(reps=3)
    slow = []
    for name, rec in base.items():
        if name == "meta" or name not in got:
            continue
        ceiling = rec["ms"] * KERNEL_BAND
        if got[name]["ms"] > ceiling:
            slow.append(f"{name}: {got[name]['ms']:.2f}ms vs recorded "
                        f"{rec['ms']:.2f}ms (ceiling {ceiling:.2f}ms)")
    assert not slow, (
        "kernel microbench regressed (band "
        f"{KERNEL_BAND}x): {'; '.join(slow)}. If deliberate, re-record "
        "with tools/record_perf.py and say so in the commit.")
