"""Stateful incremental operators vs oracles.

The reference's key test pattern (SURVEY.md §4): an incremental operator's
accumulated output must equal re-evaluating the non-incremental operator on
the fully accumulated input, tick for tick. Oracles are python dicts.
"""

import random

import pytest
import jax.numpy as jnp

from dbsp_tpu.circuit import RootCircuit
from dbsp_tpu.operators import add_input_zset, Count, Sum, Min, Max, Average
from dbsp_tpu.zset import Batch


def dict_add(d, delta):
    for r, w in delta.items():
        d[r] = d.get(r, 0) + w
        if d[r] == 0:
            del d[r]
    return d


def rand_delta(rng, n, key_range=6, val_range=8):
    rows = {}
    for _ in range(n):
        r = (rng.randrange(key_range), rng.randrange(val_range))
        rows[r] = rows.get(r, 0) + rng.choice([-1, 1, 1, 2])
    return {r: w for r, w in rows.items() if w != 0}


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------


def oracle_join(a, b):
    """Z-set join on key: {(k, va, vb): wa*wb}."""
    out = {}
    for (ka, va), wa in a.items():
        for (kb, vb), wb in b.items():
            if ka == kb:
                r = (ka, va, vb)
                out[r] = out.get(r, 0) + wa * wb
                if out[r] == 0:
                    del out[r]
    return out


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(3))
def test_incremental_join_matches_full_reevaluation(seed):
    rng = random.Random(seed)

    def build(c):
        a, ha = add_input_zset(c, [jnp.int64], [jnp.int32])
        b, hb = add_input_zset(c, [jnp.int64], [jnp.int32])
        joined = a.join_index(
            b, lambda k, lv, rv: (k, (*lv, *rv)),
            [jnp.int64], [jnp.int32, jnp.int32])
        return ha, hb, joined.output()

    circuit, (ha, hb, out) = RootCircuit.build(build)
    accum_a, accum_b, accum_out = {}, {}, {}
    for tick in range(8):
        da = rand_delta(rng, rng.randrange(0, 10))
        db = rand_delta(rng, rng.randrange(0, 10))
        ha.extend([(r, w) for r, w in da.items()])
        hb.extend([(r, w) for r, w in db.items()])
        circuit.step()
        dict_add(accum_a, da)
        dict_add(accum_b, db)
        dict_add(accum_out, out.to_dict())
        assert accum_out == oracle_join(accum_a, accum_b), f"tick {tick}"


def test_join_cancellation():
    def build(c):
        a, ha = add_input_zset(c, [jnp.int64], [jnp.int32])
        b, hb = add_input_zset(c, [jnp.int64], [jnp.int32])
        j = a.join_index(b, lambda k, lv, rv: (k, (*lv, *rv)),
                         [jnp.int64], [jnp.int32, jnp.int32])
        return ha, hb, j.integrate().output()

    circuit, (ha, hb, out) = RootCircuit.build(build)
    ha.push((1, 10), 1)
    hb.push((1, 20), 1)
    circuit.step()
    assert out.to_dict() == {(1, 10, 20): 1}
    ha.push((1, 10), -1)  # retract the left row
    circuit.step()
    assert out.to_dict() == {}


def test_join_fanout_growth():
    # one delta key matching many trace rows exercises the grow-on-demand
    # output capacity path
    def build(c):
        a, ha = add_input_zset(c, [jnp.int64], [jnp.int32])
        b, hb = add_input_zset(c, [jnp.int64], [jnp.int32])
        j = a.join_index(b, lambda k, lv, rv: (k, (*lv, *rv)),
                         [jnp.int64], [jnp.int32, jnp.int32])
        return ha, hb, j.integrate().output()

    circuit, (ha, hb, out) = RootCircuit.build(build)
    hb.extend([(((1, v)), 1) for v in range(300)])
    circuit.step()
    ha.push((1, 7), 1)
    circuit.step()
    got = out.to_dict()
    assert len(got) == 300
    assert all(w == 1 for w in got.values())


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------


def oracle_aggregate(z, agg):
    groups = {}
    for (k, v), w in z.items():
        assert w >= 0, "oracle expects set-like accumulated input"
        if w > 0:
            groups.setdefault(k, []).extend([v] * w)
    out = {}
    for k, vs in groups.items():
        if agg == "count":
            out[(k, len(vs))] = 1
        elif agg == "sum":
            out[(k, sum(vs))] = 1
        elif agg == "min":
            out[(k, min(vs))] = 1
        elif agg == "max":
            out[(k, max(vs))] = 1
        elif agg == "avg":
            out[(k, sum(vs) // len(vs))] = 1
    return out


AGGS = {"count": Count(), "sum": Sum(0), "min": Min(0), "max": Max(0),
        "avg": Average(0)}


@pytest.mark.parametrize("agg_name", list(AGGS))
@pytest.mark.parametrize("seed", range(2))
def test_incremental_aggregate_matches_oracle(agg_name, seed):
    rng = random.Random(seed)

    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int32])
        return h, s.aggregate(AGGS[agg_name]).output()

    circuit, (h, out) = RootCircuit.build(build)
    accum_in, accum_out = {}, {}
    for tick in range(8):
        # keep accumulated weights non-negative (insert-biased, targeted
        # deletions of existing rows)
        delta = {}
        for _ in range(rng.randrange(0, 8)):
            r = (rng.randrange(5), rng.randrange(8))
            delta[r] = delta.get(r, 0) + 1
        if accum_in and rng.random() < 0.6:
            victim = rng.choice(list(accum_in))
            delta[victim] = delta.get(victim, 0) - 1
            if delta[victim] == 0:
                del delta[victim]
        h.extend(list(delta.items()))
        circuit.step()
        dict_add(accum_in, delta)
        dict_add(accum_out, out.to_dict())
        assert accum_out == oracle_aggregate(accum_in, agg_name), \
            f"{agg_name} tick {tick}"


def test_aggregate_group_disappears():
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int32])
        return h, s.aggregate(Max(0)).integrate().output()

    circuit, (h, out) = RootCircuit.build(build)
    h.extend([((1, 5), 1), ((1, 9), 1)])
    circuit.step()
    assert out.to_dict() == {(1, 9): 1}
    h.push((1, 9), -1)  # max moves down
    circuit.step()
    assert out.to_dict() == {(1, 5): 1}
    h.push((1, 5), -1)  # group gone
    circuit.step()
    assert out.to_dict() == {}


# ---------------------------------------------------------------------------
# distinct
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_incremental_distinct_matches_oracle(seed):
    rng = random.Random(100 + seed)

    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int32])
        return h, s.distinct().output(), s.stream_distinct().output()

    circuit, (h, inc_out, _) = RootCircuit.build(build)
    accum_in, accum_out = {}, {}
    for tick in range(10):
        delta = rand_delta(rng, rng.randrange(0, 8), key_range=4, val_range=3)
        h.extend(list(delta.items()))
        circuit.step()
        dict_add(accum_in, delta)
        dict_add(accum_out, inc_out.to_dict())
        want = {r: 1 for r, w in accum_in.items() if w > 0}
        assert accum_out == want, f"tick {tick}"


def test_stream_distinct():
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [])
        return h, s.stream_distinct().output()

    circuit, (h, out) = RootCircuit.build(build)
    h.extend([((1,), 5), ((2,), -3), ((3,), 1)])
    circuit.step()
    assert out.to_dict() == {(1,): 1, (3,): 1}


def test_average_truncates_toward_zero():
    # SQL/Rust semantics: AVG of {-3, -4} = -3 (truncation), not -4 (floor)
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int32])
        return h, s.aggregate(Average(0)).output()

    circuit, (h, out) = RootCircuit.build(build)
    h.extend([((1, -3), 1), ((1, -4), 1)])
    circuit.step()
    assert out.to_dict() == {(1, -3): 1}


def test_order_preserving_map_merges_collisions():
    # monotone non-injective map must still produce a consolidated batch
    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int32])
        halved = s.map_rows(lambda k, v: (k, (v[0] // 2,)),
                            [jnp.int64], [jnp.int32],
                            name="halve", preserves_order=True)
        return h, halved.output(), halved.distinct().output()

    circuit, (h, out, dist) = RootCircuit.build(build)
    h.extend([((1, 4), 1), ((1, 5), 1), ((2, 7), 2)])
    circuit.step()
    got = out.peek()
    assert got.to_dict() == {(1, 2): 2, (2, 3): 2}
    # no duplicate live rows (the invariant distinct's probe relies on)
    import numpy as np
    w = np.asarray(got.weights)
    live = int((w != 0).sum())
    rows = list(zip(np.asarray(got.keys[0])[:live].tolist(),
                    np.asarray(got.vals[0])[:live].tolist()))
    assert len(set(rows)) == live
    assert dist.to_dict() == {(1, 2): 1, (2, 3): 1}
