"""Bit-identity of the REDUCTION-OFFENSIVE megakernels vs the stitched
chains they replaced, with ``DBSP_TPU_NATIVE`` per-kernel force-off as the
control.

The tentpole collapsed CAggregate's reduce chain — unique-keys, out-trace
probe + TupleMax, ladder gather, cross-level netting, aggregator segment
reduction, fast-path delta reduction — into ONE ``cursor.agg_ladder`` call
(native C++ megakernel on CPU, a composed Pallas lowering on accelerators,
the stitched chain as fallback/control), rewired every built-in Aggregator
through the shared five-op ``segment_reduce`` dispatch, and made the join
emit each side as ONE consolidated run (``join_sorted``) so the post-join
consolidate rank-folds instead of sorting. All of that is only legal
because every backend produces identical values:

* kernel level: ``segment_reduce`` / ``agg_ladder`` / sorted-emit join
  across native megakernel, Pallas interpret, the stitched-control
  (``join_sorted,agg_ladder,segment_reduce`` forced off — the PR-12 code
  path) and pure XLA — on adversarial inputs (all-retraction groups, empty
  deltas, int32 weights, gather-cap overflow with exact unclamped totals,
  duplicate keys across levels, runtime fast/slow flag both ways);
* engine level: q1–q8 accumulated outputs, host AND compiled, fused vs the
  reduction-off control, plus the fast→slow ``ever_negative`` transition
  bit-identical on BOTH sides of the flip;
* dispatch level: the new fused labels must actually fire (non-vacuous)
  and drop to zero under force-off.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dbsp_tpu.zset import cursor, kernels
from dbsp_tpu.zset.batch import Batch, concat_batches
from dbsp_tpu.operators.aggregate import (Average, Count, Max, Min, Sum,
                                          segment_reduce)
from dbsp_tpu.operators.join import fn_permutation

from test_fused_ladder import (REDUCE_OFF, _consolidated, _run_compiled,
                               _run_host)

pytestmark = pytest.mark.fast

# env settings per backend: (DBSP_TPU_NATIVE, DBSP_TPU_PALLAS).
# "stitched_control" is the committed A/B control (the PR-12 code path:
# fused ladder consumers still native, the reduction layer forced off);
# "pure_xla" strips the native kernels entirely.
BACKENDS = {
    "native_megakernel": ("1", "0"),
    "pallas_interpret": ("0", "interpret"),
    "stitched_control": (REDUCE_OFF, "0"),
    "pure_xla": ("0", "0"),
}


def _with_backend(monkeypatch, backend, fn):
    native, pallas = BACKENDS[backend]
    monkeypatch.setenv("DBSP_TPU_NATIVE", native)
    monkeypatch.setenv("DBSP_TPU_PALLAS", pallas)
    try:
        return fn()
    finally:
        monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
        monkeypatch.setenv("DBSP_TPU_PALLAS", "0")


def _assert_same(got, want, ctx=""):
    for i, (g, w) in enumerate(zip(got, want)):
        if g is None and w is None:
            continue
        g, w = np.asarray(g), np.asarray(w)
        assert g.dtype == w.dtype, f"{ctx}[{i}]: dtype {g.dtype}!={w.dtype}"
        np.testing.assert_array_equal(g, w, err_msg=f"{ctx}[{i}]")


# ---------------------------------------------------------------------------
# segment_reduce: the shared five-op vocabulary
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("weight_dtype", [np.int64, np.int32])
def test_segment_reduce_backends_bitidentical(monkeypatch, weight_dtype):
    rng = np.random.default_rng(0)
    spec = (("count", 0), ("sum", 0), ("min", 0), ("max", 1), ("avg", 1),
            ("present", 0))
    for n, S in ((1, 1), (64, 7), (300, 41)):
        v1 = jnp.asarray(rng.integers(-1000, 1000, n))
        v2 = jnp.asarray(rng.integers(-9, 9, n).astype(np.int32))
        w = jnp.asarray(rng.integers(-3, 4, n).astype(weight_dtype))
        # seg ids PAST num_segments must be dropped on every backend
        seg = jnp.asarray(rng.integers(0, S + 3, n).astype(np.int32))
        ref = None
        for backend in BACKENDS:
            got = _with_backend(
                monkeypatch, backend,
                lambda: segment_reduce(spec, (v1, v2), w, seg, S))
            if ref is None:
                ref = got
            else:
                _assert_same(got, ref, f"segment_reduce {backend} n={n}")


def test_segment_reduce_all_retractions(monkeypatch):
    """Groups whose every row is a retraction: the additive ops see zero
    positive mass, min/max stay at their identity, present stays 0."""
    v = jnp.asarray([5, 9, -2, 7])
    w = jnp.asarray([-1, -2, -1, 3])
    seg = jnp.asarray([0, 0, 1, 2], jnp.int32)
    spec = (("count", 0), ("sum", 0), ("max", 0), ("present", 0))
    ref = None
    for backend in BACKENDS:
        got = _with_backend(
            monkeypatch, backend,
            lambda: segment_reduce(spec, (v,), w, seg, 3))
        if ref is None:
            ref = got
        else:
            _assert_same(got, ref, f"all-retraction {backend}")
    cnt, s, mx, pres = (np.asarray(x) for x in ref)
    assert cnt[0] == 0 and s[0] == 0 and pres[0] == 0
    assert mx[0] == np.iinfo(np.int64).min  # identity never escapes raw
    assert cnt[2] == 3 and pres[2] == 1


# ---------------------------------------------------------------------------
# agg_ladder: the whole CAggregate chain
# ---------------------------------------------------------------------------

AGGS = [(Max(0), True), (Min(0), True), (Count(), False), (Sum(0), False),
        (Average(0), False)]


def _agg_case(rng, weight_dtype=np.int64, empty_delta=False,
              all_retract=False):
    delta = _consolidated(rng, 0 if empty_delta else 22, 32,
                          weight_dtype=weight_dtype)
    if all_retract and not empty_delta:
        delta = Batch(delta.keys, delta.vals,
                      -jnp.abs(delta.weights), delta.runs)
    levels = [_consolidated(rng, 40, 64, weight_dtype=weight_dtype),
              Batch.empty((jnp.int64, jnp.int64), (jnp.int64,), cap=16,
                          weight_dtype=jnp.dtype(weight_dtype)),
              _consolidated(rng, 10, 16, weight_dtype=weight_dtype)]
    out_trace = _consolidated(rng, 12, 16, weight_dtype=weight_dtype)
    return delta, levels, out_trace


@pytest.mark.parametrize("weight_dtype", [np.int64, np.int32])
def test_agg_ladder_backends_bitidentical(monkeypatch, weight_dtype):
    rng = np.random.default_rng(1)
    for case in ({}, {"empty_delta": True}, {"all_retract": True}):
        delta, levels, out_trace = _agg_case(rng, weight_dtype, **case)
        for agg, fast in AGGS:
            for flag in ((True, False) if fast else (True,)):
                ref = None
                for backend in BACKENDS:
                    got = _with_backend(
                        monkeypatch, backend,
                        lambda: cursor.agg_ladder(
                            delta, 2, out_trace, levels, agg, 16, 512,
                            fast, jnp.asarray(flag)))
                    leaves = jax.tree_util.tree_leaves(got)
                    if ref is None:
                        ref = leaves
                    else:
                        _assert_same(
                            leaves, ref,
                            f"agg_ladder {backend} {agg.name} {case} "
                            f"flag={flag}")


def test_agg_ladder_gather_overflow_exact(monkeypatch):
    """gather-cap overflow: every backend must report the SAME unclamped
    total (the requirement the runner's grow/replay keys off) AND the same
    clamped buffers — the megakernel counts raw rows in the stitched
    level-major order, so even the discarded overflow launch matches."""
    rng = np.random.default_rng(2)
    delta = _consolidated(rng, 30, 32, key_range=5)
    levels = [_consolidated(rng, 60, 128, key_range=5),
              _consolidated(rng, 40, 64, key_range=5)]
    out_trace = _consolidated(rng, 8, 16, key_range=5)
    ref = None
    totals = {}
    for backend in BACKENDS:
        got = _with_backend(
            monkeypatch, backend,
            lambda: cursor.agg_ladder(delta, 2, out_trace, levels, Sum(0),
                                      16, 8, False, jnp.asarray(True)))
        totals[backend] = int(got[-1])
        leaves = jax.tree_util.tree_leaves(got)
        if ref is None:
            ref = leaves
        else:
            _assert_same(leaves, ref, f"agg overflow {backend}")
    assert len(set(totals.values())) == 1, totals
    assert totals["pure_xla"] > 8, "shape must actually overflow the cap"


def test_agg_ladder_counts_dispatch(monkeypatch):
    """Force-off non-vacuity at the cursor level: agg_ladder:native fires
    on the hot path and drops to zero (stitched fallback engaged) under
    DBSP_TPU_NATIVE force-off."""
    rng = np.random.default_rng(3)
    delta, levels, out_trace = _agg_case(rng)
    monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
    before = dict(kernels.KERNEL_DISPATCH_COUNTS)
    monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
    cursor.agg_ladder(delta, 2, out_trace, levels, Max(0), 16, 256, True,
                      jnp.asarray(True))
    monkeypatch.setenv("DBSP_TPU_NATIVE", REDUCE_OFF)
    cursor.agg_ladder(delta, 2, out_trace, levels, Max(0), 16, 256, True,
                      jnp.asarray(True))

    def delta_of(kern, backend):
        return kernels.KERNEL_DISPATCH_COUNTS.get((kern, backend), 0) - \
            before.get((kern, backend), 0)

    assert delta_of("agg_ladder", "native") == 1
    assert delta_of("agg_ladder", "xla") == 1


# ---------------------------------------------------------------------------
# sorted-emit join: the post-join sort dies
# ---------------------------------------------------------------------------


def test_fn_permutation_probe():
    """A pure column selection yields its permutation; anything computing
    (arithmetic, astype, constants) is conservatively rejected."""
    fn = lambda k, lv, rv: ((k[0], rv[0]), (lv[0], lv[1], rv[1]))  # noqa
    assert fn_permutation(fn, 2, 2, 2) == (2, (0, 4, 2, 3, 5))
    ident = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    assert fn_permutation(ident, 1, 1, 1) == (1, (0, 1, 2))
    arith = lambda k, lv, rv: (k, (-lv[0],))  # noqa: E731
    assert fn_permutation(arith, 1, 1, 1) is None
    cast = lambda k, lv, rv: (k, (rv[0].astype(jnp.int32),))  # noqa: E731
    assert fn_permutation(cast, 1, 1, 1) is None
    oob = lambda k, lv, rv: (k, (lv[5],))  # noqa: E731
    assert fn_permutation(oob, 1, 1, 1) is None


@pytest.mark.parametrize("weight_dtype", [np.int64, np.int32])
def test_join_sorted_emits_consolidated_run(monkeypatch, weight_dtype):
    """The sorted-emit buffer IS one canonical run (re-consolidating is a
    no-op) and its Z-set equals the unsorted control's consolidation."""
    fn = lambda k, lv, rv: ((k[0], rv[0]), (lv[0], k[1], rv[1]))  # noqa
    n_out_keys, perm = fn_permutation(fn, 2, 1, 2)
    se = (n_out_keys, perm, tuple(jnp.dtype(jnp.int64) for _ in range(5)))
    rng = np.random.default_rng(4)
    for ladder_seed in range(3):
        delta = _consolidated(rng, 20, 32, weight_dtype=weight_dtype)
        levels = [_consolidated(rng, 40, 64, nv=2,
                                weight_dtype=weight_dtype),
                  _consolidated(rng, 10, 16, nv=2,
                                weight_dtype=weight_dtype)]
        monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
        sb, st = cursor.join_ladder(delta, levels, 2, fn, 512,
                                    sorted_emit=se)
        assert sb.runs == (512,), "sorted emit must tag ONE run"
        monkeypatch.setenv("DBSP_TPU_NATIVE", REDUCE_OFF)
        cb, ct = cursor.join_ladder(delta, levels, 2, fn, 512)
        monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
        assert int(st) == int(ct)
        assert sb.to_dict() == cb.consolidate().to_dict()
        resorted = sb.tagged(None).consolidate()
        _assert_same((*resorted.cols, resorted.weights),
                     (*sb.cols, sb.weights), "sorted emit not canonical")


def test_join_sorted_post_consolidate_rank_folds(monkeypatch):
    """The acceptance shape: concat of two sorted-emit sides consolidates
    through the RANK regime (2 runs, one linear merge — no sort), and the
    result is bit-identical to the full-sort control."""
    fn = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    rng = np.random.default_rng(5)
    delta = _consolidated(rng, 20, 32)
    levels = [_consolidated(rng, 40, 64)]
    se = (2, (0, 1, 2, 3), tuple(jnp.dtype(jnp.int64) for _ in range(4)))
    monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
    lout, _ = cursor.join_ladder(delta, levels, 2, fn, 256, sorted_emit=se)
    rout, _ = cursor.join_ladder(delta, levels, 2, fn, 128, sorted_emit=se)
    cat = concat_batches([lout, rout])
    assert cat.runs == (256, 128)
    before = dict(kernels.CONSOLIDATE_COUNTS)
    got = cat.consolidate()
    assert kernels.CONSOLIDATE_COUNTS["rank"] == before["rank"] + 1
    monkeypatch.setenv("DBSP_TPU_NATIVE", REDUCE_OFF)
    lc, _ = cursor.join_ladder(delta, levels, 2, fn, 256)
    rc, _ = cursor.join_ladder(delta, levels, 2, fn, 128)
    want = concat_batches([lc, rc]).consolidate()
    monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
    _assert_same((*got.cols, got.weights), (*want.cols, want.weights),
                 "rank-folded != sorted control")


def test_join_sorted_overflow_totals_exact(monkeypatch):
    fn = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    se = (2, (0, 1, 2, 3), tuple(jnp.dtype(jnp.int64) for _ in range(4)))
    rng = np.random.default_rng(6)
    delta = _consolidated(rng, 40, 64, key_range=5)
    levels = [_consolidated(rng, 60, 128, key_range=5) for _ in range(2)]
    monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
    _, st = cursor.join_ladder(delta, levels, 2, fn, 16, sorted_emit=se)
    monkeypatch.setenv("DBSP_TPU_NATIVE", REDUCE_OFF)
    _, ct = cursor.join_ladder(delta, levels, 2, fn, 16)
    monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
    assert int(st) == int(ct) and int(st) > 16


# ---------------------------------------------------------------------------
# engine level: fused vs the reduction-off control
# ---------------------------------------------------------------------------

CONTROL_ENV = {"DBSP_TPU_NATIVE": REDUCE_OFF}

QUERIES_FAST = ("q4", "q8")
QUERIES_ALL = ("q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8")


@pytest.mark.parametrize("qname", QUERIES_ALL)
def test_host_engine_fused_vs_reduction_off(monkeypatch, qname):
    want = _run_host(qname)
    for k, v in CONTROL_ENV.items():
        monkeypatch.setenv(k, v)
    assert _run_host(qname) == want


@pytest.mark.parametrize("qname", QUERIES_FAST)
def test_compiled_engine_fused_vs_reduction_off(monkeypatch, qname):
    want = _run_compiled(qname)
    assert want, f"{qname} produced no output — vacuous comparison"
    for k, v in CONTROL_ENV.items():
        monkeypatch.setenv(k, v)
    assert _run_compiled(qname) == want


@pytest.mark.slow
@pytest.mark.parametrize("qname", QUERIES_ALL)
def test_compiled_engine_fused_vs_reduction_off_full(monkeypatch, qname):
    want = _run_compiled(qname)
    for k, v in CONTROL_ENV.items():
        monkeypatch.setenv(k, v)
    assert _run_compiled(qname) == want


def _flip_feeds():
    """A feed schedule that crosses the ever_negative flip mid-run: pure
    inserts, then the FIRST retraction (tick 2 — the fast path's runtime
    ladder gate flips on, no retrace), then inserts again, then a
    retraction of the current maximum (only the slow re-gather can answer
    it), then a tick that fully retracts one group (present must drop)."""
    K, V = (jnp.int64,), (jnp.int64,)
    ticks = [
        [((7, 1), 1), ((7, 5), 1), ((9, 3), 1)],
        [((7, 7), 1), ((9, 6), 1)],
        [((7, 5), -1), ((11, 2), 1)],          # flip: first retraction
        [((7, 4), 1), ((9, 9), 1)],
        [((7, 7), -1)],                        # retract the current max
        [((11, 2), -1)],                       # all-retraction group
        [],                                    # empty delta after the flip
        [((7, 2), 1)],
    ]
    return K, V, ticks


def _run_flip_compiled():
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.operators import add_input_zset

    jax.clear_caches()  # trace-time dispatch — see test_fused_ladder
    K, V, ticks = _flip_feeds()

    def build(c):
        s, h = add_input_zset(c, K, V)
        return h, s.aggregate(Max(0)).output()

    handle, (h, out) = Runtime.init_circuit(1, build)
    ch = compile_circuit(handle)
    outs = []
    for t, rows in enumerate(ticks):
        feeds = {h: Batch.from_tuples(rows, K, V)} if rows else {}
        ch.step(tick=t, feeds=feeds)
        ch.validate()
        b = ch.output(out)
        outs.append(b.to_dict() if b is not None else {})
    return outs


def _run_flip_host():
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.operators import add_input_zset

    jax.clear_caches()
    K, V, ticks = _flip_feeds()

    def build(c):
        s, h = add_input_zset(c, K, V)
        return h, s.aggregate(Max(0)).output()

    handle, (h, out) = Runtime.init_circuit(1, build)
    outs = []
    for rows in ticks:
        if rows:
            h.push_batch(Batch.from_tuples(rows, K, V))
        handle.step()
        b = out.take()
        outs.append(b.to_dict() if b is not None else {})
    return outs


def test_fast_to_slow_flip_bitidentical(monkeypatch):
    """The insert-combinable fast path's ever_negative transition: per-tick
    output deltas are bit-identical to the reduction-off control AND to
    the host engine on BOTH sides of the flip — including the
    retract-the-maximum tick (slow re-gather), the all-retraction group
    (present drops), and an empty delta after the flip."""
    fused = _run_flip_compiled()
    host = _run_flip_host()
    for k, v in CONTROL_ENV.items():
        monkeypatch.setenv(k, v)
    control = _run_flip_compiled()
    host_control = _run_flip_host()
    assert fused == control, "compiled flip run diverged from control"
    assert host == host_control, "host flip run diverged from control"
    assert fused == host, "compiled flip run diverged from host engine"
    # ground truth spot checks: the retracted max falls back to 4, the
    # fully retracted group 11 disappears
    acc = {}
    for d in fused:
        for r, w in d.items():
            acc[r] = acc.get(r, 0) + w
            if not acc[r]:
                del acc[r]
    assert acc == {(7, 4): 1, (9, 9): 1}
