"""Interpret-mode bit-identity of the Pallas kernel prototypes.

The Pallas programs (zset/pallas_kernels.py) are selected on accelerator
backends, where the tier-1 suite cannot run them compiled — so the suite
pins them through the Pallas INTERPRETER on CPU instead: same kernel
bodies, same traced control flow, executed without Mosaic. Every test
compares against the pure-XLA reference on the adversarial ladder shapes
from tests/test_cursor.py (duplicate keys across levels, empty levels,
full-capacity batches, cancelling weights, sentinel tails).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dbsp_tpu.zset import cursor, kernels, pallas_kernels
from dbsp_tpu.zset.batch import Batch

pytestmark = pytest.mark.fast


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Force the Pallas dispatch path (interpreter) regardless of backend."""
    monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")


def _consolidated(rng, n_live, cap, nk=2, nv=1, key_range=40,
                  allow_neg=True):
    lo = -3 if allow_neg else 1
    rows = []
    for _ in range(n_live):
        key = tuple(int(rng.integers(0, key_range)) for _ in range(nk + nv))
        w = int(rng.integers(lo, 4)) or 1
        rows.append((key, w))
    cols = [np.array([r[0][i] for r in rows], dtype=np.int64)
            for i in range(nk + nv)]
    ws = np.array([r[1] for r in rows], dtype=np.int64)
    return Batch.from_columns(cols[:nk], cols[nk:], ws, cap=cap)


def _adversarial_ladders(rng):
    """Ladder shapes that broke per-level loops before: duplicate keys
    across levels, an EMPTY level, a FULL-capacity level (no dead tail),
    heterogeneous caps."""
    # a FULL-capacity level: every slot live, no dead sentinel tail
    full = Batch.from_columns(
        [np.arange(64, dtype=np.int64), np.arange(64, dtype=np.int64) % 7],
        [np.zeros(64, np.int64)], np.ones(64, np.int64), cap=64)
    assert int(full.live_count()) == 64
    yield [_consolidated(rng, max(2, c // 3), c) for c in (256, 64, 32, 16)]
    yield [_consolidated(rng, 20, 64), Batch.empty((jnp.int64, jnp.int64),
                                                   (jnp.int64,), cap=32),
           _consolidated(rng, 10, 16)]
    yield [full, _consolidated(rng, 30, 64, key_range=8)]


# ---------------------------------------------------------------------------
# ladder-wide lex probe
# ---------------------------------------------------------------------------


def test_probe_ladder_interpret_bitidentical(pallas_interpret, monkeypatch):
    rng = np.random.default_rng(0)
    for ladder in _adversarial_ladders(rng):
        tables = [lvl.keys for lvl in ladder]
        delta = _consolidated(rng, 20, 32)
        for side in ("left", "right"):
            got = np.asarray(pallas_kernels.lex_probe_ladder_pallas(
                tables, delta.keys, side))
            monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
            monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
            want = np.asarray(cursor.lex_probe_ladder(tables, delta.keys,
                                                      side))
            monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
            monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
            np.testing.assert_array_equal(got, want, err_msg=side)


def test_probe_ladder_dispatches_pallas(pallas_interpret):
    """The cursor entry point routes to the Pallas kernel (and counts the
    dispatch) when the override is active."""
    rng = np.random.default_rng(1)
    levels = [_consolidated(rng, 10, 32), _consolidated(rng, 5, 16)]
    delta = _consolidated(rng, 8, 16)
    before = dict(kernels.KERNEL_DISPATCH_COUNTS)
    out = cursor.lex_probe_ladder([lvl.keys for lvl in levels], delta.keys)
    assert out.shape == (2, 16)
    assert kernels.KERNEL_DISPATCH_COUNTS.get(("probe_ladder", "pallas"), 0) \
        > before.get(("probe_ladder", "pallas"), 0)


def test_use_pallas_gates_float_columns(pallas_interpret):
    f = jnp.zeros((8,), jnp.float32)
    i = jnp.zeros((8,), jnp.int64)
    assert pallas_kernels.use_pallas("probe_ladder", (i, i))
    assert not pallas_kernels.use_pallas("probe_ladder", (i, f))


def test_pallas_disabled_by_default_on_cpu(monkeypatch):
    monkeypatch.delenv("DBSP_TPU_PALLAS", raising=False)
    assert not pallas_kernels.enabled()  # tier-1 runs JAX_PLATFORMS=cpu
    monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
    assert not pallas_kernels.enabled()
    monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
    assert pallas_kernels.enabled() and pallas_kernels.interpret_mode()


# ---------------------------------------------------------------------------
# fused ladder-consumer megakernels (join_ladder / gather_ladder)
# ---------------------------------------------------------------------------


def test_join_ladder_megakernel_interpret_bitidentical(pallas_interpret,
                                                       monkeypatch):
    """The grid-over-levels join megakernel vs the pure-XLA stitched chain
    on the adversarial ladders — whole-Batch output + unclamped total."""
    fn = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    rng = np.random.default_rng(30)
    for ladder in _adversarial_ladders(rng):
        delta = _consolidated(rng, 20, 32)
        got, gt = cursor.join_ladder(delta, ladder, 2, fn, 1024)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
        monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
        want, wt = cursor.join_ladder(delta, ladder, 2, fn, 1024)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
        monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
        assert int(gt) == int(wt)
        for g, w in zip((*got.cols, got.weights), (*want.cols,
                                                   want.weights)):
            assert g.dtype == w.dtype
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_gather_ladder_megakernel_interpret_bitidentical(pallas_interpret,
                                                         monkeypatch):
    rng = np.random.default_rng(31)
    for ladder in _adversarial_ladders(rng):
        delta = _consolidated(rng, 24, 32)
        qlive = jnp.asarray(np.asarray(delta.weights) != 0)
        got = cursor.gather_ladder(delta.keys, qlive, ladder, 1024)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
        monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
        want = cursor.gather_ladder(delta.keys, qlive, ladder, 1024)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
        monkeypatch.setenv("DBSP_TPU_NATIVE", "1")
        (gq, gv, gw), gt = got
        (wq, wv, ww), wt = want
        assert int(gt) == int(wt)
        for g, w in zip((gq, *gv, gw), (wq, *wv, ww)):
            assert g.dtype == w.dtype
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_ladder_megakernels_dispatch_pallas(pallas_interpret):
    """The cursor entry points route to the megakernels (and count the
    dispatch) when the override is active."""
    fn = lambda k, lv, rv: (k, (*lv, *rv))  # noqa: E731
    rng = np.random.default_rng(32)
    levels = [_consolidated(rng, 10, 32), _consolidated(rng, 5, 16)]
    delta = _consolidated(rng, 8, 16)
    before = dict(kernels.KERNEL_DISPATCH_COUNTS)
    cursor.join_ladder(delta, levels, 2, fn, 256)
    cursor.gather_ladder(delta.keys, delta.weights != 0, levels, 256)
    for kern in ("join_ladder", "gather_ladder"):
        assert kernels.KERNEL_DISPATCH_COUNTS.get((kern, "pallas"), 0) > \
            before.get((kern, "pallas"), 0), kern


# ---------------------------------------------------------------------------
# rank-merge inner loop
# ---------------------------------------------------------------------------


def _xla_rank_scatter(cols_a, w_a, cols_b, w_b):
    """The XLA formulation of the rank-merge inner loop (the reference the
    Pallas program must reproduce bit-for-bit)."""
    na, nb = w_a.shape[0], w_b.shape[0]
    ra = kernels.lex_probe(cols_b, cols_a, side="left")
    rb = kernels.lex_probe(cols_a, cols_b, side="right")
    pos_a = jnp.arange(na, dtype=jnp.int32) + ra
    pos_b = jnp.arange(nb, dtype=jnp.int32) + rb
    out = []
    for ca, cb in zip(cols_a, cols_b):
        buf = kernels.sentinel_fill((na + nb,), ca.dtype)
        out.append(buf.at[pos_a].set(ca).at[pos_b].set(cb.astype(ca.dtype)))
    w = jnp.zeros((na + nb,), w_a.dtype).at[pos_a].set(w_a) \
        .at[pos_b].set(w_b)
    return tuple(out), w


@pytest.mark.parametrize("seed", range(4))
def test_rank_merge_scatter_interpret_bitidentical(pallas_interpret,
                                                   monkeypatch, seed):
    rng = np.random.default_rng(10 + seed)
    a = _consolidated(rng, int(rng.integers(0, 50)), 64, key_range=12)
    b = _consolidated(rng, int(rng.integers(0, 100)), 128, key_range=12)
    got_cols, got_w = pallas_kernels.rank_merge_scatter(
        a.cols, a.weights, b.cols, b.weights)
    monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
    want_cols, want_w = _xla_rank_scatter(a.cols, a.weights, b.cols,
                                          b.weights)
    for g, w in zip((*got_cols, got_w), (*want_cols, want_w)):
        assert g.dtype == w.dtype
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_merge_sorted_cols_rank_path_via_pallas(pallas_interpret,
                                                monkeypatch):
    """Force the accelerator strategy on CPU: merge_sorted_cols' rank
    branch must select the Pallas program and still produce the canonical
    merge (== the sort path)."""
    rng = np.random.default_rng(20)
    a = _consolidated(rng, 40, 64, key_range=10)
    b = _consolidated(rng, 70, 128, key_range=10)
    monkeypatch.setattr(kernels, "merge_strategy", lambda: "rank")
    before = dict(kernels.KERNEL_DISPATCH_COUNTS)
    got = kernels.merge_sorted_cols(a.cols, a.weights, b.cols, b.weights)
    assert kernels.KERNEL_DISPATCH_COUNTS.get(("merge", "pallas"), 0) > \
        before.get(("merge", "pallas"), 0)
    monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
    xla_rank = kernels.merge_sorted_cols(a.cols, a.weights, b.cols,
                                         b.weights)
    monkeypatch.undo()
    cols = tuple(jnp.concatenate([x, y.astype(x.dtype)])
                 for x, y in zip(a.cols, b.cols))
    sort_ref = kernels.consolidate_cols(
        cols, jnp.concatenate([a.weights, b.weights]))
    for g, w, s in zip((*got[0], got[1]), (*xla_rank[0], xla_rank[1]),
                       (*sort_ref[0], sort_ref[1])):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
        np.testing.assert_array_equal(np.asarray(g), np.asarray(s))


def test_rank_merge_full_capacity_no_dead_tail(pallas_interpret,
                                               monkeypatch):
    """Full-cap inputs (every slot live) — the overflow-adjacent shape:
    no sentinel tail to hide scatter mistakes behind."""
    a = Batch.from_columns([jnp.arange(0, 16, dtype=jnp.int64)], [],
                           jnp.ones((16,), jnp.int64), cap=16,
                           consolidated=True)
    b = Batch.from_columns([jnp.arange(8, 24, dtype=jnp.int64)], [],
                           -jnp.ones((16,), jnp.int64), cap=16,
                           consolidated=True)
    got_cols, got_w = pallas_kernels.rank_merge_scatter(
        a.cols, a.weights, b.cols, b.weights)
    monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
    want_cols, want_w = _xla_rank_scatter(a.cols, a.weights, b.cols,
                                          b.weights)
    for g, w in zip((*got_cols, got_w), (*want_cols, want_w)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


# ---------------------------------------------------------------------------
# segment reduce + composed aggregate megakernel (the reduction offensive)
# ---------------------------------------------------------------------------


def test_segment_reduce_interpret_bitidentical(pallas_interpret,
                                               monkeypatch):
    """The five-op segment reduction as one Pallas program per segment
    block — identical to the jax.ops.segment_* formulation, including
    identity fills for empty segments, retraction-only segments, and
    dropped out-of-range seg ids."""
    from dbsp_tpu.operators.aggregate import segment_reduce

    rng = np.random.default_rng(20)
    spec = (("count", 0), ("sum", 0), ("min", 0), ("max", 1), ("avg", 1),
            ("present", 0))
    for n, S in ((1, 1), (64, 7), (500, 130)):  # crosses the 128 block
        v1 = jnp.asarray(rng.integers(-1000, 1000, n))
        v2 = jnp.asarray(rng.integers(-9, 9, n).astype(np.int32))
        w = jnp.asarray(rng.integers(-3, 4, n))
        seg = jnp.asarray(rng.integers(0, S + 5, n).astype(np.int32))
        monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
        got = segment_reduce(spec, (v1, v2), w, seg, S)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
        want = segment_reduce(spec, (v1, v2), w, seg, S)
        monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
        for i, (g, ww) in enumerate(zip(got, want)):
            assert g.dtype == ww.dtype, (i, g.dtype, ww.dtype)
            np.testing.assert_array_equal(np.asarray(g), np.asarray(ww),
                                          err_msg=f"op {i} n={n}")


def test_agg_ladder_composed_interpret_bitidentical(pallas_interpret,
                                                    monkeypatch):
    """The composed accelerator lowering of cursor.agg_ladder (Pallas
    gather megakernel + Pallas segment reduce) equals the pure-XLA
    stitched chain on adversarial ladders, both fast-path flag values."""
    from dbsp_tpu.operators.aggregate import Average, Count, Max

    import jax

    rng = np.random.default_rng(21)
    for ladder in _adversarial_ladders(rng):
        delta = _consolidated(rng, 20, 32)
        out_trace = _consolidated(rng, 10, 16)
        for agg, fast in ((Max(0), True), (Count(), False),
                          (Average(0), False)):
            for flag in ((True, False) if fast else (True,)):
                monkeypatch.setenv("DBSP_TPU_NATIVE", "0")
                got = cursor.agg_ladder(delta, 2, out_trace, ladder, agg,
                                        16, 512, fast, jnp.asarray(flag))
                monkeypatch.setenv("DBSP_TPU_PALLAS", "0")
                want = cursor.agg_ladder(delta, 2, out_trace, ladder, agg,
                                         16, 512, fast, jnp.asarray(flag))
                monkeypatch.setenv("DBSP_TPU_PALLAS", "interpret")
                for i, (g, w) in enumerate(zip(
                        jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want))):
                    g, w = np.asarray(g), np.asarray(w)
                    assert g.dtype == w.dtype, (agg.name, i)
                    np.testing.assert_array_equal(
                        g, w, err_msg=f"{agg.name} flag={flag} leaf {i}")


def test_new_kernels_dispatch_pallas(pallas_interpret):
    """Non-vacuity: the interpret runs above actually ride the Pallas
    dispatch counters (segment_reduce + agg_ladder labels)."""
    from dbsp_tpu.operators.aggregate import Max, segment_reduce

    rng = np.random.default_rng(22)
    before = dict(kernels.KERNEL_DISPATCH_COUNTS)
    segment_reduce((("max", 0),), (jnp.asarray([1, 2]),),
                   jnp.asarray([1, 1]), jnp.asarray([0, 1], jnp.int32), 2)
    delta = _consolidated(rng, 8, 16)
    cursor.agg_ladder(delta, 2, _consolidated(rng, 4, 8),
                      [_consolidated(rng, 6, 8)], Max(0), 8, 64, True,
                      jnp.asarray(True))

    def delta_of(kern):
        return kernels.KERNEL_DISPATCH_COUNTS.get((kern, "pallas"), 0) - \
            before.get((kern, "pallas"), 0)

    assert delta_of("segment_reduce") >= 1
    assert delta_of("agg_ladder") == 1
