"""Incremental equi-join — the bilinear delta form, as TPU merge kernels.

Reference: ``operator/join.rs`` — ``stream_join`` (:52), incremental ``join``
(:180) / ``join_index`` (:200) / ``join_generic`` (:217), with the math in the
derivation comment (join.rs:225-265):

    Δ(A ⋈ B)_t = ΔA_t ⋈ T(B)_t  +  ΔB_t ⋈ T(A)_{t-1}

where T(X)_t is the integral of X up to and including t. Each term runs as a
sorted probe-and-expand kernel against the spine levels of the traced side:
binary-search probes (delta-proportional), prefix-sum range expansion with a
host-managed grow-on-demand output capacity (SURVEY.md §7 "join output
explosion" — count/scan/scatter as static-shape gathers), weight products,
then one consolidation over all levels' outputs.

The reference re-shards both inputs by key hash before joining
(join.rs:268-270); here sharding is a property of the stream (parallel/
exchange.py) and the single-worker path needs none.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.circuit.operator import BinaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches

# fn(key_cols, left_val_cols, right_val_cols) -> (out_key_cols, out_val_cols)
JoinFn = Callable[[Tuple, Tuple, Tuple], Tuple[Tuple, Tuple]]


class _ColRef:
    """Column-identity marker for probing a join pair-fn: supports nothing
    but being selected, so any fn that computes (arithmetic, astype, ...)
    raises and falls off the permutation fast path."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def fn_permutation(fn: JoinFn, nk: int, ndv: int, nlv: int):
    """``(n_out_keys, perm)`` when ``fn`` is a pure column SELECTION —
    every output column is exactly one input column, so the whole pair
    function is a permutation/projection of the raw
    ``(probed keys, delta vals, level vals)`` column space (raw index
    ``0..nk-1`` = key, ``nk..nk+ndv-1`` = delta val, ``nk+ndv..`` = level
    val). ``None`` otherwise. Probed by CALLING the fn once with
    :class:`_ColRef` markers: plain tuple indexing/splatting works (every
    Nexmark join qualifies), anything value-dependent raises and is
    conservatively rejected. The permutation is what lets the native
    sorted-emit join megakernel apply the fn in-call
    (``cursor.join_ladder(..., sorted_emit=...)``) and emit each side as
    one consolidated run — killing the post-join full sort."""
    ks = tuple(_ColRef(i) for i in range(nk))
    lv = tuple(_ColRef(nk + i) for i in range(ndv))
    rv = tuple(_ColRef(nk + ndv + i) for i in range(nlv))
    try:
        ok, ov = fn(ks, lv, rv)
        out = (*tuple(ok), *tuple(ov))
    except Exception:  # noqa: BLE001 — any computing fn lands here
        return None
    if not out or not all(type(c) is _ColRef for c in out):
        return None
    return len(tuple(ok)), tuple(c.i for c in out)


_PERM_UNSET = object()


def _join_level_impl(delta: Batch, level: Batch, nk: int, fn: JoinFn,
                     out_cap: int) -> Tuple[Batch, jnp.ndarray]:
    """Join a delta batch against one spine level; static out_cap.

    The output is RAW (unconsolidated: arbitrary row order, possible
    duplicates, weight-0 padding) — callers concat all level outputs and
    consolidate once, instead of sorting per level and re-sorting the
    concat.
    """
    dk = delta.keys[:nk]
    lk = level.keys[:nk]
    lo = kernels.lex_probe(lk, dk, side="left")
    hi = kernels.lex_probe(lk, dk, side="right")
    # dead delta rows carry sentinel keys, which match the level's dead tail —
    # zero their ranges instead of emitting weight-0 garbage
    live = delta.weights != 0
    lo = jnp.where(live, lo, 0)
    hi = jnp.where(live, hi, lo)
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    w = jnp.where(valid, delta.weights[row] * level.weights[src], 0)
    key_cols = tuple(c[row] for c in delta.keys[:nk])
    lvals = tuple(c[row] for c in delta.vals)
    rvals = tuple(c[src] for c in level.vals)
    out_keys, out_vals = fn(key_cols, lvals, rvals)
    # dead slots must carry sentinels so they sort to the tail later
    out_keys = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_keys)
    out_vals = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_vals)
    out = Batch(out_keys, out_vals, w)
    return out, total


_join_level = jax.jit(_join_level_impl, static_argnames=("nk", "fn", "out_cap"))


def _join_ladder_factory(nk: int, fn: JoinFn, out_cap: int,
                         sorted_emit=None):
    from dbsp_tpu.zset import cursor

    return lambda d, levels: cursor.join_ladder(d, levels, nk, fn, out_cap,
                                                sorted_emit)


@partial(jax.jit, static_argnames=("nk", "fn", "out_cap", "sorted_emit"))
def _join_ladder(delta: Batch, levels, nk: int, fn: JoinFn, out_cap: int,
                 sorted_emit=None):
    from dbsp_tpu.zset import cursor

    return cursor.join_ladder(delta, levels, nk, fn, out_cap, sorted_emit)


class JoinCore:
    """Grow-on-demand driver for joining deltas against spine levels.

    One FUSED launch for the whole level ladder (zset/cursor.py): a single
    probe pair over every level, one cross-level expansion into one shared
    buffer with ONE monotone output capacity — where the per-level loop
    paid K probe kernels, K output buffers with K grow-on-demand caps, and
    a K-buffer concat for the downstream consolidate. Still exactly one
    host sync per eval (the batched overflow check).
    """

    def __init__(self, nk: int, fn: JoinFn, out_schema):
        self.nk = nk
        self.fn = fn
        self.out_schema = out_schema
        self.out_cap = 0  # fused ladder output capacity (monotone)
        self._perm = _PERM_UNSET  # fn_permutation, probed on first eval

    def sorted_emit(self, delta: Batch, levels):
        """``(n_out_keys, perm, out_dtypes)`` when the sorted-emit join
        megakernel may replace the pair fn for these operands: the fn is a
        pure column permutation AND every projected source column's dtype
        equals the declared out_schema dtype (a permutation cannot cast,
        so a declared widening keeps the stitched path). ``None``
        otherwise."""
        if not levels:
            return None
        if self._perm is _PERM_UNSET:
            self._perm = fn_permutation(self.fn, self.nk, len(delta.vals),
                                        len(levels[0].vals))
        if self._perm is None:
            return None
        n_out_keys, perm = self._perm
        out_dts = tuple(jnp.dtype(d)
                        for d in (*self.out_schema[0], *self.out_schema[1]))
        raw = (*delta.keys[:self.nk], *delta.vals, *levels[0].vals)
        if len(perm) != len(out_dts) or any(p >= len(raw) for p in perm):
            return None
        if tuple(raw[p].dtype for p in perm) != out_dts:
            return None
        return n_out_keys, perm, out_dts

    def _launch(self, delta: Batch, levels, cap: int, sorted_emit=None):
        if delta.sharded:
            return lifted(_join_ladder_factory, self.nk, self.fn, cap,
                          sorted_emit)(delta, levels)
        return _join_ladder(delta, levels, self.nk, self.fn, cap,
                            sorted_emit)

    def join_levels(self, delta: Batch, levels: Sequence[Batch]
                    ) -> List[Batch]:
        """Launch the fused ladder join; returns the RAW combined output
        (a 1-element list — the concat-and-consolidate call sites are
        shared with the empty/ladder cases). With a permutation pair fn on
        the native CPU path the element comes back as ONE consolidated run
        (see :meth:`sorted_emit`), so the caller's consolidate is a skip or
        a 2-run rank fold — never a sort."""
        if not levels:
            return []
        levels = tuple(levels)
        se = self.sorted_emit(delta, levels)
        if not self.out_cap:
            self.out_cap = bucket_cap(max(64, delta.cap))
        out, total = self._launch(delta, levels, self.out_cap, se)
        t = int(np.max(jax.device_get(total)))  # ONE sync; worst worker
        if t > self.out_cap:
            self.out_cap = bucket_cap(t)
            out, _ = self._launch(delta, levels, self.out_cap, se)
        return [out]


class JoinOp(BinaryOperator):
    """Consumes the two trace streams; emits the output delta Z-set.

    Reference: the JoinTrace operator pair assembled by join_generic
    (join.rs:581 + :268-290); both terms and the final sum are fused into one
    host eval here — and consolidated with ONE sort over the concatenated
    raw level expansions rather than per-level sorts plus a re-sort.
    """

    def __init__(self, fn: JoinFn, nk: int, out_schema, name="join"):
        self.name = name
        self.nk = nk  # probed key-column count (read by analysis/schema S001)
        self.out_schema = out_schema
        # Left delta joins the right trace INCLUDING this tick's right delta;
        # right delta joins the left trace EXCLUDING this tick's (delayed).
        self._left_core = JoinCore(nk, fn, out_schema)
        flipped = lambda k, rv, lv: fn(k, lv, rv)  # noqa: E731
        self._right_core = JoinCore(nk, flipped, out_schema)

    def eval(self, left: TraceView, right: TraceView) -> Batch:
        from dbsp_tpu.circuit.runtime import Runtime

        outs = self._left_core.join_levels(left.delta, right.spine.batches)
        outs += self._right_core.join_levels(right.delta, left.pre_levels)
        if not outs:
            w = Runtime.worker_count()
            return Batch.empty(*self.out_schema, lead=(w,) if w > 1 else ())
        if len(outs) == 1:
            return outs[0].consolidate().shrink_to_fit()
        return concat_batches(outs).consolidate().shrink_to_fit()


@stream_method
def join_index(self: Stream, other: Stream, fn: JoinFn, out_key_dtypes,
               out_val_dtypes, name: str = "join",
               preserves_first_key: bool = False) -> Stream:
    """Incremental equi-join on the streams' key columns.

    ``fn(key_cols, left_val_cols, right_val_cols)`` maps each matching pair
    to output key/value columns (join.rs:200 ``join_index`` semantics; plain
    ``join`` == identity keys).

    ``preserves_first_key=True``: every output row's first key column is
    the probed join key's first column (``fn`` emits ``(k[0], ...)`` keys).
    Both inputs are co-partitioned by that column's hash, so the output is
    born partitioned and downstream exchanges elide — the fast path that
    keeps join -> aggregate chains on-worker.
    """
    from dbsp_tpu.circuit.builder import CircuitError
    from dbsp_tpu.operators.registry import require_schema

    ls = require_schema(self, "join (left input)")
    rs = require_schema(other, "join (right input)")
    if ls[0] != rs[0]:
        # build-time twin of analysis rule S001 (a silent key cast changes
        # the hash shard and probe order — wrong answers, not an exception)
        raise CircuitError(
            f"join key dtypes differ: {ls[0]} vs {rs[0]} — cast one side "
            "(map_rows/index_by) so both inputs share identical key dtypes")
    out_schema = (tuple(out_key_dtypes), tuple(out_val_dtypes))
    if getattr(self.circuit, "nested_incremental", False):
        # inside a recursive() child: joins are incremental over the
        # (epoch, iteration) product lattice and own their state.
        # Shard-lifted: both sides co-locate by first-key hash (equal join
        # keys share the first column) so each worker's corner spines hold
        # its key-slice's full history; no-op on one worker.
        left = self.shard()
        right = other.shard()
        from dbsp_tpu.operators.nested_ops import NestedJoinOp

        out = left.circuit.add_binary_operator(
            NestedJoinOp(fn, len(ls[0]), (ls, rs), out_schema, left.circuit,
                         name=f"nested-{name}"), left, right)
        out.schema = out_schema
        if preserves_first_key:
            # same fast path as the root-clock branch below: the output is
            # born partitioned by the probe key's first column, so the
            # nested distinct/aggregate sugar's .shard() elides instead of
            # paying an all_to_all per child-clock iteration
            out.key_sharded = (getattr(left, "key_sharded", False)
                               and getattr(right, "key_sharded", False))
        return out
    lt = self.trace()
    rt = other.trace()
    out = self.circuit.add_binary_operator(
        JoinOp(fn, len(ls[0]), out_schema, name), lt, rt)
    out.schema = out_schema
    if preserves_first_key:
        out.key_sharded = (getattr(lt, "key_sharded", False)
                           and getattr(rt, "key_sharded", False))
    return out


@stream_method
def stream_join(self: Stream, other: Stream, fn: JoinFn, out_key_dtypes,
                out_val_dtypes, name: str = "stream_join") -> Stream:
    """Non-incremental per-tick join: ΔA_t ⋈ ΔB_t only (join.rs:52) — joins
    the two CURRENT tick values, no state."""
    core = JoinCore(len(getattr(self, "schema", ((), ()))[0]) or 1, fn, None)

    def eval_fn(a: Batch, b: Batch) -> Batch:
        core.nk = len(a.keys)  # late-bound; capacity estimates persist
        outs = core.join_levels(a, [b])  # raw — consolidate before emitting
        return concat_batches(outs).consolidate() if len(outs) > 1 \
            else outs[0].consolidate()

    from dbsp_tpu.operators.basic import Apply2

    out = self.circuit.add_binary_operator(
        Apply2(eval_fn, name), self, other)
    out.schema = (tuple(out_key_dtypes), tuple(out_val_dtypes))
    return out
