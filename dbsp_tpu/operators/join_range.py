"""Range joins: each left key matches a contiguous interval of right keys.

Reference: ``operator/join_range.rs:39-90`` — ``stream_join_range`` /
``stream_join_range_index``: per tick, for every ``(k1, v1, w1)`` in the
left batch and ``(k2, v2, w2)`` in the right batch with
``k2 ∈ [lower(k1), upper(k1))``, emit ``join_func(k1, v1, k2, v2)`` with
weight ``w1 * w2``. The reference operator is NON-incremental (it joins the
two current tick batches); :func:`stream_join_range` matches that contract.

:func:`join_range` additionally provides an INCREMENTAL variant for
RELATIVE ranges (``k2 ∈ [k1 + lo_off, k1 + hi_off]``, the
``RelRange``/temporal-join shape): because the inverse of a relative range
is itself a relative range (``k1 ∈ [k2 - hi_off, k2 - lo_off]``), the
bilinear delta form applies with range probes in both directions::

    Δ(A ⋈r B) = ΔA ⋈r trace(B)  +  trace(A)⁻ ⋈r ΔB

This goes beyond the reference (which only ships the stream variant) and is
what the SQL layer lowers BETWEEN-joins onto.

All probes/expansions are the same static-shape kernels as the equi-join
(lex_probe + expand_ranges, SURVEY §7 "join output explosion").
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import BinaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches

# fn(l_key_cols, l_val_cols, r_key_cols, r_val_cols) -> (out_keys, out_vals)
RangeJoinFn = Callable


def _range_join_level_impl(delta: Batch, level: Batch, lo_off, hi_off,
                           fn: RangeJoinFn, out_cap: int):
    """Expand matches of delta rows against one level where the level's
    (single) key lies in [delta.key + lo_off, delta.key + hi_off]."""
    dk = delta.keys[0]
    lk = level.keys[0]
    qlo = (dk + jnp.asarray(lo_off, dk.dtype),)
    qhi = (dk + jnp.asarray(hi_off, dk.dtype),)
    lo = kernels.lex_probe((lk,), qlo, side="left")
    hi = kernels.lex_probe((lk,), qhi, side="right")
    live = delta.weights != 0
    lo = jnp.where(live, lo, 0)
    hi = jnp.where(live, hi, lo)
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    w = jnp.where(valid, delta.weights[row] * level.weights[src], 0)
    lkeys = tuple(c[row] for c in delta.keys)
    lvals = tuple(c[row] for c in delta.vals)
    rkeys = tuple(c[src] for c in level.keys)
    rvals = tuple(c[src] for c in level.vals)
    out_keys, out_vals = fn(lkeys, lvals, rkeys, rvals)
    out_keys = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_keys)
    out_vals = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_vals)
    return Batch(out_keys, out_vals, w), total


_range_join_level = jax.jit(
    _range_join_level_impl,
    static_argnames=("lo_off", "hi_off", "fn", "out_cap"))


class RangeJoinCore:
    """Grow-on-demand driver (one batched overflow sync per eval)."""

    def __init__(self, lo_off: int, hi_off: int, fn: RangeJoinFn):
        self.lo_off = lo_off
        self.hi_off = hi_off
        self.fn = fn
        self.caps: Dict[int, int] = {}

    def join_levels(self, delta: Batch, levels: Sequence[Batch]
                    ) -> List[Batch]:
        outs, totals, caps = [], [], []
        for level in levels:
            cap = self.caps.get(level.cap, max(64, delta.cap))
            out, total = _range_join_level(delta, level, self.lo_off,
                                           self.hi_off, self.fn, cap)
            outs.append(out)
            totals.append(total)
            caps.append(cap)
        if not outs:
            return []
        for i, t in enumerate(jax.device_get(totals)):
            t = int(np.max(t))
            if t > caps[i]:
                cap = bucket_cap(t)
                self.caps[levels[i].cap] = cap
                outs[i], _ = _range_join_level(delta, levels[i], self.lo_off,
                                               self.hi_off, self.fn, cap)
        return outs


class RangeJoinOp(BinaryOperator):
    """Incremental relative-range join over the two trace streams."""

    def __init__(self, lo_off: int, hi_off: int, fn: RangeJoinFn, out_schema,
                 name="join_range"):
        self.name = name
        self.out_schema = out_schema
        self._left = RangeJoinCore(lo_off, hi_off, fn)
        # inverse direction: k1 ∈ [k2 - hi_off, k2 - lo_off], with the
        # closure flipped back so fn always sees (left..., right...)
        flipped = (lambda rk, rv, lk, lv: fn(lk, lv, rk, rv))
        self._right = RangeJoinCore(-hi_off, -lo_off, flipped)

    def eval(self, left: TraceView, right: TraceView) -> Batch:
        outs = self._left.join_levels(left.delta, right.spine.batches)
        outs += self._right.join_levels(right.delta, left.pre_levels)
        if not outs:
            return Batch.empty(*self.out_schema)
        out = outs[0] if len(outs) == 1 else concat_batches(outs)
        return out.consolidate().shrink_to_fit()


@stream_method
def join_range(self: Stream, other: Stream, lo_off: int, hi_off: int,
               fn: RangeJoinFn, out_key_dtypes, out_val_dtypes,
               name: str = "join_range") -> Stream:
    """Incremental relative-range join: pairs every left row with right rows
    whose (single, numeric) key lies in ``[k + lo_off, k + hi_off]``
    (inclusive). ``fn(l_keys, l_vals, r_keys, r_vals) -> (keys, vals)``."""
    ls, rs = getattr(self, "schema", None), getattr(other, "schema", None)
    assert ls is not None and rs is not None, "join_range needs schemas"
    assert len(ls[0]) == 1 and len(rs[0]) == 1, (
        "join_range operands must be keyed by one numeric column")
    out_schema = (tuple(out_key_dtypes), tuple(out_val_dtypes))
    lt = self.trace(shard=False)   # range partitioning is not hash-local
    rt = other.trace(shard=False)
    out = self.circuit.add_binary_operator(
        RangeJoinOp(lo_off, hi_off, fn, out_schema, name), lt, rt)
    out.schema = out_schema
    return out


@stream_method
def stream_join_range(self: Stream, other: Stream,
                      range_fn: Callable, fn: RangeJoinFn,
                      out_key_dtypes, out_val_dtypes,
                      name: str = "stream_join_range") -> Stream:
    """Per-tick range join (the reference's exact contract,
    join_range.rs:39): ``range_fn(l_key_cols) -> (lower_cols, upper_cols)``
    gives each left row's half-open right-key interval ``[lower, upper)``.
    Non-incremental: joins only the two current tick batches."""
    ls, rs = getattr(self, "schema", None), getattr(other, "schema", None)
    assert ls is not None and rs is not None, "stream_join_range needs schemas"
    out_schema = (tuple(out_key_dtypes), tuple(out_val_dtypes))
    caps: Dict[int, int] = {}

    def launch(a: Batch, b: Batch, cap: int):
        return _stream_range_join(a, b, range_fn, fn, cap)

    def eval_fn(a: Batch, b: Batch) -> Batch:
        cap = caps.get(b.cap, max(64, a.cap))
        out, total = launch(a, b, cap)
        t = int(jax.device_get(total))
        if t > cap:
            cap = bucket_cap(t)
            caps[b.cap] = cap
            out, _ = launch(a, b, cap)
        return out.consolidate().shrink_to_fit()

    from dbsp_tpu.operators.basic import Apply2

    out = self.circuit.add_binary_operator(Apply2(eval_fn, name), self, other)
    out.schema = out_schema
    return out


@partial(jax.jit, static_argnames=("range_fn", "fn", "out_cap"))
def _stream_range_join(a: Batch, b: Batch, range_fn, fn, out_cap: int):
    lower, upper = range_fn(a.keys)
    lo = kernels.lex_probe(b.keys, tuple(lower), side="left")
    hi = kernels.lex_probe(b.keys, tuple(upper), side="left")  # half-open
    live = a.weights != 0
    lo = jnp.where(live, lo, 0)
    hi = jnp.where(live, jnp.maximum(hi, lo), lo)
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    w = jnp.where(valid, a.weights[row] * b.weights[src], 0)
    lkeys = tuple(c[row] for c in a.keys)
    lvals = tuple(c[row] for c in a.vals)
    rkeys = tuple(c[src] for c in b.keys)
    rvals = tuple(c[src] for c in b.vals)
    out_keys, out_vals = fn(lkeys, lvals, rkeys, rvals)
    out_keys = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_keys)
    out_vals = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_vals)
    return Batch(out_keys, out_vals, w), total
