"""Incremental group-by aggregation.

Reference: ``operator/aggregate/mod.rs`` — the ``Aggregator`` trait (:75),
``stream_aggregate`` (:172), incremental ``aggregate`` (:204) whose
``AggregateIncremental::eval`` (:600) recomputes aggregates ONLY for keys
touched by the delta, reading the full group from the input trace, and emits
retract/insert pairs against the previous output.

TPU shape of the same algorithm, per tick:
  1. unique touched keys Q  = distinct live keys of the delta (one compact);
  2. group gather           = probe every input-spine level for Q's ranges,
                              expand (grow-on-demand caps), gather rows;
  3. net weights            = consolidate gathered rows on (q, vals) so a
                              (key,val) split across levels nets out;
  4. reduce                 = aggregator's segment reduction per q;
  5. diff                   = probe the operator's own output spine for Q's
                              previous values; emit -1 old / +1 new where
                              changed (skip unchanged; empty group retracts).
All steps are static-shape kernels; per-step cost scales with the delta and
the touched groups, not the accumulated state.

Weights semantics: a (key, val) with net weight w > 0 is present (w copies);
non-positive net weights mean absent. Inputs whose groups net to negative
multiplicities are ill-formed for aggregation (same contract as the
reference's aggregates over indexed Z-sets).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches

# ---------------------------------------------------------------------------
# Aggregators (reference: Fold/Min/Max/Avg, operator/aggregate/{fold,...}.rs)
# ---------------------------------------------------------------------------


class Aggregator:
    """Segment-reduction spec: vals+weights grouped by segment id -> outputs.

    ``reduce`` sees every gathered row (including absent ones, net w <= 0) and
    must ignore non-present rows itself; identity segments are reported
    through the separate nonempty mask, so identity values never escape.

    The built-ins declare their reduction DECLARATIVELY via
    :meth:`reduce_spec` — a tuple of ``(op, source column)`` pairs from the
    shared five-op vocabulary (count / sum / min / max / avg) — and inherit
    ``reduce`` from the spec through :func:`segment_reduce`, which
    dispatches the whole spec as ONE native custom call on CPU
    (``ZsetSegmentReduceFfi``) instead of 2-4 XLA dispatches per output.
    The spec is also what lets the compiled engine's fused aggregate
    megakernel (``cursor.agg_ladder``) run the reduction inside the trace
    walk; spec-less aggregators (``Fold``) keep their hand-written
    ``reduce`` and the stitched path.
    """

    out_dtypes: Tuple = ()
    name = "agg"
    #: semigroup aggregates (Min/Max) set this: when a group's delta holds
    #: ONLY insertions, the new output is combine(old output, reduce(delta))
    #: — no re-gather of the group's history from the input trace. The
    #: compiled path uses it to make append-mostly streams (e.g. Nexmark
    #: bids) cost O(delta) instead of O(touched history) per tick.
    insert_combinable = False

    def reduce_spec(self) -> Optional[Tuple[Tuple[str, int], ...]]:
        """``((op, src_col), ...)`` per output — ``None`` for opaque
        (hand-written) reductions, which the fused paths skip."""
        return None

    def reduce(self, val_cols: Tuple[jnp.ndarray, ...], weights: jnp.ndarray,
               seg: jnp.ndarray, num_segments: int
               ) -> Tuple[jnp.ndarray, ...]:
        spec = self.reduce_spec()
        if spec is None:
            raise NotImplementedError
        return segment_reduce(spec, val_cols, weights, seg, num_segments)

    def combine(self, a_vals: Tuple[jnp.ndarray, ...], a_present,
                b_vals: Tuple[jnp.ndarray, ...], b_present
                ) -> Tuple[jnp.ndarray, ...]:
        """Semigroup combine of two per-segment partial outputs (only
        required when ``insert_combinable``); absent sides must not leak
        their identity values into the result."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Count(Aggregator):
    out_dtypes = (jnp.int64,)
    name = "count"

    def reduce_spec(self):
        return (("count", 0),)


@dataclasses.dataclass(frozen=True)
class Sum(Aggregator):
    col: int = 0
    out_dtypes = (jnp.int64,)
    name = "sum"

    def reduce_spec(self):
        return (("sum", self.col),)


@dataclasses.dataclass(frozen=True)
class Max(Aggregator):
    col: int = 0
    out_dtypes = (jnp.int64,)
    name = "max"
    insert_combinable = True

    def reduce_spec(self):
        return (("max", self.col),)

    def combine(self, a_vals, a_present, b_vals, b_present):
        a, b = a_vals[0], b_vals[0].astype(a_vals[0].dtype)
        return (jnp.where(a_present & b_present, jnp.maximum(a, b),
                          jnp.where(a_present, a, b)),)


@dataclasses.dataclass(frozen=True)
class Min(Aggregator):
    col: int = 0
    out_dtypes = (jnp.int64,)
    name = "min"
    insert_combinable = True

    def reduce_spec(self):
        return (("min", self.col),)

    def combine(self, a_vals, a_present, b_vals, b_present):
        a, b = a_vals[0], b_vals[0].astype(a_vals[0].dtype)
        return (jnp.where(a_present & b_present, jnp.minimum(a, b),
                          jnp.where(a_present, a, b)),)


@dataclasses.dataclass(frozen=True)
class Average(Aggregator):
    """Integer average sum//count (deterministic across worker counts, unlike
    float accumulation order). Truncating division (SQL/Rust semantics),
    not Python floor: -7 / 2 == -3, matching the reference engine on
    negative sums — the shared "avg" op implements exactly that."""

    col: int = 0
    out_dtypes = (jnp.int64,)
    name = "avg"

    def reduce_spec(self):
        return (("avg", self.col),)


@dataclasses.dataclass(frozen=True)
class Fold(Aggregator):
    """General user-defined aggregation (reference: ``aggregate/fold.rs:25``).

    ``reduce_fn(val_cols, weights, seg, num_segments) -> out_cols`` is any
    segment reduction over the gathered group rows (rows with net weight
    <= 0 must be ignored by masking on ``weights > 0``, exactly like the
    built-ins). Example — sum of squares:

        Fold(lambda v, w, s, n: (segment_sum(v[0]**2 * maximum(w, 0), s, n),),
             out_dtypes=(jnp.int64,))
    """

    reduce_fn: Callable = None
    out_dtypes: Tuple = (jnp.int64,)
    name: str = "fold"

    def reduce(self, val_cols, weights, seg, num_segments):
        return tuple(self.reduce_fn(val_cols, weights, seg, num_segments))


# ---------------------------------------------------------------------------
# Shared segment-reduction dispatch (the five-op Aggregator vocabulary)
# ---------------------------------------------------------------------------


def _seg_out_dtype(op: str, col: int, val_cols, weights):
    """Result dtype of one reduction op under the XLA formulation — what
    the native kernel's int64 accumulators re-narrow to (two's-complement
    truncation == wrapping narrow-dtype accumulation, so int32-weight
    paths stay bit-identical)."""
    if op == "count":
        return weights.dtype
    if op == "present":
        return jnp.int64  # jnp.where(w > 0, 1, 0) under x64
    v = val_cols[col]
    if op in ("min", "max"):
        return v.dtype
    return jnp.promote_types(v.dtype, weights.dtype)  # sum / avg


def segment_reduce(spec, val_cols, weights: jnp.ndarray, seg: jnp.ndarray,
                   num_segments: int) -> Tuple[jnp.ndarray, ...]:
    """Run a whole reduce spec — ``((op, src_col), ...)`` over the shared
    count/sum/min/max/avg(/present) vocabulary — per segment id, as ONE
    native custom call on CPU (``ZsetSegmentReduceFfi``; the
    ``DBSP_TPU_NATIVE=segment_reduce`` force-off and non-int dtypes fall
    back to the ``jax.ops.segment_*`` formulation below). Semantics per op
    (bit-identical on every backend): count = Σ max(w, 0); sum =
    Σ v·max(w, 0); min/max over rows with w > 0 (empty segments fill with
    the source dtype's identity); avg = truncating sum/count division;
    present = any w > 0 (as the 0/1 int the XLA formulation produces).
    Out-of-range seg ids are dropped (the trash-segment contract)."""
    out_dtypes = tuple(_seg_out_dtype(op, col, val_cols, weights)
                       for op, col in spec)
    # avg DIVIDES: the fused backends accumulate in int64 and narrow the
    # quotient, which equals the XLA formulation only when the result
    # dtype IS int64 (for sums, truncating an int64 accumulation equals a
    # wrapping narrow accumulation — division breaks that congruence).
    # Narrower promotions (int32 weights x int32 vals — no engine path,
    # weights are int64 everywhere) keep the XLA chain.
    fused_ok = all(op != "avg" or jnp.dtype(dt) == jnp.int64
                   for (op, _), dt in zip(spec, out_dtypes))
    if fused_ok and weights.ndim == 1 and num_segments >= 1:
        if kernels.pallas_requested():
            from dbsp_tpu.zset import pallas_kernels

            if pallas_kernels.use_pallas("segment_reduce",
                                         (*val_cols, weights)):
                kernels.count_kernel_dispatch("segment_reduce", "pallas")
                return pallas_kernels.segment_reduce_pallas(
                    spec, val_cols, weights, seg, num_segments, out_dtypes)
        if kernels.native_kernel("segment_reduce"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports((*(c.dtype for c in val_cols),
                                      weights.dtype)):
                kernels.count_kernel_dispatch("segment_reduce", "native")
                return native_merge.segment_reduce_native(
                    spec, val_cols, weights, seg, num_segments, out_dtypes)
    kernels.count_kernel_dispatch("segment_reduce", "xla")
    wpos = jnp.maximum(weights, 0)
    outs: List[jnp.ndarray] = []
    for op, col in spec:
        if op == "count":
            outs.append(jax.ops.segment_sum(wpos, seg,
                                            num_segments=num_segments))
        elif op == "sum":
            outs.append(jax.ops.segment_sum(val_cols[col] * wpos, seg,
                                            num_segments=num_segments))
        elif op == "min":
            v = val_cols[col]
            hi = jnp.iinfo(v.dtype).max \
                if jnp.issubdtype(v.dtype, jnp.integer) else jnp.inf
            outs.append(jax.ops.segment_min(
                jnp.where(weights > 0, v, hi), seg,
                num_segments=num_segments))
        elif op == "max":
            v = val_cols[col]
            lo = jnp.iinfo(v.dtype).min \
                if jnp.issubdtype(v.dtype, jnp.integer) else -jnp.inf
            outs.append(jax.ops.segment_max(
                jnp.where(weights > 0, v, lo), seg,
                num_segments=num_segments))
        elif op == "avg":
            s = jax.ops.segment_sum(val_cols[col] * wpos, seg,
                                    num_segments=num_segments)
            c = jnp.maximum(jax.ops.segment_sum(
                wpos, seg, num_segments=num_segments), 1)
            outs.append(jnp.where(s >= 0, s // c, -((-s) // c)))
        elif op == "present":
            outs.append(jax.ops.segment_max(
                jnp.where(weights > 0, 1, 0), seg,
                num_segments=num_segments))
        else:
            raise ValueError(f"unknown segment-reduce op {op!r}")
    return tuple(outs)


def reduce_with_present(agg: "Aggregator", val_cols, weights, seg,
                        num_segments: int):
    """(outputs, presence) in as few dispatches as the aggregator allows:
    spec'd aggregators append a ``present`` op to their own spec, so the
    whole thing is ONE fused ``segment_reduce`` call; opaque ones pay
    their hand-written reduce plus the separate presence reduction."""
    spec = agg.reduce_spec()
    if spec is not None:
        res = segment_reduce((*spec, ("present", 0)), val_cols, weights,
                             seg, num_segments)
        return tuple(res[:-1]), res[-1]
    outs = tuple(agg.reduce(val_cols, weights, seg, num_segments))
    present = jax.ops.segment_max(
        jnp.where(weights > 0, 1, 0), seg, num_segments=num_segments)
    return outs, present


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _delta_groups_impl(delta: Batch, nk: int):
    """Group structure of a consolidated delta in ONE run-boundary scan:
    ``(unique key cols, unique live mask, row live mask, segment id per
    row)``. The delta's sorted-run contract (``sorted_runs == 1`` — live
    rows packed, equal keys adjacent) is what makes the single
    prev-row comparison exact; the same ``first``-of-group mask feeds both
    the unique-key compaction and the fast path's per-row segment ids, so
    the boundaries are never scanned twice (they previously were —
    ``_unique_keys_impl`` then a second ``rows_equal_prev`` in
    CAggregate's fast path)."""
    keys = delta.keys[:nk]
    first = ~kernels.rows_equal_prev(keys, n=delta.cap)
    anylive = delta.weights != 0
    live = anylive & first
    cols, w = kernels.compact(keys, jnp.where(live, 1, 0).astype(jnp.int32),
                              live)
    seg = jnp.cumsum(jnp.where(live, 1, 0)) - 1
    return cols, w != 0, anylive, seg


def _unique_keys_impl(delta: Batch, nk: int
                      ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Distinct live keys of a consolidated batch, compacted to the front.

    Returns (key_cols, live_mask) at the delta's capacity. The one
    run-boundary scan lives in :func:`_delta_groups_impl`; the segment
    ids computed there are dead code under jit for callers that only
    need the keys."""
    cols, qlive, _, _ = _delta_groups_impl(delta, nk)
    return cols, qlive


_unique_keys_jit = jax.jit(_unique_keys_impl, static_argnames=("nk",))


def _unique_keys_factory(nk: int):
    return lambda d: _unique_keys_impl(d, nk)


def _unique_keys(delta: Batch, nk: int):
    """Distinct live keys + live mask, re-bucketed to the distinct-key count.

    The trim (one scalar sync) is what keeps aggregation cost proportional
    to TOUCHED KEYS, not delta capacity: a 64k-cap delta over 16 groups
    would otherwise drag 64k-sized gathers/diffs through the whole eval.
    """
    if delta.sharded:
        qkeys, qlive = lifted(_unique_keys_factory, nk)(delta)
        nq = int(jnp.max(jnp.sum(qlive, axis=-1)))
    else:
        qkeys, qlive = _unique_keys_jit(delta, nk)
        nq = int(jnp.sum(qlive))
    cap = bucket_cap(max(nq, 1))
    if cap < qlive.shape[-1]:
        qkeys = tuple(k[..., :cap] for k in qkeys)
        qlive = qlive[..., :cap]
    return qkeys, qlive


def _gather_level_impl(qkeys: Tuple[jnp.ndarray, ...], qlive: jnp.ndarray,
                       level: Batch, out_cap: int):
    """Expand one spine level's matching rows for the query keys.

    Returns (qrow ids, gathered val cols, weights, total). The output is
    SORTED by (qrow, vals): expansion follows query order and each group's
    rows keep the level's (key, vals) order; dead slots carry qrow ==
    q_cap (the trash segment) + sentinel vals, so they sort last. That
    ordering is what lets cross-level results combine with a rank-merge
    instead of a sort."""
    nk = len(qkeys)
    q_cap = qkeys[0].shape[0]
    lo = kernels.lex_probe(level.keys[:nk], qkeys, side="left")
    hi = kernels.lex_probe(level.keys[:nk], qkeys, side="right")
    lo = jnp.where(qlive, lo, 0)
    hi = jnp.where(qlive, hi, lo)
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    w = jnp.where(valid, level.weights[src], 0)
    vals = tuple(jnp.where(valid, c[src], kernels.sentinel_for(c.dtype))
                 for c in level.vals)
    qrow = jnp.where(valid, row, jnp.int32(q_cap))
    return qrow, vals, w, total


def _gather_ladder_factory(out_cap: int):
    from dbsp_tpu.zset import cursor

    return lambda qk, ql, levels: cursor.gather_ladder(qk, ql, levels,
                                                       out_cap)


@partial(jax.jit, static_argnames=("out_cap",))
def _gather_ladder(qkeys, qlive, levels, out_cap: int):
    from dbsp_tpu.zset import cursor

    return cursor.gather_ladder(qkeys, qlive, levels, out_cap)


class GroupGather:
    """Host driver: gather the full groups of the query keys across ALL
    spine levels in ONE fused launch (zset/cursor.py: one probe pair over
    the ladder, one cross-level expansion, one shared buffer with one
    monotone capacity — the per-level loop paid K probe kernels and K
    grow-on-demand buffers). One batched overflow sync per eval.

    With several levels the fused part may hold cross-level insert/retract
    rows for one (qrow, vals) — reducers net them
    (``_reduce_groups(..., net=len(levels) > 1)``)."""

    def __init__(self):
        self.out_cap = 0  # fused ladder output capacity (monotone)

    @staticmethod
    def _launch(qkeys, qlive, levels, cap):
        if qlive.ndim > 1:  # sharded query set
            return lifted(_gather_ladder_factory, cap)(qkeys, qlive, levels)
        return _gather_ladder(qkeys, qlive, levels, cap)

    def __call__(self, qkeys, qlive, levels: Sequence[Batch], q_cap: int):
        """Returns a 1-element list holding the fused (qrow, val_cols, w)
        part, or None for an empty ladder."""
        if not levels:
            return None
        levels = tuple(levels)
        if not self.out_cap:
            self.out_cap = bucket_cap(max(64, q_cap))
        part, total = self._launch(qkeys, qlive, levels, self.out_cap)
        t = int(np.max(jax.device_get(total)))  # ONE sync; worst worker
        if t > self.out_cap:
            self.out_cap = bucket_cap(t)
            part, _ = self._launch(qkeys, qlive, levels, self.out_cap)
        return [part]


def concat_parts(parts):
    """Flatten per-level gather parts to one (qrow, val_cols, w) triple —
    for consumers that net rows themselves (topk, upsert)."""
    qrow = jnp.concatenate([p[0] for p in parts], axis=-1)
    nvals = len(parts[0][1])
    vals = tuple(jnp.concatenate([p[1][i] for p in parts], axis=-1)
                 for i in range(nvals))
    w = jnp.concatenate([p[2] for p in parts], axis=-1)
    return qrow, vals, w


def _reduce_groups_impl(parts, agg: Aggregator, q_cap: int,
                        net: bool | None = None):
    """Net out cross-level duplicates (each part is sorted by (qrow, vals)
    — see :func:`_gather_level_impl`), then run the aggregator per q segment.

    One gathered level needs no netting (its rows are unique); multiple
    levels combine with one sort-consolidation on CPU or a fold of
    rank-merges on TPU (kernels.merge_strategy). ``net=True`` forces the
    consolidation for a SINGLE part that was itself combined from several
    levels (compiled ``gather_levels``) and so may carry cross-level
    insert/retract rows for one (qrow, vals)."""
    (qrow, val_cols, w), *rest = parts
    cols = (qrow, *val_cols)
    if not rest and net:
        cols, w = kernels.consolidate_cols(cols, w)
        qrow, val_cols = cols[0], cols[1:]
        cols = (qrow, *val_cols)
    if rest and kernels.merge_strategy() == "sort":
        all_cols = tuple(
            jnp.concatenate([p[i] if i == 0 else p[1][i - 1]
                             for p in parts])
            for i in range(1 + len(val_cols)))
        all_w = jnp.concatenate([p[2] for p in parts])
        cols, w = kernels.consolidate_cols(all_cols, all_w)
    else:
        for (qrow2, vals2, w2) in rest:
            cols, w = kernels.merge_sorted_cols(cols, w, (qrow2, *vals2), w2)
    qrow, val_cols = cols[0], cols[1:]
    # dead rows carry qrow >= q_cap (q_cap marker, or int32 sentinel after
    # a merge compaction) — clamp everything dead into the trash segment
    seg = jnp.minimum(qrow, q_cap).astype(jnp.int32)
    outs, present = reduce_with_present(agg, val_cols, w, seg, q_cap + 1)
    return tuple(o[:q_cap] for o in outs), present[:q_cap] > 0


_reduce_groups_jit = jax.jit(_reduce_groups_impl,
                             static_argnames=("agg", "q_cap", "net"))


def _reduce_groups_factory(agg: Aggregator, q_cap: int, net=None):
    return lambda parts: _reduce_groups_impl(parts, agg, q_cap, net)


def _reduce_groups(parts, agg: Aggregator, q_cap: int, net=None):
    if parts[0][2].ndim > 1:  # sharded gather parts
        return lifted(_reduce_groups_factory, agg, q_cap, net)(parts)
    return _reduce_groups_jit(parts, agg, q_cap, net)


def _diff_outputs_impl(qkeys, qlive, new_vals, new_present, old_vals,
                       old_present):
    """Build the retract/insert output delta (2*q_cap capacity)."""
    changed = jnp.zeros(qlive.shape, jnp.bool_)
    for nv, ov in zip(new_vals, old_vals):
        changed = changed | ~kernels._col_eq(nv.astype(ov.dtype), ov)
    changed = changed | (new_present != old_present)
    insert_w = jnp.where(qlive & new_present & changed, 1, 0)
    retract_w = jnp.where(qlive & old_present & changed, -1, 0)
    keys = tuple(jnp.concatenate([c, c]) for c in qkeys)
    vals = tuple(jnp.concatenate([nv.astype(ov.dtype), ov])
                 for nv, ov in zip(new_vals, old_vals))
    w = jnp.concatenate([insert_w, retract_w]).astype(jnp.int64)
    cols, w = kernels.consolidate_cols((*keys, *vals), w)
    return cols, w


_diff_outputs_jit = jax.jit(_diff_outputs_impl)


def _diff_outputs_factory():
    return _diff_outputs_impl


def _diff_outputs(qkeys, qlive, new_vals, new_present, old_vals, old_present):
    if qlive.ndim > 1:  # sharded
        return lifted(_diff_outputs_factory)(
            qkeys, qlive, new_vals, new_present, old_vals, old_present)
    return _diff_outputs_jit(qkeys, qlive, new_vals, new_present, old_vals,
                             old_present)


class AggregateOp(UnaryOperator):
    """Incremental aggregate over a traced indexed Z-set (aggregate/mod.rs:410)."""

    def __init__(self, agg: Aggregator, key_dtypes, name=None):
        self.agg = agg
        self.name = name or f"aggregate<{agg.name}>"
        self.key_dtypes = tuple(key_dtypes)
        self.out_schema = (self.key_dtypes, tuple(agg.out_dtypes))
        self.out_spine = Spine(self.key_dtypes, tuple(agg.out_dtypes))
        self._group_gather = GroupGather()
        self._old_gather = GroupGather()

    def clock_start(self, scope: int) -> None:
        if scope > 0:  # nested clock: reset per parent tick (nested.py)
            self.out_spine = Spine(self.key_dtypes, tuple(self.agg.out_dtypes))

    def eval(self, view: TraceView) -> Batch:
        from dbsp_tpu.circuit.runtime import Runtime

        delta = view.delta
        nk = len(self.key_dtypes)
        if int(delta.live_count()) == 0:
            w = Runtime.worker_count()
            return Batch.empty(*self.out_schema, lead=(w,) if w > 1 else ())
        qkeys, qlive = _unique_keys(delta, nk)
        q_cap = qlive.shape[-1]  # trimmed to distinct-key bucket

        gathered = self._group_gather(qkeys, qlive, view.spine.batches, q_cap)
        if gathered is None:
            new_vals = tuple(
                jnp.zeros(qlive.shape, d) for d in self.agg.out_dtypes)
            new_present = jnp.zeros(qlive.shape, jnp.bool_)
        else:
            # the fused part holds cross-level rows when the spine has
            # several levels — net them before reducing
            new_vals, new_present = _reduce_groups(
                tuple(gathered), self.agg, q_cap,
                net=len(view.spine.batches) > 1)

        old = self._old_gather(qkeys, qlive, self.out_spine.batches, q_cap)
        if old is None:
            old_vals = tuple(kernels.sentinel_fill(qlive.shape, d)
                             for d in self.agg.out_dtypes)
            old_present = jnp.zeros(qlive.shape, jnp.bool_)
        else:
            # previous outputs are single rows per key; Max over net-positive
            # rows reconstructs the value, presence from net weight
            old_vals, old_present = _reduce_groups(
                tuple(old), _TupleMax(len(self.agg.out_dtypes)), q_cap,
                net=len(self.out_spine.batches) > 1)

        cols, w = _diff_outputs(qkeys, qlive, new_vals, new_present,
                                old_vals, old_present)
        # re-bucket to live rows: the diff has 2*q_cap capacity but few live
        # rows, and downstream operators inherit whatever cap we emit
        out = Batch(cols[:nk], cols[nk:], w,
                    runs=(int(w.shape[-1]),)).shrink_to_fit()
        self.out_spine.insert(out)
        return out

    def fixedpoint(self, scope: int) -> bool:
        return True

    def state_dict(self):
        return {"out_spine": self.out_spine}

    def load_state_dict(self, state):
        self.out_spine = state["out_spine"]


@dataclasses.dataclass(frozen=True)
class _TupleMax(Aggregator):
    """Internal: recover the (unique) previous output row per key — a
    per-column "max over net-positive rows", i.e. one shared-vocabulary
    max op per column."""

    ncols: int = 1

    def reduce_spec(self):
        return tuple(("max", i) for i in range(self.ncols))


@stream_method
def aggregate(self: Stream, agg, name=None) -> Stream:
    """Incremental aggregate by the stream's key columns; output is an
    indexed Z-set (key -> aggregate value) maintained under retractions.

    A :class:`~dbsp_tpu.operators.aggregate_linear.LinearAggregator`
    (Count/Sum/Average) dispatches to the linear fast path, which consumes
    the raw delta stream — no input trace, delta-sized work only
    (aggregate/mod.rs:253). Other aggregators (Min/Max/Fold) use the
    general trace-gather path (aggregate/mod.rs:204,600)."""
    from dbsp_tpu.operators.aggregate_linear import (LinearAggregateOp,
                                                     LinearAggregator)
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "aggregate")
    if getattr(self.circuit, "nested_incremental", False):
        # inside a recursive() child: aggregate over the (epoch, iteration)
        # product lattice (reference: aggregate/mod.rs:204,410 is generic
        # over Timestamp incl. NestedTimestamp32). All aggregator kinds go
        # through the four-corner path — the linear fast path's
        # delta-only accumulators are not 2-d-incremental.
        from dbsp_tpu.operators.nested_ops import NestedAggregateOp

        # shard-lifted: group keys co-locate by first-key hash so each
        # worker aggregates complete groups; no-op on one worker
        src = self.shard()
        out = src.circuit.add_unary_operator(
            NestedAggregateOp(agg, schema, src.circuit, name), src)
        out.schema = (tuple(schema[0]), tuple(agg.out_dtypes))
        out.key_sharded = getattr(src, "key_sharded", False)
        return out
    if isinstance(agg, LinearAggregator):
        src = self.shard()  # co-locate keys (no-op on one worker)
        out = src.circuit.add_unary_operator(
            LinearAggregateOp(agg, schema[0], name), src)
        out.schema = (tuple(schema[0]), tuple(agg.out_dtypes))
        out.key_sharded = getattr(src, "key_sharded", False)
        return out
    t = self.trace()
    out = self.circuit.add_unary_operator(
        AggregateOp(agg, schema[0], name), t)
    out.schema = (tuple(schema[0]), tuple(agg.out_dtypes))
    out.key_sharded = getattr(t, "key_sharded", False)
    return out


@stream_method
def stream_aggregate(self: Stream, agg: Aggregator, name=None) -> Stream:
    """Non-incremental variant: aggregates each tick's batch alone
    (aggregate/mod.rs:172) — the differential-testing oracle for
    :func:`aggregate` via ``integrate().stream_aggregate()``."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "stream_aggregate")
    nk = len(schema[0])
    op_name = name or f"stream_aggregate<{agg.name}>"

    def eval_fn(batch: Batch) -> Batch:
        if batch.sharded:  # oracle path runs host-side; collapse first
            from dbsp_tpu.parallel.exchange import unshard_batch

            batch = unshard_batch(batch)
        qkeys, qlive = _unique_keys(batch, nk)
        q_cap = qlive.shape[-1]
        gg = GroupGather()
        gathered = gg(qkeys, qlive, [batch], q_cap)
        new_vals, new_present = _reduce_groups(tuple(gathered), agg, q_cap)
        w = jnp.where(qlive & new_present, 1, 0).astype(jnp.int64)
        cols, w = kernels.consolidate_cols(
            (*qkeys, *(v for v in new_vals)), w)
        return Batch(cols[:nk], cols[nk:], w, runs=(int(w.shape[-1]),))

    from dbsp_tpu.operators.basic import Apply

    out = self.circuit.add_unary_operator(Apply(eval_fn, op_name), self)
    out.schema = (tuple(schema[0]), tuple(agg.out_dtypes))
    return out
