"""Stream-method registration.

The reference exposes operators as Rust extension-trait methods on ``Stream``
(e.g. ``operator/filter_map.rs`` impl blocks); the Python analog is attaching
functions to the Stream class at import time. Every operator module registers
its sugar through :func:`stream_method` so `dbsp_tpu.operators` import order
is the only wiring needed.
"""

from dbsp_tpu.circuit.builder import Stream


def stream_method(fn):
    assert not hasattr(Stream, fn.__name__), (
        f"Stream.{fn.__name__} registered twice")
    setattr(Stream, fn.__name__, fn)
    return fn
