"""Stream-method registration.

The reference exposes operators as Rust extension-trait methods on ``Stream``
(e.g. ``operator/filter_map.rs`` impl blocks); the Python analog is attaching
functions to the Stream class at import time. Every operator module registers
its sugar through :func:`stream_method` so `dbsp_tpu.operators` import order
is the only wiring needed.
"""

from dbsp_tpu.circuit.builder import CircuitError, Stream


def stream_method(fn):
    if hasattr(Stream, fn.__name__):
        raise CircuitError(f"Stream.{fn.__name__} registered twice")
    setattr(Stream, fn.__name__, fn)
    return fn


def require_schema(stream: Stream, who: str):
    """Typed replacement for the sugar's ``assert schema is not None``
    guards: user-facing validation must survive ``python -O`` (the static
    analyzer backs this up at pipeline start, but build-time is earlier)."""
    schema = getattr(stream, "schema", None)
    if schema is None:
        raise CircuitError(
            f"{who} needs stream schema metadata on {stream!r}; build the "
            "stream through the operator sugar (add_input_zset/map_rows/"
            "index_by) or set .schema = (key_dtypes, val_dtypes)")
    return schema
