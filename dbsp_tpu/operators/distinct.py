"""Incremental distinct: set semantics over Z-set multiplicities.

Reference: ``operator/distinct.rs`` — ``stream_distinct`` (:40) and the
root-scope-optimized incremental ``distinct`` (:64, eval :196): for each row
in the delta, compare the row's accumulated weight before vs after the tick;
emit +1 when it becomes positive, -1 when it stops being positive.

TPU shape: one probe of the input's pre-tick trace for the delta's rows
(full-row lex probe across spine levels), a segment-sum to net the old weight,
then a pure elementwise old/new comparison. Cost: O(|delta| log |trace|).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch


def _old_weights_level_impl(delta: Batch, level: Batch) -> jnp.ndarray:
    """Accumulated weight of each delta ROW (keys+vals) in one spine level.

    Rows are unique within a consolidated level, so the [lo, hi) range per
    row is 0 or 1 wide; gather the weight when present.
    """
    cols = delta.cols
    lo = kernels.lex_probe(level.cols, cols, side="left")
    hi = kernels.lex_probe(level.cols, cols, side="right")
    found = (hi > lo) & (delta.weights != 0)
    w = level.weights[jnp.minimum(lo, level.cap - 1)]
    return jnp.where(found, w, 0)


def _distinct_delta_impl(delta: Batch, old_w: jnp.ndarray) -> Batch:
    new_w = old_w + delta.weights
    became = (old_w <= 0) & (new_w > 0)
    ceased = (old_w > 0) & (new_w <= 0)
    live = delta.weights != 0
    out_w = jnp.where(live & became, 1,
                      jnp.where(live & ceased, -1, 0)).astype(delta.weights.dtype)
    cols, w = kernels.compact(delta.cols, out_w, out_w != 0)
    # a consolidated delta's row order survives the compaction
    runs = (delta.cap,) if delta.sorted_runs == 1 else None
    return Batch(cols[: len(delta.keys)], cols[len(delta.keys):], w, runs)


_distinct_delta = jax.jit(_distinct_delta_impl)


def _distinct_delta_factory():
    return _distinct_delta_impl


def _distinct_ladder_impl(delta: Batch, levels) -> Batch:
    """Fused eval: one ladder probe for the old weights across every
    pre-tick level (zset/cursor.py), then the delta comparison."""
    from dbsp_tpu.zset import cursor

    return _distinct_delta_impl(delta,
                                cursor.old_weights_ladder(delta, levels))


_distinct_ladder = jax.jit(_distinct_ladder_impl)


def _distinct_ladder_factory():
    return _distinct_ladder_impl


class DistinctOp(UnaryOperator):
    name = "distinct"

    def eval(self, view: TraceView) -> Batch:
        delta = view.delta
        sharded = delta.sharded
        if not view.pre_levels:
            old_w = jnp.zeros_like(delta.weights)
            if sharded:
                return lifted(_distinct_delta_factory)(delta, old_w)
            return _distinct_delta(delta, old_w)
        levels = tuple(view.pre_levels)
        if sharded:
            return lifted(_distinct_ladder_factory)(delta, levels)
        return _distinct_ladder(delta, levels)


class StreamDistinct(UnaryOperator):
    """Per-tick set projection (distinct.rs:40): weight>0 -> 1, else drop."""

    name = "stream_distinct"

    @staticmethod
    @jax.jit
    def _kernel(batch: Batch) -> Batch:
        w = jnp.where(batch.weights > 0, 1, 0).astype(batch.weights.dtype)
        cols, w = kernels.compact(batch.cols, w, w != 0)
        runs = (batch.cap,) if batch.sorted_runs == 1 else None
        return Batch(cols[: len(batch.keys)], cols[len(batch.keys):], w, runs)

    def eval(self, batch: Batch) -> Batch:
        return self._kernel(batch)


@stream_method
def distinct(self: Stream) -> Stream:
    """Incremental distinct; dispatches to the nested (epoch, iteration)
    variant inside a recursive() child (distinct.rs:64 nested scope)."""
    schema = getattr(self, "schema", None)
    if getattr(self.circuit, "nested_incremental", False):
        from dbsp_tpu.operators.nested_ops import NestedDistinctOp
        from dbsp_tpu.operators.registry import require_schema

        schema = require_schema(self, "distinct (nested)")
        # shard-lifted: co-locate equal rows (equal full rows share the
        # first key column) so each worker's per-row corner spines hold
        # every occurrence of its rows; no-op on one worker
        src = self.shard()
        out = src.circuit.add_unary_operator(
            NestedDistinctOp(schema, src.circuit), src)
        out.schema = schema
        out.key_sharded = getattr(src, "key_sharded", False)
        return out
    t = self.trace()
    out = self.circuit.add_unary_operator(DistinctOp(), t)
    out.schema = schema
    out.key_sharded = getattr(t, "key_sharded", False)
    return out


@stream_method
def stream_distinct(self: Stream) -> Stream:
    out = self.circuit.add_unary_operator(StreamDistinct(), self)
    out.schema = getattr(self, "schema", None)
    return out
