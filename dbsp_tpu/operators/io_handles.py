"""Input and output handles: the host <-> circuit data boundary.

Reference: ``operator/input.rs`` (``add_input_zset`` :75,
``add_input_indexed_zset`` :107, upsert-style ``add_input_set/map``
:230,313) and ``operator/output.rs:29``.

Differences by design: the reference spreads input across worker threads
round-robin and merges worker outputs with ``gather``; here a single handle
owns the (device-resident) batch, and worker distribution is the shard
operator's hash exchange inside the SPMD step (parallel/exchange.py), so
handles are worker-count agnostic.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.circuit.operator import SinkOperator, SourceOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.zset.batch import Batch, Row, concat_batches


class ZSetInput(SourceOperator):
    """Source draining a host-side buffer of rows/batches once per tick."""

    name = "input"

    # Optional lineage tap (obs/lineage.py enable_taps): a host spine this
    # source folds every drained delta into — the raw input-table integral
    # backward provenance slicing resolves to. Both engines drain inputs
    # through this eval (the compiled serving driver calls it per tick),
    # so one tap serves both. Opt-in: None = zero cost.
    lineage_tap = None

    def __init__(self, key_dtypes: Sequence, val_dtypes: Sequence = ()):
        self.key_dtypes = tuple(key_dtypes)
        self.val_dtypes = tuple(val_dtypes)
        self._rows: List[Tuple[Row, int]] = []
        self._batches: List[Tuple[Batch, bool]] = []  # (batch, consolidated)

    def eval(self) -> Batch:
        from dbsp_tpu.circuit.runtime import Runtime

        rt = Runtime.current()
        workers = rt.workers if rt is not None else 1
        # SWAP the buffers out FIRST (one atomic-under-the-GIL statement):
        # consolidation below can jit-compile for hundreds of ms, and rows
        # pushed from other threads during that window must land in the
        # NEXT tick's buffer — a clear-after-read here destroyed them
        # (found by the slow-consumer fault test: a stalling sink widened
        # the eval window and rows pushed mid-step vanished)
        rows, self._rows = self._rows, []
        batches, self._batches = self._batches, []
        # canonicalize each part once, then fold with rank-merges — pushed
        # batches that are already consolidated (the common generator path)
        # are never re-sorted
        parts = [b if done else b.consolidate() for b, done in batches]
        if rows:
            parts.append(Batch.from_tuples(
                rows, self.key_dtypes, self.val_dtypes))
        if not parts:
            return Batch.empty(self.key_dtypes, self.val_dtypes,
                               lead=(workers,) if workers > 1 else ())
        acc = parts[0]
        for p in parts[1:]:
            acc = acc.merge_with(p)
        if self.lineage_tap is not None:
            # tapped BEFORE sharding: the tap is a 1-D host integral even
            # on a worker mesh (lineage readers union state host-side)
            self.lineage_tap.insert(acc)
        if workers > 1:
            # distribute by key hash over the mesh (the reference spreads
            # input across workers at the handle, input.rs:66-67/309-311)
            from dbsp_tpu.parallel.exchange import shard_batch

            acc = shard_batch(acc, rt.mesh).shrink_to_fit()
        return acc

    def state_dict(self):
        # host checkpoints carry the lineage tap so restored pipelines
        # keep answering provenance queries (the pending buffers stay
        # transient — consumed counts are the controller's to persist)
        if self.lineage_tap is not None:
            return {"lineage_tap": self.lineage_tap}
        return {}

    def load_state_dict(self, state):
        tap = state.get("lineage_tap")
        if tap is not None:
            self.lineage_tap = tap


class InputHandle:
    """Host-side feeder for a :class:`ZSetInput` (reference:
    ``CollectionHandle``, input.rs:591)."""

    def __init__(self, op: ZSetInput):
        self._op = op

    def push(self, row: Row, weight: int = 1) -> None:
        self._op._rows.append((row, weight))

    def extend(self, rows: Sequence[Tuple[Row, int]]) -> None:
        self._op._rows.extend(rows)

    def push_batch(self, batch: Batch, consolidated: bool = False) -> None:
        """Zero-copy path: feed an already-built (device) batch. Pass
        ``consolidated=True`` when the batch already satisfies the
        consolidated invariant (sorted, unique, dead sentinel tail) to skip
        its canonicalization sort."""
        self._op._batches.append((batch, consolidated))


class OutputOperator(SinkOperator):
    name = "output"

    # lagging consumers coalesce their backlog past this many queued deltas
    MAX_QUEUED = 256

    # build-time view-mode stamp (set by ``output()``): True when the
    # stream feeding this sink ends in ``integrate()``, i.e. every emitted
    # batch is the FULL INTEGRAL of the view (the read plane serves
    # "last"), not a per-tick delta to fold
    integral = False

    def __init__(self):
        self.current: Optional[Batch] = None
        self.step_id = 0  # monotone tick counter (lets HTTP readers dedup)
        self._consumers: Dict[int, List[Batch]] = {}
        self._next_cid = 0

    def eval(self, v: Batch) -> None:
        if isinstance(v, Batch) and v.sharded:
            # collapse to one host-side batch so every consumer (tests,
            # transports, HTTP readers) sees worker-count-independent output
            from dbsp_tpu.parallel.exchange import unshard_batch

            v = unshard_batch(v)
        self.current = v
        self.step_id += 1
        for q in self._consumers.values():
            q.append(v)
            if len(q) > self.MAX_QUEUED:
                # Z-set deltas compose additively, so a backlog coalesces to
                # their sum without losing information
                q[:] = [concat_batches(q).consolidate().shrink_to_fit()]


class OutputHandle:
    """Reads the value a stream produced in the latest step (reference:
    ``OutputHandle::take_from_all/consolidate``, output.rs:173-219).

    Multiple consumers (e.g. an output transport endpoint AND the HTTP
    server's ``/read``) must not share the destructive :meth:`take`: each
    should :meth:`register_consumer` and poll :meth:`read_consumer`, which
    delivers every delta exactly once per consumer (a slow consumer gets
    the Z-set sum of everything it missed, never a gap).
    """

    def __init__(self, op: OutputOperator):
        self._op = op

    def take(self) -> Optional[Batch]:
        v, self._op.current = self._op.current, None
        return v

    def peek(self) -> Optional[Batch]:
        return self._op.current

    @property
    def step_id(self) -> int:
        """Tick counter of the latest produced batch."""
        return self._op.step_id

    def register_consumer(self) -> int:
        cid = self._op._next_cid
        self._op._next_cid += 1
        self._op._consumers[cid] = []
        return cid

    def read_consumer(self, cid: int) -> Optional[Batch]:
        """Drain this consumer's pending deltas (coalesced into one batch)."""
        q = self._op._consumers[cid]
        if not q:
            return None
        out = q[0] if len(q) == 1 else \
            concat_batches(q).consolidate().shrink_to_fit()
        q.clear()
        return out

    def to_dict(self) -> Dict[Row, int]:
        v = self._op.current
        return {} if v is None else v.to_dict()

    @property
    def integral(self) -> bool:
        """True when emissions are full integrals (``integrate()`` tail),
        False for per-tick deltas — the read plane's mode switch."""
        return self._op.integral


def add_input_zset(circuit: Circuit, key_dtypes: Sequence,
                   val_dtypes: Sequence = ()) -> Tuple[Stream, InputHandle]:
    """reference: ``add_input_zset`` (input.rs:75). The returned stream's
    schema metadata propagates through schema-preserving operators."""
    from dbsp_tpu.circuit.runtime import Runtime

    op = ZSetInput(key_dtypes, val_dtypes)
    s = circuit.add_source(op)
    s.schema = (op.key_dtypes, op.val_dtypes)
    s.key_sharded = Runtime.worker_count() > 1  # sources hash-distribute
    s.shard_intent = True  # ... and would on any larger mesh too
    return s, InputHandle(op)


@stream_method
def output(self: Stream) -> OutputHandle:
    op = OutputOperator()
    # the `integrate()` builder ends in a _PlusNamed("integrate") node, so
    # the final node's operator name is a reliable build-time marker that
    # this sink sees full integrals every tick
    op.integral = getattr(self.node.operator, "name", "") == "integrate"
    self.circuit.add_sink(op, self)
    return OutputHandle(op)
