"""Semijoin, antijoin, and stream_fold — derived relational operators.

Reference: ``operator/semijoin.rs:38`` (``semijoin_stream``), ``antijoin``
(``operator/join.rs:298``), ``stream_fold``.

Composed from the core incremental operators (the reference does the same:
antijoin = A - A ⋉ distinct(keys(B))), so they inherit incrementality and
sharding for free.
"""

from __future__ import annotations

from typing import Any, Callable

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.zset.batch import Batch


@stream_method
def keys_distinct(self: Stream) -> Stream:
    """Distinct set of this indexed Z-set's keys (drops value columns)."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "keys_distinct")
    key_dtypes = schema[0]
    projected = self.map_rows(lambda k, v: (k, ()), key_dtypes, (),
                              name="keys")
    return projected.distinct()


@stream_method
def semijoin(self: Stream, other: Stream) -> Stream:
    """Rows of self whose key appears in other (semijoin.rs:38) —
    incremental; preserves self's weights (multiplied by key presence)."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "semijoin")
    return self.join_index(
        other.keys_distinct(),
        lambda k, lv, rv: (k, lv),
        schema[0], schema[1], name="semijoin")


@stream_method
def antijoin(self: Stream, other: Stream) -> Stream:
    """Rows of self whose key does NOT appear in other (join.rs:298)."""
    return self.minus(self.semijoin(other))


class StreamFold(UnaryOperator):
    """Host-side running fold over the stream's per-tick batches
    (reference: ``stream_fold``); the accumulator is any Python/device value.
    """

    name = "stream_fold"

    def __init__(self, init: Any, fold: Callable[[Any, Batch], Any]):
        self.init = init
        self.fold = fold
        self.acc = init

    def clock_start(self, scope: int) -> None:
        self.acc = self.init

    def eval(self, batch: Batch) -> Any:
        self.acc = self.fold(self.acc, batch)
        return self.acc

    def state_dict(self):
        return {"acc": self.acc}

    def load_state_dict(self, state):
        self.acc = state["acc"]


@stream_method
def stream_fold(self: Stream, init: Any, fold) -> Stream:
    return self.circuit.add_unary_operator(StreamFold(init, fold), self)
