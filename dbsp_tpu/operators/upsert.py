"""Upsert inputs: keyed set/map semantics over the Z-set engine.

Reference: ``operator/input.rs`` ``add_input_set`` (:230) / ``add_input_map``
(:313) and the upsert->delta conversion in ``operator/upsert.rs:37``: the
host pushes (key, new value | delete) commands; the operator diffs them
against the maintained state to emit exact Z-set deltas (retract old value,
insert new).

TPU shape: touched keys probe the internal spine (same grow-on-demand group
gather as aggregates); retractions are the gathered live rows negated; the
inserts are the new values; one consolidation fuses both.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.circuit.operator import SourceOperator
from dbsp_tpu.operators.aggregate import GroupGather, concat_parts
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, Row, bucket_cap, concat_batches


@jax.jit
def _retractions(qrow: jnp.ndarray, qkeys, val_cols, w: jnp.ndarray) -> Batch:
    """Gathered (qrow, vals, w) rows -> negated live rows keyed by qkeys[qrow]."""
    cols, w = kernels.consolidate_cols((qrow, *val_cols), w)
    qrow, val_cols = cols[0], cols[1:]
    live = w > 0
    keys = tuple(
        jnp.where(live, k[jnp.clip(qrow, 0, k.shape[0] - 1)],
                  kernels.sentinel_for(k.dtype))
        for k in qkeys)
    out_cols, out_w = kernels.compact((*keys, *val_cols),
                                      jnp.where(live, -w, 0), live)
    return Batch(out_cols[: len(keys)], out_cols[len(keys):], out_w)


class UpsertInput(SourceOperator):
    """Source converting host upserts into deltas against maintained state."""

    name = "upsert_input"

    def __init__(self, key_dtypes: Sequence, val_dtypes: Sequence):
        self.key_dtypes = tuple(key_dtypes)
        self.val_dtypes = tuple(val_dtypes)
        self.spine = Spine(self.key_dtypes, self.val_dtypes)
        self._pending: Dict[Row, Optional[Row]] = {}
        self._gather = GroupGather()

    def eval(self) -> Batch:
        from dbsp_tpu.circuit.runtime import Runtime

        rt = Runtime.current()
        workers = rt.workers if rt is not None else 1
        if not self._pending:
            return Batch.empty(self.key_dtypes, self.val_dtypes,
                               lead=(workers,) if workers > 1 else ())
        # swap-first (atomic under the GIL): upserts arriving from other
        # threads during the (jit-compiling) drain below belong to the
        # next tick — a clear-after-read would destroy them
        pending, self._pending = self._pending, {}
        items = list(pending.items())

        # touched keys (sorted batch of unique keys)
        qcap = bucket_cap(len(items))
        kcols = [np.empty((len(items),), jnp.dtype(d)) for d in self.key_dtypes]
        for i, (k, _) in enumerate(items):
            for j, c in enumerate(kcols):
                c[i] = k[j]
        order = sorted(range(len(items)), key=lambda i: items[i][0])
        qkeys = tuple(
            jnp.concatenate([jnp.asarray(c[order]),
                             kernels.sentinel_fill((qcap - len(items),),
                                                   c.dtype)])
            for c in kcols)
        qlive = jnp.arange(qcap) < len(items)

        parts = []
        gathered = self._gather(qkeys, qlive, self.spine.batches, qcap)
        if gathered is not None:
            g = concat_parts(gathered)
            parts.append(_retractions(g[0], qkeys, g[1], g[2]))
        inserts = [((*(k), *(v)), 1) for k, v in items if v is not None]
        if inserts:
            parts.append(Batch.from_tuples(inserts, self.key_dtypes,
                                           self.val_dtypes))
        if not parts:
            return Batch.empty(self.key_dtypes, self.val_dtypes,
                               lead=(workers,) if workers > 1 else ())
        delta = parts[0] if len(parts) == 1 else \
            concat_batches(parts).consolidate().shrink_to_fit()
        # upsert state diffing stays host-side (the spine above); only the
        # emitted delta is distributed over the mesh
        self.spine.insert(delta)
        if workers > 1:
            from dbsp_tpu.parallel.exchange import shard_batch

            return shard_batch(delta, rt.mesh).shrink_to_fit()
        return delta


    def take_commands(self) -> Batch:
        """Drain pending upserts as a COMMAND batch for the compiled path
        (cnodes.CUpsertIn): unique sorted keys; weight +1 rows carry the
        new values, -1 rows are deletes (values zero-filled)."""
        # swap-first (atomic under the GIL): commands upserted from other
        # threads while this drain runs must land in the next tick, not
        # vanish in a clear-after-read (same race as ZSetInput.eval)
        pending, self._pending = self._pending, {}
        items = sorted(pending.items())
        rows = []
        for k, v in items:
            if v is None:
                rows.append(((*k, *([0] * len(self.val_dtypes))), -1))
            else:
                rows.append(((*k, *v), 1))
        return Batch.from_tuples(rows, self.key_dtypes, self.val_dtypes)

    def state_dict(self):
        assert not self._pending, (
            "cannot checkpoint with undrained upserts pending — step() first")
        return {"spine": self.spine}

    def load_state_dict(self, state):
        self.spine = state["spine"]


class UpsertHandle:
    """Host feeder (reference: ``UpsertHandle``, input.rs:747)."""

    def __init__(self, op: UpsertInput):
        self._op = op

    def upsert(self, key: Row, val: Optional[Row]) -> None:
        """Insert/replace the value under ``key``; None deletes (last write
        per key within a tick wins)."""
        self._op._pending[tuple(key)] = None if val is None else tuple(val)

    def delete(self, key: Row) -> None:
        self.upsert(key, None)


def add_input_map(circuit: Circuit, key_dtypes: Sequence,
                  val_dtypes: Sequence) -> Tuple[Stream, UpsertHandle]:
    """Keyed map input: at most one live value per key (input.rs:313)."""
    from dbsp_tpu.circuit.runtime import Runtime

    op = UpsertInput(key_dtypes, val_dtypes)
    s = circuit.add_source(op)
    s.schema = (op.key_dtypes, op.val_dtypes)
    s.key_sharded = Runtime.worker_count() > 1  # deltas are hash-distributed
    s.shard_intent = True  # ... and would be on any larger mesh too
    return s, UpsertHandle(op)


def add_input_set(circuit: Circuit, key_dtypes: Sequence
                  ) -> Tuple[Stream, UpsertHandle]:
    """Set input: membership toggled by upsert/delete (input.rs:230)."""
    return add_input_map(circuit, key_dtypes, ())
