"""Linear per-record operators: map, filter, flat_map, map_index.

Reference surface: the ``FilterMap`` trait family
(``operator/filter_map.rs:67,124,143``) and ``operator/index.rs:29,61``.

TPU-native shape: the user function is a *traced columnar transform* — it
receives the batch's columns as device arrays and returns new key/value
columns — so one jitted kernel handles the whole batch (no per-record host
calls, unlike the reference's per-record closures). Row validity rides on the
weight column: transforms run on dead (sentinel) rows too, but their weight
stays 0 and consolidation drops them — user functions therefore must be
total (no assertions on padding garbage), which numeric jnp ops are.

A ``RowFn`` takes ``(key_cols, val_cols)`` (tuples of [cap] arrays) and
returns ``(new_key_cols, new_val_cols)`` of the same length.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import CircuitError, Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.parallel.lift import lifted_op
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch

Cols = Tuple[jnp.ndarray, ...]
RowFn = Callable[[Cols, Cols], Tuple[Cols, Cols]]
PredFn = Callable[[Cols, Cols], jnp.ndarray]


def _pin_schema(nk: Cols, nv: Cols, out_schema, name: str
                ) -> Tuple[Cols, Cols]:
    """Cast transform outputs to the declared (key_dtypes, val_dtypes) so
    downstream spines/probes never see drifted dtypes (silent truncation in
    lex_probe was the failure mode)."""
    kd, vd = out_schema
    assert len(nk) == len(kd) and len(nv) == len(vd), (
        f"{name}: transform arity ({len(nk)},{len(nv)}) != "
        f"declared schema arity ({len(kd)},{len(vd)})")
    return (tuple(c.astype(d) for c, d in zip(nk, kd)),
            tuple(c.astype(d) for c, d in zip(nv, vd)))


class MapOp(UnaryOperator):
    """Per-row transform + re-consolidation (transforms may collide rows).

    ``preserves_order=True`` asserts the transform is monotone w.r.t. the
    row order (e.g. currency scaling, dropping trailing columns) and skips
    the re-sort. Monotonicity means colliding outputs are contiguous, so the
    kernel still merges duplicates (segment-sum + compact) to uphold the
    consolidated-batch invariant — it only skips the sort itself.
    """

    def __init__(self, fn: RowFn, name: str = "map",
                 preserves_order: bool = False, out_schema=None):
        self.fn = fn
        self.name = name
        self.preserves_order = preserves_order
        self.out_schema = out_schema  # (key_dtypes, val_dtypes) or None
        self._kernel = jax.jit(self._inner)

    def _inner(self, batch: Batch) -> Batch:
        nk, nv = self.fn(batch.keys, batch.vals)
        nk, nv = tuple(nk), tuple(nv)
        if self.out_schema is not None:
            nk, nv = _pin_schema(nk, nv, self.out_schema, self.name)
        if self.preserves_order:
            # sort-free consolidation: inputs are sorted and the map is
            # monotone, so equal output rows are adjacent (dead rows got
            # garbage transforms but weight 0 and merge/drop cleanly)
            cap = batch.cap
            live = batch.weights != 0
            cols = tuple(
                jnp.where(live, c, kernels.sentinel_for(c.dtype))
                for c in (*nk, *nv))
            dup = kernels.rows_equal_prev(cols, n=cap) & live
            seg = jnp.cumsum(~dup) - 1
            sums = jax.ops.segment_sum(batch.weights, seg,
                                       num_segments=cap)
            w = jnp.where(dup, 0, sums[seg]).astype(batch.weights.dtype)
            cols, w = kernels.compact(cols, w, w != 0)
        else:
            cols, w = kernels.consolidate_cols((*nk, *nv), batch.weights)
        # both paths emit a canonical batch: one sorted run
        return Batch(cols[: len(nk)], cols[len(nk):], w,
                     runs=(batch.cap,))

    def _inner_raw(self, batch: Batch) -> Batch:
        """Transform WITHOUT the trailing consolidation — the compiled
        placement pass dispatches here when every consumer canonicalizes
        anyway (row-wise transforms commute with netting). Dead rows keep
        sentinel cols + 0 weight; output order is unknown (runs=None)."""
        nk, nv = self.fn(batch.keys, batch.vals)
        nk, nv = tuple(nk), tuple(nv)
        if self.out_schema is not None:
            nk, nv = _pin_schema(nk, nv, self.out_schema, self.name)
        live = batch.weights != 0
        cols = tuple(jnp.where(live, c, kernels.sentinel_for(c.dtype))
                     for c in (*nk, *nv))
        return Batch(cols[: len(nk)], cols[len(nk):],
                     jnp.where(live, batch.weights, 0))

    def eval(self, batch: Batch) -> Batch:
        if batch.sharded:
            return lifted_op(self)(batch)
        return self._kernel(batch)


class FilterOp(UnaryOperator):
    """Keep rows where the predicate holds. Input order is preserved, so the
    kernel is a mask + compaction — no sort."""

    def __init__(self, pred: PredFn, name: str = "filter"):
        self.pred = pred
        self.name = name
        self._kernel = jax.jit(self._inner)

    def _inner(self, batch: Batch) -> Batch:
        keep = self.pred(batch.keys, batch.vals) & (batch.weights != 0)
        return batch.compacted(keep)

    def eval(self, batch: Batch) -> Batch:
        if batch.sharded:
            return lifted_op(self)(batch)
        return self._kernel(batch)


class FlatMapOp(UnaryOperator):
    """Each row expands to up to ``fanout`` rows (static bound, XLA shapes).

    ``fn(keys, vals) -> (new_keys, new_vals, keep)`` where each new column has
    shape [fanout, cap] and keep is a [fanout, cap] bool mask. Reference:
    ``flat_map`` (filter_map.rs:143) — the static bound replaces the
    reference's unbounded per-record iterators.
    """

    def __init__(self, fn, fanout: int, name: str = "flat_map",
                 out_schema=None):
        self.fn = fn
        self.fanout = fanout
        self.name = name
        self.out_schema = out_schema
        self._kernel = jax.jit(self._inner)

    def _inner(self, batch: Batch) -> Batch:
        nk, nv, keep = self.fn(batch.keys, batch.vals)
        nk, nv = tuple(nk), tuple(nv)
        if self.out_schema is not None:
            nk, nv = _pin_schema(nk, nv, self.out_schema, self.name)
        cap = batch.cap
        f = self.fanout
        w = jnp.broadcast_to(batch.weights, (f, cap))
        w = jnp.where(keep, w, 0).reshape(f * cap)
        flat_k = tuple(c.reshape(f * cap) for c in nk)
        flat_v = tuple(c.reshape(f * cap) for c in nv)
        cols, w = kernels.consolidate_cols((*flat_k, *flat_v), w)
        return Batch(cols[: len(flat_k)], cols[len(flat_k):], w,
                     runs=(f * cap,))

    def _inner_raw(self, batch: Batch) -> Batch:
        """Expansion without the trailing consolidation (see MapOp)."""
        nk, nv, keep = self.fn(batch.keys, batch.vals)
        nk, nv = tuple(nk), tuple(nv)
        if self.out_schema is not None:
            nk, nv = _pin_schema(nk, nv, self.out_schema, self.name)
        cap = batch.cap
        f = self.fanout
        w = jnp.broadcast_to(batch.weights, (f, cap))
        w = jnp.where(keep, w, 0).reshape(f * cap)
        live = w != 0
        cols = tuple(jnp.where(live, c.reshape(f * cap),
                               kernels.sentinel_for(c.dtype))
                     for c in (*nk, *nv))
        return Batch(cols[: len(nk)], cols[len(nk):], w)

    def eval(self, batch: Batch) -> Batch:
        if batch.sharded:
            return lifted_op(self)(batch)
        return self._kernel(batch)


# -- Stream sugar -----------------------------------------------------------


def _set_schema(s: Stream, key_dtypes, val_dtypes) -> Stream:
    s.schema = (tuple(key_dtypes), tuple(val_dtypes))
    return s


@stream_method
def map_rows(self: Stream, fn: RowFn, key_dtypes, val_dtypes=(),
             name: str = "map", preserves_order: bool = False,
             preserves_first_key: bool = False) -> Stream:
    """General columnar map; declares the output schema (transform outputs
    are cast to it, so declared and device dtypes cannot drift).

    ``preserves_first_key=True`` asserts every output row's FIRST key
    column equals the input row's first key column (e.g. re-keying on the
    same leading column, projecting value columns). Rows then stay on
    their hash-assigned worker, so the stream keeps its ``key_sharded``
    placement and a downstream shard() elides its all_to_all — the
    exchange fast path."""
    out = self.circuit.add_unary_operator(
        MapOp(fn, name, preserves_order,
              out_schema=(tuple(jnp.dtype(d) for d in key_dtypes),
                          tuple(jnp.dtype(d) for d in val_dtypes))), self)
    if preserves_first_key:
        out.key_sharded = getattr(self, "key_sharded", False)
    return _set_schema(out, key_dtypes, val_dtypes)


@stream_method
def filter_rows(self: Stream, pred: PredFn, name: str = "filter") -> Stream:
    out = self.circuit.add_unary_operator(FilterOp(pred, name), self)
    out.schema = getattr(self, "schema", None)
    # filtering moves no rows between workers: placement survives
    out.key_sharded = getattr(self, "key_sharded", False)
    return out


@stream_method
def flat_map_rows(self: Stream, fn, fanout: int, key_dtypes, val_dtypes=(),
                  name: str = "flat_map") -> Stream:
    out = self.circuit.add_unary_operator(
        FlatMapOp(fn, fanout, name,
                  out_schema=(tuple(jnp.dtype(d) for d in key_dtypes),
                              tuple(jnp.dtype(d) for d in val_dtypes))), self)
    return _set_schema(out, key_dtypes, val_dtypes)


@stream_method
def index_by(self: Stream, key_fn: Callable[[Cols, Cols], Cols],
             key_dtypes, val_fn: Callable[[Cols, Cols], Cols] = None,
             val_dtypes=None, name: str = "index",
             preserves_first_key: bool = False) -> Stream:
    """Re-key a Z-set (reference: ``index_with``, operator/index.rs:61).

    The resulting batch's key columns are what joins/aggregates group by.
    ``preserves_first_key=True``: the new first key column is the old one
    (``key_fn`` returns ``(k[0], ...)``), so hash placement survives and
    downstream exchanges elide (see :func:`map_rows`).
    """
    if val_fn is None:
        val_fn = lambda k, v: (*k, *v)  # noqa: E731
        schema = getattr(self, "schema", None)
        if schema is None and val_dtypes is None:
            raise CircuitError(
                "index_by needs val_dtypes when the input stream has no "
                "schema")
        if val_dtypes is None:
            val_dtypes = (*schema[0], *schema[1])
    fn = lambda k, v: (key_fn(k, v), val_fn(k, v))  # noqa: E731
    return map_rows(self, fn, key_dtypes, val_dtypes, name=name,
                    preserves_first_key=preserves_first_key)
