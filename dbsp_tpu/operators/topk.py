"""Incremental per-key top-K: maintain the K extreme rows of each group.

The reference expresses top-K via SQL window functions (ROW_NUMBER <= K,
compiled by its SQL frontend into per-key sorted traversals); engine-side it
is the same delta pattern as aggregation (``aggregate/mod.rs:600``): for keys
touched by the delta, recompute the group's top-K from the input trace and
diff against the previous output.

TPU shape: gather touched groups (grow-on-demand expansion), consolidate,
then a segmented rank computed from cumulative-sum algebra — rank-from-end
``r`` of a present row within its group is O(1) from prefix sums, no sort
beyond the consolidation's. Rows with rank < K (ordered lexicographically by
the value columns; ``largest`` picks the tail) form the new top-K set;
deltas are new(+1) + old(-1) consolidated.

Ordering contract: rows rank by their VALUE columns lexicographically —
index the stream so the priority column(s) come first (e.g. for "last 10 by
close time", vals = (close_ts, ...)). Set semantics: a row with multiplicity
w > 1 occupies one slot.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.aggregate import (GroupGather, _unique_keys,
                                          concat_parts)
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, concat_batches


def _topk_rows_impl(qrow, qkeys, val_cols, w, k: int, largest: bool,
                    weight_sign: int, q_cap: int) -> Batch:
    """Select the top-K present rows per q segment; emit with ±1 weights.

    Segment ids are query-slot indices in [0, q_cap) — sized by q_cap (like
    aggregate's _reduce_groups), NOT by the gathered-row count, which can be
    smaller when the gather capacity cache was trained on denser deltas."""
    cols, w = kernels.consolidate_cols((qrow, *val_cols), w)
    qrow, val_cols = cols[0], cols[1:]
    present = w > 0
    seg = qrow  # consolidation sorted by (qrow, vals); dead rows at the end
    cum = jnp.cumsum(present)
    base_src = cum - jnp.where(present, 1, 0)
    num_seg = q_cap + 1
    seg_ids = jnp.where((qrow >= 0) & (qrow < q_cap), qrow,
                        q_cap).astype(jnp.int32)
    base = jax.ops.segment_min(base_src, seg_ids, num_segments=num_seg)
    total = jax.ops.segment_sum(jnp.where(present, 1, 0), seg_ids,
                                num_segments=num_seg)
    within = cum - base[seg_ids]          # 1-based rank among present rows
    if largest:
        rank = total[seg_ids] - within    # 0 == last (largest) present row
    else:
        rank = within - 1                 # 0 == first (smallest)
    keep = present & (rank < k) & (qrow >= 0)
    keys = tuple(
        jnp.where(keep, kc[jnp.clip(qrow, 0, kc.shape[0] - 1)],
                  kernels.sentinel_for(kc.dtype))
        for kc in qkeys)
    out_w = jnp.where(keep, weight_sign, 0).astype(w.dtype)
    out_cols, out_w = kernels.compact((*keys, *val_cols), out_w, keep)
    nk = len(qkeys)
    return Batch(out_cols[:nk], out_cols[nk:], out_w)


_topk_rows_jit = jax.jit(_topk_rows_impl,
                         static_argnames=("k", "largest", "weight_sign",
                                          "q_cap"))


def _topk_rows_factory(k: int, largest: bool, weight_sign: int, q_cap: int):
    return lambda qrow, qkeys, val_cols, w: _topk_rows_impl(
        qrow, qkeys, val_cols, w, k, largest, weight_sign, q_cap)


def _topk_rows(qrow, qkeys, val_cols, w, k, largest, weight_sign, q_cap):
    """Dispatch: per-worker under the mesh when the parts are sharded."""
    if w.ndim > 1:
        from dbsp_tpu.parallel.lift import lifted

        return lifted(_topk_rows_factory, k, largest, weight_sign, q_cap)(
            qrow, qkeys, val_cols, w)
    return _topk_rows_jit(qrow, qkeys, val_cols, w, k, largest, weight_sign,
                          q_cap)


class TopKOp(UnaryOperator):
    def __init__(self, k: int, schema, largest: bool = True, name=None):
        self.k = k
        self.largest = largest
        self.schema = schema
        self.name = name or f"topk<{k}>"
        self.out_spine = Spine(*schema)
        self._group_gather = GroupGather()
        self._old_gather = GroupGather()

    def clock_start(self, scope: int) -> None:
        if scope > 0:
            self.out_spine = Spine(*self.schema)

    def eval(self, view: TraceView) -> Batch:
        delta = view.delta
        nk = len(self.schema[0])
        if int(delta.live_count()) == 0:
            return Batch.empty(*self.schema,
                               lead=tuple(delta.weights.shape[:-1]))
        qkeys, qlive = _unique_keys(delta, nk)
        q_cap = qlive.shape[-1]  # trimmed to distinct-key bucket
        parts = []
        gathered = self._group_gather(qkeys, qlive, view.spine.batches, q_cap)
        if gathered is not None:
            g = concat_parts(gathered)
            parts.append(_topk_rows(g[0], qkeys, g[1], g[2],
                                    self.k, self.largest, 1, q_cap))
        old = self._old_gather(qkeys, qlive, self.out_spine.batches, q_cap)
        if old is not None:
            # previous top-K rows of the touched keys, retracted; K is
            # larger than any group's slot count so keep=present suffices
            o = concat_parts(old)
            parts.append(_topk_rows(o[0], qkeys, o[1], o[2],
                                    self.k, self.largest, -1, q_cap))
        if not parts:
            return Batch.empty(*self.schema)
        out = parts[0] if len(parts) == 1 else \
            concat_batches(parts).consolidate().shrink_to_fit()
        self.out_spine.insert(out)
        return out

    def state_dict(self):
        return {"out_spine": self.out_spine}

    def load_state_dict(self, state):
        self.out_spine = state["out_spine"]


@stream_method
def topk(self: Stream, k: int, largest: bool = True, name=None) -> Stream:
    """Top-K rows per key, ordered by the value columns (see module doc)."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "topk")
    # sharded streams stay sharded: rows are key-hash distributed, so every
    # group lives wholly on one worker and per-worker top-K unions exactly
    # (the reference's window-function path self-shards the same way)
    t = self.trace()
    out = self.circuit.add_unary_operator(
        TopKOp(k, (tuple(schema[0]), tuple(schema[1])), largest, name), t)
    out.schema = schema
    out.key_sharded = getattr(t, "key_sharded", False)
    return out
