"""The trace operator: shares one integrated spine of a stream among
consumers, with delayed access for bilinear operators.

Reference: ``operator/trace.rs`` — ``Stream::trace`` (:173),
``integrate_trace`` (:238), ``delay_trace`` (:312), and the circuit-cache
sharing so a stream's trace is built once (``circuit/cache.rs``).

Design notes vs the reference:
* ``TraceOp`` appends this tick's delta to a :class:`~dbsp_tpu.trace.Spine`
  and emits the spine object itself on the stream (operators downstream probe
  it; spines are host objects owning device batches).
* The reference splits Z1Trace/UntimedTraceAppend to get "trace as of the
  previous tick" vs "including this tick". Here ``TraceOp`` emits a
  ``TraceView`` that exposes both: ``delayed`` (levels before this tick's
  append — what bilinear join needs for one side) and ``current``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset.batch import Batch


@dataclasses.dataclass
class TraceView:
    """What downstream operators see on a trace stream each tick.

    ``spine``      — the spine AFTER appending this tick's delta.
    ``delta``      — this tick's delta batch.
    ``pre_levels`` — snapshot of the spine's level list BEFORE the append
                     (the z^-1 trace view; batches are immutable so the
                     snapshot is free).
    """

    spine: Spine
    delta: Batch
    pre_levels: List[Batch]


class TraceOp(UnaryOperator):
    """Maintains the integral of a stream as a spine (integrate_trace)."""

    name = "trace"

    def __init__(self, key_dtypes, val_dtypes):
        self.key_dtypes = key_dtypes
        self.val_dtypes = val_dtypes
        self.spine = Spine(key_dtypes, val_dtypes)

    def clock_start(self, scope: int) -> None:
        if scope > 0:
            # nested clock: child state resets each parent tick (nested.py)
            self.spine = Spine(self.key_dtypes, self.val_dtypes)

    def eval(self, delta: Batch) -> TraceView:
        pre = list(self.spine.batches)
        self.spine.clear_dirty()  # dirty == "this tick's delta was nonempty"
        self.spine.insert(delta)
        return TraceView(self.spine, delta, pre)

    def metadata(self):
        return {"levels": len(self.spine.batches),
                "total_cap": self.spine.total_cap}

    def fixedpoint(self, scope: int) -> bool:
        return not self.spine.dirty

    def state_dict(self):
        return {"spine": self.spine}

    def load_state_dict(self, state):
        self.spine = state["spine"]


@stream_method
def trace(self: Stream, shard: bool = True) -> Stream:
    """Stream of TraceViews of this stream's integral; built once per source
    stream via the circuit cache (reference: trace.rs:173 + cache.rs).

    Under a multi-worker runtime the stream is hash-sharded first so each
    worker's spine holds a disjoint key slice — the reference's stateful
    operators call shard() on their inputs the same way (shard.rs:89,
    join.rs:268-270). ``shard=False`` instead collapses the stream to a
    host-resident trace — only for consumers whose access pattern is not
    hash-local (range partitioning: join_range); hash-keyed consumers
    (join/aggregate/distinct/topk/window/rolling) are all shard-lifted."""
    from dbsp_tpu.operators.registry import require_schema

    src = self.shard() if shard else self.unshard()
    key = ("trace", src.node_index)
    cached = src.circuit.cache.get(key)
    if cached is not None:
        return cached
    schema = require_schema(src, "trace()")
    out = src.circuit.add_unary_operator(TraceOp(*schema), src)
    out.schema = schema
    out.key_sharded = getattr(src, "key_sharded", False)
    src.circuit.cache[key] = out
    return out
