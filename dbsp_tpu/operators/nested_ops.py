"""Nested-timestamp operators: join and distinct that are incremental
ACROSS parent ticks inside a recursive (fixedpoint) child circuit.

Reference: ``time/nested_ts32.rs:34`` ((epoch, iteration) timestamps),
``operator/recursive.rs:255-276``, the nested-scope ``DistinctIncremental``
(distinct.rs) and nested ``JoinTrace`` over timed ``OrdValBatch`` traces, and
``trace/mod.rs:93-118`` (``recede_to`` time compression).

The model: inside a ``recursive()`` child, streams carry 2-d deltas
``δ(e, i)`` (epoch = parent tick, i = child iteration). Operators must be
incremental over the PRODUCT lattice of the two clocks.

**Join.** With ``z(e,i) = Σ_{e'<=e, i'<=i} δ`` (the 2-d integral over the
product lattice), expanding the four corners of ``D_e D_i (zA ⋈ zB)`` with
``zX(e,i) = PX(i) + cX(i-1) + δX`` — ``PX(i)`` = previous epochs' rows at
iterations <= i, ``cX`` = the current epoch's accumulation — gives seven
delta-proportional terms::

    out(e,i) = δA ⋈ PB(i)   + δA ⋈ cB(i-1) + δA ⋈ δB
             + PA(i) ⋈ δB   + cA(i-1) ⋈ δB
             + a2 ⋈ cB(i-1) + cA(i-1) ⋈ b2

where ``a2/b2`` = previous epochs' rows at EXACTLY iteration i. Note
``PX(i)`` is iteration-bounded — using the prev-epoch total instead (the
obvious mistake) derives facts from state the feedback hasn't produced yet
at iteration i and breaks deletion propagation. The operator keeps, per
side: a row-keyed prev-epoch spine whose value columns carry the iteration
tag (probes mask weights to tags <= i), a current-epoch row-keyed spine,
and a prev-epoch spine keyed (iteration, row...) whose contiguous
iteration slices supply a2/b2.

**Distinct.** ``out(e,i) = [z(e,i)>0] - [z(e-1,i)>0] - [z(e,i-1)>0]
+ [z(e-1,i-1)>0]`` per row — the 2-d differentiation of set-projection of
the 2-d integral. Corner sums split into P(j) = prev-epoch weight with
iteration <= j (needs an iteration-resolved per-row trace: a spine keyed by
row with an iteration value column) and C(j) = current-epoch weight (plain
row-keyed sums). Rows to evaluate at iteration i: the delta's rows plus any
row touched earlier THIS epoch whose previous epochs have weight at exactly
iteration i (those corners shift even with an empty delta).

**Termination.** Cross/corner terms can fire at iterations where the
current epoch's delta is already empty, so ``fixedpoint()`` holds the child
clock open until the iteration count passes the deepest iteration any past
epoch was active at (``max_prev_iter``) — the executor's condition check
(empty δ) plus this bound give exact termination.

Epoch end (``clock_end``) folds the epoch's per-iteration deltas into the
persistent spines. Identical (row, iteration) entries from different epochs
cancel by weight there — the analog of ``recede_to``'s compression of
historical times.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import BinaryOperator, UnaryOperator
from dbsp_tpu.operators.aggregate import GroupGather, _unique_keys
from dbsp_tpu.operators.join import JoinCore, JoinFn
from dbsp_tpu.parallel.lift import lifted, worker_scalar
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches

ITER_DTYPE = jnp.int64

# Sharded execution ([W, cap] batches inside a shard-lifted recursive
# child): every jitted kernel below keeps its single-worker body and gains
# a ``lifted`` dispatch — the factory builds the per-worker function, the
# SPMD wrapper squeezes the worker axis, and host-side grow-on-demand
# capacity checks take the WORST worker (np.max over the [W] totals). The
# child-clock iteration rides in as a ``worker_scalar`` runtime argument so
# iterating the fixedpoint never recompiles the SPMD programs.


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------


def _slice_iter_level_impl(level: Batch, it, out_cap: int):
    """Rows of an (iter, row...)-keyed level with iter == it, re-keyed to the
    row columns (iter stripped). Returns (cols..., weights, total)."""
    ik = level.keys[0]
    q = (jnp.full((1,), it, ik.dtype),)
    lo = kernels.lex_probe((ik,), q, side="left")
    hi = kernels.lex_probe((ik,), q, side="right")
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    w = jnp.where(valid, level.weights[src], 0)
    cols = tuple(jnp.where(valid, c[src], kernels.sentinel_for(c.dtype))
                 for c in (*level.keys[1:], *level.vals))
    return cols, w, total


_slice_iter_level = jax.jit(_slice_iter_level_impl,
                            static_argnames=("out_cap",))


def _slice_iter_level_factory(out_cap: int):
    return lambda level, it: _slice_iter_level_impl(level, it, out_cap)


class _IterSlicer:
    """Grow-on-demand driver extracting one iteration's slice per level."""

    def __init__(self):
        self.caps = {}

    @staticmethod
    def _launch(level: Batch, it: int, cap: int):
        if level.sharded:
            return lifted(_slice_iter_level_factory, cap)(
                level, worker_scalar(it, ITER_DTYPE))
        return _slice_iter_level(level, it, cap)

    def __call__(self, spine: Spine, it: int, nk: int,
                 out_schema) -> Optional[Batch]:
        """Consolidated batch of the spine's rows at iteration ``it``."""
        if not spine.batches:
            return None
        outs, totals, caps = [], [], []
        for level in spine.batches:
            cap = self.caps.get(level.cap, 64)
            cols, w, total = self._launch(level, it, cap)
            outs.append((cols, w))
            totals.append(total)
            caps.append(cap)
        for i, t in enumerate(jax.device_get(totals)):
            t = int(np.max(t))  # worst worker on sharded levels
            if t > caps[i]:
                cap = bucket_cap(t)
                self.caps[spine.batches[i].cap] = cap
                cols, w, _ = self._launch(spine.batches[i], it, cap)
                outs[i] = (cols, w)
        batches = [Batch(cols[:nk], cols[nk:], w) for cols, w in outs]
        out = batches[0] if len(batches) == 1 else \
            concat_batches(batches).consolidate()
        # slices are usually tiny vs the gather cap: re-bucket (one sync)
        return out.shrink_to_fit()


@jax.jit
def _presence(batch: Batch) -> Batch:
    """Weights clamped to {0, 1}: keeps row identity through unions where
    true weights could cancel."""
    return Batch(batch.keys, batch.vals,
                 jnp.where(batch.weights != 0, 1, 0).astype(jnp.int64))


def _with_iter_key(batch: Batch, it: int) -> Batch:
    """Prepend a constant iteration key column (for (iter, row...) spines)."""
    ic = jnp.where(batch.weights != 0, jnp.asarray(it, ITER_DTYPE),
                   kernels.sentinel_for(ITER_DTYPE))
    return Batch((ic, *batch.keys, *batch.vals), (), batch.weights)


def _with_iter_val(batch: Batch, it: int) -> Batch:
    """All row columns as keys + the iteration as the value column (for
    row-keyed iteration-resolved spines)."""
    ic = jnp.where(batch.weights != 0, jnp.asarray(it, ITER_DTYPE),
                   kernels.sentinel_for(ITER_DTYPE))
    return Batch((*batch.keys, *batch.vals), (ic,), batch.weights)


def _with_iter_tag(batch: Batch, it: int) -> Batch:
    """Keys kept, iteration appended as the LAST value column (for
    join-probeable prev-epoch spines whose weights get iteration-masked)."""
    ic = jnp.where(batch.weights != 0, jnp.asarray(it, ITER_DTYPE),
                   kernels.sentinel_for(ITER_DTYPE))
    return Batch(batch.keys, (*batch.vals, ic), batch.weights)


def _join_level_iter_le_impl(delta: Batch, level: Batch, it, nk: int,
                             fn: JoinFn, out_cap: int):
    """Like join._join_level_impl, but the level's LAST value column is an
    iteration tag: matches with tag > ``it`` contribute weight 0 (they are
    future state relative to the (epoch, i) corner being computed), and the
    tag is stripped before ``fn``."""
    dk = delta.keys[:nk]
    lk = level.keys[:nk]
    lo = kernels.lex_probe(lk, dk, side="left")
    hi = kernels.lex_probe(lk, dk, side="right")
    live = delta.weights != 0
    lo = jnp.where(live, lo, 0)
    hi = jnp.where(live, hi, lo)
    row, src, valid, total = kernels.expand_ranges(lo, hi, out_cap)
    tag = level.vals[-1][src]
    valid = valid & (tag <= it)
    w = jnp.where(valid, delta.weights[row] * level.weights[src], 0)
    key_cols = tuple(c[row] for c in delta.keys[:nk])
    lvals = tuple(c[row] for c in delta.vals)
    rvals = tuple(c[src] for c in level.vals[:-1])
    out_keys, out_vals = fn(key_cols, lvals, rvals)
    out_keys = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_keys)
    out_vals = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_vals)
    return Batch(out_keys, out_vals, w), total


_join_level_iter_le = jax.jit(_join_level_iter_le_impl,
                              static_argnames=("nk", "fn", "out_cap"))


def _join_level_iter_le_factory(nk: int, fn: JoinFn, out_cap: int):
    return lambda delta, level, it: _join_level_iter_le_impl(
        delta, level, it, nk, fn, out_cap)


class _MaskedJoinCore:
    """Grow-on-demand driver for iteration-masked joins against prev-epoch
    tagged spines (same shape as join.JoinCore)."""

    def __init__(self, nk: int, fn: JoinFn):
        self.nk = nk
        self.fn = fn
        self.caps = {}

    def _launch(self, delta: Batch, level: Batch, it: int, cap: int):
        if delta.sharded:
            return lifted(_join_level_iter_le_factory, self.nk, self.fn,
                          cap)(delta, level, worker_scalar(it, ITER_DTYPE))
        return _join_level_iter_le(delta, level,
                                   jnp.asarray(it, ITER_DTYPE), self.nk,
                                   self.fn, cap)

    def join_levels(self, delta: Batch, levels, it) -> List[Batch]:
        outs, totals, caps = [], [], []
        for level in levels:
            cap = self.caps.get(level.cap, max(64, delta.cap))
            out, total = self._launch(delta, level, it, cap)
            outs.append(out)
            totals.append(total)
            caps.append(cap)
        if not outs:
            return []
        for i, t in enumerate(jax.device_get(totals)):
            t = int(np.max(t))
            if t > caps[i]:
                cap = bucket_cap(t)
                self.caps[levels[i].cap] = cap
                outs[i], _ = self._launch(delta, levels[i], it, cap)
        return outs


# ---------------------------------------------------------------------------
# Nested join
# ---------------------------------------------------------------------------


class NestedJoinOp(BinaryOperator):
    """Bilinear incremental join over (epoch, iteration) time (module doc).

    Consumes the two RAW delta streams (it owns all its state; no shared
    trace operator)."""

    def __init__(self, fn: JoinFn, nk: int, in_schemas, out_schema,
                 child, name="nested-join"):
        self.name = name
        self.fn = fn
        self.nk = nk
        self.out_schema = out_schema
        self.child = child
        a_schema, b_schema = in_schemas
        self._a_schema, self._b_schema = a_schema, b_schema
        # previous epochs, row-keyed, iteration tag as last value column
        # (probes mask weights to tags <= i — these answer PX(i))
        self.prev_a = Spine(a_schema[0], (*a_schema[1], ITER_DTYPE))
        self.prev_b = Spine(b_schema[0], (*b_schema[1], ITER_DTYPE))
        # current-epoch accumulations at iterations < i, row-keyed
        self.cur_a = Spine(*a_schema)
        self.cur_b = Spine(*b_schema)
        # previous epochs' rows keyed (iteration, row...) — iteration slices
        self.slice_a = Spine((ITER_DTYPE, *a_schema[0], *a_schema[1]), ())
        self.slice_b = Spine((ITER_DTYPE, *b_schema[0], *b_schema[1]), ())
        self._epoch_a: List[Tuple[int, Batch]] = []
        self._epoch_b: List[Tuple[int, Batch]] = []
        self.max_prev_iter = 0
        flipped = (lambda k, rv, lv: fn(k, lv, rv))
        self._prev_az = _MaskedJoinCore(nk, fn)            # δA vs PB(i)
        self._prev_bz = _MaskedJoinCore(nk, flipped)       # δB vs PA(i)
        self._core_ac = JoinCore(nk, fn, out_schema)       # δA vs cB(i-1)
        self._core_bc = JoinCore(nk, flipped, out_schema)  # δB vs cA(i-1)
        self._core_dd = JoinCore(nk, fn, out_schema)       # δA vs δB
        self._core_a2 = JoinCore(nk, fn, out_schema)       # a2 vs cB(i-1)
        self._core_b2 = JoinCore(nk, flipped, out_schema)  # b2 vs cA(i-1)
        self._slicer_a = _IterSlicer()
        self._slicer_b = _IterSlicer()

    # -- clock protocol -----------------------------------------------------
    def clock_start(self, scope: int) -> None:
        if scope > 0:
            self.cur_a = Spine(*self._a_schema)
            self.cur_b = Spine(*self._b_schema)
            self._epoch_a, self._epoch_b = [], []

    def clock_end(self, scope: int) -> None:
        if scope > 0:
            last = 0
            for it, b in self._epoch_a:
                self.slice_a.insert(_with_iter_key(b, it))
                self.prev_a.insert(_with_iter_tag(b, it))
                last = max(last, it)
            for it, b in self._epoch_b:
                self.slice_b.insert(_with_iter_key(b, it))
                self.prev_b.insert(_with_iter_tag(b, it))
                last = max(last, it)
            self.max_prev_iter = max(self.max_prev_iter, last)
            self._epoch_a, self._epoch_b = [], []

    def fixedpoint(self, scope: int) -> bool:
        # corner terms can fire until the iteration count passes every past
        # epoch's deepest active iteration
        return self.child.iteration >= self.max_prev_iter

    # -- eval ---------------------------------------------------------------
    def eval(self, da: Batch, db: Batch) -> Batch:
        it = self.child.iteration
        outs: List[Batch] = []

        # every term below uses state STRICTLY BEFORE this tick's inserts
        # (cur_* = iterations < i); bookkeeping happens at the end
        a2 = self._slicer_a(self.slice_a, it, len(self._a_schema[0]),
                            self._a_schema)
        if a2 is not None:
            outs += self._core_a2.join_levels(a2, self.cur_b.batches)
        b2 = self._slicer_b(self.slice_b, it, len(self._b_schema[0]),
                            self._b_schema)
        if b2 is not None:
            outs += self._core_b2.join_levels(b2, self.cur_a.batches)

        outs += self._prev_az.join_levels(da, self.prev_b.batches, it)
        outs += self._core_ac.join_levels(da, self.cur_b.batches)
        outs += self._core_dd.join_levels(da, [db])
        outs += self._prev_bz.join_levels(db, self.prev_a.batches, it)
        outs += self._core_bc.join_levels(db, self.cur_a.batches)

        # bookkeeping for later iterations / epochs
        if int(da.live_count()) > 0:
            self.cur_a.insert(da)
            self._epoch_a.append((it, da))
        if int(db.live_count()) > 0:
            self.cur_b.insert(db)
            self._epoch_b.append((it, db))

        if not outs:
            return Batch.empty(*self.out_schema,
                               lead=tuple(da.weights.shape[:-1]))
        out = outs[0].consolidate() if len(outs) == 1 else \
            concat_batches(outs).consolidate()
        return out.shrink_to_fit()

    def state_dict(self):
        assert not self._epoch_a and not self._epoch_b, (
            "checkpoint mid-epoch not supported")
        return {"prev_a": self.prev_a, "prev_b": self.prev_b,
                "slice_a": self.slice_a, "slice_b": self.slice_b,
                "max_prev_iter": self.max_prev_iter}

    def load_state_dict(self, state):
        self.prev_a, self.prev_b = state["prev_a"], state["prev_b"]
        self.slice_a, self.slice_b = state["slice_a"], state["slice_b"]
        self.max_prev_iter = state["max_prev_iter"]


# ---------------------------------------------------------------------------
# Nested distinct
# ---------------------------------------------------------------------------


def _corner_weights_impl(parts, it, q_cap: int):
    """From prev-spine gather parts of (row -> (iter, w)) pairs: P(i),
    P(i-1), and the mask of rows with weight at exactly iteration i."""
    p_i = jnp.zeros((q_cap,), jnp.int64)
    p_im1 = jnp.zeros((q_cap,), jnp.int64)
    at_i = jnp.zeros((q_cap,), jnp.bool_)
    for qrow, vals, w in parts:
        iters = vals[0]
        seg = jnp.minimum(qrow, q_cap).astype(jnp.int32)
        p_i = p_i + jax.ops.segment_sum(
            jnp.where(iters <= it, w, 0), seg, num_segments=q_cap + 1)[:q_cap]
        p_im1 = p_im1 + jax.ops.segment_sum(
            jnp.where(iters <= it - 1, w, 0), seg,
            num_segments=q_cap + 1)[:q_cap]
        hit = jax.ops.segment_max(
            jnp.where((iters == it) & (w != 0), 1, 0), seg,
            num_segments=q_cap + 1)[:q_cap]
        at_i = at_i | (hit > 0)
    return p_i, p_im1, at_i


_corner_weights_jit = jax.jit(_corner_weights_impl,
                              static_argnames=("q_cap",))


def _corner_weights_factory(q_cap: int):
    return lambda parts, it: _corner_weights_impl(parts, it, q_cap)


def _corner_weights(parts, it, q_cap: int):
    if parts[0][2].ndim > 1:  # sharded gather parts
        return lifted(_corner_weights_factory, q_cap)(
            parts, worker_scalar(it, ITER_DTYPE))
    return _corner_weights_jit(parts, it, q_cap)


def _cur_weights_impl(parts, q_cap: int):
    """Current-epoch accumulated weight per query row (iters < now)."""
    c = jnp.zeros((q_cap,), jnp.int64)
    for qrow, vals, w in parts:
        seg = jnp.minimum(qrow, q_cap).astype(jnp.int32)
        c = c + jax.ops.segment_sum(w, seg, num_segments=q_cap + 1)[:q_cap]
    return c


_cur_weights_jit = jax.jit(_cur_weights_impl, static_argnames=("q_cap",))


def _cur_weights_factory(q_cap: int):
    return lambda parts: _cur_weights_impl(parts, q_cap)


def _cur_weights(parts, q_cap: int):
    if parts[0][2].ndim > 1:
        return lifted(_cur_weights_factory, q_cap)(parts)
    return _cur_weights_jit(parts, q_cap)


def _row_weights_from_impl(batch: Batch, qcols):
    """Per query row: the batch's net weight for that exact row (rows are
    unique in a consolidated batch, so the [lo, hi) range is 0/1 wide)."""
    lo = kernels.lex_probe(batch.cols, qcols, side="left")
    hi = kernels.lex_probe(batch.cols, qcols, side="right")
    found = hi > lo
    w = batch.weights[jnp.minimum(lo, batch.cap - 1)]
    return jnp.where(found, w, 0)


_row_weights_from_jit = jax.jit(_row_weights_from_impl)


def _row_weights_from_factory():
    return _row_weights_from_impl


def _row_weights_from(batch: Batch, qcols):
    if batch.sharded:
        return lifted(_row_weights_from_factory)(batch, qcols)
    return _row_weights_from_jit(batch, qcols)


def _distinct_out_impl(qcols, qlive, p_i, p_im1, c_im1, dw):
    c_i = c_im1 + dw
    out = (jnp.where(p_i + c_i > 0, 1, 0) - jnp.where(p_i > 0, 1, 0)
           - jnp.where(p_im1 + c_im1 > 0, 1, 0)
           + jnp.where(p_im1 > 0, 1, 0)).astype(jnp.int64)
    out = jnp.where(qlive, out, 0)
    cols, w = kernels.compact(qcols, out, out != 0)
    return cols, w


_distinct_out_jit = jax.jit(_distinct_out_impl)


def _distinct_out_factory():
    return _distinct_out_impl


def _distinct_out(qcols, qlive, p_i, p_im1, c_im1, dw):
    if qlive.ndim > 1:
        return lifted(_distinct_out_factory)(qcols, qlive, p_i, p_im1,
                                             c_im1, dw)
    return _distinct_out_jit(qcols, qlive, p_i, p_im1, c_im1, dw)


def _corner_agg_impl(parts, it, q_cap: int, agg, nv: int):
    """Aggregate at the four (epoch, iteration) corners for touched keys.

    ``parts`` is a tuple of (qrow, val_cols[nv], iters, w, kind) with kind
    0 = this tick's delta, 1 = current-epoch accumulation (iterations < i),
    2 = previous epochs (iteration in ``iters``). Membership per corner:

        z(e,i)     = delta + cur + prev[iter <= i]
        z(e-1,i)   =               prev[iter <= i]
        z(e,i-1)   =         cur + prev[iter <= i-1]
        z(e-1,i-1) =               prev[iter <= i-1]

    Rows are netted per (key, val) PER CORNER (an insert and its retraction
    from different iterations must cancel before the positivity test), then
    ``agg.reduce`` runs per key per corner. Returns per-corner value tuples
    and presence masks, each [q_cap]."""
    qrow = jnp.concatenate([p[0] for p in parts])
    vals = tuple(jnp.concatenate([p[1][j] for p in parts])
                 for j in range(nv))
    iters = jnp.concatenate([p[2] for p in parts])
    w = jnp.concatenate([p[3] for p in parts])
    kind = jnp.concatenate([p[4] for p in parts])

    le_i = (kind == 2) & (iters <= it)
    le_im1 = (kind == 2) & (iters <= it - 1)
    members = ((kind == 0) | (kind == 1) | le_i,   # z(e, i)
               le_i,                               # z(e-1, i)
               (kind == 1) | le_im1,               # z(e, i-1)
               le_im1)                             # z(e-1, i-1)
    cws = tuple(jnp.where(m, w, 0) for m in members)

    ops = jax.lax.sort((qrow, *vals, *cws), num_keys=1 + nv,
                       is_stable=True)
    qrow_s, vals_s, cws_s = ops[0], ops[1:1 + nv], ops[1 + nv:]
    n = qrow_s.shape[0]
    dup = kernels.rows_equal_prev((qrow_s, *vals_s), n=n)
    segv = jnp.cumsum(~dup) - 1
    netted = []
    for cw in cws_s:
        net = jax.ops.segment_sum(cw, segv, num_segments=n)[segv]
        netted.append(jnp.where(dup, 0, net))
    seg_key = jnp.minimum(qrow_s, q_cap).astype(jnp.int32)
    corner_vals, corner_present = [], []
    for cw in netted:
        outs = agg.reduce(vals_s, cw, seg_key, q_cap + 1)
        corner_vals.append(tuple(o[:q_cap] for o in outs))
        corner_present.append(jax.ops.segment_max(
            jnp.where(cw > 0, 1, 0), seg_key,
            num_segments=q_cap + 1)[:q_cap] > 0)
    return tuple(corner_vals), tuple(corner_present)


_corner_agg_jit = jax.jit(_corner_agg_impl, static_argnames=("q_cap", "agg",
                                                             "nv"))


def _corner_agg_factory(q_cap: int, agg, nv: int):
    return lambda parts, it: _corner_agg_impl(parts, it, q_cap, agg, nv)


def _corner_agg(parts, it: int, q_cap: int, agg, nv: int):
    if parts[0][3].ndim > 1:  # sharded gather parts
        return lifted(_corner_agg_factory, q_cap, agg, nv)(
            parts, worker_scalar(it, ITER_DTYPE))
    return _corner_agg_jit(parts, jnp.asarray(it, ITER_DTYPE), q_cap, agg,
                           nv)


def _corner_agg_out_impl(qkeys, qlive, corner_vals, corner_present):
    """2-d output delta from the four corner aggregates:
    +A(z(e,i)) - A(z(e-1,i)) - A(z(e,i-1)) + A(z(e-1,i-1)); identical
    values cancel in the consolidation."""
    signs = (1, -1, -1, 1)
    keys = tuple(jnp.concatenate([c] * 4) for c in qkeys)
    nvo = len(corner_vals[0])
    vals = tuple(
        jnp.concatenate([corner_vals[k][j] for k in range(4)])
        for j in range(nvo))
    w = jnp.concatenate([
        jnp.where(qlive & corner_present[k], signs[k], 0).astype(jnp.int64)
        for k in range(4)])
    # dead slots: sentinel columns so consolidation sorts them out
    live = w != 0
    keys = tuple(jnp.where(live, c, kernels.sentinel_for(c.dtype))
                 for c in keys)
    vals = tuple(jnp.where(live, c, kernels.sentinel_for(c.dtype))
                 for c in vals)
    cols, w = kernels.consolidate_cols((*keys, *vals), w)
    return cols, w


_corner_agg_out_jit = jax.jit(_corner_agg_out_impl)


def _corner_agg_out_factory():
    return _corner_agg_out_impl


def _corner_agg_out(qkeys, qlive, corner_vals, corner_present):
    if qlive.ndim > 1:
        return lifted(_corner_agg_out_factory)(qkeys, qlive, corner_vals,
                                               corner_present)
    return _corner_agg_out_jit(qkeys, qlive, corner_vals, corner_present)


class NestedAggregateOp(UnaryOperator):
    """Incremental aggregate over (epoch, iteration) time — the nested-scope
    analog of :class:`~dbsp_tpu.operators.aggregate.AggregateOp` (reference:
    ``aggregate/mod.rs:204,410`` is generic over any ``Timestamp`` including
    ``NestedTimestamp32``; this is the product-lattice instantiation).

    Emits the 2-d difference of the per-key aggregate of the 2-d integral:

        out(e,i) = A(z(e,i)) - A(z(e-1,i)) - A(z(e,i-1)) + A(z(e-1,i-1))

    State mirrors :class:`NestedDistinctOp`: a prev-epochs spine keyed by
    the group key whose value rows carry an iteration tag, and a
    current-epoch spine — per-iteration cost is proportional to the keys
    touched this epoch, not the accumulated relation."""

    def __init__(self, agg, schema, child, name=None):
        self.agg = agg
        self.key_dtypes = tuple(schema[0])
        self.val_dtypes = tuple(schema[1])
        self.out_schema = (self.key_dtypes, tuple(agg.out_dtypes))
        self.child = child
        self.name = name or f"nested-aggregate<{agg.name}>"
        # previous epochs: key -> (val cols..., iteration tag) rows
        self.prev = Spine(self.key_dtypes, (*self.val_dtypes, ITER_DTYPE))
        # current epoch: plain key -> vals accumulation (iterations < now)
        self.cur = Spine(self.key_dtypes, self.val_dtypes)
        self._epoch: List[Tuple[int, Batch]] = []
        self.max_prev_iter = 0
        self._prev_gather = GroupGather()
        self._cur_gather = GroupGather()
        self._delta_gather = GroupGather()
        # observability: keys evaluated since the counter was last reset —
        # the delta-cost contract's measurable (tests assert a small update
        # evaluates far fewer keys than the initial derivation)
        self.epoch_eval_rows = 0

    # -- clock protocol -----------------------------------------------------
    def clock_start(self, scope: int) -> None:
        if scope > 0:
            self.cur = Spine(self.key_dtypes, self.val_dtypes)
            self._epoch = []

    def clock_end(self, scope: int) -> None:
        if scope > 0:
            last = 0
            for it, b in self._epoch:
                self.prev.insert(_with_iter_tag(b, it))
                last = max(last, it)
            self.max_prev_iter = max(self.max_prev_iter, last)
            self._epoch = []

    def fixedpoint(self, scope: int) -> bool:
        return self.child.iteration >= self.max_prev_iter

    # -- eval ---------------------------------------------------------------
    @staticmethod
    def _norm(parts, kind: int, nv: int, with_tag: bool):
        """Normalize gather parts to (qrow, vals[nv], iters, w, kind)."""
        out = []
        for qrow, vals, w in parts or ():
            if with_tag:
                vs, iters = vals[:-1], vals[-1].astype(ITER_DTYPE)
            else:
                vs = vals[:nv]
                iters = jnp.zeros(qrow.shape, ITER_DTYPE)
            out.append((qrow.astype(jnp.int32), tuple(vs), iters, w,
                        jnp.full(qrow.shape, kind, jnp.int32)))
        return out

    def eval(self, delta: Batch) -> Batch:
        it = self.child.iteration
        nk, nv = len(self.key_dtypes), len(self.val_dtypes)

        # touched keys: the delta's, plus keys already touched this epoch
        # (their (e,i) vs (e,i-1) corners move when prev rows exist at
        # exactly iteration i — evaluating them costs one formula pass and
        # yields 0 when nothing moved)
        kd = _presence(Batch(delta.keys, (), delta.weights))
        if self.cur.batches:
            ck = self.cur.consolidated()
            probe = concat_batches(
                [kd, _presence(Batch(ck.keys[:nk], (), ck.weights))]
            ).consolidate()
        else:
            probe = kd.consolidate()
        qkeys, qlive = _unique_keys(probe, nk)
        q_cap = qlive.shape[-1]
        self.epoch_eval_rows += int(jnp.sum(qlive))

        delta_live = int(delta.live_count()) > 0  # ONE host sync per eval

        parts = []
        parts += self._norm(
            self._prev_gather(qkeys, qlive, self.prev.batches, q_cap),
            2, nv, with_tag=True)
        parts += self._norm(
            self._cur_gather(qkeys, qlive, self.cur.batches, q_cap),
            1, nv, with_tag=False)
        if delta_live:
            parts += self._norm(
                self._delta_gather(qkeys, qlive, [delta], q_cap),
                0, nv, with_tag=False)

        if not parts:
            return Batch.empty(*self.out_schema,
                               lead=tuple(delta.weights.shape[:-1]))
        corner_vals, corner_present = _corner_agg(
            tuple(parts), it, q_cap, self.agg, nv)
        cols, w = _corner_agg_out(qkeys, qlive, corner_vals, corner_present)
        out = Batch(cols[:nk], cols[nk:], w).shrink_to_fit()

        if delta_live:
            self.cur.insert(delta)
            self._epoch.append((it, delta))
        return out

    def state_dict(self):
        assert not self._epoch, "checkpoint mid-epoch not supported"
        return {"prev": self.prev, "max_prev_iter": self.max_prev_iter}

    def load_state_dict(self, state):
        self.prev = state["prev"]
        self.max_prev_iter = state["max_prev_iter"]


class NestedDistinctOp(UnaryOperator):
    """2-d incremental distinct (module doc). Consumes the RAW delta stream."""

    def __init__(self, schema, child, name="nested-distinct"):
        self.name = name
        self.schema = schema
        self.child = child
        self.row_dtypes = (*schema[0], *schema[1])
        self.nk = len(schema[0])
        # prev epochs: row -> (iteration, weight) entries
        self.prev = Spine(self.row_dtypes, (ITER_DTYPE,))
        # current epoch: plain row-keyed accumulation (iters < now)
        self.cur = Spine(self.row_dtypes, ())
        self._epoch: List[Tuple[int, Batch]] = []
        self.max_prev_iter = 0
        self._prev_gather = GroupGather()
        self._cur_gather = GroupGather()

    def clock_start(self, scope: int) -> None:
        if scope > 0:
            self.cur = Spine(self.row_dtypes, ())
            self._epoch = []

    def clock_end(self, scope: int) -> None:
        if scope > 0:
            last = 0
            rows = 0
            for it, b in self._epoch:
                self.prev.insert(_with_iter_val(b, it))
                last = max(last, it)
                rows += int(b.live_count())
            self.max_prev_iter = max(self.max_prev_iter, last)
            # observability: per-epoch processed rows — the delta-cost
            # contract's measurable (tests assert small updates stay small)
            self.last_epoch_rows = rows
            self._epoch = []

    def fixedpoint(self, scope: int) -> bool:
        return self.child.iteration >= self.max_prev_iter

    def eval(self, delta: Batch) -> Batch:
        it = self.child.iteration
        # rows to evaluate: the delta's rows, plus rows already touched this
        # epoch whose PREVIOUS epochs have weight at exactly iteration i
        # (their corners move even with an empty delta)
        flat_delta = Batch((*delta.keys, *delta.vals), (), delta.weights)
        if self.cur.batches:
            # presence-weighted union: real weights could cancel (a delta
            # retracting exactly the epoch's weight) and silently drop a row
            # whose output diff is nonzero
            cur_flat = self.cur.consolidated()
            probe = concat_batches(
                [_presence(flat_delta), _presence(cur_flat)]).consolidate()
        else:
            probe = flat_delta
        qcols, qlive = _unique_keys(probe, len(self.row_dtypes))
        q_cap = qlive.shape[-1]

        prev_parts = self._prev_gather(qcols, qlive, self.prev.batches, q_cap)
        if prev_parts is None:
            p_i = p_im1 = jnp.zeros(qlive.shape, jnp.int64)
            at_i = jnp.zeros(qlive.shape, jnp.bool_)
        else:
            p_i, p_im1, at_i = _corner_weights(tuple(prev_parts), it, q_cap)

        cur_parts = self._cur_gather(qcols, qlive, self.cur.batches, q_cap)
        c_im1 = jnp.zeros(qlive.shape, jnp.int64) if cur_parts is None else \
            _cur_weights(tuple(cur_parts), q_cap)

        dw = _row_weights_from(flat_delta, qcols)
        # rows outside the delta and without prev-epoch weight at exactly i
        # cannot change (their four corners move in lockstep) — but rather
        # than masking on (dw != 0) | at_i we just evaluate: the formula
        # yields 0 for them. at_i is consumed implicitly through p_i/p_im1.
        del at_i
        cols, w = _distinct_out(qcols, qlive, p_i, p_im1, c_im1, dw)
        out = Batch(cols[:self.nk], cols[self.nk:], w).shrink_to_fit()

        if int(delta.live_count()) > 0:
            self.cur.insert(flat_delta)
            self._epoch.append((it, flat_delta))
        return out

    def state_dict(self):
        assert not self._epoch, "checkpoint mid-epoch not supported"
        return {"prev": self.prev, "max_prev_iter": self.max_prev_iter}

    def load_state_dict(self, state):
        self.prev = state["prev"]
        self.max_prev_iter = state["max_prev_iter"]
