"""Unit-delay (z^-1), feedback sugar, integrate and differentiate.

Reference: ``operator/z1.rs:40`` (Z1), ``operator/integrate.rs:67``,
``operator/differentiate.rs:24``, ``DelayedFeedback`` (z1.rs:129).

``integrate`` materializes the running sum as a value stream; stateful
incremental operators do NOT use it (they maintain spines — see
``operators/trace_op.py``), matching the reference's split between
``integrate()`` and ``integrate_trace()``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from dbsp_tpu.circuit.builder import (CircuitError, FeedbackConnector,
                                      Stream)
from dbsp_tpu.circuit.operator import BinaryOperator, StrictOperator
from dbsp_tpu.operators.basic import group_add
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.zset.batch import Batch


class Z1(StrictOperator):
    """out(t) = in(t-1); out(0) = zero. The only primitive that introduces
    time, and the strict node that legalizes feedback cycles."""

    name = "z1"

    def __init__(self, zero_factory: Callable[[], Any]):
        self.zero_factory = zero_factory
        self.state: Any = None

    def clock_start(self, scope: int) -> None:
        self.state = self.zero_factory()

    def clock_end(self, scope: int) -> None:
        self.state = self.zero_factory()

    def get_output(self):
        return self.state

    def eval_strict(self, value):
        self.state = value

    def fixedpoint(self, scope: int) -> bool:
        # At a fixedpoint iff the delayed value is (close to) zero is NOT the
        # right test in general; the executor checks trace dirt instead. Z1
        # itself reports True — its output converges when its input does.
        return True

    def state_dict(self):
        return {"state": self.state}

    def load_state_dict(self, state):
        self.state = state["state"]


def _zero_like_factory(example_schema):
    key_dtypes, val_dtypes = example_schema

    def zero():
        from dbsp_tpu.circuit.runtime import Runtime

        w = Runtime.worker_count()
        return Batch.empty(key_dtypes, val_dtypes,
                           lead=(w,) if w > 1 else ())

    return zero


@stream_method
def delay(self: Stream, zero_factory: Optional[Callable[[], Any]] = None
          ) -> Stream:
    """z^-1 applied to this stream."""
    zf = zero_factory or _schema_zero(self)
    fb = self.circuit.add_feedback(Z1(zf))
    fb.connect(self)
    fb.stream.schema = getattr(self, "schema", None)
    # a delay emits the input's own batches one tick later (or a
    # same-placement zero): partitioning survives
    fb.stream.key_sharded = getattr(self, "key_sharded", False)
    return fb.stream


@stream_method
def integrate(self: Stream, zero_factory: Optional[Callable[[], Any]] = None
              ) -> Stream:
    """Running sum including the current tick: I(s)(t) = Σ_{u<=t} s(u).

    Built as the feedback loop  acc = s + z1(acc)  (reference circuit shape,
    integrate.rs:67).
    """
    zf = zero_factory or _schema_zero(self)
    fb = self.circuit.add_feedback(Z1(zf))
    acc = self.circuit.add_binary_operator(
        _PlusNamed("integrate"), self, fb.stream)
    fb.connect(acc)
    acc.schema = getattr(self, "schema", None)
    # the running sum merges per worker; partitioning survives
    acc.key_sharded = getattr(self, "key_sharded", False)
    fb.stream.key_sharded = acc.key_sharded
    return acc


@stream_method
def differentiate(self: Stream,
                  zero_factory: Optional[Callable[[], Any]] = None) -> Stream:
    """D(s)(t) = s(t) - s(t-1); inverse of integrate (differentiate.rs:24)."""
    from dbsp_tpu.operators.basic import Minus

    delayed = self.delay(zero_factory)
    out = self.circuit.add_binary_operator(Minus(), self, delayed)
    out.schema = getattr(self, "schema", None)
    out.key_sharded = getattr(self, "key_sharded", False)
    return out


class _PlusNamed(BinaryOperator):
    def __init__(self, name: str):
        self.name = name

    def eval(self, a, b):
        return group_add(a, b)


def _schema_zero(stream: Stream) -> Callable[[], Any]:
    schema = getattr(stream, "schema", None)
    if schema is None:
        raise CircuitError(
            "stream has no schema metadata; pass zero_factory= explicitly "
            "(needed by delay/integrate/differentiate to produce the t=0 "
            "value)")
    # placement-aware default: a stream explicitly collapsed to the host
    # (unshard() — the P003-waived host-resident shape) carries 1-D
    # batches even on a W>1 mesh, and Z1 emits its zero at clock_start
    # BEFORE any value is seen, so the placement must be decided at build
    # time — a [W, cap] zero against the stream's 1-D batches is a
    # mixed-placement merge downstream. Placement-preserving transforms
    # between the unshard and the delay (map/filter/...) carry the 1-D
    # shape through, so walk back across them, not just one hop.
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp

    node, seen = stream.node, set()
    while node.index not in seen:
        seen.add(node.index)
        op = node.operator
        if isinstance(op, UnshardOp):
            key_dtypes, val_dtypes = schema
            return lambda: Batch.empty(key_dtypes, val_dtypes)
        if isinstance(op, ExchangeOp) or not _placement_thru(op) \
                or not node.inputs:
            break
        node = stream.circuit.nodes[node.inputs[0]]
    return _zero_like_factory(schema)


def _placement_thru(op) -> bool:
    """Ops whose output batches keep their (first) input's lead-axis
    placement — the backward-walk pass-through set for _schema_zero."""
    from dbsp_tpu.operators.basic import Minus, Neg, Plus, SumN
    from dbsp_tpu.operators.filter_map import FilterOp, FlatMapOp, MapOp

    return isinstance(op, (FilterOp, MapOp, FlatMapOp, Neg, Plus, Minus,
                           SumN, Z1))
