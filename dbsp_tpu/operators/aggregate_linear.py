"""Linear aggregation fast path: maintain per-key accumulators from delta
segment-sums alone — no group re-gather from the input trace.

Reference: ``operator/aggregate/mod.rs:253`` (``aggregate_linear``) and
``:287`` (``weigh``). A *linear* aggregate is one where the output is a
function of a weight-linear accumulator:

    out(key) = finalize( sum_rows weight * weigh(vals),  sum_rows weight )

Count/Sum/Average are linear; Min/Max are not (a retraction can expose a
value only the full group knows — they stay on the general gather path in
``operators/aggregate.py``).

Why this is the fast path, and TPU-native: per tick the operator needs only
(1) a segment-sum of the (already sorted) delta by key, (2) a probe of its
own per-key accumulator state (one net row per key — NOT the input history),
(3) an elementwise combine + diff. Every kernel is delta-sized; the input
stream needs no trace at all, so upstream spines vanish unless some other
consumer wants them. The general path instead gathers each touched group's
full history from the input trace — O(group size) work the linear form
avoids entirely.

State representation: an ``acc`` spine of (key -> (acc..., count)) rows
maintained by retract/insert deltas, exactly like the general path's output
spine; reconstruction is linear (net acc = sum of weight * acc over the
key's rows), so probes need no merge/netting pass.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.aggregate import GroupGather, _unique_keys
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch

# ---------------------------------------------------------------------------
# Linear aggregators
# ---------------------------------------------------------------------------


class LinearAggregator:
    """Spec: ``weigh`` maps each row's val columns to per-row contributions
    (multiplied by the row's Z-set weight and summed per key); ``finalize``
    maps the summed accumulator (+ the summed weight, ``count``) to the
    output columns. Reference: aggregate/mod.rs:253,287."""

    acc_dtypes: Tuple = ()
    out_dtypes: Tuple = ()
    name = "linear"

    def weigh(self, val_cols: Tuple[jnp.ndarray, ...]
              ) -> Tuple[jnp.ndarray, ...]:
        return ()

    def finalize(self, acc_cols: Tuple[jnp.ndarray, ...], count: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, ...]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class LinearCount(LinearAggregator):
    acc_dtypes = ()
    out_dtypes = (jnp.int64,)
    name = "count"

    def finalize(self, acc_cols, count):
        return (count,)


@dataclasses.dataclass(frozen=True)
class LinearSum(LinearAggregator):
    col: int = 0
    acc_dtypes = (jnp.int64,)
    out_dtypes = (jnp.int64,)
    name = "sum"

    def weigh(self, val_cols):
        return (val_cols[self.col].astype(jnp.int64),)

    def finalize(self, acc_cols, count):
        return (acc_cols[0],)


@dataclasses.dataclass(frozen=True)
class LinearAverage(LinearAggregator):
    """Integer average sum/count with truncating division (SQL semantics,
    matches the general-path Average)."""

    col: int = 0
    acc_dtypes = (jnp.int64,)
    out_dtypes = (jnp.int64,)
    name = "avg"

    def weigh(self, val_cols):
        return (val_cols[self.col].astype(jnp.int64),)

    def finalize(self, acc_cols, count):
        s = acc_cols[0]
        c = jnp.maximum(count, 1)
        return (jnp.where(s >= 0, s // c, -((-s) // c)),)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _weigh_deltas_impl(delta: Batch, agg: LinearAggregator, nk: int):
    """Per-unique-key accumulator deltas: seg-sum of weight * weigh(vals).

    Segment ids follow the same first-live-distinct-key order as
    :func:`_unique_keys`, so outputs align with its compacted key columns.
    """
    cap = delta.cap
    live = delta.weights != 0
    first = ~kernels.rows_equal_prev(delta.keys[:nk], n=cap) & live
    seg = jnp.cumsum(first) - 1
    seg = jnp.where(live, seg, cap).astype(jnp.int32)
    w = delta.weights
    accs = tuple(
        jax.ops.segment_sum(a.astype(d) * w, seg, num_segments=cap + 1)[:cap]
        for a, d in zip(agg.weigh(delta.vals), agg.acc_dtypes))
    cnt = jax.ops.segment_sum(w, seg, num_segments=cap + 1)[:cap]
    return accs, cnt


_weigh_deltas_jit = jax.jit(_weigh_deltas_impl, static_argnames=("agg", "nk"))


def _weigh_deltas_factory(agg: LinearAggregator, nk: int):
    return lambda d: _weigh_deltas_impl(d, agg, nk)


def _weigh_deltas(delta: Batch, agg: LinearAggregator, nk: int):
    if delta.sharded:
        return lifted(_weigh_deltas_factory, agg, nk)(delta)
    return _weigh_deltas_jit(delta, agg, nk)


def _net_state_impl(parts, q_cap: int):
    """Linear reconstruction of per-key state from acc-spine probe results:
    net acc columns, net count, and net row count (presence) — plain
    segment-sums per level, no merge/netting needed (linearity)."""
    accs = None
    cnt = None
    rows = None
    for qrow, vals, w in parts:
        seg = jnp.minimum(qrow, q_cap).astype(jnp.int32)
        # vals = (*acc_cols, count_col); dead slots have w == 0 so their
        # sentinel values contribute nothing
        sums = tuple(
            jax.ops.segment_sum(v * w, seg, num_segments=q_cap + 1)[:q_cap]
            for v in vals)
        r = jax.ops.segment_sum(w, seg, num_segments=q_cap + 1)[:q_cap]
        if accs is None:
            accs, cnt, rows = sums[:-1], sums[-1], r
        else:
            accs = tuple(a + b for a, b in zip(accs, sums[:-1]))
            cnt = cnt + sums[-1]
            rows = rows + r
    return accs, cnt, rows


_net_state_jit = jax.jit(_net_state_impl, static_argnames=("q_cap",))


def _net_state_factory(q_cap: int):
    return lambda parts: _net_state_impl(parts, q_cap)


def _net_state(parts, q_cap: int):
    if parts[0][2].ndim > 1:  # sharded gather parts
        return lifted(_net_state_factory, q_cap)(parts)
    return _net_state_jit(parts, q_cap)


def _combine_diff_impl(qkeys, qlive, acc_delta, cnt_delta, old_accs, old_cnt,
                       old_rows, agg: LinearAggregator, nk: int):
    """Combine old state + deltas; build the output diff and the state diff.

    Two DISTINCT presence notions (conflating them dropped negative-count
    accumulator state and later resurrected a phantom zero-sum group —
    found by the property fuzzer, tests/test_proptest.py):
      * a group is VISIBLE in the output iff its net count > 0;
      * a STATE row must exist iff any accumulator component is nonzero —
        a group retracted below zero still owes its (negative) sums.
    """
    q_cap = qlive.shape[0]
    old_has_row = qlive & (old_rows > 0)   # a state row existed
    old_present = qlive & (old_cnt > 0)    # group visible in the output
    new_accs = tuple(o + d for o, d in zip(old_accs, acc_delta))
    new_cnt = old_cnt + cnt_delta
    new_present = qlive & (new_cnt > 0)

    fin_old = tuple(c.astype(d) for c, d in
                    zip(agg.finalize(old_accs, old_cnt), agg.out_dtypes))
    fin_new = tuple(c.astype(d) for c, d in
                    zip(agg.finalize(new_accs, new_cnt), agg.out_dtypes))
    changed = new_present != old_present
    for a, b in zip(fin_new, fin_old):
        changed = changed | ~kernels._col_eq(a, b)

    def two_sided(vals_new, vals_old, ins_mask, ret_mask):
        keys = tuple(jnp.concatenate([c, c]) for c in qkeys)
        vals = tuple(jnp.concatenate([n, o])
                     for n, o in zip(vals_new, vals_old))
        w = jnp.concatenate([jnp.where(ins_mask, 1, 0),
                             jnp.where(ret_mask, -1, 0)]).astype(jnp.int64)
        cols, w = kernels.consolidate_cols((*keys, *vals), w)
        return Batch(cols[:nk], cols[nk:], w, runs=(int(w.shape[-1]),))

    out = two_sided(fin_new, fin_old,
                    new_present & changed, old_present & changed)
    # state rows change iff any accumulator or the count moved
    state_changed = cnt_delta != 0
    for d in acc_delta:
        state_changed = state_changed | (d != 0)
    new_has_row = new_cnt != 0
    for a in new_accs:
        new_has_row = new_has_row | (a != 0)
    state = two_sided((*new_accs, new_cnt), (*old_accs, old_cnt),
                      qlive & new_has_row & state_changed,
                      old_has_row & state_changed)
    return out, state


_combine_diff_jit = jax.jit(_combine_diff_impl, static_argnames=("agg", "nk"))


def _combine_diff_factory(agg: LinearAggregator, nk: int):
    return lambda qk, ql, ad, cd, oa, oc, orr: _combine_diff_impl(
        qk, ql, ad, cd, oa, oc, orr, agg, nk)


def _combine_diff(qkeys, qlive, acc_delta, cnt_delta, old_accs, old_cnt,
                  old_rows, agg: LinearAggregator, nk: int):
    if qlive.ndim > 1:  # sharded
        return lifted(_combine_diff_factory, agg, nk)(
            qkeys, qlive, acc_delta, cnt_delta, old_accs, old_cnt, old_rows)
    return _combine_diff_jit(qkeys, qlive, acc_delta, cnt_delta, old_accs,
                             old_cnt, old_rows, agg, nk)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


class LinearAggregateOp(UnaryOperator):
    """Incremental linear aggregate. Consumes the RAW delta stream (no input
    trace); keeps only its own (key -> acc) state spine."""

    def __init__(self, agg: LinearAggregator, key_dtypes, name=None):
        self.agg = agg
        self.name = name or f"aggregate_linear<{agg.name}>"
        self.key_dtypes = tuple(key_dtypes)
        self.out_schema = (self.key_dtypes, tuple(agg.out_dtypes))
        self._state_schema = (self.key_dtypes,
                              (*agg.acc_dtypes, jnp.int64))  # + count col
        self.acc_spine = Spine(*self._state_schema)
        self._gather = GroupGather()

    def clock_start(self, scope: int) -> None:
        if scope > 0:  # nested clock: reset per parent tick (nested.py)
            self.acc_spine = Spine(*self._state_schema)

    def eval(self, delta: Batch) -> Batch:
        from dbsp_tpu.circuit.runtime import Runtime

        nk = len(self.key_dtypes)
        if int(delta.live_count()) == 0:
            w = Runtime.worker_count()
            return Batch.empty(*self.out_schema, lead=(w,) if w > 1 else ())
        qkeys, qlive = _unique_keys(delta, nk)
        q_cap = qlive.shape[-1]  # trimmed to distinct-key bucket
        acc_delta, cnt_delta = _weigh_deltas(delta, self.agg, nk)
        # _weigh_deltas aligns to the delta's cap; the distinct-key trim
        # means only the first q_cap slots are populated
        acc_delta = tuple(a[..., :q_cap] for a in acc_delta)
        cnt_delta = cnt_delta[..., :q_cap]

        parts = self._gather(qkeys, qlive, self.acc_spine.batches, q_cap)
        if parts is None:
            zeros = tuple(jnp.zeros(qlive.shape, d)
                          for d in self.agg.acc_dtypes)
            old = (zeros, jnp.zeros(qlive.shape, jnp.int64),
                   jnp.zeros(qlive.shape, jnp.int64))
        else:
            old = _net_state(tuple(parts), q_cap)

        out, state = _combine_diff(qkeys, qlive, tuple(acc_delta), cnt_delta,
                                   *old, self.agg, nk)
        # re-bucket to live rows before emitting/storing: the diffs carry
        # 2*q_cap capacity but few live rows
        self.acc_spine.insert(state.shrink_to_fit())
        return out.shrink_to_fit()

    def fixedpoint(self, scope: int) -> bool:
        return True

    def metadata(self):
        return {"state_levels": len(self.acc_spine.batches),
                "state_cap": self.acc_spine.total_cap}

    def state_dict(self):
        return {"acc_spine": self.acc_spine}

    def load_state_dict(self, state):
        self.acc_spine = state["acc_spine"]
