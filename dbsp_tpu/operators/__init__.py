"""Operator library. Importing this package attaches all Stream sugar
(map/filter/join/aggregate/...) — the analog of bringing the reference's
operator extension traits into scope."""

from dbsp_tpu.operators import (  # noqa: F401  (Stream-method registration)
    aggregate, basic, distinct, filter_map, io_handles, join, recursive,
    semijoin, shard_op, topk, trace_op, upsert, z1)
import dbsp_tpu.timeseries  # noqa: F401, E402  (register window/watermark)
from dbsp_tpu.operators.aggregate import Average, Count, Fold, Max, Min, Sum
from dbsp_tpu.operators.aggregate_linear import (LinearAverage, LinearCount,
                                                 LinearSum)
from dbsp_tpu.operators.basic import Generator
from dbsp_tpu.operators.io_handles import InputHandle, OutputHandle, add_input_zset
from dbsp_tpu.operators.upsert import UpsertHandle, add_input_map, add_input_set
from dbsp_tpu.operators.z1 import Z1

__all__ = ["Generator", "InputHandle", "OutputHandle", "add_input_zset", "Z1",
           "Count", "Sum", "Min", "Max", "Average", "Fold",
           "LinearCount", "LinearSum", "LinearAverage",
           "UpsertHandle", "add_input_map", "add_input_set"]
