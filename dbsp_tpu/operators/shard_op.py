"""The shard operator: key-hash repartition of a stream across workers.

Reference: ``operator/communication/shard.rs:35-101`` — ``shard()`` is a
no-op on one worker; stateful operators (trace/join/aggregate/distinct)
re-shard their own inputs so state is partitioned by key hash and each
worker's slice can be processed independently; the circuit cache makes
repeated ``shard()`` of one stream share a single exchange.

Here the exchange is a ``lax.all_to_all`` over the worker mesh inside the
SPMD step (parallel/exchange.py); placement metadata (``key_sharded``) on
streams elides exchanges that cannot move any row (the stream is already
hash-partitioned on its current key).
"""

from __future__ import annotations

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.parallel.exchange import exchange_local
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.zset.batch import Batch


def _exchange_factory(nworkers: int):
    return lambda b: exchange_local(b, nworkers)


def _row_bytes(batch: Batch) -> int:
    """Bytes per row across all columns + the weight column."""
    return sum(c.dtype.itemsize for c in batch.cols) + \
        batch.weights.dtype.itemsize


class _MovedRowsMixin:
    """Rows/bytes-moved accounting shared by shard and unshard.

    Accumulates ONLY when instrumentation flips ``obs_enabled``
    (obs/instrument.py) — the live-row count is one extra device->host
    sync per tick on this path (a [W] vector for sharded outputs, which
    also yields the per-worker occupancy the skew gauges export)."""

    obs_enabled = False

    def _init_obs(self) -> None:
        self.rows_moved = 0
        self.bytes_moved = 0
        # last eval's per-worker live rows ([n] for unsharded outputs) and
        # the max/mean skew ratio derived from it — obs/instrument.py
        # exports these as dbsp_tpu_exchange_worker_occupancy_rows{worker}
        # and dbsp_tpu_exchange_skew_ratio
        self.last_occupancy: list = []

    def _note_moved(self, out: Batch) -> None:
        if self.obs_enabled:
            import jax
            import jax.numpy as jnp

            if out.sharded:
                per = jax.device_get(jnp.sum(out.weights != 0, axis=-1))
                self.last_occupancy = [int(x) for x in per]
                n = int(sum(self.last_occupancy))
            else:
                n = int(out.live_count())
                self.last_occupancy = [n]
            self.rows_moved += n
            self.bytes_moved += n * _row_bytes(out)

    @property
    def skew_ratio(self) -> float:
        """max/mean worker occupancy of the last observed eval (1.0 =
        perfectly balanced; W = everything on one worker)."""
        occ = self.last_occupancy
        total = sum(occ)
        if len(occ) <= 1 or total == 0:
            return 1.0
        return max(occ) / (total / len(occ))

    def metadata(self):
        return {"rows_moved": self.rows_moved,
                "bytes_moved": self.bytes_moved,
                "occupancy": list(self.last_occupancy),
                "skew_ratio": round(self.skew_ratio, 3)}


class ExchangeOp(_MovedRowsMixin, UnaryOperator):
    name = "shard"

    def __init__(self, nworkers: int):
        self.nworkers = nworkers
        self._init_obs()

    def eval(self, batch: Batch) -> Batch:
        if not batch.sharded:
            # host-resident input (e.g. an operator that ran unsharded, see
            # unshard()): distribute it instead of exchanging
            from dbsp_tpu.circuit.runtime import Runtime
            from dbsp_tpu.parallel.exchange import shard_batch

            out = shard_batch(batch, Runtime.current().mesh).shrink_to_fit()
            self._note_moved(out)
            return out
        out = lifted(_exchange_factory, self.nworkers)(batch)
        # all_to_all output cap is nworkers * cap_local; re-bucket to the
        # worst worker's live rows (one scalar sync)
        out = out.shrink_to_fit()
        self._note_moved(out)
        return out


class UnshardOp(_MovedRowsMixin, UnaryOperator):
    """Collapse a sharded stream to host-resident 1-D batches (all-gather +
    consolidate) — the reference's gather() (communication/gather.rs:41).
    Since the shard-lift of recursive children and the rolling radix path,
    NO operator sugar inserts this mid-circuit (analyzer rule P003 keeps
    it that way); it remains for output boundaries, range-partitioned
    traces (``trace(shard=False)``, join_range) and explicit user
    ``.unshard()`` calls."""

    name = "unshard"

    def __init__(self):
        self._init_obs()

    def eval(self, batch: Batch) -> Batch:
        if not batch.sharded:
            return batch
        from dbsp_tpu.parallel.exchange import unshard_batch

        out = unshard_batch(batch).shrink_to_fit()
        self._note_moved(out)
        return out


@stream_method
def shard(self: Stream) -> Stream:
    """Hash-repartition this stream by its first key column so equal keys
    co-locate on one worker. No-op on a single worker or when the stream is
    already key-sharded; cached so all consumers share one exchange."""
    from dbsp_tpu.circuit.runtime import Runtime

    rt = Runtime.current()
    if rt is None or rt.workers <= 1:
        self.shard_intent = True  # exchange elided on a 1-worker mesh
        return self
    if getattr(self, "key_sharded", False):
        return self
    key = ("shard", self.node_index)
    cached = self.circuit.cache.get(key)
    if cached is not None:
        return cached
    out = self.circuit.add_unary_operator(ExchangeOp(rt.workers), self)
    out.schema = getattr(self, "schema", None)
    out.key_sharded = True
    out.shard_intent = True
    self.circuit.cache[key] = out
    return out


@stream_method
def unshard(self: Stream) -> Stream:
    """Collapse to host-resident batches; no-op on a single worker."""
    from dbsp_tpu.circuit.runtime import Runtime

    rt = Runtime.current()
    if rt is None or rt.workers <= 1:
        self.host_intent = True  # collapse elided on a 1-worker mesh
        return self
    key = ("unshard", self.node_index)
    cached = self.circuit.cache.get(key)
    if cached is not None:
        return cached
    out = self.circuit.add_unary_operator(UnshardOp(), self)
    out.schema = getattr(self, "schema", None)
    out.key_sharded = False
    self.circuit.cache[key] = out
    return out
