"""Recursive (fixedpoint) queries — semi-naive iteration in a nested clock.

Reference: ``operator/recursive.rs:255`` with the circuit shape documented at
recursive.rs:260-276:

        ┌── delta0 (import I) ──┐
        ▼                       │
      plus ─► distinct ─► δ ────┴─► z^-1 ─► f ──► (back to plus)
                           │
                           └─► integrate ─► export (accumulated relation)

Per child tick i: δ_{i+1} = distinct_new(f(δ_i) + [i==0]·I), where
``distinct_new`` (the incremental distinct against the child-local trace)
keeps exactly the rows not yet derived — semi-naive evaluation. The clock
terminates when δ is empty (the Condition), and the accumulated trace is
exported to the parent.
"""

from __future__ import annotations

from typing import Callable

from dbsp_tpu.circuit.builder import Circuit, CircuitError, Stream
from dbsp_tpu.circuit.nested import ChildCircuit, subcircuit
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.z1 import Z1, _zero_like_factory


def recursive_streams(parent: Circuit, inputs, f):
    """Mutual least fixedpoint of R_k = distinct(f_k(R_1..R_n) ∪ I_k).

    The n-ary generalization of :func:`recursive` (reference:
    ``recursive.rs`` implements the same via tuples of streams): ``f(child,
    [R_1..R_n]) -> [step_1..step_n]`` builds every relation's rule body in
    ONE child circuit, so rules may join across relations (mutual
    recursion, e.g. galen's p/q). Returns one delta stream per relation.
    """
    from dbsp_tpu.operators.registry import require_schema

    schemas = [require_schema(s, "recursive_streams") for s in inputs]
    # SHARD-LIFTED: each relation's rows co-locate by hash of its first key
    # column, the fixedpoint inner circuit evaluates per worker key-slice
    # ([W, cap] batches through the nested operators' lifted kernels), and
    # only the convergence check reduces across workers (a condition
    # batch's live_count() sums the worker axis). No-op on a 1-worker mesh.
    inputs = [s.shard() for s in inputs]

    def ctor(child: ChildCircuit):
        child.nested_incremental = True
        i0s = [child.import_stream(s) for s in inputs]
        fbs = []
        for schema, i0 in zip(schemas, i0s):
            # worker-aware zero: the z^-1 seed must carry the same [W, cap]
            # placement as the deltas it merges with
            fb = child.add_feedback(Z1(_zero_like_factory(schema)))
            fb.stream.schema = schema
            fb.stream.key_sharded = getattr(i0, "key_sharded", False)
            fbs.append(fb)
        steps = f(child, [fb.stream for fb in fbs])
        if len(steps) != len(inputs):
            raise CircuitError(
                f"f must return {len(inputs)} streams, got {len(steps)}")
        for step, i0, fb, schema in zip(steps, i0s, fbs, schemas):
            if getattr(step, "schema", None) != schema:
                raise CircuitError(
                    f"f must preserve the relation schema {schema}, got "
                    f"{getattr(step, 'schema', None)}")
            new = step.plus(i0)
            new.schema = schema
            delta = new.distinct()
            delta.schema = schema
            fb.connect(delta)
            child.add_condition(delta)
            child.export(delta.integrate())
        return None

    exports, _ = subcircuit(parent, ctor, iterative=True)
    outs = []
    for i, (schema, i0) in enumerate(zip(schemas, inputs)):
        out = exports.apply(lambda t, _i=i: t[_i], name=f"export{i}")
        out.schema = schema
        # the exported integral accumulates distinct deltas that the nested
        # distinct re-sharded by first-key hash — placement survives
        out.key_sharded = getattr(i0, "key_sharded", False)
        outs.append(out)
    return outs


def recursive(parent: Circuit, input_stream: Stream,
              f: Callable[[ChildCircuit, Stream], Stream]) -> Stream:
    """Least fixedpoint of R = distinct(f(R) ∪ I), as a parent stream.

    ``f(child, delta_stream) -> stream`` builds the recursive step inside
    the child circuit (it may use map/filter/flat_map/plus/minus, joins —
    including against other imported streams — and distinct; those dispatch
    to the nested (epoch, iteration)-incremental variants,
    operators/nested_ops.py).

    INCREMENTAL ACROSS PARENT TICKS (reference: recursive.rs:255-276 +
    nested_ts32.rs): child operator state persists between epochs, imports
    are parent DELTAS (import auxiliary streams raw:
    ``child.import_stream(aux)``), and per-epoch work is proportional to
    the input change, not the accumulated relation. The output stream
    carries the DELTA of the fixedpoint relation per parent tick.
    """
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(input_stream, "recursive")
    # SHARD-LIFTED (see recursive_streams): the fixedpoint child evaluates
    # per worker key-slice; the nested join/distinct sugar re-shards
    # re-keyed intermediates inside the child, so no mid-circuit unshard
    # remains. No-op on a 1-worker mesh.
    input_stream = input_stream.shard()

    def ctor(child: ChildCircuit):
        child.nested_incremental = True
        i0 = child.import_stream(input_stream)
        fb = child.add_feedback(Z1(_zero_like_factory(schema)))
        fb.stream.schema = schema
        fb.stream.key_sharded = getattr(i0, "key_sharded", False)
        step = f(child, fb.stream)
        if getattr(step, "schema", None) != schema:
            raise CircuitError(
                f"f must preserve the relation schema {schema}, got "
                f"{getattr(step, 'schema', None)}")
        new = step.plus(i0)
        new.schema = schema
        delta = new.distinct()      # nested: only rows whose status changed
        delta.schema = schema
        fb.connect(delta)
        child.add_condition(delta)
        # within-epoch integral of the 2-d deltas == this epoch's change of
        # the fixedpoint relation (the iteration dimension telescopes), so
        # the export already IS the parent-level delta stream
        acc = delta.integrate()
        child.export(acc)
        return None

    exports, _ = subcircuit(parent, ctor, iterative=True)
    out = exports.apply(lambda t: t[0], name="export0")
    out.schema = schema
    out.key_sharded = getattr(input_stream, "key_sharded", False)
    return out


@stream_method
def recurse(self: Stream, f) -> Stream:
    """Sugar: ``edges.recurse(lambda child, R: ...)``."""
    return recursive(self.circuit, self, f)
