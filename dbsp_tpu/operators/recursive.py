"""Recursive (fixedpoint) queries — semi-naive iteration in a nested clock.

Reference: ``operator/recursive.rs:255`` with the circuit shape documented at
recursive.rs:260-276:

        ┌── delta0 (import I) ──┐
        ▼                       │
      plus ─► distinct ─► δ ────┴─► z^-1 ─► f ──► (back to plus)
                           │
                           └─► integrate ─► export (accumulated relation)

Per child tick i: δ_{i+1} = distinct_new(f(δ_i) + [i==0]·I), where
``distinct_new`` (the incremental distinct against the child-local trace)
keeps exactly the rows not yet derived — semi-naive evaluation. The clock
terminates when δ is empty (the Condition), and the accumulated trace is
exported to the parent.
"""

from __future__ import annotations

from typing import Callable

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.circuit.nested import ChildCircuit, subcircuit
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.z1 import Z1
from dbsp_tpu.zset.batch import Batch


def recursive(parent: Circuit, input_stream: Stream,
              f: Callable[[ChildCircuit, Stream], Stream]) -> Stream:
    """Least fixedpoint of R = distinct(f(R) ∪ I), as a parent stream.

    ``f(child, delta_stream) -> stream`` builds the recursive step inside the
    child circuit (it may use any operators, including joins against other
    imported streams). The result is the full accumulated relation, exported
    once the iteration converges — re-derived per parent tick (see
    circuit/nested.py scope note).
    """
    schema = getattr(input_stream, "schema", None)
    assert schema is not None, "recursive needs schema metadata on the input"

    # Child state resets each parent tick (nested.py scope note), so the
    # child must see the FULL current relation, not the tick's delta: import
    # the integral. (The reference instead keeps child state across ticks
    # via nested timestamps and imports deltas — the future optimization.)
    # Auxiliary streams used inside ``f`` must likewise be imported
    # integrated: child.import_stream(aux.integrate()).
    full_input = input_stream.integrate()

    def ctor(child: ChildCircuit):
        i0 = child.import_stream(full_input)
        fb = child.add_feedback(Z1(lambda: Batch.empty(*schema)))
        fb.stream.schema = schema
        step = f(child, fb.stream)
        assert getattr(step, "schema", None) == schema, (
            f"f must preserve the relation schema {schema}, got "
            f"{getattr(step, 'schema', None)}")
        new = step.plus(i0)
        new.schema = schema
        delta = new.distinct()      # incremental: only not-yet-seen rows
        delta.schema = schema
        fb.connect(delta)
        child.add_condition(delta)
        acc = delta.integrate()
        child.export(acc)
        return None

    exports, _ = subcircuit(parent, ctor, iterative=True)
    snapshot = exports.apply(lambda t: t[0], name="export0")
    snapshot.schema = schema
    # The child exports the full re-derived relation each parent tick;
    # differentiate restores the framework-wide delta-stream convention so
    # stateful consumers (traces, aggregates, joins) see changes, not
    # snapshots.
    out = snapshot.differentiate()
    out.schema = schema
    return out


@stream_method
def recurse(self: Stream, f) -> Stream:
    """Sugar: ``edges.recurse(lambda child, R: ...)``."""
    return recursive(self.circuit, self, f)
