"""Linear/basic operators: apply, inspect, plus/minus/neg, sum, generator.

Reference surface: ``operator/plus.rs:55,98,155``, ``operator/neg``, ``sum``
(n-ary), ``apply/apply2``, ``inspect``, ``generator.rs``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import (
    BinaryOperator, NaryOperator, SinkOperator, SourceOperator, UnaryOperator)
from dbsp_tpu.zset.batch import Batch, concat_batches
from dbsp_tpu.operators.registry import stream_method


def group_add(a: Any, b: Any) -> Any:
    """Group addition on stream payloads: Z-set add for batches, + otherwise."""
    if isinstance(a, Batch):
        return a.add(b)
    return a + b

def group_neg(a: Any) -> Any:
    if isinstance(a, Batch):
        return a.neg()
    return -a


class Apply(UnaryOperator):
    def __init__(self, fn: Callable[[Any], Any], name: str = "apply"):
        self.fn = fn
        self.name = name

    def eval(self, v):
        return self.fn(v)


class Apply2(BinaryOperator):
    def __init__(self, fn: Callable[[Any, Any], Any], name: str = "apply2"):
        self.fn = fn
        self.name = name

    def eval(self, a, b):
        return self.fn(a, b)


class Inspect(SinkOperator):
    name = "inspect"

    def __init__(self, cb: Callable[[Any], None]):
        self.cb = cb

    def eval(self, v):
        self.cb(v)


class Plus(BinaryOperator):
    name = "plus"

    def eval(self, a, b):
        return group_add(a, b)


class Minus(BinaryOperator):
    name = "minus"

    def eval(self, a, b):
        return group_add(a, group_neg(b))


class Neg(UnaryOperator):
    name = "neg"

    def eval(self, a):
        return group_neg(a)


class SumN(NaryOperator):
    """N-ary Z-set sum: one concat + one consolidation kernel, not a chain of
    pairwise adds (a TPU-side win over folding Plus operators)."""

    name = "sum"

    def eval(self, *vals):
        batches = [v for v in vals if isinstance(v, Batch)]
        if len(batches) == len(vals):
            return concat_batches(batches).consolidate()
        out = vals[0]
        for v in vals[1:]:
            out = group_add(out, v)
        return out


class Generator(SourceOperator):
    """Test source: yields values from a host list/iterator (reference:
    ``operator/generator.rs``); repeats zero of the last value when done."""

    name = "generator"

    def __init__(self, values: Sequence[Any], default: Any = None):
        self.values: List[Any] = list(values)
        self.pos = 0
        self.default = default

    def eval(self):
        if self.pos < len(self.values):
            v = self.values[self.pos]
            self.pos += 1
        elif self.default is not None:
            v = self.default
        else:
            raise StopIteration("Generator exhausted and no default value set")
        from dbsp_tpu.circuit.runtime import Runtime

        rt = Runtime.current()
        if rt is not None and rt.workers > 1 and isinstance(v, Batch) \
                and not v.sharded:
            from dbsp_tpu.parallel.exchange import shard_batch

            v = shard_batch(v, rt.mesh)
        return v

    def state_dict(self):
        return {"pos": self.pos}

    def load_state_dict(self, state):
        self.pos = state["pos"]


# -- Stream sugar -----------------------------------------------------------


@stream_method
def apply(self: Stream, fn, name: str = "apply") -> Stream:
    return self.circuit.add_unary_operator(Apply(fn, name), self)


@stream_method
def apply2(self: Stream, other: Stream, fn, name: str = "apply2") -> Stream:
    return self.circuit.add_binary_operator(Apply2(fn, name), self, other)


@stream_method
def inspect(self: Stream, cb) -> Stream:
    self.circuit.add_sink(Inspect(cb), self)
    return self


def _with_schema(out: Stream, like: Stream) -> Stream:
    out.schema = getattr(like, "schema", None)
    return out


def _co_sharded(out: Stream, *ins: Stream) -> Stream:
    """Exchange fast-path metadata: a per-worker union/negation of streams
    that are ALL hash-partitioned on their first key column is itself
    partitioned the same way (rows never move), so a downstream shard()
    elides its all_to_all."""
    out.key_sharded = all(getattr(s, "key_sharded", False) for s in ins)
    return out


@stream_method
def plus(self: Stream, other: Stream) -> Stream:
    return _co_sharded(_with_schema(
        self.circuit.add_binary_operator(Plus(), self, other), self),
        self, other)


@stream_method
def minus(self: Stream, other: Stream) -> Stream:
    return _co_sharded(_with_schema(
        self.circuit.add_binary_operator(Minus(), self, other), self),
        self, other)


@stream_method
def neg(self: Stream) -> Stream:
    return _co_sharded(_with_schema(
        self.circuit.add_unary_operator(Neg(), self), self), self)


@stream_method
def sum_with(self: Stream, others: Sequence[Stream]) -> Stream:
    return _co_sharded(_with_schema(
        self.circuit.add_nary_operator(SumN(), [self, *others]), self),
        self, *others)
