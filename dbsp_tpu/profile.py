"""CPU/step profiler: per-operator wall time, eval counts, state sizes.

Reference: ``profile/cpu.rs:120`` (``CPUProfiler`` consuming SchedulerEvents)
+ ``profile/mod.rs:21-50`` (graphviz dump) + per-operator ``OperatorMeta``
(``circuit/metadata.rs:18``), surfaced through
``DBSPHandle::{enable_cpu_profiler,dump_profile}`` (dbsp_handle.rs:256,268).

Here the profiler subscribes to the circuit's scheduler-event stream and
joins timings with each operator's ``metadata()`` (e.g. spine level sizes).
Note the timings are host wall-clock around operator eval: they include
kernel dispatch and any host<->device syncs, but XLA may still be executing
asynchronously — per-step latency (CircuitHandle.step_times_ns) is the
end-to-end truth; per-operator numbers locate where time is *submitted*.

Relationship to ``dbsp_tpu.obs`` (the unified metrics/tracing subsystem):
this profiler is the one-shot *report* surface (``/dump_profile`` — full
per-operator totals and graphviz dumps for a human, on demand), while
``obs.CircuitInstrumentation`` consumes the SAME scheduler-event stream to
maintain continuously-scraped histograms/gauges (``/metrics``) and the
Chrome-trace span window (``/trace``), and ``obs.flight``/``obs.slo`` are
the *incident capture* layer: the flight recorder keeps the recent tick
stream with attributed causes always in memory (``/flight``) and the SLO
watchdog freezes breach windows into self-contained ``/incidents``
reports. Oracle (monitor.py), measurement (this file + instrument.py),
incident capture, and *attribution* are separable concerns; all can
attach to one circuit simultaneously and none depends on another.

Attribution on the COMPILED path: the fused XLA step program has no
per-operator eval events for this profiler to time, so operator-level
EXPLAIN ANALYZE lives in ``dbsp_tpu.obs.opprofile`` — static per-node XLA
cost analysis plus an on-demand SEGMENTED measured mode
(``CompiledHandle.profile_ticks(n)``) asserted bit-identical to the fused
program. Both engines answer through one report schema
(``opprofile.PROFILE_SCHEMA``): :meth:`CPUProfiler.profile_report` here
and :meth:`CompiledProfiler.profile_report` below emit the same rows, and
the ``/profile`` route serves whichever engine the pipeline runs (README
§Observability profile-mode matrix).

Durability note: checkpoint/restore activity (``dbsp_tpu.checkpoint``)
shows up in the incident-capture layer, not here — ``checkpoint`` flight
events carry per-generation timing/size, restores (including the
corrupted-generation fallback) emit ``restore`` incidents at
``/incidents``, and ``/status`` carries ``last_checkpoint_tick``
(README §Durability). A profiler dump describes the live process; after a
restore it restarts from zero, which is itself a useful recovery marker.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from dbsp_tpu.circuit.builder import Circuit, SchedulerEvent


class CPUProfiler:
    def __init__(self, circuit: Circuit):
        self.circuit = circuit
        self.elapsed_ns: Dict[tuple, int] = {}
        self.counts: Dict[tuple, int] = {}
        self.steps = 0
        self._open: Dict[tuple, int] = {}
        circuit.register_scheduler_event_handler(self._on_event)

    def _on_event(self, ev: SchedulerEvent) -> None:
        if ev.kind == "eval_start":
            self._open[ev.node_id] = ev.time_ns
        elif ev.kind == "eval_end" and ev.node_id in self._open:
            dt = ev.time_ns - self._open.pop(ev.node_id)
            self.elapsed_ns[ev.node_id] = self.elapsed_ns.get(ev.node_id, 0) + dt
            self.counts[ev.node_id] = self.counts.get(ev.node_id, 0) + 1
        elif ev.kind == "step_end":
            self.steps += 1

    # -- reports ------------------------------------------------------------
    def _node(self, gid):
        c = self.circuit
        for idx in gid[:-1]:
            c = c.nodes[idx].child
        return c.nodes[gid[-1]]

    def profile(self) -> list:
        """Rows sorted by total time: (node id, name, ms, evals, metadata)."""
        rows = []
        for gid, ns in sorted(self.elapsed_ns.items(),
                              key=lambda kv: -kv[1]):
            node = self._node(gid)
            rows.append({
                "node": list(gid),
                "name": node.operator.name,
                "total_ms": round(ns / 1e6, 3),
                "evals": self.counts[gid],
                "meta": node.operator.metadata(),
            })
        return rows

    def dump_json(self) -> str:
        return json.dumps({"steps": self.steps, "operators": self.profile()})

    def profile_report(self, ticks=None, spans=None, registry=None) -> dict:
        """The shared ``/profile`` report (``opprofile.PROFILE_SCHEMA``):
        the same rows as :meth:`profile` under the schema both engines
        emit, so host and compiled pipelines answer one question the same
        way. The host profiler measures continuously off the scheduler
        events — ``ticks``/``spans``/``registry`` exist for signature
        parity with :meth:`CompiledProfiler.profile_report` and are
        ignored."""
        from dbsp_tpu.obs.opprofile import PROFILE_SCHEMA

        total_ns = sum(self.elapsed_ns.values()) or 1
        rows = []
        for gid, ns in sorted(self.elapsed_ns.items(), key=lambda kv: -kv[1]):
            node = self._node(gid)
            rows.append({
                "node": ".".join(map(str, gid)),
                "name": node.operator.name,
                "kind": type(node.operator).__name__,
                "total_ms": round(ns / 1e6, 3),
                "evals": self.counts[gid],
                "share": round(ns / total_ns, 4),
                "meta": dict(node.operator.metadata(),
                             inputs=[".".join(map(str, (*gid[:-1], i)))
                                     for i in node.inputs]),
            })
        return {"schema": PROFILE_SCHEMA, "mode": "host",
                "steps": self.steps, "attribution": "measured",
                "operators": rows, "measured": None}

    def dump_dot(self) -> str:
        """Graphviz rendering: nodes annotated with time, edges = dataflow
        (reference: per-worker .dot profiles)."""
        lines = ["digraph profile {", '  rankdir="LR";']
        total = sum(self.elapsed_ns.values()) or 1

        def emit(circuit: Circuit, prefix):
            for node in circuit.nodes:
                gid = (*prefix, node.index)
                ns = self.elapsed_ns.get(gid, 0)
                pct = 100.0 * ns / total
                label = (f"{node.operator.name}\\n{ns / 1e6:.1f}ms "
                         f"({pct:.0f}%)")
                shade = min(9, 1 + int(pct / 12))
                name = "n" + "_".join(map(str, gid))
                lines.append(
                    f'  {name} [label="{label}", style=filled, '
                    f'colorscheme=reds9, fillcolor={shade}];')
                for i in node.inputs:
                    src = "n" + "_".join(map(str, (*prefix, i)))
                    lines.append(f"  {src} -> {name};")
                if node.child is not None:
                    emit(node.child, gid)

        emit(self.circuit, ())
        lines.append("}")
        return "\n".join(lines)


class CompiledProfiler:
    """Profile source for pipelines on the compiled path: the whole tick is
    ONE XLA program, so the host profiler's per-operator eval timings do
    not exist. Reports the same JSON shape with the compiled node list
    (operator name, node id, static capacities) plus whole-tick latency
    percentiles — the observable the compiled mode actually has (the
    reference's JIT profile is similarly coarser than the interpreted
    one)."""

    def __init__(self, driver):
        self.driver = driver

    def profile(self):
        return [{"name": cn.op.name, "node": cn.node.index,
                 "kind": type(cn).__name__, "caps": dict(cn.caps)}
                for cn in self.driver.ch.cnodes]

    def _latency(self):
        lat = sorted(self.driver.ch.step_times_ns)
        if not lat:
            return {}
        return {"p50_ms": round(lat[len(lat) // 2] / 1e6, 3),
                "p99_ms": round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))] / 1e6, 3),
                "ticks": len(lat)}

    def dump_json(self) -> str:
        return json.dumps({"steps": getattr(self.driver, "_tick", 0),
                           "mode": "compiled",
                           "tick_latency": self._latency(),
                           "operators": self.profile()})

    def profile_report(self, ticks=None, spans=None, registry=None) -> dict:
        """The shared ``/profile`` report (``opprofile.PROFILE_SCHEMA``) for
        the compiled engine — operator-level EXPLAIN ANALYZE over the fused
        step program. ``ticks`` picks the attribution mode:

        * ``ticks=N`` (or ``DBSP_TPU_PROFILE=segment`` armed) — MEASURED:
          the driver flushes its open deferred-validation interval, then
          runs N segmented ticks (per-node wall time + rows), asserts
          bit-identity against the fused program, and rewinds
          (``opprofile.measured_profile``). The caller must have quiesced
          the circuit thread (the ``/profile`` route holds the controller
          step lock).
        * ``ticks=None`` unarmed — STATIC: per-node XLA cost analysis from
          one side-effect-free probe tick (``opprofile.static_profile``).

        Sharded circuits cannot be segmented; both modes degrade to the
        graph-metadata report rather than failing the route, with the
        refusal recorded under ``"degraded"``. A measured-mode
        bit-identity failure is NOT degraded — that is a real engine
        divergence and must surface."""
        from dbsp_tpu.obs import opprofile

        if ticks is None:
            ticks = opprofile.env_default_ticks()
        ch = self.driver.ch
        try:
            if ticks:
                return self.driver.profile_ticks(int(ticks), spans=spans,
                                                 registry=registry)
            return opprofile.static_profile(ch)
        except opprofile.ProfileDivergence:
            raise
        except opprofile.ProfileError as e:
            report = opprofile.graph_profile(ch)
            report["degraded"] = str(e)
            return report
