"""Lock-free read serving plane: epoch-published snapshots, view index,
changefeed, read replicas.

Everything through the residency/compilation PRs scales *ingest*;
production traffic against maintained views is mostly *reads*, and until
this module every read rode the controller step lock
(``Controller.quiesce()``). The plane moves reads off that lock entirely:

* **Epoch-published snapshots** — at each validation publish (every host
  step; every closed interval on the compiled engine) the controller
  calls :meth:`ReadPlane.publish` *while it already holds the step
  lock*; the plane builds an immutable :class:`ViewSnapshot` per changed
  view and swaps it in under the plane's own ``_lock``. Cold sorted runs
  are shared by reference between consecutive snapshots (only the new
  interval's delta becomes a fresh run), so publication is O(hot delta),
  not O(state) — the LSM idiom of the trace spines, replayed host-side.
* **Lock-free readers** — a read resolves ``view_state.snap`` with ONE
  GIL-atomic attribute load and then touches only that immutable
  snapshot: no step lock, no quiesce, not even the plane lock. Point and
  range lookups run ``np.searchsorted`` prefix narrowing over each run's
  (keys, vals)-lexicographic column arrays and Z-sum the fragments.
* **Changefeed** — every publication appends exactly one record per
  changed view to a bounded per-view ring; long-poll readers resume from
  an epoch cursor. A cursor that fell behind the ring's retention gets a
  synthesized ``kind="snapshot"`` record (full state at the current
  epoch) followed by live deltas — exactly-once per published interval,
  never a gap.
* **Read replicas** — :class:`ReplicaServer` is a stateless HTTP
  snapshot server fed by the primary's changefeed; the manager
  fans reads out across replicas and surfaces per-replica staleness.

Mode: a view whose output stream ends in ``integrate()`` emits FULL
INTEGRALS per tick (``mode="last"`` — the manager's SQL views); raw
pipelines emit per-interval deltas (``mode="delta"``) which the plane
folds into runs. Changefeed records are ALWAYS deltas (uniform replica
fold); in "last" mode the delta is the dict-diff of consecutive
integrals, so publication there is O(view) — documented, and irrelevant
to the raw ingest A/B which runs delta mode.

Kill switch: ``DBSP_TPU_READPLANE=0`` (:func:`readplane_enabled`)
disables publication; the HTTP layer then falls back to the quiesced
read path — the A/B control ``tools/bench_readpath_ab.py`` measures
against.

Staleness contract: a snapshot read is at most one validation interval
behind the writer (host engine: one step). ``snap.ts`` is the publish
wall-time; replica staleness adds one changefeed hop, surfaced per
replica via ``dbsp_tpu_read_replica_staleness_seconds{replica}``.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook
from dbsp_tpu.zset.batch import Batch

__all__ = ["readplane_enabled", "READ_ROUTES", "ViewSnapshot", "ReadPlane",
           "ReplicaServer"]


def readplane_enabled(env=None) -> bool:
    """``DBSP_TPU_READPLANE`` gate (default on). Off = no publication;
    HTTP reads fall back to the quiesced path (the A/B control)."""
    e = os.environ if env is None else env
    return e.get("DBSP_TPU_READPLANE", "1") != "0"


#: closed value set for the ``route`` metric label (check_metrics lints
#: label NAMES; the value set here is fixed by the read API surface)
READ_ROUTES = ("view_point", "view_range", "view_scan", "output",
               "changefeed", "replica_fanout")


# ---------------------------------------------------------------------------
# sorted runs + snapshots (immutable after construction)
# ---------------------------------------------------------------------------


class _Run:
    """One immutable sorted run: live rows only, columns as host numpy
    arrays in (keys, vals) lexicographic order — the layout
    ``np.searchsorted`` prefix narrowing needs. Never mutated after
    construction; snapshots share cold runs by reference."""

    __slots__ = ("cols", "weights", "n")

    def __init__(self, cols: Sequence[np.ndarray], weights: np.ndarray):
        self.cols = tuple(cols)
        self.weights = weights
        self.n = int(weights.shape[0])


def _run_from_batch(b: Optional[Batch]) -> Optional[_Run]:
    """Host-side run from an emitted batch: drop dead rows, materialize
    numpy columns, and (re)establish lexicographic order. Emitted batches
    are consolidated by engine contract, but the lexsort is cheap
    insurance on the publish path — the read path's searchsorted contract
    must never depend on an upstream invariant silently eroding."""
    if b is None:
        return None
    ws = np.asarray(b.weights).reshape(-1)
    live = ws != 0
    if not bool(live.any()):
        return None
    cols = [np.asarray(c).reshape(-1)[live] for c in b.cols]
    ws = ws[live]
    order = np.lexsort(tuple(reversed(cols)))
    if not np.array_equal(order, np.arange(ws.size)):
        cols = [c[order] for c in cols]
        ws = ws[order]
    return _Run(cols, ws)


def _run_rows(run: Optional[_Run]) -> List[list]:
    """JSON-ready ``[*row, weight]`` rows of one run."""
    if run is None:
        return []
    lists = [c.tolist() for c in run.cols] + [run.weights.tolist()]
    return [list(t) for t in zip(*lists)]


def _merge_rows(runs: Sequence[Tuple[Sequence[np.ndarray], np.ndarray]]
                ) -> List[Tuple[tuple, int]]:
    """Z-sum row fragments from several runs into one sorted
    ``[(row_tuple, weight)]`` list, dropping zero-weight rows."""
    acc: Dict[tuple, int] = {}
    for cols, ws in runs:
        if len(ws) == 0:
            continue
        lists = [c.tolist() for c in cols]
        for i, w in enumerate(ws.tolist()):
            t = tuple(col[i] for col in lists)
            nw = acc.get(t, 0) + w
            if nw:
                acc[t] = nw
            else:
                acc.pop(t, None)
    return sorted(acc.items())


def _rows_to_run(rows: List[Tuple[tuple, int]],
                 proto: Optional[_Run]) -> Tuple[_Run, ...]:
    """Single compacted run from merged rows (dtypes from ``proto``)."""
    if not rows:
        return ()
    ncols = len(rows[0][0])
    dtypes = [c.dtype for c in proto.cols] if proto is not None \
        else [np.int64] * ncols
    cols = [np.array([t[j] for t, _ in rows], dtype=dtypes[j])
            for j in range(ncols)]
    ws = np.array([w for _, w in rows], dtype=np.int64)
    return (_Run(cols, ws),)


def _bounds(run: _Run, prefix: Sequence[int]) -> Tuple[int, int]:
    """Row index window matching a key-prefix via successive
    searchsorted narrowing over the lexicographic columns."""
    lo, hi = 0, run.n
    for c, v in zip(run.cols, prefix):
        seg = c[lo:hi]
        lo, hi = (lo + int(np.searchsorted(seg, v, "left")),
                  lo + int(np.searchsorted(seg, v, "right")))
        if lo >= hi:
            break
    return lo, hi


def _range_bounds(run: _Run, lo_v, hi_v) -> Tuple[int, int]:
    """Inclusive ``[lo, hi]`` window over the FIRST key column (range
    queries address the leading key; multi-column prefixes are the point
    lookup's job)."""
    c0 = run.cols[0]
    lo = 0 if lo_v is None else int(np.searchsorted(c0, lo_v, "left"))
    hi = run.n if hi_v is None else int(np.searchsorted(c0, hi_v, "right"))
    return lo, hi


class ViewSnapshot:
    """Immutable published state of one view at one epoch. Readers hold a
    reference across their whole query; publication swaps the
    ``_ViewState.snap`` pointer and never mutates an existing snapshot."""

    __slots__ = ("view", "epoch", "step", "ts", "mode", "nkeys", "runs",
                 "last_batch", "last_step")

    def __init__(self, view: str, epoch: int, step: int, ts: float,
                 mode: str, nkeys: Optional[int], runs: Tuple[_Run, ...],
                 last_batch: Optional[Batch], last_step: int):
        self.view = view
        self.epoch = epoch
        self.step = step
        self.ts = ts
        self.mode = mode
        self.nkeys = nkeys
        self.runs = runs
        self.last_batch = last_batch
        self.last_step = last_step

    def rows(self) -> List[Tuple[tuple, int]]:
        """Full merged state (sorted ``[(row_tuple, weight)]``)."""
        return _merge_rows([(r.cols, r.weights) for r in self.runs])


class _ViewState:
    """Per-view mutable publication state. All mutation happens under the
    plane's ``_lock``; the reader-facing ``snap`` pointer is swapped
    there and read lock-free. (No ``__slots__``: the tsan class swap
    needs ``__dict__``/``__weakref__``.)"""

    def __init__(self, name: str, handle, mode: str, capacity: int):
        self.name = name
        self.handle = handle
        self.mode = mode
        self.nkeys: Optional[int] = None
        self.cid = handle.register_consumer() if mode == "delta" else None
        self.snap = ViewSnapshot(name, 0, 0, 0.0, mode, None, (), None, 0)
        self.prev_rows: Dict[tuple, int] = {}  # "last" mode diff base
        self.feed: deque = deque(maxlen=capacity)
        self.dropped_epoch = 0  # max epoch aged out of the feed ring
        self.seen_step = 0
        _tsan_hook(self)


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


class ReadPlane:
    """Primary-side read serving plane (one per controller).

    Writers: :meth:`publish` — called by the controller on its step path
    (step lock already held); takes the plane's OWN ``_lock`` for the
    epoch swap. Readers: :meth:`query`/:meth:`snapshot` — zero locks;
    :meth:`changefeed` — lock-free scan of the feed ring plus an
    OPTIONAL bounded wait on ``_wakeup`` (its own condition, never held
    while publishing runs and released during every wait)."""

    def __init__(self, enabled: Optional[bool] = None,
                 capacity: Optional[int] = None,
                 compact_after: Optional[int] = None):
        self.enabled = readplane_enabled() if enabled is None \
            else bool(enabled)
        self.capacity = int(capacity if capacity is not None else
                            os.environ.get("DBSP_TPU_CHANGEFEED_CAPACITY",
                                           "1024"))
        self.compact_after = int(
            compact_after if compact_after is not None else
            os.environ.get("DBSP_TPU_READPLANE_COMPACT_AFTER", "8"))
        self._lock = threading.Lock()
        # long-poll wakeup only — deliberately NOT the plane lock: a
        # TracedLock-wrapped lock can't back a Condition (wait() bypasses
        # the wrapper's bookkeeping), and pollers must never contend with
        # the publish path anyway
        self._wakeup = threading.Condition()
        self._views: Dict[str, _ViewState] = {}
        self.epoch = 0
        self.publishes = 0
        self.last_publish_ts: Optional[float] = None
        self.flight = None
        self._read_qps = None
        self._read_seconds = None
        self._publish_total = None
        _tsan_hook(self)

    # -- wiring -------------------------------------------------------------

    def add_view(self, name: str, handle) -> None:
        """Register one served view (controller construction time, before
        any traffic). Mode comes from the build-time ``integrate()``
        stamp on the output operator."""
        mode = "last" if getattr(handle, "integral", False) else "delta"
        with self._lock:
            self._views[name] = _ViewState(name, handle, mode,
                                           self.capacity)

    def bind(self, registry=None, pipeline: str = "", flight=None) -> None:
        """Optional observability wiring (idempotent): read metrics on a
        registry + a flight ring for staleness-breach attribution. The
        plane is fully functional unbound (raw controllers, tests)."""
        if flight is not None:
            self.flight = flight
        if registry is None or self._read_qps is not None:
            return
        from dbsp_tpu.obs.registry import default_latency_buckets

        self._read_qps = registry.counter(
            "dbsp_tpu_read_qps_total",
            "Read-plane requests served, by route (closed set: "
            "serving.READ_ROUTES)", labels=("route",))
        self._read_seconds = registry.histogram(
            "dbsp_tpu_read_seconds",
            "Read-plane request latency by route — snapshot resolution "
            "+ index lookup, never the step lock",
            labels=("route",), buckets=default_latency_buckets())
        self._publish_total = registry.counter(
            "dbsp_tpu_read_publish_total",
            "Epoch publications (snapshot swaps) performed by the "
            "controller's validation publish")

    def note_read(self, route: str, t0: float) -> None:
        """Metric stamp for one served read (``t0`` = perf_counter at
        request start). No-op when unbound."""
        if self._read_qps is not None:
            self._read_qps.labels(route=route).inc()
            self._read_seconds.labels(route=route).observe(
                time.perf_counter() - t0)

    # -- publication (controller step path; plane lock only) ---------------

    def publish(self, tracer=None) -> int:
        """Swap in new snapshots for every view whose output advanced
        since the last publication; append exactly one changefeed record
        per changed view. Returns the (possibly unchanged) epoch.

        Called by the controller AFTER outputs were emitted for the
        closing interval, while it still holds the step lock — so handle
        reads here are race-free. The epoch swap itself happens under the
        plane's own ``_lock``; readers never take it.

        ``tracer`` (the controller's :class:`~dbsp_tpu.obs.tracing.
        E2ETracer`) seals every awaiting trace context into this epoch's
        annotation, which rides each changefeed record as ``trace`` —
        that is how the context crosses to replicas. The seal is a pure
        state move under the tracer's leaf lock; its metric/span/timeline
        effects run after the plane lock is released."""
        if not self.enabled:
            return self.epoch
        now = time.time()
        ann = None
        with self._lock:
            changed = []
            for vs in self._views.values():
                sid = vs.handle.step_id
                if sid == vs.seen_step:
                    continue
                vs.seen_step = sid
                changed.append((vs, sid))
            if not changed:
                return self.epoch
            epoch = self.epoch + 1
            if tracer is not None:
                ann = tracer.note_publish(epoch, ts=now)
            for vs, sid in changed:
                self._publish_view_locked(vs, sid, epoch, now, ann)
            self.epoch = epoch
            self.publishes += 1
            self.last_publish_ts = now
        if tracer is not None:
            tracer.flush_publish(ann)
        if self._publish_total is not None:
            self._publish_total.inc()
        with self._wakeup:
            self._wakeup.notify_all()
        return epoch

    def _publish_view_locked(self, vs: _ViewState, sid: int, epoch: int,
                             now: float,
                             ann: Optional[dict] = None
                             ) -> None:  # holds: _lock
        cur = vs.handle.peek()
        if vs.nkeys is None and cur is not None:
            vs.nkeys = len(cur.keys)
        if vs.mode == "last":
            run = _run_from_batch(cur)
            runs: Tuple[_Run, ...] = (run,) if run is not None else ()
            state = dict(_merge_rows([(r.cols, r.weights) for r in runs]))
            delta_rows = _diff_rows(vs.prev_rows, state)
            vs.prev_rows = state
        else:
            delta = vs.handle.read_consumer(vs.cid)
            run = _run_from_batch(delta)
            runs = vs.snap.runs + ((run,) if run is not None else ())
            if len(runs) > self.compact_after:
                proto = runs[0]
                runs = _rows_to_run(
                    _merge_rows([(r.cols, r.weights) for r in runs]),
                    proto)
            delta_rows = _run_rows(run)
        vs.snap = ViewSnapshot(vs.name, epoch, sid, now, vs.mode,
                               vs.nkeys, runs, cur, sid)
        if vs.feed.maxlen is not None and len(vs.feed) == vs.feed.maxlen \
                and vs.feed:
            vs.dropped_epoch = max(vs.dropped_epoch, vs.feed[0]["epoch"])
        rec = {"view": vs.name, "epoch": epoch, "step": sid,
               "ts": now, "kind": "delta", "nkeys": vs.nkeys,
               "rows": delta_rows}
        if ann is not None:
            # the sealed e2e annotation (trace ids + writer-stage
            # breakdown) is shared by reference across this epoch's
            # records — JSON-safe and never mutated after the seal
            rec["trace"] = ann
        vs.feed.append(rec)

    # -- readers (zero locks on the snapshot path) --------------------------

    def views(self) -> Tuple[str, ...]:
        return tuple(self._views)

    def snapshot(self, view: str) -> ViewSnapshot:
        """Current immutable snapshot — ONE atomic attribute load."""
        vs = self._views.get(view)
        if vs is None:
            raise KeyError(view)
        return vs.snap

    def query(self, view: str, key: Optional[Sequence[int]] = None,
              lo=None, hi=None, limit: Optional[int] = None) -> dict:
        """Point (``key`` prefix), range (``[lo, hi]`` inclusive over the
        leading key column), or full-scan read against the published
        snapshot. Lock-free: resolves the snapshot once, then touches
        only immutable runs."""
        snap = self.snapshot(view)
        if key is not None:
            parts = []
            for r in snap.runs:
                b, e = _bounds(r, key)
                if b < e:
                    parts.append(([c[b:e] for c in r.cols],
                                  r.weights[b:e]))
            rows = _merge_rows(parts)
        elif lo is not None or hi is not None:
            parts = []
            for r in snap.runs:
                b, e = _range_bounds(r, lo, hi)
                if b < e:
                    parts.append(([c[b:e] for c in r.cols],
                                  r.weights[b:e]))
            rows = _merge_rows(parts)
        else:
            rows = snap.rows()
        if limit is not None:
            rows = rows[:limit]
        return {"view": view, "epoch": snap.epoch, "step": snap.step,
                "ts": snap.ts, "mode": snap.mode, "nkeys": snap.nkeys,
                "rows": [[*t, w] for t, w in rows]}

    def changefeed(self, view: str, after_epoch: int = 0,
                   timeout_s: float = 0.0,
                   limit: Optional[int] = None) -> dict:
        """Changefeed read with a resume-from-epoch cursor. Returns every
        retained record with ``epoch > after_epoch`` (at most ``limit``);
        when the cursor predates the ring's retention the first record is
        a synthesized full-state ``kind="snapshot"`` at the current
        epoch. ``timeout_s`` long-polls on the wakeup condition (released
        for the whole wait; never the plane or step lock)."""
        vs = self._views.get(view)
        if vs is None:
            raise KeyError(view)
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            if vs.dropped_epoch > after_epoch:
                snap = vs.snap
                rec = {"view": view, "epoch": snap.epoch,
                       "step": snap.step, "ts": snap.ts,
                       "kind": "snapshot", "nkeys": snap.nkeys,
                       "rows": [[*t, w] for t, w in snap.rows()]}
                recs = [rec] + [r for r in list(vs.feed)
                                if r["epoch"] > snap.epoch]
            else:
                recs = [r for r in list(vs.feed)
                        if r["epoch"] > after_epoch]
            if recs or timeout_s <= 0:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with self._wakeup:
                if self.epoch <= after_epoch:
                    self._wakeup.wait(min(0.25, remaining))
        if limit is not None:
            recs = recs[:limit]
        return {"view": view, "epoch": self.epoch, "records": recs}

    # -- checkpoint integration --------------------------------------------

    def state_batches(self) -> Dict[str, Batch]:
        """Compacted per-view state as consolidated :class:`Batch`es for
        the checkpoint payload (called under the step lock via the
        controller's checkpoint path)."""
        out: Dict[str, Batch] = {}
        with self._lock:
            for name, vs in self._views.items():
                snap = vs.snap
                if not snap.runs:
                    continue
                rows = snap.rows()
                if not rows:
                    continue
                proto = snap.runs[0]
                nk = snap.nkeys if snap.nkeys is not None else len(
                    proto.cols)
                cols = [np.array([t[j] for t, _ in rows],
                                 dtype=proto.cols[j].dtype)
                        for j in range(len(proto.cols))]
                ws = np.array([w for _, w in rows], dtype=np.int64)
                out[name] = Batch.from_columns(cols[:nk], cols[nk:], ws)
        return out

    def restore(self, epoch: int, batches: Dict[str, Batch]) -> None:
        """Adopt checkpointed plane state (controller restore path, step
        lock held). Feeds reset; any pre-restore cursor resumes via a
        synthesized snapshot record (``dropped_epoch = epoch``)."""
        now = time.time()
        with self._lock:
            self.epoch = int(epoch)
            for name, vs in self._views.items():
                b = batches.get(name)
                run = _run_from_batch(b)
                runs = (run,) if run is not None else ()
                if run is not None:
                    vs.nkeys = len(b.keys)
                sid = vs.handle.step_id
                vs.seen_step = sid
                vs.snap = ViewSnapshot(name, self.epoch, sid, now,
                                       vs.mode, vs.nkeys, runs,
                                       vs.handle.peek(), sid)
                if vs.mode == "last":
                    vs.prev_rows = dict(vs.snap.rows())
                elif vs.cid is not None:
                    try:  # discard deltas already folded into the state
                        vs.handle.read_consumer(vs.cid)
                    except KeyError:
                        vs.cid = vs.handle.register_consumer()
                vs.feed.clear()
                vs.dropped_epoch = self.epoch
        with self._wakeup:
            self._wakeup.notify_all()

    def stats(self) -> dict:
        views = {}
        for name, vs in self._views.items():
            snap = vs.snap
            views[name] = {"mode": snap.mode, "epoch": snap.epoch,
                           "step": snap.step, "runs": len(snap.runs),
                           "rows": sum(r.n for r in snap.runs),
                           "feed_len": len(vs.feed)}
        return {"enabled": self.enabled, "epoch": self.epoch,
                "publishes": self.publishes,
                "last_publish_ts": self.last_publish_ts, "views": views}


def _diff_rows(prev: Dict[tuple, int],
               cur: Dict[tuple, int]) -> List[list]:
    """Z-set delta between consecutive integrals (``cur - prev``) as
    JSON-ready sorted ``[*row, weight]`` rows."""
    out = []
    for t, w in cur.items():
        dw = w - prev.get(t, 0)
        if dw:
            out.append([*t, dw])
    for t, w in prev.items():
        if t not in cur:
            out.append([*t, -w])
    out.sort()
    return out


# ---------------------------------------------------------------------------
# read replicas
# ---------------------------------------------------------------------------


class ReplicaServer:
    """Stateless snapshot read replica: folds the primary's changefeed
    into a host-side row map per view and serves ``GET /view/<name>``
    (point/range/scan) + ``GET /status`` from it. No engine, no step
    lock anywhere in the process — the whole state is the changefeed
    fold, reconstructible from epoch 0 (or any snapshot record).

    ``stall()``/``resume()`` freeze the feed thread — the seeded
    freshness-breach hook the replica tests and the manager's staleness
    surfacing are proven against."""

    def __init__(self, primary: str, views: Sequence[str],
                 name: str = "replica", host: str = "127.0.0.1",
                 port: int = 0, poll_timeout_s: float = 0.5, e2e=None):
        from dbsp_tpu.obs.tracing import SpanRecorder

        self.primary = primary.rstrip("/")
        self.views_served = tuple(views)
        self.name = name
        self.poll_timeout_s = float(poll_timeout_s)
        # e2e delta tracing: the primary's in-process E2ETracer (manager
        # wiring) — changefeed `trace` annotations extend with this
        # replica's transport/apply stages; None = no stage attribution
        self.e2e = e2e
        # this replica's OWN span ring (its `/trace` surface): the same
        # delta shows up here and in the writer's ring under identical
        # trace ids, which is what the fleet trace merges on
        self.spans = SpanRecorder(process=name)
        self._lock = threading.Lock()  # state/cursor/cache fold guard
        self._state: Dict[str, Dict[tuple, int]] = {
            v: {} for v in self.views_served}
        self._cursor: Dict[str, int] = {v: 0 for v in self.views_served}
        self._nkeys: Dict[str, Optional[int]] = {
            v: None for v in self.views_served}
        self._applied_ts: Dict[str, Optional[float]] = {
            v: None for v in self.views_served}
        self._sorted: Dict[str, Optional[tuple]] = {
            v: None for v in self.views_served}
        self._trace: Dict[str, Optional[dict]] = {
            v: None for v in self.views_served}
        self.applied = 0
        self.stalled = False
        self._stop = threading.Event()
        plane = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj: dict,
                      headers: Optional[dict] = None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlsplit(self.path)
                q = urllib.parse.parse_qs(parsed.query)
                parts = parsed.path.strip("/").split("/")
                try:
                    if parts[0] == "status":
                        self._json(200, plane.status())
                    elif parts[0] == "trace":
                        self._json(200, plane.spans.to_chrome_trace())
                    elif parts[0] == "view" and len(parts) == 2:
                        obj = plane.answer(parts[1], q)
                        ids = (obj.get("trace") or {}).get("ids") or ()
                        hdrs = {"X-Dbsp-Trace": ",".join(ids)} \
                            if ids else None
                        self._json(200, obj, headers=hdrs)
                    else:
                        self._json(404, {"error": "unknown route"})
                except KeyError as e:
                    self._json(404, {"error": f"unknown view {e}"})
                except (ValueError, IndexError) as e:
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"{name}-http",
            daemon=True)
        self._feed_thread = threading.Thread(
            target=self._feed_loop, name=f"{name}-feed", daemon=True)
        _tsan_hook(self)

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "ReplicaServer":
        self._serve_thread.start()
        self._feed_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._feed_thread.join(timeout=5)

    def stall(self) -> None:
        """Freeze the changefeed fold (seeded staleness breach)."""
        self.stalled = True

    def resume(self) -> None:
        self.stalled = False

    # -- feed ---------------------------------------------------------------

    def _feed_loop(self) -> None:
        while not self._stop.is_set():
            if self.stalled:
                time.sleep(0.02)
                continue
            advanced = False
            for v in self.views_served:
                if self._stop.is_set() or self.stalled:
                    break
                url = (f"{self.primary}/changefeed?view={v}"
                       f"&after={self._cursor[v]}"
                       f"&timeout={self.poll_timeout_s}")
                try:
                    with urllib.request.urlopen(url, timeout=
                                                self.poll_timeout_s + 5
                                                ) as r:
                        obj = json.loads(r.read())
                except (urllib.error.URLError, OSError, ValueError):
                    time.sleep(0.1)
                    continue
                recs = obj.get("records") or []
                # re-check the stall AFTER the long-poll returns: a stall
                # raised while the request was in flight must drop the
                # response, or the freeze is porous for one poll interval
                if recs and not self.stalled:
                    self._apply(v, recs)
                    advanced = True
            if not advanced:
                time.sleep(0.02)

    def _apply(self, view: str, recs: List[dict]) -> None:
        recv_ts = time.time()
        t0 = time.perf_counter()
        with self._lock:
            st = self._state[view]
            for rec in recs:
                if rec.get("kind") == "snapshot":
                    st = self._state[view] = {}
                nk = rec.get("nkeys")
                if nk is not None:
                    self._nkeys[view] = nk
                for row in rec.get("rows", ()):
                    t, w = tuple(row[:-1]), row[-1]
                    nw = st.get(t, 0) + w
                    if nw:
                        st[t] = nw
                    else:
                        st.pop(t, None)
                self._cursor[view] = rec["epoch"]
                self._applied_ts[view] = rec["ts"]
                self.applied += 1
            self._sorted[view] = None
        if self.e2e is not None:
            # stage stamps for the newest traced record of this fold:
            # transport = receipt - primary publish (same-host wall
            # clock), apply = the measured fold above. One annotation per
            # fold — a catch-up burst is one transport/apply sample, not
            # one per record.
            ann = next((r["trace"] for r in reversed(recs)
                        if r.get("trace")), None)
            ext = self.e2e.note_apply(ann, recv_ts,
                                      time.perf_counter() - t0,
                                      spans=self.spans)
            if ext is not None:
                with self._lock:
                    self._trace[view] = ext

    # -- reads --------------------------------------------------------------

    def _table(self, view: str) -> tuple:
        """(rows, weights, epoch, ts, nkeys) — one immutable tuple built
        under the fold lock, lazily rebuilt after a fold and served to
        many readers by reference. Epoch/ts ride in the SAME tuple as the
        rows so a read can never pair one fold's rows with another fold's
        cursor (the serial-twin test hammers exactly that window)."""
        cached = self._sorted[view]
        if cached is not None:
            return cached
        with self._lock:
            items = sorted(self._state[view].items())
            cached = ([t for t, _ in items], [w for _, w in items],
                      self._cursor[view], self._applied_ts[view],
                      self._nkeys[view])
            self._sorted[view] = cached
        return cached

    def answer(self, view: str, q: Dict[str, list]) -> dict:
        t0 = time.perf_counter()
        if view not in self._state:
            raise KeyError(view)
        rows_t, ws, epoch, ts, nkeys = self._table(view)
        if "key" in q:
            prefix = tuple(int(x) for x in q["key"][0].split(","))
            b = bisect.bisect_left(rows_t, prefix)
            out = []
            while b < len(rows_t) and rows_t[b][:len(prefix)] == prefix:
                out.append([*rows_t[b], ws[b]])
                b += 1
        elif "lo" in q or "hi" in q:
            lo = int(q["lo"][0]) if "lo" in q else None
            hi = int(q["hi"][0]) if "hi" in q else None
            b = 0 if lo is None else bisect.bisect_left(rows_t, (lo,))
            e = len(rows_t) if hi is None else \
                bisect.bisect_left(rows_t, (hi + 1,))
            out = [[*rows_t[i], ws[i]] for i in range(b, e)]
        else:
            out = [[*t, w] for t, w in zip(rows_t, ws)]
        if "limit" in q:
            out = out[:int(q["limit"][0])]
        resp = {"view": view, "epoch": epoch, "ts": ts,
                "replica": self.name, "nkeys": nkeys, "rows": out}
        if self.e2e is not None:
            self.e2e.annotate_replica_read(resp, self._trace.get(view), t0)
        return resp

    def status(self) -> dict:
        return {"name": self.name, "stalled": self.stalled,
                "applied": self.applied, "epochs": dict(self._cursor),
                "applied_ts": dict(self._applied_ts),
                "trace_e2e": bool(self.e2e is not None
                                  and self.e2e.enabled)}
