"""Pallas TPU prototypes of the engine's irregular-access inner loops.

ROOFLINE §3 names XLA:TPU's lowering of the probe/gather loops as the
projection's biggest unknown: dependent gathers lower to while loops at
XLA's discretion, which is exactly the fusion guess the DBSP
delta-proportional cost model cannot afford to lose. These kernels take
that lowering into our own hands (the MegaBlocks move: stop trusting the
compiler on irregular gather/scatter and hand-write the hot loop):

* :func:`lex_probe_ladder_pallas` — the ladder-wide lexicographic binary
  search (``cursor.lex_probe_ladder``) as ONE Pallas program, grid over
  trace levels, each program resolving every query against its level's
  sorted key columns with static block shapes ([K, maxcap] stacked tables,
  [1, m] query lanes).
* :func:`rank_merge_scatter` — the rank-merge inner loop of
  ``kernels.merge_sorted_cols`` (cross-rank binary search + position
  scatter) as a single program; the netting/compaction tail stays shared
  with the XLA path.
* :func:`join_ladder_pallas` / :func:`gather_ladder_pallas` — the FUSED
  trace-ladder consumers (``cursor.join_ladder`` / ``cursor.gather_ladder``)
  as megakernels: grid over the K trace levels with static [K, maxcap]
  stacked blocks, each program probing its level, resolving its window of
  the shared output buffer through in-kernel prefix sums, and gathering its
  level's values — probe + expand + gather + weight-combine in ONE
  ``pallas_call``, with the running cross-level offset carried in the total
  output block across the (sequential) grid.

Selection: :func:`use_pallas` — ON when ``jax.default_backend() != "cpu"``
(the CPU backend keeps its native C++ custom calls), overridable with
``DBSP_TPU_PALLAS`` (``0``/``off`` force off everywhere; ``1``/``on``
force on; ``interpret`` forces the INTERPRETER — how the tier-1 suite
bit-identity-tests these kernels on CPU with no TPU attached, and the
mode every kernel here runs in automatically when the backend is CPU).
The first live tunnel run via tools/aot_tpu.py measures the compiled
variants; until then interpret-mode identity is the maintained contract.

Integer/bool columns only (widened to int64 like the native C++ path —
sign-extension preserves lexicographic order); float columns stay on the
XLA formulation. All outputs are bit-identical to the XLA reference
(tests/test_pallas_kernels.py proves it on adversarial ladders).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

Cols = Tuple[jnp.ndarray, ...]


def _mode() -> str:
    return os.environ.get("DBSP_TPU_PALLAS", "").strip().lower()


def enabled() -> bool:
    """Pallas kernels selected for dispatch (see module doc). The
    force-on spellings are shared with the dispatch pre-checks
    (``kernels.PALLAS_FORCE_ON``) so the grammar cannot drift."""
    from dbsp_tpu.zset.kernels import PALLAS_FORCE_ON

    m = _mode()
    if m in ("0", "off", "false"):
        return False
    if m in PALLAS_FORCE_ON:
        return True
    return jax.default_backend() != "cpu"


def interpret_mode() -> bool:
    """Run under the Pallas interpreter instead of Mosaic — forced by
    ``DBSP_TPU_PALLAS=interpret`` and automatic on the CPU backend (there
    is no Mosaic target there; this is what makes the tier-1 suite able
    to execute these kernels)."""
    return _mode() == "interpret" or jax.default_backend() == "cpu"


def _supported_dtype(d) -> bool:
    d = jnp.dtype(d)
    return jnp.issubdtype(d, jnp.integer) or d == jnp.bool_


def use_pallas(kernel: str, cols) -> bool:
    """Dispatch gate for one call site: pallas enabled AND every operand
    column int64-widenable. ``kernel`` mirrors the dispatch-counter name
    (``probe_ladder`` / ``rank_merge``) so a future per-kernel split of
    the env knob has a stable vocabulary."""
    return enabled() and all(_supported_dtype(c.dtype) for c in cols)


# ---------------------------------------------------------------------------
# Shared in-kernel primitive: vectorized lexicographic binary search
# ---------------------------------------------------------------------------


def _lex_search(table_cols, query_cols, n, steps: int, strict: bool,
                hi_init=None):
    """Insertion points of ``query`` lanes into ``table`` lanes ([1, m]
    int32) — the same mid-split recurrence as ``kernels.lex_probe``, so the
    converged result is bit-identical. ``n`` may be a traced per-level
    cap; ``steps`` must statically cover ceil(log2(n + 1))."""
    m = query_cols[0].shape[-1]
    lo = jnp.zeros((1, m), jnp.int32)
    hi = jnp.full((1, m), n, jnp.int32) if hi_init is None else hi_init

    def step(_, lohi):
        lo, hi = lohi
        active = lo < hi
        mid = (lo + hi) >> 1
        lt = jnp.zeros((1, m), jnp.bool_)
        eq = jnp.ones((1, m), jnp.bool_)
        for t, q in zip(table_cols, query_cols):
            tv = jnp.take_along_axis(t, mid, axis=1)
            lt = lt | (eq & (tv < q))
            eq = eq & (tv == q)
        go_right = lt if strict else (lt | eq)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, step, (lo, hi))
    return lo


# ---------------------------------------------------------------------------
# Ladder-wide probe
# ---------------------------------------------------------------------------


def _probe_ladder_kernel(caps_ref, *refs, ncols: int, steps: int,
                         strict: bool):
    tabs = [refs[i][:] for i in range(ncols)]            # [1, maxcap]
    qs = [refs[ncols + i][:] for i in range(ncols)]      # [1, m]
    out_ref = refs[2 * ncols]
    cap = caps_ref[0, 0]
    out_ref[:] = _lex_search(tabs, qs, cap, steps, strict)


def lex_probe_ladder_pallas(tables: Sequence[Cols], query_cols: Cols,
                            side: str = "left") -> jnp.ndarray:
    """Drop-in for the accelerator branch of ``cursor.lex_probe_ladder``:
    grid over the K trace levels, one program per level, each resolving
    all m queries with an in-VMEM binary search over its level's stacked
    (sentinel-padded) key columns. Returns [K, m] int32, lane (k, i) ==
    ``lex_probe(tables[k], query_cols, side)[i]`` bit-for-bit."""
    assert tables and query_cols
    K = len(tables)
    ncols = len(query_cols)
    m = query_cols[0].shape[-1]
    caps = [t[0].shape[-1] for t in tables]
    maxcap = max(caps)
    steps = max(c.bit_length() for c in caps)
    # stack heterogeneous levels into [K, maxcap] per column; the pad value
    # is never read (the search clamps hi to the level's own cap)
    stacked = []
    for ci in range(ncols):
        rows = []
        for t in tables:
            c = t[ci].astype(jnp.int64)
            if c.shape[-1] < maxcap:
                c = jnp.concatenate(
                    [c, jnp.full((maxcap - c.shape[-1],), jnp.iinfo(
                        jnp.int64).max, jnp.int64)])
            rows.append(c)
        stacked.append(jnp.stack(rows))
    qcols = [q.astype(jnp.int64).reshape(1, m) for q in query_cols]
    caps_arr = jnp.asarray(caps, jnp.int32).reshape(K, 1)

    grid = (K,)
    in_specs = [pl.BlockSpec((1, 1), lambda k: (k, 0))]
    in_specs += [pl.BlockSpec((1, maxcap), lambda k: (k, 0))
                 for _ in range(ncols)]
    in_specs += [pl.BlockSpec((1, m), lambda k: (0, 0))
                 for _ in range(ncols)]
    out = pl.pallas_call(
        partial(_probe_ladder_kernel, ncols=ncols, steps=steps,
                strict=side == "left"),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, m), lambda k: (k, 0)),
        out_shape=jax.ShapeDtypeStruct((K, m), jnp.int32),
        interpret=interpret_mode(),
    )(caps_arr, *stacked, *qcols)
    return out


# ---------------------------------------------------------------------------
# Fused ladder-consumer megakernels (probe + expand + gather, one call)
# ---------------------------------------------------------------------------


def _ladder_consumer_kernel(caps_ref, *refs, nk: int, ng: int,
                            steps_tab: int, steps_q: int, join: bool):
    """One grid step = one trace level: probe it, compute this level's
    window of the shared [1, out_cap] output (the running cross-level
    offset rides in the total block — TPU grids are sequential, so program
    k reads the sum of programs 0..k-1's totals), resolve each window slot
    to its (query, source) pair through the level-local prefix sums, and
    gather the level's values + weights into the shared buffers."""
    idx = 0
    tabs = [refs[idx + i][:] for i in range(nk)]            # [1, maxcap]
    idx += nk
    gcols = [refs[idx + i][:] for i in range(ng)]           # [1, maxcap]
    idx += ng
    lw = refs[idx][:]                                       # [1, maxcap]
    idx += 1
    qlo = [refs[idx + i][:] for i in range(nk)]             # [1, m]
    idx += nk
    qhi = [refs[idx + i][:] for i in range(nk)]             # [1, m]
    idx += nk
    qm = refs[idx][:]                                       # [1, m] int64:
    idx += 1                       # delta weights (join) / live 0|1 (gather)
    qrow_ref = refs[idx]
    out_refs = refs[idx + 1: idx + 1 + ng]
    w_ref = refs[idx + 1 + ng]
    tot_ref = refs[idx + 2 + ng]
    k = pl.program_id(0)
    m = qlo[0].shape[-1]
    out_cap = w_ref.shape[-1]

    @pl.when(k == 0)
    def _init():
        qrow_ref[:] = jnp.zeros((1, out_cap), jnp.int32)
        for r in out_refs:
            r[:] = jnp.zeros((1, out_cap), jnp.int64)
        w_ref[:] = jnp.zeros((1, out_cap), jnp.int64)
        tot_ref[:] = jnp.zeros((1, 1), jnp.int64)

    cap = caps_ref[0, 0]
    lo = _lex_search(tabs, qlo, cap, steps_tab, strict=True)
    hi = _lex_search(tabs, qhi, cap, steps_tab, strict=False)
    live = qm != 0
    lo = jnp.where(live, lo, 0)
    # distinct bounds may give an empty range (qhi < qlo): clamp gathers
    # nothing — a no-op for the equality/join form where hi >= lo always
    hi = jnp.where(live, jnp.maximum(hi, lo), lo)
    counts = (hi - lo).astype(jnp.int64)
    csum = jnp.cumsum(counts, axis=-1)
    starts = csum - counts
    tot_k = csum[0, m - 1]
    base = tot_ref[0, 0]
    j = jax.lax.broadcasted_iota(jnp.int64, (1, out_cap), 1)
    local = j - base
    sel = (local >= 0) & (local < tot_k)
    q = jnp.clip(local, 0, jnp.maximum(tot_k - 1, 0))
    # searchsorted-right over the level-local prefix sums == the stitched
    # expand_ladder's slot resolution restricted to this level's window
    flat = _lex_search([starts], [q], m, steps_q, strict=False) - 1
    flat = jnp.clip(flat, 0, m - 1)
    src = (jnp.take_along_axis(lo, flat, axis=1).astype(jnp.int64) + q
           - jnp.take_along_axis(starts, flat, axis=1))
    srci = jnp.clip(src, 0, jnp.maximum(cap - 1, 0)).astype(jnp.int32)
    lw_slot = jnp.take_along_axis(lw, srci, axis=1)
    if join:
        w_slot = jnp.take_along_axis(qm, flat, axis=1) * lw_slot
    else:
        w_slot = lw_slot
    qrow_ref[:] = jnp.where(sel, flat.astype(jnp.int32), qrow_ref[:])
    for r, g in zip(out_refs, gcols):
        r[:] = jnp.where(sel, jnp.take_along_axis(g, srci, axis=1), r[:])
    w_ref[:] = jnp.where(sel, w_slot, w_ref[:])
    tot_ref[:] = jnp.full((1, 1), base + tot_k, jnp.int64)


def _stack_levels(cols_per_level, maxcap: int, pad: int):
    """[K, maxcap] int64 stack of one column across heterogeneous levels
    (the pad value is never read: sources clamp to the level's own cap)."""
    rows = []
    for c in cols_per_level:
        c = c.astype(jnp.int64)
        if c.shape[-1] < maxcap:
            c = jnp.concatenate(
                [c, jnp.full((maxcap - c.shape[-1],), pad, jnp.int64)])
        rows.append(c)
    return jnp.stack(rows)


def _ladder_consumer_call(key_tabs, gather_tabs, weight_tab, qlo_cols,
                          qhi_cols, qmask, out_cap: int, join: bool):
    """Shared pallas_call builder for both megakernels. Returns raw
    ``(qrow, gathered int64 cols, w int64, total)`` — callers mask dead
    slots into their consumer-facing form."""
    K = len(weight_tab)
    nk = len(qlo_cols)
    ng = len(gather_tabs[0]) if gather_tabs else 0
    m = qlo_cols[0].shape[-1]
    caps = [w.shape[-1] for w in weight_tab]
    maxcap = max(caps)
    steps_tab = max(c.bit_length() for c in caps)
    steps_q = m.bit_length()
    pad = int(np.iinfo(np.int64).max)
    stacked = [_stack_levels([t[ci] for t in key_tabs], maxcap, pad)
               for ci in range(nk)]
    stacked += [_stack_levels([t[ci] for t in gather_tabs], maxcap, 0)
                for ci in range(ng)]
    stacked.append(_stack_levels(weight_tab, maxcap, 0))
    qs = [c.astype(jnp.int64).reshape(1, m) for c in qlo_cols]
    qs += [c.astype(jnp.int64).reshape(1, m) for c in qhi_cols]
    qs.append(qmask.astype(jnp.int64).reshape(1, m))
    caps_arr = jnp.asarray(caps, jnp.int32).reshape(K, 1)

    in_specs = [pl.BlockSpec((1, 1), lambda k: (k, 0))]
    in_specs += [pl.BlockSpec((1, maxcap), lambda k: (k, 0))
                 for _ in range(nk + ng + 1)]
    in_specs += [pl.BlockSpec((1, m), lambda k: (0, 0))
                 for _ in range(2 * nk + 1)]
    # every program revisits the SAME output block (index 0): the buffers
    # stay resident across the sequential grid and accumulate level windows
    out_specs = [pl.BlockSpec((1, out_cap), lambda k: (0, 0))
                 for _ in range(ng + 2)]
    out_specs.append(pl.BlockSpec((1, 1), lambda k: (0, 0)))
    out_shape = [jax.ShapeDtypeStruct((1, out_cap), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((1, out_cap), jnp.int64)
                  for _ in range(ng + 1)]
    out_shape.append(jax.ShapeDtypeStruct((1, 1), jnp.int64))
    out = pl.pallas_call(
        partial(_ladder_consumer_kernel, nk=nk, ng=ng, steps_tab=steps_tab,
                steps_q=steps_q, join=join),
        grid=(K,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(caps_arr, *stacked, *qs)
    qrow = out[0].reshape(out_cap)
    gathered = tuple(c.reshape(out_cap) for c in out[1:1 + ng])
    w = out[1 + ng].reshape(out_cap)
    total = out[2 + ng].reshape(())
    return qrow, gathered, w, total


def join_ladder_pallas(delta_keys, delta_w, levels, nk: int, out_cap: int):
    """The fused incremental-join core (``cursor.join_ladder`` minus the
    pair function) as ONE Pallas megakernel: both ladder probes, dead-row
    zeroing, cross-level expansion and the level-side value/weight gather.
    Returns ``(qrow, level_val_cols, w, valid, total)``; the caller applies
    the delta-side gathers, the pair function and the sentinel mask."""
    lval_dts = tuple(c.dtype for c in levels[0].vals)
    qrow, gathered, w, total = _ladder_consumer_call(
        [lvl.keys[:nk] for lvl in levels],
        [lvl.vals for lvl in levels],
        [lvl.weights for lvl in levels],
        delta_keys, delta_keys, delta_w, out_cap, join=True)
    j = jnp.arange(out_cap, dtype=jnp.int64)
    valid = j < total
    lvals = tuple(c.astype(d) for c, d in zip(gathered, lval_dts))
    return qrow, lvals, jnp.where(valid, w, 0).astype(delta_w.dtype), \
        valid, total


def gather_ladder_pallas(qkeys, qlive, levels, out_cap: int,
                         qhi_keys=None, gather_keys: int = 0):
    """The fused group gather (``cursor.gather_ladder``) as ONE Pallas
    megakernel, ``qhi_keys``/``gather_keys`` included. Returns the final
    consumer-facing ``((qrow, vals, w), total)`` with dead slots already
    canonical (qrow == q_cap, sentinel vals, weight 0)."""
    from dbsp_tpu.zset import kernels

    nk = len(qkeys)
    q_cap = qlive.shape[-1]
    gtabs = [(*lvl.keys[nk - gather_keys:nk], *lvl.vals) if gather_keys
             else tuple(lvl.vals) for lvl in levels]
    g_dts = tuple(c.dtype for c in gtabs[0])
    qrow, gathered, w, total = _ladder_consumer_call(
        [lvl.keys[:nk] for lvl in levels], gtabs,
        [lvl.weights for lvl in levels],
        qkeys, qkeys if qhi_keys is None else qhi_keys,
        qlive, out_cap, join=False)
    j = jnp.arange(out_cap, dtype=jnp.int64)
    valid = j < total
    vals = tuple(jnp.where(valid, c.astype(d), kernels.sentinel_for(d))
                 for c, d in zip(gathered, g_dts))
    qrow = jnp.where(valid, qrow, jnp.int32(q_cap)).astype(jnp.int32)
    w = jnp.where(valid, w, 0).astype(levels[0].weights.dtype)
    return (qrow, vals, w), total


# ---------------------------------------------------------------------------
# Segment reduction (the Aggregator zoo's five-op vocabulary)
# ---------------------------------------------------------------------------


_SEG_BLOCK = 128  # segments per program — one lane-width output block


def _segment_reduce_kernel(*refs, nv: int, ops):
    """One program = one block of segment ids: broadcast-compare the whole
    (vals, weights, seg) row set against the block's ids and reduce along
    the row axis — a scatter-free formulation (TPU segment scatters are
    exactly the lowering the engine does not trust), bit-identical to the
    ``jax.ops.segment_*`` semantics including identity fills for empty
    segments and dropped out-of-range ids."""
    vals = [refs[i][:] for i in range(nv)]            # [1, n] int64
    wv = refs[nv][:]                                  # [1, n]
    segv = refs[nv + 1][:]                            # [1, n]
    out_refs = refs[nv + 2:]
    sb = out_refs[0].shape[-1]
    s0 = pl.program_id(0) * sb
    sid = s0 + jax.lax.broadcasted_iota(jnp.int64, (sb, 1), 0)
    mask = segv == sid                                # [sb, n]
    wpos = jnp.maximum(wv, 0)
    live = mask & (wv > 0)
    for r, (op, col, ident) in zip(out_refs, ops):
        if op == "count":
            out = jnp.sum(jnp.where(mask, wpos, 0), axis=1)
        elif op == "sum":
            out = jnp.sum(jnp.where(mask, wpos * vals[col], 0), axis=1)
        elif op == "min":
            out = jnp.min(jnp.where(live, vals[col], ident), axis=1)
        elif op == "max":
            out = jnp.max(jnp.where(live, vals[col], ident), axis=1)
        elif op == "avg":
            s = jnp.sum(jnp.where(mask, wpos * vals[col], 0), axis=1)
            c = jnp.maximum(jnp.sum(jnp.where(mask, wpos, 0), axis=1), 1)
            out = jnp.where(s >= 0, s // c, -((-s) // c))
        else:  # present: exact segment_max(where(w>0,1,0)) — EVERY row of
            # the segment participates (retraction-only segments max to 0);
            # only truly empty segments keep the int64-min identity fill
            out = jnp.max(
                jnp.where(mask, (wv > 0).astype(jnp.int64), ident), axis=1)
        r[:] = out[None, :].astype(jnp.int64)


def segment_reduce_pallas(spec, val_cols, weights: jnp.ndarray,
                          seg: jnp.ndarray, num_segments: int, out_dtypes):
    """Drop-in for the accelerator branch of
    ``operators.aggregate.segment_reduce``: ONE Pallas program per
    :data:`_SEG_BLOCK` segment ids runs the WHOLE reduce spec (count / sum
    / min / max / avg / present) over the row set — where the XLA
    formulation paid 2-4 masked segment ops per output."""
    n = weights.shape[-1]
    nv = len(val_cols)
    nseg_pad = -(-num_segments // _SEG_BLOCK) * _SEG_BLOCK
    # int-only columns by the use_pallas gate, so the int64-widened
    # identities are exact
    ops = tuple((op, col, _seg_ident(op, col, val_cols))
                for op, col in spec)
    operands = [c.astype(jnp.int64).reshape(1, n) for c in val_cols]
    operands.append(weights.astype(jnp.int64).reshape(1, n))
    operands.append(seg.astype(jnp.int64).reshape(1, n))
    in_specs = [pl.BlockSpec((1, n), lambda b: (0, 0))
                for _ in range(nv + 2)]
    out_specs = [pl.BlockSpec((1, _SEG_BLOCK), lambda b: (0, b))
                 for _ in spec]
    out_shape = [jax.ShapeDtypeStruct((1, nseg_pad), jnp.int64)
                 for _ in spec]
    out = pl.pallas_call(
        partial(_segment_reduce_kernel, nv=nv, ops=ops),
        grid=(nseg_pad // _SEG_BLOCK,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(*operands)
    return tuple(c.reshape(nseg_pad)[:num_segments].astype(d)
                 for c, d in zip(out, out_dtypes))


def _seg_ident(op: str, col: int, val_cols) -> int:
    from dbsp_tpu.zset.native_merge import seg_op_identity

    src = val_cols[col].dtype if op in ("min", "max") else jnp.int64
    return seg_op_identity(op, src)


def agg_ladder_pallas(delta, nk: int, out_trace, levels, agg, q_cap: int,
                      gather_cap: int, fast: bool, flag):
    """The accelerator lowering of ``cursor.agg_ladder``: the same chain as
    the stitched control, with its two heavy phases on hand-written Pallas
    programs — the grid-over-levels GATHER megakernel
    (:func:`gather_ladder_pallas`, selected inside ``cursor.gather_ladder``
    when Pallas is on) and the spec'd segment reduction
    (:func:`segment_reduce_pallas`, selected inside
    ``operators.aggregate.segment_reduce``). The run-boundary compaction
    and the cross-level netting stay ``lax``-native (sort-free compaction;
    the netting sort is the rank-merge regime's problem on TPU) — by
    construction bit-identical to every other backend."""
    from dbsp_tpu.zset import cursor

    return cursor._agg_ladder_stitched(delta, nk, out_trace, levels, agg,
                                       q_cap, gather_cap, fast, flag)


# ---------------------------------------------------------------------------
# Rank-merge inner loop (cross-rank probe + position scatter)
# ---------------------------------------------------------------------------


def _rank_merge_kernel(*refs, ncols: int, na: int, nb: int, steps_a: int,
                       steps_b: int):
    acols = [refs[i][:] for i in range(ncols)]                   # [1, na]
    wa = refs[ncols][:]
    bcols = [refs[ncols + 1 + i][:] for i in range(ncols)]       # [1, nb]
    wb = refs[2 * ncols + 1][:]
    sent_ref = refs[2 * ncols + 2]                               # [1, ncols]
    out_refs = refs[2 * ncols + 3: 3 * ncols + 3]
    ow_ref = refs[3 * ncols + 3]
    # cross-ranks: b-rows strictly before a_i; a-rows at-or-before b_j —
    # the bijective position map of kernels.merge_sorted_cols' rank path
    ra = _lex_search(bcols, acols, nb, steps_b, strict=True)
    rb = _lex_search(acols, bcols, na, steps_a, strict=False)
    pos_a = (jax.lax.broadcasted_iota(jnp.int32, (1, na), 1) + ra)[0]
    pos_b = (jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1) + rb)[0]
    for ci in range(ncols):
        buf = jnp.full((na + nb,), sent_ref[0, ci], jnp.int64)
        buf = buf.at[pos_a].set(acols[ci][0]).at[pos_b].set(bcols[ci][0])
        out_refs[ci][:] = buf[None, :]
    w = jnp.zeros((na + nb,), jnp.int64)
    w = w.at[pos_a].set(wa[0]).at[pos_b].set(wb[0])
    ow_ref[:] = w[None, :]


def rank_merge_scatter(cols_a: Cols, w_a: jnp.ndarray, cols_b: Cols,
                       w_b: jnp.ndarray):
    """The rank-merge inner loop as ONE Pallas program: both cross-rank
    binary searches plus the position scatters of every column and the
    weights. Returns the scattered (pre-netting) ``(cols, w)`` buffers of
    capacity na+nb — bit-identical to the ``.at[pos].set`` formulation in
    ``kernels.merge_sorted_cols``; the caller's netting + compaction tail
    is unchanged."""
    ncols = len(cols_a)
    assert ncols and w_a.ndim == 1 and w_b.ndim == 1
    na, nb = int(w_a.shape[0]), int(w_b.shape[0])
    dtypes = tuple(c.dtype for c in cols_a)
    sent = jnp.asarray(
        [1 if np.dtype(d) == np.bool_ else int(np.iinfo(np.dtype(d)).max)
         for d in dtypes], jnp.int64).reshape(1, ncols)
    a64 = [c.astype(jnp.int64).reshape(1, na) for c in cols_a]
    b64 = [c.astype(jnp.int64).reshape(1, nb) for c in cols_b]
    out_shapes = tuple(jax.ShapeDtypeStruct((1, na + nb), jnp.int64)
                       for _ in range(ncols + 1))
    out = pl.pallas_call(
        partial(_rank_merge_kernel, ncols=ncols, na=na, nb=nb,
                steps_a=na.bit_length(), steps_b=nb.bit_length()),
        out_shape=out_shapes,
        interpret=interpret_mode(),
    )(*a64, w_a.astype(jnp.int64).reshape(1, na),
      *b64, w_b.astype(jnp.int64).reshape(1, nb), sent)
    out_cols = tuple(c.reshape(na + nb).astype(d)
                     for c, d in zip(out[:ncols], dtypes))
    w = out[ncols].reshape(na + nb).astype(w_a.dtype)
    return out_cols, w
