"""Columnar Z-set batches — the TPU-native answer to the reference's ordered
batch family (``crates/dbsp/src/trace/ord/``: ``OrdZSet``, ``OrdIndexedZSet``)
and its trie layers (``trace/layers/column_layer/mod.rs:31`` — whose
struct-of-arrays ``keys``/``diffs`` vectors validate this representation).

A :class:`Batch` is a pytree of flat device columns with a *static capacity*:

    keys:    tuple of [cap] arrays — the indexing columns (lexicographic order)
    vals:    tuple of [cap] arrays — the value columns
    weights: [cap] signed integers — Z-set multiplicities (0 == dead row)

Invariants of a *consolidated* batch (the canonical form every operator
produces):
  * rows are sorted lexicographically by (keys, vals),
  * no two live rows are equal on (keys, vals),
  * live rows (weight != 0) are packed at the front; dead rows carry per-dtype
    sentinel keys (max value) so a plain ascending sort keeps them last.

Capacities are powers of two chosen by the host (see :func:`bucket_cap`);
growth recompiles the operator kernel for the next bucket only, so the set of
compiled shapes stays logarithmic in state size (XLA static-shape discipline).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.zset import kernels

WEIGHT_DTYPE = jnp.int64

Row = Tuple  # host-side row: tuple of python scalars

# consolidate() folds rank/native merges over a batch's sorted runs instead
# of sorting when it carries at most this many runs (more runs than this and
# the fold's N-1 sequential merges lose to one O(n log n) sort; 12 covers a
# window delta's 1 + 2*K-level slide parts at the default K=4 ladder)
RANK_FOLD_MAX_RUNS = int(os.environ.get("DBSP_TPU_RANK_FOLD_MAX_RUNS", "12"))


def bucket_cap(n: int, minimum: int = 8) -> int:
    """Round ``n`` up to a power-of-two capacity bucket."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class Batch:
    """An immutable columnar Z-set batch (possibly un-consolidated).

    ``runs`` is STATIC sorted-run metadata: a tuple of segment lengths
    (summing to ``cap``, along the row axis) such that each segment is
    itself a consolidated batch slice — sorted lexicographically, no two
    equal live rows, live rows packed at the segment front, dead sentinel
    tail. ``None`` means unknown/unordered (the conservative default every
    bare constructor call keeps). The metadata is what lets
    :meth:`consolidate` dispatch by regime: a 1-run batch is already
    canonical (no-op), few runs fold with rank/native sorted merges, and
    only genuinely unordered data pays a full sort. It lives in the pytree
    AUX data, so it survives jit/shard_map boundaries and distinct run
    structures compile separately (their consolidation programs differ).
    """

    keys: Tuple[jnp.ndarray, ...]
    vals: Tuple[jnp.ndarray, ...]
    weights: jnp.ndarray
    runs: Optional[Tuple[int, ...]] = None

    # -- pytree plumbing ----------------------------------------------------
    def tree_flatten(self):
        return ((self.keys, self.vals, self.weights),
                (len(self.keys), len(self.vals), self.runs))

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, vals, weights = children
        runs = aux[2] if len(aux) > 2 else None
        return cls(tuple(keys), tuple(vals), weights, runs)

    # -- basic properties ---------------------------------------------------
    # Arrays are [cap] on a single worker, or [W, cap] for a batch sharded
    # over a worker mesh (parallel/): the row axis is always the LAST axis,
    # and per-worker row invariants hold along it independently.
    @property
    def cap(self) -> int:
        return int(self.weights.shape[-1])

    @property
    def sorted_runs(self) -> int:
        """Number of known sorted-consolidated runs (0 = unknown/unordered)."""
        return len(self.runs) if self.runs is not None else 0

    def tagged(self, runs: Optional[Tuple[int, ...]]) -> "Batch":
        """Same columns with different sorted-run metadata. Callers assert
        the invariant; :func:`check_runs` (tests) verifies it."""
        return Batch(self.keys, self.vals, self.weights, runs)

    @property
    def sharded(self) -> bool:
        return self.weights.ndim == 2

    @property
    def cols(self) -> Tuple[jnp.ndarray, ...]:
        return (*self.keys, *self.vals)

    def key_dtypes(self):
        return tuple(k.dtype for k in self.keys)

    def val_dtypes(self):
        return tuple(v.dtype for v in self.vals)

    def live_count(self) -> jnp.ndarray:
        """Total number of live rows (device scalar; all workers)."""
        return jnp.sum(self.weights != 0)

    def max_worker_live(self) -> jnp.ndarray:
        """Max live rows on any one worker — what capacity bucketing needs
        for sharded batches (each worker slice has the same static cap)."""
        if self.sharded:
            return jnp.max(jnp.sum(self.weights != 0, axis=-1))
        return self.live_count()

    # -- constructors -------------------------------------------------------
    @staticmethod
    def empty(key_dtypes: Sequence, val_dtypes: Sequence = (), cap: int = 8,
              weight_dtype=WEIGHT_DTYPE, lead: Tuple[int, ...] = ()) -> "Batch":
        """``lead=(W,)`` builds an empty sharded batch (worker axis first)."""
        keys = tuple(kernels.sentinel_fill((*lead, cap), d) for d in key_dtypes)
        vals = tuple(kernels.sentinel_fill((*lead, cap), d) for d in val_dtypes)
        return Batch(keys, vals, jnp.zeros((*lead, cap), weight_dtype),
                     runs=(cap,))

    @staticmethod
    def from_columns(keys: Sequence[jnp.ndarray], vals: Sequence[jnp.ndarray],
                     weights: jnp.ndarray, cap: int | None = None,
                     consolidated: bool = False) -> "Batch":
        """Build (and by default consolidate) a batch from raw device columns."""
        n = int(weights.shape[0])
        for c in (*keys, *vals):
            assert c.shape[0] == n, (
                f"column length {c.shape[0]} != weights length {n}")
        cap = cap or bucket_cap(n)
        keys = tuple(_pad_sentinel(jnp.asarray(k), cap) for k in keys)
        vals = tuple(_pad_sentinel(jnp.asarray(v), cap) for v in vals)
        w = jnp.zeros((cap,), WEIGHT_DTYPE).at[:n].set(
            jnp.asarray(weights, WEIGHT_DTYPE))
        b = Batch(keys, vals, w, runs=(cap,) if consolidated else None)
        return b if consolidated else b.consolidate()

    @staticmethod
    def from_tuples(rows: Sequence[Tuple[Row, int]], key_dtypes: Sequence,
                    val_dtypes: Sequence = (), cap: int | None = None) -> "Batch":
        """Host-side constructor from ((key..., val...), weight) pairs.

        The analog of the reference's ``Batch::from_tuples``
        (``trace/mod.rs:237``); used by tests and input handles.
        """
        nk, nv = len(key_dtypes), len(val_dtypes)
        n = len(rows)
        cap = cap or bucket_cap(max(n, 1))
        kcols = [np.empty((n,), jnp.dtype(d)) for d in key_dtypes]
        vcols = [np.empty((n,), jnp.dtype(d)) for d in val_dtypes]
        ws = np.empty((n,), jnp.dtype(WEIGHT_DTYPE))
        for i, (row, w) in enumerate(rows):
            assert len(row) == nk + nv, f"row arity {len(row)} != {nk}+{nv}"
            for j in range(nk):
                kcols[j][i] = row[j]
            for j in range(nv):
                vcols[j][i] = row[nk + j]
            ws[i] = w
        # DOMAIN CONTRACT: the max representable value of each column dtype
        # is the engine's dead-row sentinel; a live row carrying it would be
        # conflated with padding in probes/window slices. Reject at the host
        # boundary (zero-cost here; device-batch pushers uphold it by
        # contract — see push_batch).
        for col, d in ((c, d) for cols, dts in
                       ((kcols, key_dtypes), (vcols, val_dtypes))
                       for c, d in zip(cols, dts)):
            dt = jnp.dtype(d)
            if np.issubdtype(dt, np.integer) and n and \
                    col.max(initial=np.iinfo(dt).min) == np.iinfo(dt).max:
                raise ValueError(
                    f"value {np.iinfo(dt).max} ({dt}) is reserved as the "
                    "dead-row sentinel; remap the input domain (e.g. use a "
                    "wider dtype)")
        return Batch.from_columns(kcols, vcols, ws, cap=cap)

    # -- canonicalization ---------------------------------------------------
    def consolidate(self) -> "Batch":
        """Canonicalize, dispatching by sorted-run regime (module doc of
        :mod:`dbsp_tpu.zset.kernels` for the path accounting):

        * 1 known run — the batch IS consolidated; free by construction.
        * few runs — fold rank/native sorted merges over the run slices
          (no sort of the combined rows); output capacity unchanged.
        * unknown/many runs — full sort (or native argsort) consolidation.

        Every path produces the identical canonical batch (sorted unique
        live rows packed front, netted weights, sentinel dead tail)."""
        if self.sorted_runs == 1:
            kernels.count_consolidate_path("skipped")
            return self
        if self.sharded:  # canonicalize each worker slice under the mesh
            from dbsp_tpu.parallel.lift import lifted_consolidate

            return lifted_consolidate(self)
        return consolidate_regime(self)

    def compacted(self, keep: jnp.ndarray) -> "Batch":
        """Rows where ``keep`` holds, packed to the front (dead-sentinel
        tail), same capacity; preserves sort order — so a consolidated
        (1-run) input stays consolidated. Multi-run inputs lose their
        boundaries (segments shift arbitrarily under global packing)."""
        cols, w = kernels.compact(self.cols, self.weights, keep)
        nk = len(self.keys)
        runs = (self.cap,) if self.sorted_runs == 1 else None
        return Batch(cols[:nk], cols[nk:], w, runs)

    def masked(self, cond) -> "Batch":
        """The whole batch where ``cond`` (broadcastable) holds, dead
        (sentinel cols, zero weight) where it doesn't — the traced analog of
        'empty until X' host logic. A SCALAR cond is row-uniform (identity
        or all-dead-sentinel), so run metadata survives; a per-row cond
        interleaves sentinel rows with live ones and breaks sortedness."""
        cols = tuple(jnp.where(cond, c, kernels.sentinel_for(c.dtype))
                     for c in self.cols)
        nk = len(self.keys)
        runs = self.runs if jnp.ndim(cond) == 0 else None
        return Batch(cols[:nk], cols[nk:], jnp.where(cond, self.weights, 0),
                     runs)

    def with_cap(self, cap: int) -> "Batch":
        """Grow or shrink row capacity (last axis). Shrinking assumes live
        rows fit (caller checked the live count); consolidated batches keep
        live rows first on every worker."""
        if cap == self.cap:
            return self
        if cap > self.cap:
            # the sentinel pad extends the LAST run (all-dead tail keeps the
            # segment consolidated)
            runs = (*self.runs[:-1], self.runs[-1] + cap - self.cap) \
                if self.runs else None
            keys = tuple(_pad_sentinel(k, cap) for k in self.keys)
            vals = tuple(_pad_sentinel(v, cap) for v in self.vals)
            w = jnp.zeros((*self.weights.shape[:-1], cap),
                          self.weights.dtype).at[..., : self.cap].set(self.weights)
            return Batch(keys, vals, w, runs)
        runs = (cap,) if self.sorted_runs == 1 else None
        return Batch(tuple(k[..., :cap] for k in self.keys),
                     tuple(v[..., :cap] for v in self.vals),
                     self.weights[..., :cap], runs)

    # -- algebra (reference: crates/dbsp/src/algebra) -----------------------
    def neg(self) -> "Batch":
        """Z-set group inverse: negate all weights (order and zero-ness are
        untouched, so run metadata survives)."""
        return Batch(self.keys, self.vals, -self.weights, self.runs)

    def scale(self, c) -> "Batch":
        # c == 0 zeroes weights of rows still carrying live keys, which
        # breaks the packed-live-prefix part of the run invariant for the
        # native merge walk — drop the metadata rather than special-case it
        return Batch(self.keys, self.vals, self.weights * c)

    def add(self, other: "Batch") -> "Batch":
        """Z-set group addition of two CONSOLIDATED batches (the invariant
        every stream value upholds) via the rank-based sorted merge — no
        re-sort.

        The shrink keeps capacities in power-of-two buckets proportional to
        live rows — without it, iterated adds (the integrator loop) would grow
        capacity by cap_other per tick and trigger a fresh XLA compile each
        step. Costs one scalar device->host sync; host-level callers only.
        """
        return self.merge_with(other).shrink_to_fit()

    def merge_with(self, other: "Batch") -> "Batch":
        """Sorted merge of two consolidated batches; output cap is the sum
        of the input caps (see :func:`kernels.merge_sorted_cols`)."""
        assert len(self.keys) == len(other.keys) and \
            len(self.vals) == len(other.vals), "schema mismatch in merge"
        assert self.weights.ndim == other.weights.ndim, (
            "cannot merge a sharded batch with an unsharded one — check "
            "that every source in the circuit produces the same placement")
        if self.sharded:
            from dbsp_tpu.parallel.lift import lifted_merge

            return lifted_merge(self, other)
        return _merge_kernel(self, other)

    def shrink_to_fit(self, minimum: int = 8) -> "Batch":
        """Re-bucket a consolidated batch to bucket_cap(max worker live)."""
        return self.with_cap(bucket_cap(int(self.max_worker_live()), minimum))

    # -- host-side views (tests / output handles) ---------------------------
    def to_dict(self) -> Dict[Row, int]:
        """Materialize as {(key..., val...): weight} — the test oracle format
        and the serving-path row view. A sharded batch materializes the
        union over all worker slices. Vectorized: one boolean-mask gather +
        ``tolist`` per column instead of a per-row Python loop (the
        host-side analog of compaction; NDJSON encoders and HTTP output
        endpoints sit on this path at rate)."""
        ws = np.asarray(self.weights).reshape(-1)
        live = ws != 0
        if not live.any():
            return {}
        ws = ws[live]
        if not self.cols:  # unit-keyed batch: all rows are ()
            total = int(ws.sum())
            return {(): total} if total else {}
        cols = [np.asarray(c).reshape(-1)[live].tolist() for c in self.cols]
        out: Dict[Row, int] = {}
        for row, w in zip(zip(*cols), ws.tolist()):
            nw = out.get(row, 0) + w
            if nw:
                out[row] = nw
            else:
                out.pop(row, None)
        return out


@jax.jit
def _merge_kernel(a: Batch, b: Batch) -> Batch:
    cols, w = kernels.merge_sorted_cols(a.cols, a.weights, b.cols, b.weights)
    nk = len(a.keys)
    return Batch(cols[:nk], cols[nk:], w, runs=(w.shape[-1],))


def consolidate_regime(batch: Batch) -> Batch:
    """Single-worker regime dispatch behind :meth:`Batch.consolidate` (also
    the per-worker body of the lifted sharded consolidate — arrays are 1-D
    here). The 1-run no-op short-circuits in the caller."""
    nk = len(batch.keys)
    runs = batch.runs
    if runs is not None and 2 <= len(runs) <= RANK_FOLD_MAX_RUNS:
        kernels.count_consolidate_path("rank")
        # native fast path: ONE k-way C++ merge over the run slices
        # (ZsetRankFoldImpl) instead of a fold of R-1 pairwise merges —
        # same canonical output, R-1 fewer custom calls and no
        # intermediate accumulator buffers
        if batch.cols and batch.weights.ndim == 1 and \
                kernels.native_kernel("rank_fold"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports(c.dtype for c in batch.cols):
                kernels.count_kernel_dispatch("rank_fold", "native")
                cols, w = native_merge.rank_fold_native(
                    batch.cols, batch.weights, runs)
                return Batch(cols[:nk], cols[nk:], w, runs=(batch.cap,))
        kernels.count_kernel_dispatch("rank_fold", "xla")
        # fold sorted merges over the run slices, smallest runs first so
        # each merge probes the smaller side into the accumulator
        bounds = []
        off = 0
        for r in runs:
            bounds.append((off, off + r))
            off += r
        parts = sorted(bounds, key=lambda se: se[1] - se[0])
        cols = batch.cols
        acc = tuple(c[..., parts[0][0]:parts[0][1]] for c in cols)
        acc_w = batch.weights[..., parts[0][0]:parts[0][1]]
        for s, e in parts[1:]:
            acc, acc_w = kernels.merge_sorted_cols(
                acc, acc_w, tuple(c[..., s:e] for c in cols),
                batch.weights[..., s:e])
        return Batch(acc[:nk], acc[nk:], acc_w, runs=(batch.cap,))
    cols, w = kernels.consolidate_cols(batch.cols, batch.weights)
    return Batch(cols[:nk], cols[nk:], w, runs=(batch.cap,))


def _pad_sentinel(col: jnp.ndarray, cap: int) -> jnp.ndarray:
    n = col.shape[-1]
    if n == cap:
        return col
    assert n < cap, f"column of {n} rows exceeds capacity {cap}"
    fill = kernels.sentinel_fill((*col.shape[:-1], cap - n), col.dtype)
    return jnp.concatenate([col, fill], axis=-1)


def concat_batches(batches: Sequence[Batch]) -> Batch:
    """Stack batches into one (un-consolidated) batch of summed capacity
    (row axis = last axis, so sharded batches concat per worker).

    Sorted-run metadata concatenates: stacking consolidated inputs yields a
    known multi-run batch, whose ``consolidate()`` folds sorted merges
    instead of re-sorting (unknown inputs poison the result to unknown)."""
    assert batches
    first = batches[0]
    keys = tuple(
        jnp.concatenate([b.keys[i] for b in batches], axis=-1)
        for i in range(len(first.keys)))
    vals = tuple(
        jnp.concatenate([b.vals[i] for b in batches], axis=-1)
        for i in range(len(first.vals)))
    w = jnp.concatenate([b.weights for b in batches], axis=-1)
    runs: Optional[Tuple[int, ...]] = ()
    for b in batches:
        if b.runs is None:
            runs = None
            break
        runs = (*runs, *b.runs)
    return Batch(keys, vals, w, runs)
