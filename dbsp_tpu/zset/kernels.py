"""Low-level device kernels shared by the Z-set batch layer.

These are the TPU-native replacements for the reference engine's
consolidation / trie-layer machinery (reference: ``crates/dbsp/src/trace/
consolidation/`` and ``trace/layers/advance.rs``): instead of in-place
quicksort + pairwise merges over growable vectors, everything is expressed as
static-shape ``lax.sort`` / segmented-scan programs that XLA can fuse and tile.

All kernels operate on flat ``[cap]`` columns. Row validity is carried by the
weight column (weight == 0 <=> dead row); dead rows hold per-dtype sentinel
keys (max value) so that a single ascending sort moves them to the end.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# Consolidation-path accounting
# ---------------------------------------------------------------------------

# Dispatch decisions per consolidation regime, exported by obs as
# ``dbsp_tpu_zset_consolidate_total{path=...}`` and reported in bench JSON.
#   skipped  — sorted-run metadata proved the batch already consolidated
#              (consolidate() was a no-op);
#   rank     — few sorted runs, folded with rank/native sorted merges
#              (no sort of the combined rows);
#   native   — full consolidation via the C++ argsort custom call;
#   sort     — full multi-operand ``lax.sort`` consolidation;
#   native_unsupported_dtype — native was SELECTED but a column dtype
#              (float) is not int64-widenable, so the call demoted to the
#              sort path. Counted separately so a schema change that
#              silently knocks a pipeline off the native kernels is
#              visible in /metrics instead of folding into plain "sort";
#   deferred — the compiled placement pass removed the consolidation from
#              the program entirely (its consumers canonicalize anyway).
# Eager host-path calls count once per eval; calls under an XLA trace count
# once per TRACE — the counter attributes which regimes fire where, not
# per-tick kernel volume.
CONSOLIDATE_COUNTS: Dict[str, int] = {
    "sort": 0, "rank": 0, "native": 0, "skipped": 0, "deferred": 0,
    "native_unsupported_dtype": 0}


def count_consolidate_path(path: str) -> None:
    CONSOLIDATE_COUNTS[path] = CONSOLIDATE_COUNTS.get(path, 0) + 1


# ---------------------------------------------------------------------------
# Kernel dispatch accounting + the per-kernel native gate
# ---------------------------------------------------------------------------

# Which implementation each kernel entry point dispatched to, keyed by
# (kernel, backend) with backend one of "native" (C++ FFI custom call),
# "xla" (pure-XLA lowering) or "pallas" (hand-written Pallas program).
# Same counting convention as CONSOLIDATE_COUNTS (eager calls per eval,
# traced calls per trace); exported by obs as
# ``dbsp_tpu_zset_kernel_dispatch_total{kernel,backend}`` and embedded in
# bench JSON as ``kernel_paths`` — so which path a deployment's hot loop
# actually took is observable, not inferred from env vars.
KERNEL_DISPATCH_COUNTS: Dict[Tuple[str, str], int] = {}


def count_kernel_dispatch(kernel: str, backend: str) -> None:
    key = (kernel, backend)
    KERNEL_DISPATCH_COUNTS[key] = KERNEL_DISPATCH_COUNTS.get(key, 0) + 1


def native_kernel(kernel: str) -> bool:
    """Should ``kernel`` dispatch to its native C++ implementation HERE?

    True only on the CPU backend, with the FFI library loadable, and with
    the kernel not forced off via ``DBSP_TPU_NATIVE`` (csv force-off list;
    ``0`` = all off — see ``native_merge.kernel_enabled``). Callers still
    check dtype support per call site."""
    import jax

    if jax.default_backend() != "cpu":
        return False
    from dbsp_tpu.zset import native_merge

    return native_merge.available() and native_merge.kernel_enabled(kernel)


# The DBSP_TPU_PALLAS spellings that force the Pallas kernels ON even off
# an accelerator backend — the ONE definition shared by the dispatch
# pre-checks here/in cursor.py and pallas_kernels.enabled(), so the
# grammar cannot drift between the cheap check and the real one.
PALLAS_FORCE_ON = ("1", "on", "interpret")


def pallas_requested() -> bool:
    """Cheap pre-check for the Pallas dispatch branch WITHOUT importing
    the pallas module (not free on CPU cold start): an accelerator
    backend, or an explicit DBSP_TPU_PALLAS force-on. The full gate
    (including the force-off spellings and dtype support) lives in
    ``pallas_kernels.use_pallas`` — this only decides whether that module
    is worth importing."""
    import os

    import jax

    if jax.default_backend() != "cpu":
        return True
    return os.environ.get("DBSP_TPU_PALLAS", "").strip().lower() in \
        PALLAS_FORCE_ON

# ---------------------------------------------------------------------------
# Sentinels
# ---------------------------------------------------------------------------


def sentinel_scalar(dtype):
    """Largest representable value of ``dtype`` as a HOST scalar — the ONE
    definition of the dead-row sentinel; callers that need the value
    outside a device array (the native FFI wrappers widening it to int64)
    read it here so it can never drift from :func:`sentinel_for`."""
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return float("inf")
    if jnp.issubdtype(dtype, jnp.integer):
        return int(jnp.iinfo(dtype).max)
    if dtype == jnp.bool_:
        return True
    raise TypeError(f"unsupported column dtype {dtype}")


def sentinel_for(dtype) -> jnp.ndarray:
    """Largest representable value of ``dtype`` — reserved to mark dead rows."""
    return jnp.array(sentinel_scalar(dtype), dtype=jnp.dtype(dtype))


def sentinel_fill(shape, dtype) -> jnp.ndarray:
    return jnp.full(shape, sentinel_for(dtype), dtype=dtype)


# ---------------------------------------------------------------------------
# Row-wise lexicographic sort
# ---------------------------------------------------------------------------


def sort_rows(cols: Sequence[jnp.ndarray], payload: Sequence[jnp.ndarray]
              ) -> Tuple[Tuple[jnp.ndarray, ...], Tuple[jnp.ndarray, ...]]:
    """Stable ascending lexicographic sort by ``cols``; ``payload`` rides along.

    Zero-column rows (unit-keyed Z-sets, e.g. a global COUNT(*)) are a valid
    degenerate case: every row is equal, nothing to sort.
    """
    if not cols:
        return (), tuple(payload)
    ops = (*cols, *payload)
    out = lax.sort(ops, num_keys=len(cols), is_stable=True)
    return tuple(out[: len(cols)]), tuple(out[len(cols):])


def _col_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Element equality under the total order lax.sort uses: NaN == NaN."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    return eq


def rows_equal_prev(cols: Sequence[jnp.ndarray], n: int | None = None
                    ) -> jnp.ndarray:
    """For sorted columns: mask[i] = row i equals row i-1 (mask[0] = False).

    With zero columns all rows are the unit row, hence equal; ``n`` supplies
    the row count for that case.
    """
    if not cols:
        assert n is not None
        return jnp.arange(n) > 0
    n = cols[0].shape[0]
    eq = jnp.ones((n,), dtype=jnp.bool_)
    for c in cols:
        eq = eq & jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), _col_eq(c[1:], c[:-1])])
    return eq


# ---------------------------------------------------------------------------
# Compaction: scatter live rows to the front, sentinel-fill the rest
# ---------------------------------------------------------------------------


def compact(cols: Sequence[jnp.ndarray], weights: jnp.ndarray,
            keep: jnp.ndarray) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Move rows with ``keep`` to the front (order preserved); rest is dead.

    Equivalent of the reference's in-place ``retain`` on batch vectors.
    GATHER formulation: output slot j reads the (j+1)-th kept row, found by
    one searchsorted over the inclusive keep-prefix-sums — a scatter
    formulation measured ~40ns/element on XLA:CPU (scatters lower to a
    sequential update loop; a 16k-row x 7-col filter cost ~5ms/tick), while
    searchsorted + gathers vectorize. On CPU with the native library the
    whole pass is ONE sequential C++ copy (ZsetCompactImpl). Bit-identical
    output on every path.
    """
    if cols and weights.ndim == 1 and native_kernel("compact"):
        from dbsp_tpu.zset import native_merge

        if native_merge.supports(c.dtype for c in cols):
            count_kernel_dispatch("compact", "native")
            return native_merge.compact_native(cols, weights, keep)
    count_kernel_dispatch("compact", "xla")
    cap = weights.shape[0]
    csum = jnp.cumsum(keep.astype(jnp.int32))
    total = csum[-1]
    j = jnp.arange(cap, dtype=jnp.int32)
    src = jnp.minimum(searchsorted1(csum, j + 1, side="left"), cap - 1)
    valid = j < total
    out_cols = tuple(
        jnp.where(valid, c[src], sentinel_for(c.dtype)) for c in cols)
    w = jnp.where(valid, weights[src], 0)
    return tuple(out_cols), w


# ---------------------------------------------------------------------------
# Consolidation: sort + sum weights of identical rows + compact
# ---------------------------------------------------------------------------


def consolidate_cols(cols: Sequence[jnp.ndarray], weights: jnp.ndarray
                     ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Canonicalize a weighted row set (reference: ``trace/consolidation``).

    Sorts rows lexicographically, sums weights of equal rows, drops rows whose
    net weight is zero, and packs survivors to the front. Output capacity ==
    input capacity; tail rows are dead (weight 0, sentinel keys).
    """
    if cols and weights.ndim == 1 and native_kernel("consolidate"):
        from dbsp_tpu.zset import native_merge

        if native_merge.supports(c.dtype for c in cols):
            count_consolidate_path("native")
            count_kernel_dispatch("consolidate", "native")
            return native_merge.consolidate_cols_native(cols, weights)
        # native was selected but a column dtype (float) is not
        # int64-widenable: the demotion is its own counter bucket so the
        # silent fallback is visible in /metrics
        count_consolidate_path("native_unsupported_dtype")
    else:
        count_consolidate_path("sort")
    count_kernel_dispatch("consolidate", "xla")
    cap = weights.shape[0]
    cols, (weights,) = sort_rows(cols, (weights,))
    dup = rows_equal_prev(cols, n=cap)
    seg = jnp.cumsum(~dup) - 1  # segment id per row, first-of-group gets new id
    sums = jax.ops.segment_sum(weights, seg, num_segments=cap)
    w_new = jnp.where(dup, 0, sums[seg]).astype(weights.dtype)
    keep = w_new != 0
    return compact(cols, w_new, keep)


# ---------------------------------------------------------------------------
# Sorted merge of two consolidated row sets (no re-sort)
# ---------------------------------------------------------------------------


def merge_strategy() -> str:
    """Backend-dependent choice for combining sorted row sets.

    ``rank`` (cross-rank binary-search merge) does O(log n) *dependent*
    gather passes — cheap on TPU where a bitonic ``lax.sort`` costs
    O(n log^2 n) full passes of HBM traffic, but measurably SLOWER than the
    XLA:CPU native sort (one fused C++ quicksort). So on accelerators:
    rank-merge. On CPU: a ``jax.pure_callback`` into the native two-pointer
    merge (native/zset_merge.cpp) — already-sorted runs need no sort, and
    XLA:CPU's comparator-based multi-operand sort measured ~50x slower than
    the C++ walk at spine-tail shapes (1.2s vs ~25ms for 1.5M rows x 7
    cols). ``sort`` remains the fallback when the native library can't
    build, the ``merge`` kernel is forced off (``DBSP_TPU_NATIVE``), or a
    column dtype (float) isn't int64-widenable.
    """
    import jax

    if jax.default_backend() != "cpu":
        return "rank"
    return "native" if native_kernel("merge") else "sort"


def merge_sorted_cols(cols_a: Sequence[jnp.ndarray], w_a: jnp.ndarray,
                      cols_b: Sequence[jnp.ndarray], w_b: jnp.ndarray
                      ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Merge two SORTED row sets into one consolidated set, capacity |a|+|b|.
    Strategy is backend-dependent (see :func:`merge_strategy`); the rank
    path below is the TPU fast path.

    The replacement for the reference's pairwise batch ``Merger``
    (``trace/ord/merge_batcher``): since both inputs are sorted, output
    positions follow from cross-ranks — row i of ``a`` lands at
    ``i + |{b < a_i}|``, row j of ``b`` at ``j + |{a <= b_j}|`` — so the
    whole merge is two binary-search probes (O(n log m)) plus scatters, not
    an O((n+m) log(n+m)) re-sort. The position map stays bijective even
    with duplicate rows (each side's equal block lands contiguously, a's
    block first, because the ``+i``/``+j`` terms advance within a block).
    Equal rows land adjacent; their weights are summed and zero-net rows
    dropped, so the result is consolidated. Dead sentinel rows merge into
    the dead tail and vanish in the compaction.
    """
    if not cols_a:  # zero-column (unit-row) sets: nothing to order
        return consolidate_cols((), jnp.concatenate([w_a, w_b]))
    strategy = merge_strategy()
    if strategy == "native":
        from dbsp_tpu.zset import native_merge

        if w_a.ndim == 1 and \
                native_merge.supports(c.dtype for c in cols_a):
            count_kernel_dispatch("merge", "native")
            return native_merge.merge_consolidated_cols(cols_a, w_a,
                                                        cols_b, w_b)
        strategy = "sort"
    if strategy == "sort":
        count_kernel_dispatch("merge", "xla")
        cols = tuple(jnp.concatenate([a, b.astype(a.dtype)])
                     for a, b in zip(cols_a, cols_b))
        return consolidate_cols(cols, jnp.concatenate([w_a, w_b]))
    na, nb = w_a.shape[0], w_b.shape[0]
    # rank path (accelerators): the probe + position-scatter inner loop,
    # either the Pallas program (zset/pallas_kernels.py) or the XLA
    # formulation — bit-identical buffers either way; the netting +
    # compaction tail below is shared.
    from dbsp_tpu.zset import pallas_kernels

    if pallas_kernels.use_pallas("rank_merge", (*cols_a, *cols_b)) and \
            w_a.ndim == 1:
        count_kernel_dispatch("merge", "pallas")
        out_cols, w = pallas_kernels.rank_merge_scatter(
            cols_a, w_a, cols_b, w_b)
        out_cols = list(out_cols)
    else:
        count_kernel_dispatch("merge", "xla")
        ra = lex_probe(cols_b, cols_a, side="left")   # b-rows strictly < a_i
        rb = lex_probe(cols_a, cols_b, side="right")  # a-rows <= b_j
        pos_a = jnp.arange(na, dtype=jnp.int32) + ra
        pos_b = jnp.arange(nb, dtype=jnp.int32) + rb
        out_cols = []
        for ca, cb in zip(cols_a, cols_b):
            buf = sentinel_fill((na + nb,), ca.dtype)
            out_cols.append(
                buf.at[pos_a].set(ca).at[pos_b].set(cb.astype(ca.dtype)))
        w = jnp.zeros((na + nb,), w_a.dtype).at[pos_a].set(w_a) \
            .at[pos_b].set(w_b)
    dup = rows_equal_prev(out_cols, n=na + nb)
    seg = jnp.cumsum(~dup) - 1
    sums = jax.ops.segment_sum(w, seg, num_segments=na + nb)
    w = jnp.where(dup, 0, sums[seg]).astype(w_a.dtype)
    return compact(out_cols, w, w != 0)


# ---------------------------------------------------------------------------
# Lexicographic searchsorted over multi-column sorted tables
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("side",))
def lex_searchsorted(table_cols: Tuple[jnp.ndarray, ...],
                     query_cols: Tuple[jnp.ndarray, ...],
                     side: str = "left") -> jnp.ndarray:
    """Insertion points of ``query`` rows into lexicographically sorted ``table``.

    TPU-native replacement for the reference's exponential-search ``advance``
    (``trace/layers/advance.rs``): instead of data-dependent binary search we
    sort table and query rows together once; a query row's position in the
    merged order, minus the number of queries before it, is its insertion
    index. O((n+m) log(n+m)), fully static shapes, any number of key columns.
    """
    assert len(table_cols) == len(query_cols) and table_cols
    n = table_cols[0].shape[0]
    m = query_cols[0].shape[0]
    # Tie-break flag: for 'left' queries sort before equal table rows.
    tflag = 1 if side == "left" else 0
    flags = jnp.concatenate(
        [jnp.full((n,), tflag, jnp.int32), jnp.full((m,), 1 - tflag, jnp.int32)]
    )
    pos = jnp.concatenate(
        [jnp.zeros((n,), jnp.int32), jnp.arange(m, dtype=jnp.int32)]
    )
    cols = tuple(
        jnp.concatenate([t.astype(jnp.promote_types(t.dtype, q.dtype)),
                         q.astype(jnp.promote_types(t.dtype, q.dtype))])
        for t, q in zip(table_cols, query_cols)
    )
    *_, sflags, spos = lax.sort((*cols, flags, pos), num_keys=len(cols) + 1,
                                is_stable=True)
    is_query = sflags == (1 - tflag)
    q_before = jnp.cumsum(is_query) - jnp.where(is_query, 1, 0)
    insertion = jnp.arange(n + m, dtype=jnp.int32) - q_before.astype(jnp.int32)
    out = jnp.zeros((m,), jnp.int32)
    out = out.at[jnp.where(is_query, spos, m)].set(insertion, mode="drop")
    return out


def searchsorted1(table: jnp.ndarray, query: jnp.ndarray,
                  side: str = "left") -> jnp.ndarray:
    """Single-column searchsorted.

    Both operands widen to their COMMON dtype: casting the query down to the
    table dtype (the old behavior) silently truncates a wider query — an
    int64 query of 2^40 against an int32 table wrapped negative and probed
    the wrong end of the table.

    (A native-FFI dispatch was tried here and measured ~25% SLOWER at the
    q4 tick: the custom call breaks XLA fusion with the surrounding
    expansion arithmetic and pays an int64-widening copy per operand —
    the vectorized scan lowering stays.)"""
    dt = jnp.promote_types(table.dtype, query.dtype)
    return jnp.searchsorted(table.astype(dt), query.astype(dt), side=side
                            ).astype(jnp.int32)


def _lex_le_rows(table_cols, idx, query_cols, strict: bool):
    """Per-query compare: table[idx] < query (strict) or <= query, under the
    same total order lax.sort uses (NaN ranks greatest, NaN == NaN).

    Both sides widen to their COMMON dtype — casting the query down to the
    table dtype silently truncates a wider query (the same hazard class
    :func:`searchsorted1` fixes; a no-op when dtypes already match, which
    the schema-pinned engine paths guarantee)."""
    lt = jnp.zeros(idx.shape, jnp.bool_)
    all_eq = jnp.ones(idx.shape, jnp.bool_)
    for t, q in zip(table_cols, query_cols):
        dt = jnp.promote_types(t.dtype, q.dtype)
        tv = t[idx].astype(dt)
        qv = q.astype(dt)
        col_lt = tv < qv
        if jnp.issubdtype(dt, jnp.floating):
            col_lt = col_lt | (jnp.isnan(qv) & ~jnp.isnan(tv))
        lt = lt | (all_eq & col_lt)
        all_eq = all_eq & _col_eq(tv, qv)
    return lt if strict else lt | all_eq


def lex_probe(table_cols: Tuple[jnp.ndarray, ...],
              query_cols: Tuple[jnp.ndarray, ...],
              side: str = "left") -> jnp.ndarray:
    """Delta-proportional searchsorted: O(m log n) vectorized binary search.

    The hot-path probe used by incremental operators to look a delta's keys up
    in a large trace (the analog of the reference's exponential-search
    ``advance``, ``trace/layers/advance.rs``). Unlike :func:`lex_searchsorted`
    (which sorts table+query together, O(n+m)), cost here scales with the
    *delta*, preserving DBSP's per-step cost model; the trace is only gathered
    at log2(n) probe indices per query. Unrolled loop — n is static under jit.
    """
    assert table_cols, "lex_probe requires at least one key column"
    if table_cols[0].ndim == 1 and query_cols[0].ndim == 1 and \
            native_kernel("probe"):
        from dbsp_tpu.zset import native_merge

        if native_merge.supports(c.dtype for c in (*table_cols,
                                                   *query_cols)):
            count_kernel_dispatch("probe", "native")
            return native_merge.lex_probe_native(table_cols, query_cols,
                                                 side)
    count_kernel_dispatch("probe", "xla")
    n = table_cols[0].shape[0]
    m = query_cols[0].shape[0]
    lo = jnp.zeros((m,), jnp.int32)
    hi = jnp.full((m,), n, jnp.int32)
    # n+1 candidate insertion points [0, n] => ceil(log2(n+1)) halvings
    steps = n.bit_length()
    strict = side == "left"
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) >> 1  # < hi <= n on active lanes; clamped gather else
        go_right = _lex_le_rows(table_cols, mid, query_cols, strict=strict)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


# ---------------------------------------------------------------------------
# Range expansion: turn per-row [lo, hi) ranges into a flat gather index list
# ---------------------------------------------------------------------------


def expand_ranges(lo: jnp.ndarray, hi: jnp.ndarray, out_cap: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Flatten variable-length ranges into static-capacity index arrays.

    Given m ranges [lo_i, hi_i), produces for each output slot j < total:
      src_row[j]  — which input row the slot belongs to,
      src_idx[j]  — lo[src_row] + offset within the range,
      valid[j]    — j < total,
    plus the (device, scalar) total. This is the two-pass count/scan/scatter
    shape the reference's join fan-out uses, with the scatter replaced by a
    searchsorted over the prefix sums (static shapes; TPU-friendly gathers).

    OVERFLOW CONTRACT: when ``total > out_cap`` only the first ``out_cap``
    range elements are emitted. Callers MUST host-check ``total`` against
    ``out_cap`` and re-run with a grown capacity bucket — see
    ``operators/join.py``. ``total`` is returned (not clamped) precisely so
    that check is possible.

    On CPU with the native library the count/scan/search pass is ONE
    sequential C++ walk (ZsetExpandImpl) with the identical tail contract
    (invalid slots anchor at the last non-empty range).
    """
    if lo.ndim == 1 and native_kernel("expand"):
        count_kernel_dispatch("expand", "native")
        from dbsp_tpu.zset import native_merge

        return native_merge.expand_ranges_native(lo, hi, out_cap)
    count_kernel_dispatch("expand", "xla")
    counts = jnp.maximum(hi - lo, 0)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix sum
    total = jnp.sum(counts, dtype=jnp.int64)  # 64-bit: see expand_ladder
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = searchsorted1(starts, jnp.minimum(j, total - 1), side="right") - 1
    row = jnp.clip(row, 0, lo.shape[0] - 1)
    offset = j - starts[row]
    src = lo[row] + offset
    valid = j < total
    return row, src.astype(jnp.int32), valid, total
