"""Native (C++) sorted-merge bridge for the CPU backend.

``merge_sorted_cols`` (zset/kernels.py) combines two consolidated runs.  On
CPU its sort strategy pays for a comparator-based multi-operand ``lax.sort``
of the full combined capacity — measured ~1.2s for a 1.5M-row 7-column
merge, which made spine tail merges the dominant cost of state-heavy
queries (Nexmark q4).  Two already-sorted runs need no sort at all: this
module routes the merge through an **XLA FFI custom call**
(native/zset_merge.cpp) — a C++ two-pointer walk that nets equal rows,
drops zero weights, packs survivors and sentinel-fills the tail,
bit-identical to the XLA path.  The FFI route keeps the whole compiled
circuit program on the XLA executor with zero Python round-trips per merge
(a ``jax.pure_callback`` route was tried first and deadlocks XLA:CPU when
converting >=8MB operands on the callback thread).

Only integer/bool columns take this path (every column is widened to int64
for the call; sign-extension preserves lexicographic order).  Float columns
fall back to the XLA sort.  The TPU backend never loads this library — its
rank-merge strategy is pure XLA and runs on-device (kernels.merge_strategy).

Reference analog: the pairwise batch merger the spine drives,
crates/dbsp/src/trace/ord/merge_batcher.rs (the same two-pointer walk,
generic over Rust ords instead of columns).
"""

from __future__ import annotations

import ctypes
import os
import sys
import threading
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _ffi_module():
    """The XLA FFI surface for this jax version.

    ``jax.ffi`` (>= 0.5) and ``jax.extend.ffi`` (0.4.35-0.4.38) expose the
    SAME API (``ffi_call`` returning a callable, ``register_ffi_target``,
    ``pycapsule``, ``include_dir``); only the module moved. Anything older
    has a different registration ABI and stays gated off."""
    if hasattr(jax, "ffi"):
        return jax.ffi
    try:
        from jax.extend import ffi as xffi
    except ImportError:
        return None
    # the modern API landed in jax.extend.ffi before moving to jax.ffi;
    # require the exact entry points this module drives
    if all(hasattr(xffi, n) for n in (
            "ffi_call", "register_ffi_target", "pycapsule", "include_dir")):
        return xffi
    return None


_FFI = _ffi_module()


def _vma_of(x):
    """Varying-manual-axes tag of a traced value (None before jax grew vma
    tracking — there is nothing to re-tag on those versions)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "zset_merge.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libzset_merge.so")

_lib: Optional[ctypes.CDLL] = None
_registered = False
_build_error: Optional[str] = None
_lock = threading.Lock()

_PP = ctypes.POINTER(ctypes.c_int64)

FFI_TARGET = "dbsp_zset_merge"
PROBE_TARGET = "dbsp_zset_probe"
CONSOLIDATE_TARGET = "dbsp_zset_consolidate"
EXPAND_TARGET = "dbsp_zset_expand"
GATHER_TARGET = "dbsp_zset_gather"
COMPACT_TARGET = "dbsp_zset_compact"
PROBE_LADDER_TARGET = "dbsp_zset_probe_ladder"
RANK_FOLD_TARGET = "dbsp_zset_rank_fold"
JOIN_LADDER_TARGET = "dbsp_zset_join_ladder"
GATHER_LADDER_TARGET = "dbsp_zset_gather_ladder"
OLD_WEIGHTS_TARGET = "dbsp_zset_old_weights"
SEGMENT_REDUCE_TARGET = "dbsp_zset_segment_reduce"
AGG_LADDER_TARGET = "dbsp_zset_agg_ladder"
JOIN_SORTED_TARGET = "dbsp_zset_join_sorted"

# every native kernel the per-kernel force-off knob can address (the
# DBSP_TPU_NATIVE csv grammar — see :func:`kernel_enabled`). `join_ladder`
# / `gather_ladder` / `old_weights` are the FUSED ladder consumers (PR 12):
# forcing one off falls back to the stitched probe/expand/gather chain
# (which still dispatches the granular kernels above). `segment_reduce` /
# `agg_ladder` / `join_sorted` are the reduction offensive: the Aggregator
# zoo's opcode segment reduction, the whole-CAggregate megakernel, and the
# sorted-emit join mode whose per-side consolidated runs kill the
# post-join sort — forcing those off restores the previous round's code
# path exactly, so an A/B isolates just this fusion layer.
KERNELS = ("merge", "consolidate", "probe", "probe_ladder", "expand",
           "gather", "compact", "rank_fold", "join_ladder",
           "gather_ladder", "old_weights", "segment_reduce", "agg_ladder",
           "join_sorted")


def _build() -> str:
    global _build_error
    if _build_error is not None:
        raise RuntimeError(_build_error)
    if _FFI is None:
        # pre-0.4.35 jax has a different registration ABI; gate the whole
        # native route off rather than drive an untested bridge (kernels
        # fall back to the XLA sort path)
        _build_error = "XLA FFI API unavailable in this jax version"
        raise RuntimeError(_build_error)
    if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        # route through the stamped build chokepoint (tools/build_native)
        # so dev rebuilds embed the source SHA-256 exactly like the
        # recorded builds — the staleness lint depends on it
        if _REPO_ROOT not in sys.path:
            sys.path.insert(0, _REPO_ROOT)
        from tools.build_native import compile_so

        try:
            compile_so(_SRC, _SO,
                       ["-O3", "-march=native", "-std=c++17", "-shared",
                        "-fPIC"], [_FFI.include_dir()])
        except RuntimeError as e:
            _build_error = f"native merge: {e}"
            raise RuntimeError(_build_error) from None
    return _SO


def _load() -> ctypes.CDLL:
    """Build + load the library and register the FFI target (once)."""
    global _lib, _registered
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            lib.zset_merge.restype = None
            lib.zset_merge.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(_PP), _PP,
                ctypes.POINTER(_PP), _PP,
                _PP,
                ctypes.POINTER(_PP), _PP,
            ]
            _lib = lib
        if not _registered:
            for target, symbol in (
                    (FFI_TARGET, "ZsetMergeFfi"),
                    (PROBE_TARGET, "ZsetProbeFfi"),
                    (CONSOLIDATE_TARGET, "ZsetConsolidateFfi"),
                    (EXPAND_TARGET, "ZsetExpandFfi"),
                    (GATHER_TARGET, "ZsetGatherFfi"),
                    (COMPACT_TARGET, "ZsetCompactFfi"),
                    (PROBE_LADDER_TARGET, "ZsetProbeLadderFfi"),
                    (RANK_FOLD_TARGET, "ZsetRankFoldFfi"),
                    (JOIN_LADDER_TARGET, "ZsetJoinLadderFfi"),
                    (GATHER_LADDER_TARGET, "ZsetGatherLadderFfi"),
                    (OLD_WEIGHTS_TARGET, "ZsetOldWeightsFfi"),
                    (SEGMENT_REDUCE_TARGET, "ZsetSegmentReduceFfi"),
                    (AGG_LADDER_TARGET, "ZsetAggLadderFfi"),
                    (JOIN_SORTED_TARGET, "ZsetJoinLadderSortedFfi")):
                _FFI.register_ffi_target(
                    target, _FFI.pycapsule(getattr(_lib, symbol)),
                    platform="cpu")
            _registered = True
    return _lib


def available() -> bool:
    """Library builds/loads on this machine (cached) and the knobs allow
    SOME native kernel (``DBSP_TPU_NATIVE=0`` / legacy
    ``DBSP_TPU_NATIVE_MERGE=0`` are the all-off switches)."""
    if os.environ.get("DBSP_TPU_NATIVE_MERGE", "1") == "0":
        return False
    if os.environ.get("DBSP_TPU_NATIVE", "1").strip() == "0":
        return False
    try:
        _load()
        return True
    except RuntimeError:
        return False


_warned_unknown_kernels: set = set()


def kernel_enabled(kernel: str) -> bool:
    """Per-kernel A/B switch: ``DBSP_TPU_NATIVE=<csv|0|1>``.

    Unset/``1`` — every native kernel enabled (the default). ``0`` — all
    disabled (same as the legacy ``DBSP_TPU_NATIVE_MERGE=0``). A csv of
    names from :data:`KERNELS` (e.g. ``expand,gather``) FORCES those
    kernels onto their XLA fallback while the rest stay native — so any
    single kernel can be A/B'd from bench.py without code edits. A csv
    entry that names no known kernel warns LOUDLY (once per value): a
    typo'd force-off would otherwise no-op silently and corrupt the very
    A/B evidence the knob exists to produce. Does not check library
    availability; pair with :func:`available`."""
    v = os.environ.get("DBSP_TPU_NATIVE", "1").strip()
    if v == "0":
        return False
    if v in ("", "1"):
        return True
    off = {s.strip() for s in v.split(",") if s.strip()}
    unknown = off - set(KERNELS)
    if unknown and v not in _warned_unknown_kernels:
        _warned_unknown_kernels.add(v)
        import warnings

        warnings.warn(
            f"DBSP_TPU_NATIVE names unknown kernel(s) {sorted(unknown)} — "
            f"they match nothing and force nothing off. Valid names: "
            f"{', '.join(KERNELS)}", stacklevel=2)
    return kernel not in off


def _supported_dtype(d) -> bool:
    d = jnp.dtype(d)
    if d == jnp.bool_:
        return True
    if not jnp.issubdtype(d, jnp.integer):
        return False
    # every column is widened via astype(int64) before the C++ kernels:
    # unsigned widths <= 32 zero-extend losslessly, but uint64 values
    # >= 2^63 wrap NEGATIVE and break the lexicographic order the
    # two-pointer merge/probe assumes — those columns take the XLA path
    if jnp.issubdtype(d, jnp.unsignedinteger) and d.itemsize >= 8:
        return False
    return True


def supports(dtypes) -> bool:
    return all(_supported_dtype(d) for d in dtypes)


def _ptr(a: np.ndarray) -> _PP:
    return a.ctypes.data_as(_PP)


def _ptr_array(arrays) -> "ctypes.Array":
    return (_PP * len(arrays))(*[_ptr(a) for a in arrays])


def merge_raw(a_cols, a_w, b_cols, b_w, sentinels) -> Tuple[list, np.ndarray]:
    """Host-side (numpy-in, numpy-out) entry via the plain C ABI — used by
    tests to exercise the kernel without the XLA runtime in the loop."""
    ncols = len(a_cols)
    a_cols = [np.ascontiguousarray(a, np.int64) for a in a_cols]
    b_cols = [np.ascontiguousarray(b, np.int64) for b in b_cols]
    a_w = np.ascontiguousarray(a_w, np.int64)
    b_w = np.ascontiguousarray(b_w, np.int64)
    na, nb = a_w.shape[0], b_w.shape[0]
    cap = na + nb
    out_cols = [np.empty(cap, np.int64) for _ in range(ncols)]
    out_w = np.empty(cap, np.int64)
    sent = np.asarray(sentinels, np.int64)
    _load().zset_merge(
        ncols, na, nb,
        _ptr_array(a_cols), _ptr(a_w),
        _ptr_array(b_cols), _ptr(b_w),
        _ptr(sent),
        _ptr_array(out_cols), _ptr(out_w))
    return out_cols, out_w


def merge_consolidated_cols(cols_a: Sequence[jnp.ndarray], w_a: jnp.ndarray,
                            cols_b: Sequence[jnp.ndarray], w_b: jnp.ndarray
                            ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Drop-in for the CPU branch of ``kernels.merge_sorted_cols``.

    Caller guarantees: both inputs consolidated (sorted, netted, packed),
    integer/bool columns only (see :func:`supports`). Works eagerly and
    under an outer trace (it lowers to one XLA custom call).
    """
    _load()
    ncols = len(cols_a)
    dtypes = tuple(c.dtype for c in cols_a)
    # int64-widened per-dtype sentinel (host ints — this runs under trace)
    sentinels = tuple(
        1 if np.dtype(d) == np.bool_ else int(np.iinfo(np.dtype(d)).max)
        for d in dtypes)
    cap = w_a.shape[-1] + w_b.shape[-1]
    a64 = tuple(c.astype(jnp.int64) for c in cols_a)
    b64 = tuple(c.astype(jnp.int64) for c in cols_b)
    result = tuple(jax.ShapeDtypeStruct((cap,), jnp.int64)
                   for _ in range(ncols + 1))
    out = _FFI.ffi_call(FFI_TARGET, result, vmap_method="sequential")(
        *a64, w_a.astype(jnp.int64), *b64, w_b.astype(jnp.int64),
        jnp.asarray(sentinels, jnp.int64))
    # inside a shard_map the inputs carry varying-manual-axes (vma) types;
    # custom-call results come back untagged, which breaks scan carries —
    # re-tag them to match the inputs
    vma = _vma_of(w_a)
    if vma:
        out = tuple(jax.lax.pcast(o, tuple(vma), to="varying") for o in out)
    out_cols = tuple(c.astype(d) for c, d in zip(out[:ncols], dtypes))
    return out_cols, out[ncols].astype(w_a.dtype)


def consolidate_cols_native(cols: Sequence[jnp.ndarray], weights: jnp.ndarray
                            ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Native consolidation of an unsorted run — drop-in for the CPU branch
    of ``kernels.consolidate_cols`` (argsort + net + pack in C++; the XLA
    comparator sort it replaces is the per-tick cost of every operator
    output consolidation)."""
    _load()
    ncols = len(cols)
    dtypes = tuple(c.dtype for c in cols)
    sentinels = tuple(
        1 if np.dtype(d) == np.bool_ else int(np.iinfo(np.dtype(d)).max)
        for d in dtypes)
    cap = weights.shape[-1]
    c64 = tuple(c.astype(jnp.int64) for c in cols)
    result = tuple(jax.ShapeDtypeStruct((cap,), jnp.int64)
                   for _ in range(ncols + 1))
    out = _FFI.ffi_call(CONSOLIDATE_TARGET, result,
                        vmap_method="sequential")(
        *c64, weights.astype(jnp.int64),
        jnp.asarray(sentinels, jnp.int64))
    vma = _vma_of(weights)
    if vma:
        out = tuple(jax.lax.pcast(o, tuple(vma), to="varying") for o in out)
    out_cols = tuple(c.astype(d) for c, d in zip(out[:ncols], dtypes))
    return out_cols, out[ncols].astype(weights.dtype)


def lex_probe_native(table_cols: Sequence[jnp.ndarray],
                     query_cols: Sequence[jnp.ndarray],
                     side: str = "left") -> jnp.ndarray:
    """Native lexicographic searchsorted: per-query C++ binary search over
    the sorted table (native/zset_merge.cpp::ZsetProbeImpl). Drop-in for
    the CPU branch of ``kernels.lex_probe`` — the XLA unrolled-search loop
    there pays log2(n) rounds of whole-query-vector gathers per column
    (~175ms for 16k queries x 1M rows; this call is ~1ms)."""
    _load()
    t64 = tuple(c.astype(jnp.int64) for c in table_cols)
    q64 = tuple(c.astype(jnp.int64) for c in query_cols)
    m = q64[0].shape[-1]
    result = (jax.ShapeDtypeStruct((m,), jnp.int32),)
    out = _FFI.ffi_call(PROBE_TARGET, result, vmap_method="sequential")(
        *t64, *q64,
        jnp.asarray([1 if side == "right" else 0], jnp.int64))
    pos = out[0]
    vma = _vma_of(q64[0])
    if vma:
        pos = jax.lax.pcast(pos, tuple(vma), to="varying")
    return pos


def _retag(out, ref):
    """Re-tag custom-call results with the reference value's vma (see
    merge_consolidated_cols — custom calls drop the tag under shard_map)."""
    vma = _vma_of(ref)
    if vma:
        return tuple(jax.lax.pcast(o, tuple(vma), to="varying") for o in out)
    return tuple(out)


def lex_probe_ladder_native(tables, query_cols, side: str = "left"
                            ) -> jnp.ndarray:
    """ONE custom call probing the query rows into EVERY level's sorted
    table (native/zset_merge.cpp::ZsetProbeLadderImpl) — drop-in for the
    CPU branch of ``cursor.lex_probe_ladder``, replacing K separate probe
    dispatches + a stack with a single [K, m] result."""
    _load()
    K = len(tables)
    ncols = len(tables[0])
    m = query_cols[0].shape[-1]
    t64 = [c.astype(jnp.int64) for t in tables for c in t]
    q64 = [c.astype(jnp.int64) for c in query_cols]
    meta = jnp.asarray([K, ncols, 1 if side == "right" else 0], jnp.int64)
    result = (jax.ShapeDtypeStruct((K, m), jnp.int32),)
    out = _FFI.ffi_call(PROBE_LADDER_TARGET, result,
                        vmap_method="sequential")(*t64, *q64, meta)
    return _retag(out, query_cols[0])[0]


def expand_ranges_native(lo: jnp.ndarray, hi: jnp.ndarray, out_cap: int):
    """Sequential range expansion (ZsetExpandImpl) — drop-in for the CPU
    branch of ``kernels.expand_ranges`` (and, over flattened [K*m] ranges,
    ``cursor.expand_ladder``). Returns ``(row, src, valid, total)`` with
    the same dtypes/tail contract as the searchsorted formulation."""
    _load()
    result = (jax.ShapeDtypeStruct((out_cap,), jnp.int32),
              jax.ShapeDtypeStruct((out_cap,), jnp.int32),
              jax.ShapeDtypeStruct((out_cap,), jnp.bool_),
              jax.ShapeDtypeStruct((1,), jnp.int64))
    out = _FFI.ffi_call(EXPAND_TARGET, result, vmap_method="sequential")(
        lo.astype(jnp.int64), hi.astype(jnp.int64))
    row, src, valid, total = _retag(out, lo)
    return row, src, valid, total.reshape(())


def gather_levels_native(cols_per_level, level: jnp.ndarray,
                         src: jnp.ndarray):
    """Grouped gather across trace levels (ZsetGatherImpl) — drop-in for
    ``cursor._select_gather``: out[ci][j] = level[j]'s column ci at the
    clamped src[j]. One pass instead of K clamped gathers + selects per
    column."""
    _load()
    ncols = len(cols_per_level[0])
    if not ncols:
        return ()
    dtypes = tuple(c.dtype for c in cols_per_level[0])
    n = level.shape[-1]
    tabs = [cols[ci].astype(jnp.int64)
            for ci in range(ncols) for cols in cols_per_level]
    result = tuple(jax.ShapeDtypeStruct((n,), jnp.int64)
                   for _ in range(ncols))
    out = _FFI.ffi_call(GATHER_TARGET, result, vmap_method="sequential")(
        level.astype(jnp.int32), src.astype(jnp.int32), *tabs)
    out = _retag(out, level)
    return tuple(c.astype(d) for c, d in zip(out, dtypes))


def compact_native(cols, weights: jnp.ndarray, keep: jnp.ndarray):
    """Single-pass compaction (ZsetCompactImpl) — drop-in for the CPU
    branch of ``kernels.compact``."""
    _load()
    ncols = len(cols)
    dtypes = tuple(c.dtype for c in cols)
    sentinels = tuple(
        1 if np.dtype(d) == np.bool_ else int(np.iinfo(np.dtype(d)).max)
        for d in dtypes)
    cap = weights.shape[-1]
    c64 = tuple(c.astype(jnp.int64) for c in cols)
    result = tuple(jax.ShapeDtypeStruct((cap,), jnp.int64)
                   for _ in range(ncols + 1))
    out = _FFI.ffi_call(COMPACT_TARGET, result, vmap_method="sequential")(
        *c64, weights.astype(jnp.int64), keep.astype(jnp.bool_),
        jnp.asarray(sentinels, jnp.int64))
    out = _retag(out, weights)
    out_cols = tuple(c.astype(d) for c, d in zip(out[:ncols], dtypes))
    return out_cols, out[ncols].astype(weights.dtype)


def _sentinel64(dtypes) -> tuple:
    """Per-dtype sentinel values widened to int64 (host ints — traceable),
    derived from the ONE dead-row sentinel definition
    (``kernels.sentinel_scalar``) so the native megakernels' dead slots
    can never drift from the stitched/Pallas backends' bit-identity
    contract."""
    from dbsp_tpu.zset import kernels

    return tuple(int(kernels.sentinel_scalar(d)) for d in dtypes)


def join_ladder_native(delta, levels, nk: int, out_cap: int):
    """The WHOLE fused incremental join in one custom call
    (ZsetJoinLadderImpl): both ladder probes, dead-row zeroing, the
    cross-level expansion, the delta-side qrow gathers (keys + vals), the
    level-side value gather and the weight product — where even the native
    stitched path paid 4+ custom calls with XLA where-mask glue between
    them. Returns ``(key_cols, delta_val_cols, level_val_cols, w, valid,
    total)`` in the original dtypes; the caller applies the pair function
    and the dead-slot sentinel mask (cheap elementwise XLA) on top."""
    _load()
    K = len(levels)
    dk = delta.keys[:nk]
    ndv = len(delta.vals)
    nlv = len(levels[0].vals)
    key_dts = tuple(c.dtype for c in dk)
    dval_dts = tuple(c.dtype for c in delta.vals)
    lval_dts = tuple(c.dtype for c in levels[0].vals)
    ops = [c.astype(jnp.int64) for c in (*dk, *delta.vals)]
    ops.append(delta.weights.astype(jnp.int64))
    for lvl in levels:
        ops.extend(c.astype(jnp.int64)
                   for c in (*lvl.keys[:nk], *lvl.vals, lvl.weights))
    ops.append(jnp.asarray([K, nk, ndv, nlv], jnp.int64))
    n_out = nk + ndv + nlv
    result = (*(jax.ShapeDtypeStruct((out_cap,), jnp.int64)
                for _ in range(n_out + 1)),
              jax.ShapeDtypeStruct((out_cap,), jnp.bool_),
              jax.ShapeDtypeStruct((1,), jnp.int64))
    out = _FFI.ffi_call(JOIN_LADDER_TARGET, result,
                        vmap_method="sequential")(*ops)
    out = _retag(out, delta.weights)
    key_cols = tuple(c.astype(d) for c, d in zip(out[:nk], key_dts))
    dvals = tuple(c.astype(d)
                  for c, d in zip(out[nk:nk + ndv], dval_dts))
    lvals = tuple(c.astype(d)
                  for c, d in zip(out[nk + ndv:n_out], lval_dts))
    w = out[n_out].astype(delta.weights.dtype)
    valid = out[n_out + 1]
    total = out[n_out + 2].reshape(())
    return key_cols, dvals, lvals, w, valid, total


def gather_ladder_native(qkeys, qlive, levels, out_cap: int,
                         qhi_keys=None, gather_keys: int = 0):
    """The WHOLE fused group gather in one custom call
    (ZsetGatherLadderImpl): both ladder probes (equality or distinct
    [lo, hi] range bounds), the cross-level expansion, the leveled value
    gather and the dead-slot canonicalization (qrow == q_cap, sentinel
    cols, weight 0) — the consumer-facing ``((qrow, vals, w), total)``
    part comes back FINAL, no XLA post-pass. Shares the contract of
    ``cursor.gather_ladder`` exactly (``qhi_keys``/``gather_keys``
    included)."""
    _load()
    K = len(levels)
    nk = len(qkeys)
    gcols0 = (*levels[0].keys[nk - gather_keys:nk], *levels[0].vals) \
        if gather_keys else tuple(levels[0].vals)
    g_dts = tuple(c.dtype for c in gcols0)
    ng = len(gcols0)
    ops = [c.astype(jnp.int64) for c in qkeys]
    if qhi_keys is not None:
        ops.extend(c.astype(jnp.int64) for c in qhi_keys)
    ops.append(qlive.astype(jnp.bool_))
    for lvl in levels:
        gc = (*lvl.keys[nk - gather_keys:nk], *lvl.vals) if gather_keys \
            else tuple(lvl.vals)
        ops.extend(c.astype(jnp.int64)
                   for c in (*lvl.keys[:nk], *gc, lvl.weights))
    ops.append(jnp.asarray(_sentinel64(g_dts), jnp.int64))
    ops.append(jnp.asarray([K, nk, 1 if qhi_keys is not None else 0],
                           jnp.int64))
    result = (jax.ShapeDtypeStruct((out_cap,), jnp.int32),
              *(jax.ShapeDtypeStruct((out_cap,), jnp.int64)
                for _ in range(ng + 1)),
              jax.ShapeDtypeStruct((1,), jnp.int64))
    out = _FFI.ffi_call(GATHER_LADDER_TARGET, result,
                        vmap_method="sequential")(*ops)
    out = _retag(out, qlive)
    qrow = out[0]
    vals = tuple(c.astype(d) for c, d in zip(out[1:1 + ng], g_dts))
    w = out[1 + ng].astype(levels[0].weights.dtype)
    total = out[2 + ng].reshape(())
    return (qrow, vals, w), total


def old_weights_ladder_native(delta, levels) -> jnp.ndarray:
    """Distinct's old-weight lookup in one custom call
    (ZsetOldWeightsImpl): per delta row, one exact-match binary search per
    level with the present weights summed — drop-in for the CPU branch of
    ``cursor.old_weights_ladder``."""
    _load()
    K = len(levels)
    nc = len(delta.cols)
    ops = [c.astype(jnp.int64) for c in delta.cols]
    ops.append(delta.weights.astype(jnp.int64))
    for lvl in levels:
        ops.extend(c.astype(jnp.int64) for c in (*lvl.cols, lvl.weights))
    ops.append(jnp.asarray([K, nc], jnp.int64))
    m = delta.weights.shape[-1]
    result = (jax.ShapeDtypeStruct((m,), jnp.int64),)
    out = _FFI.ffi_call(OLD_WEIGHTS_TARGET, result,
                        vmap_method="sequential")(*ops)
    return _retag(out, delta.weights)[0].astype(delta.weights.dtype)


# Segment-reduction opcodes shared with the C++ SegAccum (zset_merge.cpp)
# and the Pallas twin — ONE vocabulary for every backend of the Aggregator
# zoo's five reductions (+ the presence mask).
SEG_OPS = {"count": 0, "sum": 1, "min": 2, "max": 3, "avg": 4, "present": 5}


def seg_op_identity(op: str, src_dtype) -> int:
    """The accumulator init / empty-segment fill of one reduction op, as a
    host int — EXACTLY what the ``jax.ops.segment_*`` formulation fills
    empty segments with (min fills with the SOURCE dtype's max, max — and
    present, which IS a segment_max over 0/1 — with its min, the additive
    ops with 0), so the native kernel's untouched segments can never drift
    from the XLA fills."""
    if op == "min":
        return int(jnp.iinfo(jnp.dtype(src_dtype)).max)
    if op in ("max", "present"):
        return int(jnp.iinfo(jnp.dtype(src_dtype)).min)
    return 0


def _ops_meta(spec, val_dtypes) -> list:
    """[opcode, src_col, identity] triples for a reduce spec (tuples of
    (op name, source column)) — the meta layout the C++ kernels consume."""
    out = []
    for op, col in spec:
        src = val_dtypes[col] if op in ("min", "max") else jnp.int64
        out.extend((SEG_OPS[op], col, seg_op_identity(op, src)))
    return out


def segment_reduce_native(spec, val_cols, weights: jnp.ndarray,
                          seg: jnp.ndarray, num_segments: int, out_dtypes):
    """ONE custom call running a whole reduce spec (ZsetSegmentReduceImpl)
    — drop-in for the CPU branch of ``operators.aggregate.segment_reduce``:
    every op's jax.ops.segment_* chain (mask + reduce, 2-4 dispatches per
    output) collapses into a single pass over (vals, weights, seg)."""
    _load()
    val_dtypes = tuple(c.dtype for c in val_cols)
    meta = jnp.asarray([len(val_cols), *_ops_meta(spec, val_dtypes)],
                       jnp.int64)
    result = tuple(jax.ShapeDtypeStruct((num_segments,), jnp.int64)
                   for _ in spec)
    out = _FFI.ffi_call(SEGMENT_REDUCE_TARGET, result,
                        vmap_method="sequential")(
        *(c.astype(jnp.int64) for c in val_cols),
        weights.astype(jnp.int64), seg.astype(jnp.int32), meta)
    out = _retag(out, weights)
    return tuple(c.astype(d) for c, d in zip(out, out_dtypes))


def agg_ladder_native(delta, nk: int, out_trace, levels, spec,
                      q_cap: int, gather_cap: int, fast: bool,
                      flag: jnp.ndarray, lad_dtypes, d_dtypes):
    """The WHOLE CAggregate reduce chain in one custom call
    (ZsetAggLadderImpl): run-boundary unique keys, the out-trace exact-match
    probe (per-column TupleMax of the previous outputs), the touched
    groups' ladder history walk — cross-level netting + the aggregator's
    segment reduction folded into the walk, nothing materialized — and, in
    fast (insert-combinable) mode, the delta's own reduction in the same
    run scan. ``flag`` is the RUNTIME ladder gate (ever_negative on the
    fast path; constant true on the general path). Returns
    ``(qkeys, qlive, nq, old_vals, old_present, lad_vals, lad_present,
    d_vals, d_present, gather_total)`` with the stitched chain's exact
    dtypes and clamping behavior."""
    _load()
    dk = delta.keys[:nk]
    key_dts = tuple(c.dtype for c in dk)
    old_dts = tuple(c.dtype for c in out_trace.vals)
    nov = len(spec)
    lval_dts = tuple(c.dtype for c in levels[0].vals)
    meta = [len(levels), nk, len(delta.vals), len(levels[0].vals), nov,
            1 if fast else 0, gather_cap]
    meta += _ops_meta(spec, lval_dts)
    meta += [seg_op_identity("max", d) for d in old_dts]  # TupleMax inits
    meta += [int(kernels_sentinel(d)) for d in key_dts]
    ops = [c.astype(jnp.int64) for c in (*dk, *delta.vals)]
    ops.append(delta.weights.astype(jnp.int64))
    ops.extend(c.astype(jnp.int64)
               for c in (*out_trace.keys[:nk], *out_trace.vals,
                         out_trace.weights))
    for lvl in levels:
        ops.extend(c.astype(jnp.int64)
                   for c in (*lvl.keys[:nk], *lvl.vals, lvl.weights))
    ops.append(flag.astype(jnp.int64).reshape(1))
    ops.append(jnp.asarray(meta, jnp.int64))
    result = (*(jax.ShapeDtypeStruct((q_cap,), jnp.int64)
                for _ in range(nk)),
              jax.ShapeDtypeStruct((q_cap,), jnp.bool_),
              jax.ShapeDtypeStruct((1,), jnp.int64),
              *(jax.ShapeDtypeStruct((q_cap,), jnp.int64)
                for _ in range(nov)),
              jax.ShapeDtypeStruct((q_cap,), jnp.bool_),
              *(jax.ShapeDtypeStruct((q_cap,), jnp.int64)
                for _ in range(nov)),
              jax.ShapeDtypeStruct((q_cap,), jnp.bool_),
              *(jax.ShapeDtypeStruct((q_cap,), jnp.int64)
                for _ in range(nov)),
              jax.ShapeDtypeStruct((q_cap,), jnp.bool_),
              jax.ShapeDtypeStruct((1,), jnp.int64))
    out = _FFI.ffi_call(AGG_LADDER_TARGET, result,
                        vmap_method="sequential")(*ops)
    out = _retag(out, delta.weights)
    qkeys = tuple(c.astype(d) for c, d in zip(out[:nk], key_dts))
    qlive = out[nk]
    nq = out[nk + 1].reshape(())
    i = nk + 2
    old_vals = tuple(c.astype(d) for c, d in zip(out[i:i + nov], old_dts))
    old_present = out[i + nov]
    i += nov + 1
    lad_vals = tuple(c.astype(d) for c, d in zip(out[i:i + nov],
                                                 lad_dtypes))
    lad_present = out[i + nov]
    i += nov + 1
    if fast:
        d_vals = tuple(c.astype(d)
                       for c, d in zip(out[i:i + nov], d_dtypes))
        d_present = out[i + nov]
    else:
        d_vals, d_present = None, None  # general path never reads them
    gtotal = out[i + nov + 1].reshape(())
    return (qkeys, qlive, nq, old_vals, old_present, lad_vals, lad_present,
            d_vals, d_present, gtotal)


def kernels_sentinel(dtype) -> int:
    from dbsp_tpu.zset import kernels

    return int(kernels.sentinel_scalar(dtype))


def join_ladder_sorted_native(delta, levels, nk: int, perm, n_out_keys: int,
                              out_dtypes, out_cap: int):
    """Sorted-emit join megakernel (ZsetJoinLadderSortedImpl): the whole
    fused join with a permutation pair-fn applied IN the call and the
    side's buffer emitted as ONE consolidated run (sorted by the projected
    columns, equal rows netted, packed, sentinel tail). Returns
    ``(Batch tagged runs=(out_cap,), unclamped total)`` — the caller's
    post-join ``concat().consolidate()`` then rank-folds two runs with one
    linear native merge instead of a full argsort."""
    _load()
    K = len(levels)
    dk = delta.keys[:nk]
    n_out = len(perm)
    sentinels = tuple(kernels_sentinel(d) for d in out_dtypes)
    ops = [c.astype(jnp.int64) for c in (*dk, *delta.vals)]
    ops.append(delta.weights.astype(jnp.int64))
    for lvl in levels:
        ops.extend(c.astype(jnp.int64)
                   for c in (*lvl.keys[:nk], *lvl.vals, lvl.weights))
    ops.append(jnp.asarray(sentinels, jnp.int64))
    ops.append(jnp.asarray(
        [K, nk, len(delta.vals), len(levels[0].vals), n_out, *perm],
        jnp.int64))
    result = (*(jax.ShapeDtypeStruct((out_cap,), jnp.int64)
                for _ in range(n_out + 1)),
              jax.ShapeDtypeStruct((1,), jnp.int64))
    out = _FFI.ffi_call(JOIN_SORTED_TARGET, result,
                        vmap_method="sequential")(*ops)
    out = _retag(out, delta.weights)
    cols = tuple(c.astype(d) for c, d in zip(out[:n_out], out_dtypes))
    w_dt = jnp.promote_types(delta.weights.dtype, levels[0].weights.dtype)
    w = out[n_out].astype(w_dt)
    total = out[n_out + 1].reshape(())
    from dbsp_tpu.zset.batch import Batch

    return Batch(cols[:n_out_keys], cols[n_out_keys:], w,
                 runs=(out_cap,)), total


def rank_fold_native(cols, weights: jnp.ndarray, runs):
    """K-way merge consolidation of an R-run batch (ZsetRankFoldImpl) —
    drop-in for the rank regime of ``batch.consolidate_regime``: one
    custom call instead of a fold of R-1 pairwise merges. ``runs`` is the
    STATIC sorted-run metadata (segment lengths summing to cap)."""
    _load()
    ncols = len(cols)
    dtypes = tuple(c.dtype for c in cols)
    sentinels = tuple(
        1 if np.dtype(d) == np.bool_ else int(np.iinfo(np.dtype(d)).max)
        for d in dtypes)
    cap = weights.shape[-1]
    c64 = tuple(c.astype(jnp.int64) for c in cols)
    result = tuple(jax.ShapeDtypeStruct((cap,), jnp.int64)
                   for _ in range(ncols + 1))
    out = _FFI.ffi_call(RANK_FOLD_TARGET, result, vmap_method="sequential")(
        *c64, weights.astype(jnp.int64),
        jnp.asarray(tuple(runs), jnp.int64),
        jnp.asarray(sentinels, jnp.int64))
    out = _retag(out, weights)
    out_cols = tuple(c.astype(d) for c, d in zip(out[:ncols], dtypes))
    return out_cols, out[ncols].astype(weights.dtype)
