from dbsp_tpu.zset.batch import Batch, concat_batches, bucket_cap, WEIGHT_DTYPE
from dbsp_tpu.zset import kernels

__all__ = ["Batch", "concat_batches", "bucket_cap", "WEIGHT_DTYPE", "kernels"]
