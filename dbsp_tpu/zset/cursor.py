"""Fused trace cursors: probe EVERY level of a trace ladder in one kernel.

A trace (host ``Spine`` or the compiled leveled state) is a small set of
consolidated batches in geometric capacity classes. Every traced operator
used to probe it one level at a time: K probe launches, K expansion buffers
with K grow-on-demand capacities, then a concat (+ full sort on the host
path) to combine — per-tick kernel count proportional to delta x
spine-depth, where the DBSP cost model (VLDB'23) wants it proportional to
the delta alone.

This module collapses that fan-out (the engine's answer to the reference's
``CursorList`` k-way merge cursor, ``trace/cursor/cursor_list.rs``):

* :func:`lex_probe_ladder` — ONE vectorized lexicographic search over the
  whole level ladder: [K, m] (level, query) lanes share a single unrolled
  binary-search loop (on CPU with the native library, ONE ladder-wide C++
  probe call; on accelerator backends a Pallas grid-over-levels program —
  same result, same shape).
* :func:`expand_ladder` — ONE ``expand_ranges``-style prefix-sum allocation
  whose [K*m] counts span levels: each output slot resolves to (level,
  query row, source row) through a single searchsorted over the cross-level
  prefix sums. Level-major order, so the output layout matches the old
  offset-scatter scheme exactly.
* :func:`join_ladder` / :func:`gather_ladder` / :func:`old_weights_ladder`
  — the three hot consumers (incremental join, aggregate group gather,
  distinct old-weight lookup) as single fused kernels over the ladder.
  On CPU with the native library each consumer is ONE megakernel custom
  call (probe + expand + gather + weight-combine —
  ``native_merge.join_ladder_native`` & co.); with Pallas selected it is
  one grid-over-levels megakernel (``pallas_kernels.join_ladder_pallas``);
  the stitched probe-ladder/expand/gather chain below is the pure-XLA
  fallback and the force-off A/B control (``DBSP_TPU_NATIVE=join_ladder``
  etc. — see ``native_merge.kernel_enabled``).

All functions are pure/traceable over 1-D row axes; sharded callers lift
them per worker exactly like the per-level kernels they replace
(``parallel/lift.py``). Outputs are bit-identical to the per-level loops:
the same (row, weight) multiset in the same level-major order, with dead
padding packed at the tail instead of scattered per level
(tests/test_cursor.py proves both).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch

Cols = Tuple[jnp.ndarray, ...]


# ---------------------------------------------------------------------------
# Fused probe
# ---------------------------------------------------------------------------


def lex_probe_ladder(tables: Sequence[Cols], query_cols: Cols,
                     side: str = "left") -> jnp.ndarray:
    """Insertion points of ``query`` rows into EVERY sorted table at once.

    ``tables`` is one tuple of key columns per trace level (heterogeneous
    capacities are fine — each level's lanes clamp to its own row count);
    returns ``[K, m]`` int32. Lane (k, i) equals
    ``lex_probe(tables[k], query_cols, side)[i]`` exactly.
    """
    assert tables, "lex_probe_ladder: empty ladder"
    K = len(tables)
    m = query_cols[0].shape[0] if query_cols else 0
    if query_cols and query_cols[0].ndim == 1:
        dts = [c.dtype for t in tables for c in t]
        # cheap pre-check before importing the pallas module: the CPU
        # backend without an explicit override never selects it, and the
        # import itself is not free on cold start
        if kernels.pallas_requested():
            from dbsp_tpu.zset import pallas_kernels

            all_cols = (*(c for t in tables for c in t), *query_cols)
            if pallas_kernels.use_pallas("probe_ladder", all_cols):
                kernels.count_kernel_dispatch("probe_ladder", "pallas")
                return pallas_kernels.lex_probe_ladder_pallas(
                    tables, query_cols, side)
        if kernels.native_kernel("probe_ladder"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports(
                    (*dts, *(c.dtype for c in query_cols))):
                kernels.count_kernel_dispatch("probe_ladder", "native")
                return native_merge.lex_probe_ladder_native(
                    tables, query_cols, side)
    kernels.count_kernel_dispatch("probe_ladder", "xla")
    caps = [t[0].shape[0] for t in tables]
    steps = max(c.bit_length() for c in caps)
    strict = side == "left"
    lo = jnp.zeros((K, m), jnp.int32)
    hi = jnp.stack([jnp.full((m,), c, jnp.int32) for c in caps])
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) >> 1
        go_right = jnp.stack([
            kernels._lex_le_rows(t, mid[k], query_cols, strict=strict)
            for k, t in enumerate(tables)])
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


# ---------------------------------------------------------------------------
# Fused expansion
# ---------------------------------------------------------------------------


def expand_ladder(lo: jnp.ndarray, hi: jnp.ndarray, out_cap: int):
    """Flatten ``[K, m]`` per-(level, query) ranges into ONE static buffer.

    Level-major: slot order is level 0's matches (query-major within the
    level), then level 1's, ... — the same layout the per-level
    offset-scatter produced. Returns ``(level, qrow, src, valid, total)``
    each of shape [out_cap] (total is the unclamped device scalar; the
    standard overflow contract of :func:`kernels.expand_ranges` applies).
    """
    K, m = lo.shape
    if kernels.native_kernel("expand"):
        kernels.count_kernel_dispatch("expand", "native")
        from dbsp_tpu.zset import native_merge

        flat, src, valid, total = native_merge.expand_ranges_native(
            lo.reshape(K * m), hi.reshape(K * m), out_cap)
        level = flat // m
        qrow = flat - level * m
        return level, qrow, src, valid, total
    kernels.count_kernel_dispatch("expand", "xla")
    counts = jnp.maximum(hi - lo, 0).reshape(K * m)
    starts = jnp.cumsum(counts) - counts
    # the OVERFLOW total accumulates in 64-bit: a ladder-wide match count
    # past 2^31 would wrap an int32 sum negative and defeat the runner's
    # requirement check. Slot resolution below stays int32: a wrapped
    # prefix-sum WOULD corrupt even valid slots, but any such launch has
    # total > out_cap by orders of magnitude, so the int64 total forces a
    # grow/replay (host) or overflow replay (compiled) and the garbage
    # buffer is discarded unread.
    total = jnp.sum(counts, dtype=jnp.int64)
    j = jnp.arange(out_cap, dtype=jnp.int32)
    flat = kernels.searchsorted1(starts, jnp.minimum(j, total - 1),
                                 side="right") - 1
    flat = jnp.clip(flat, 0, K * m - 1)
    offset = j - starts[flat]
    src = lo.reshape(K * m)[flat] + offset
    valid = j < total
    level = flat // m
    qrow = flat - level * m
    return level, qrow, src.astype(jnp.int32), valid, total


def _select_gather(cols_per_level: Sequence[Cols], level: jnp.ndarray,
                   src: jnp.ndarray) -> Cols:
    """Gather column values from the level each output slot resolved to:
    one clamped gather per level per column, combined by level-id select
    (no scatters, no per-level buffers). On CPU with the native library the
    whole select tree is ONE C++ pass reading exactly the (level, src) cell
    each slot resolved to (ZsetGatherImpl) — bit-identical values, clamped
    reads on dead slots included."""
    if not cols_per_level[0]:
        return ()
    if level.ndim == 1 and kernels.native_kernel("gather"):
        from dbsp_tpu.zset import native_merge

        if native_merge.supports(c.dtype for cols in cols_per_level
                                 for c in cols):
            kernels.count_kernel_dispatch("gather", "native")
            return native_merge.gather_levels_native(cols_per_level, level,
                                                     src)
    kernels.count_kernel_dispatch("gather", "xla")
    outs: List[jnp.ndarray] = []
    for ci in range(len(cols_per_level[0])):
        acc = None
        for k, cols in enumerate(cols_per_level):
            c = cols[ci]
            v = c[jnp.clip(src, 0, c.shape[0] - 1)]
            acc = v if acc is None else jnp.where(level == k, v, acc)
        outs.append(acc)
    return tuple(outs)


# ---------------------------------------------------------------------------
# Fused consumers
# ---------------------------------------------------------------------------


def _finish_join(fn, key_cols, lvals, rvals, w, valid, total
                 ) -> Tuple[Batch, jnp.ndarray]:
    """Apply the pair function + dead-slot sentinel mask — the (cheap,
    elementwise) tail every join_ladder backend shares, so the fused
    megakernels and the stitched chain produce bit-identical batches."""
    out_keys, out_vals = fn(key_cols, lvals, rvals)
    # dead slots must carry sentinels so they sort to the tail later
    out_keys = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_keys)
    out_vals = tuple(jnp.where(valid, c, kernels.sentinel_for(c.dtype))
                     for c in out_vals)
    return Batch(out_keys, out_vals, w), total


def _ladder_dtypes(delta: Batch, levels: Sequence[Batch]):
    return (*(c.dtype for c in delta.cols), delta.weights.dtype,
            *(c.dtype for lvl in levels for c in (*lvl.cols, lvl.weights)))


def join_ladder(delta: Batch, levels: Sequence[Batch], nk: int, fn,
                out_cap: int, sorted_emit=None) -> Tuple[Batch, jnp.ndarray]:
    """Join a delta against ALL trace levels: one probe pair, one expansion,
    one output buffer. Replaces the per-level ``_join_level_impl`` loop
    (operators/join.py) and the compiled offset-scatter (cnodes).

    Output is RAW (callers consolidate once); the returned total is the
    UNCLAMPED cross-level requirement — when it exceeds ``out_cap`` the
    tail matches drop off the end and the caller grows + relaunches
    (host) or the runner's validation replays (compiled).

    ``sorted_emit`` — ``(n_out_keys, perm, out_dtypes)`` when the pair
    function is a pure column PERMUTATION of the raw (probed keys, delta
    vals, level vals) columns (``operators.join.fn_permutation`` probes the
    fn to find out) — selects the SORTED-EMIT megakernel on the native CPU
    path: the projection is applied in-call and the side's buffer comes
    back as ONE consolidated run (``runs=(out_cap,)``), so the caller's
    post-join ``concat().consolidate()`` rank-folds two runs with a single
    linear merge instead of full-sorting the doubled buffer, and the
    pair-fn + dead-slot-mask XLA passes disappear. The emitted Z-set is
    identical (netting only canonicalizes), so the post-consolidation
    batch is bit-identical to every other backend; the
    ``DBSP_TPU_NATIVE=join_sorted`` force-off is the A/B control.

    Backend dispatch (1-D operands, int64-widenable columns): ONE native
    megakernel custom call on CPU (probe + expand + both-side gathers +
    weight product — ``native_merge.join_ladder_native``); one Pallas
    grid-over-levels megakernel when Pallas is selected; else the stitched
    probe-ladder/expand/gather chain below (also the
    ``DBSP_TPU_NATIVE=join_ladder`` force-off control).
    """
    assert levels, "join_ladder: trace has no levels"
    dk = delta.keys[:nk]
    if nk >= 1 and delta.weights.ndim == 1 and out_cap >= 1:
        # Pallas takes precedence: there is no sorted-emit Pallas mode
        # (the TPU rank-merge regime owns consolidation there), and a
        # DBSP_TPU_PALLAS force-on must actually measure the Pallas
        # program — a native kernel preempting it would silently turn the
        # Pallas-vs-XLA A/B into a native measurement
        if sorted_emit is not None and not kernels.pallas_requested() and \
                kernels.native_kernel("join_sorted"):
            from dbsp_tpu.zset import native_merge

            n_out_keys, perm, out_dts = sorted_emit
            if native_merge.supports((*_ladder_dtypes(delta, levels),
                                      *out_dts)):
                kernels.count_kernel_dispatch("join_sorted", "native")
                return native_merge.join_ladder_sorted_native(
                    delta, levels, nk, perm, n_out_keys, out_dts, out_cap)
        if kernels.pallas_requested():
            from dbsp_tpu.zset import pallas_kernels

            if pallas_kernels.use_pallas(
                    "join_ladder",
                    (*delta.cols, delta.weights,
                     *(c for lvl in levels
                       for c in (*lvl.cols, lvl.weights)))):
                kernels.count_kernel_dispatch("join_ladder", "pallas")
                qrow, rvals, w, valid, total = \
                    pallas_kernels.join_ladder_pallas(
                        dk, delta.weights, levels, nk, out_cap)
                key_cols = tuple(c[qrow] for c in dk)
                lvals = tuple(c[qrow] for c in delta.vals)
                return _finish_join(fn, key_cols, lvals, rvals, w, valid,
                                    total)
        if kernels.native_kernel("join_ladder"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports(_ladder_dtypes(delta, levels)):
                kernels.count_kernel_dispatch("join_ladder", "native")
                key_cols, lvals, rvals, w, valid, total = \
                    native_merge.join_ladder_native(delta, levels, nk,
                                                    out_cap)
                return _finish_join(fn, key_cols, lvals, rvals, w, valid,
                                    total)
    kernels.count_kernel_dispatch("join_ladder", "xla")
    tables = [lvl.keys[:nk] for lvl in levels]
    lo = lex_probe_ladder(tables, dk, side="left")
    hi = lex_probe_ladder(tables, dk, side="right")
    # dead delta rows carry sentinel keys, which match every level's dead
    # tail — zero their ranges instead of emitting weight-0 garbage
    live = delta.weights != 0
    lo = jnp.where(live[None, :], lo, 0)
    hi = jnp.where(live[None, :], hi, lo)
    level, qrow, src, valid, total = expand_ladder(lo, hi, out_cap)
    (lw,) = _select_gather([(lvl.weights,) for lvl in levels], level, src)
    w = jnp.where(valid, delta.weights[qrow] * lw, 0)
    key_cols = tuple(c[qrow] for c in dk)
    lvals = tuple(c[qrow] for c in delta.vals)
    rvals = _select_gather([lvl.vals for lvl in levels], level, src)
    return _finish_join(fn, key_cols, lvals, rvals, w, valid, total)


def gather_ladder(qkeys: Cols, qlive: jnp.ndarray, levels: Sequence[Batch],
                  out_cap: int, qhi_keys: Cols = None,
                  gather_keys: int = 0):
    """Gather the query keys' rows from ALL trace levels into one
    (qrow, val_cols, w) part of capacity ``out_cap``. Dead slots carry
    qrow == q_cap (the trash segment) and sentinel vals — the same contract
    as the per-level gather + offset scatter it replaces. Returns
    ``(part, unclamped total)``.

    The ONE leveled-gather entry point, shared by equality and range
    consumers (the aggregate family, rolling aggregates, the radix time
    index): ``qhi_keys`` optionally gives DISTINCT upper-bound query
    columns for the right-side probe — each query then matches the key
    range [qkeys[i], qhi_keys[i]] instead of the exact group (empty
    ranges, qhi < qlo, gather nothing); ``gather_keys`` returns that many
    trailing PROBED KEY columns ahead of the vals (range gathers need the
    time column back; equality gathers already hold their keys).

    NOTE: with K > 1 the part may hold cross-level insert/retract rows for
    one (qrow, vals) — reducers must net them
    (``_reduce_groups_impl(..., net=True)``), exactly as with the old
    combined buffer.

    Backend dispatch mirrors :func:`join_ladder`: ONE native megakernel
    custom call on CPU (``native_merge.gather_ladder_native`` — the part
    comes back final, dead slots canonical), one Pallas megakernel when
    selected, else the stitched chain (the ``DBSP_TPU_NATIVE=gather_ladder``
    force-off control)."""
    assert levels, "gather_ladder: trace has no levels"
    nk = len(qkeys)
    q_cap = qlive.shape[-1]
    if nk >= 1 and qlive.ndim == 1 and out_cap >= 1:
        _all_cols = (*qkeys, *(qhi_keys or ()),
                     *(c for lvl in levels
                       for c in (*lvl.cols, lvl.weights)))
        if kernels.pallas_requested():
            from dbsp_tpu.zset import pallas_kernels

            if pallas_kernels.use_pallas("gather_ladder", _all_cols):
                kernels.count_kernel_dispatch("gather_ladder", "pallas")
                return pallas_kernels.gather_ladder_pallas(
                    qkeys, qlive, levels, out_cap, qhi_keys=qhi_keys,
                    gather_keys=gather_keys)
        if kernels.native_kernel("gather_ladder"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports(c.dtype for c in _all_cols):
                kernels.count_kernel_dispatch("gather_ladder", "native")
                return native_merge.gather_ladder_native(
                    qkeys, qlive, levels, out_cap, qhi_keys=qhi_keys,
                    gather_keys=gather_keys)
    kernels.count_kernel_dispatch("gather_ladder", "xla")
    tables = [lvl.keys[:nk] for lvl in levels]
    lo = lex_probe_ladder(tables, qkeys, side="left")
    hi = lex_probe_ladder(tables, qkeys if qhi_keys is None else qhi_keys,
                          side="right")
    lo = jnp.where(qlive[None, :], lo, 0)
    # probes are monotone, so with distinct bounds an empty query range
    # (qhi < qlo) lands hi <= lo — the clamp makes it gather nothing;
    # with qhi_keys=None hi >= lo always holds and the clamp is a no-op
    hi = jnp.where(qlive[None, :], jnp.maximum(hi, lo), lo)
    level, qrow, src, valid, total = expand_ladder(lo, hi, out_cap)
    (lw,) = _select_gather([(lvl.weights,) for lvl in levels], level, src)
    w = jnp.where(valid, lw, 0)
    gcols = [(*lvl.keys[nk - gather_keys:nk], *lvl.vals) for lvl in levels] \
        if gather_keys else [lvl.vals for lvl in levels]
    vals = tuple(jnp.where(valid, v, kernels.sentinel_for(v.dtype))
                 for v in _select_gather(gcols, level, src))
    qrow = jnp.where(valid, qrow, jnp.int32(q_cap)).astype(jnp.int32)
    return (qrow, vals, w), total


def agg_ladder(delta: Batch, nk: int, out_trace: Batch,
               levels: Sequence[Batch], agg, q_cap: int, gather_cap: int,
               fast: bool, flag: jnp.ndarray):
    """The WHOLE general-aggregate reduce chain over a trace ladder in one
    entry point — unique touched keys (run-boundary scan of the
    consolidated delta), the previous outputs from the operator's own
    out-trace (exact-match probe + per-column ``_TupleMax``), the touched
    groups' ladder histories netted across levels and reduced by the
    aggregator's :func:`~dbsp_tpu.operators.aggregate.segment_reduce`
    spec, and (``fast`` mode) the delta's own reduction from the same run
    scan. ``flag`` is the RUNTIME ladder gate: ``ever_negative`` on the
    insert-combinable fast path (the slow re-gather engages only once a
    retraction has entered the stream), constant true on the general path.

    Returns ``(qkeys, qlive, nq, old_vals, old_present, lad_vals,
    lad_present, d_vals, d_present, gather_total)`` — ``nq`` and
    ``gather_total`` are the UNCLAMPED ``queries``/``gather`` capacity
    requirements (the standard grow/replay contract; on overflow the
    clamped buffers match the stitched chain bit for bit and are discarded
    by the replay either way).

    Backend dispatch mirrors :func:`join_ladder`: ONE native megakernel
    custom call on CPU for spec'd aggregators
    (``native_merge.agg_ladder_native`` — the gathered history never
    materializes at all); a composed Pallas lowering when Pallas is
    selected (the grid-over-levels gather megakernel + the Pallas segment
    reduce); else the stitched unique-keys/gather/net/reduce chain below
    (also the ``DBSP_TPU_NATIVE=agg_ladder`` force-off control)."""
    from dbsp_tpu.operators import aggregate as A

    assert levels, "agg_ladder: trace has no levels"
    spec = agg.reduce_spec()
    # the fused backends assume the CAggregate state shape: the out trace
    # carries exactly one value column per aggregate output, and the
    # ladder levels share the delta's value schema (they are its integral)
    fusable = (spec is not None and nk >= 1 and delta.weights.ndim == 1
               and q_cap >= 1 and gather_cap >= 1
               and len(out_trace.vals) == len(spec)
               and len(levels[0].vals) == len(delta.vals)
               # avg divides — fused int64 accumulation equals the XLA
               # wrap only for int64 results (see segment_reduce)
               and all(op != "avg" or jnp.promote_types(
                           levels[0].vals[col].dtype,
                           levels[0].weights.dtype) == jnp.int64
                       for op, col in spec))
    if fusable:
        lad_dts = tuple(
            A._seg_out_dtype(op, col, levels[0].vals, levels[0].weights)
            for op, col in spec)
        d_dts = tuple(
            A._seg_out_dtype(op, col, delta.vals, delta.weights)
            for op, col in spec)
        _all_cols = (*delta.cols, delta.weights, *out_trace.cols,
                     out_trace.weights,
                     *(c for lvl in levels for c in (*lvl.cols,
                                                     lvl.weights)))
        if kernels.pallas_requested():
            from dbsp_tpu.zset import pallas_kernels

            if pallas_kernels.use_pallas("agg_ladder", _all_cols):
                kernels.count_kernel_dispatch("agg_ladder", "pallas")
                return pallas_kernels.agg_ladder_pallas(
                    delta, nk, out_trace, levels, agg, q_cap, gather_cap,
                    fast, flag)
        if kernels.native_kernel("agg_ladder"):
            from dbsp_tpu.zset import native_merge

            if native_merge.supports(c.dtype for c in _all_cols):
                kernels.count_kernel_dispatch("agg_ladder", "native")
                return native_merge.agg_ladder_native(
                    delta, nk, out_trace, levels, spec, q_cap, gather_cap,
                    fast, flag, lad_dts, d_dts)
    kernels.count_kernel_dispatch("agg_ladder", "xla")
    return _agg_ladder_stitched(delta, nk, out_trace, levels, agg, q_cap,
                                gather_cap, fast, flag)


def _agg_ladder_stitched(delta: Batch, nk: int, out_trace: Batch, levels,
                         agg, q_cap: int, gather_cap: int, fast: bool,
                         flag):
    """The pure-XLA fallback and force-off A/B control: the chain
    CAggregate.eval used to stitch inline, with the run-boundary scan done
    ONCE (``_delta_groups_impl`` feeds both the unique-key compaction and
    the fast path's segment ids — the boundaries were previously scanned
    twice)."""
    from dbsp_tpu.operators import aggregate as A

    qkeys_full, qlive_full, anylive, seg_full = A._delta_groups_impl(
        delta, nk)
    nq = jnp.sum(qlive_full)
    qkeys = tuple(c[..., :q_cap] for c in qkeys_full)
    qlive = qlive_full[..., :q_cap]

    # previous outputs: the out trace holds one live row per present key,
    # so a q_cap expansion is exact
    oqrow, ovals, ow, _ = A._gather_level_impl(qkeys, qlive, out_trace,
                                               q_cap)
    old_vals, old_present = A._reduce_groups_impl(
        ((oqrow, ovals, ow),), A._TupleMax(len(agg.out_dtypes)), q_cap)

    if fast:
        seg = jnp.where(anylive, seg_full, q_cap).astype(jnp.int32)
        d_vals = tuple(o[:q_cap] for o in agg.reduce(
            delta.vals, delta.weights, seg, q_cap + 1))
        one = jnp.where(delta.weights > 0, 1, 0)
        d_present = jax.ops.segment_max(
            one, seg, num_segments=q_cap + 1)[:q_cap] > 0
    else:
        d_vals, d_present = None, None  # general path never reads them
    mask = qlive & jnp.broadcast_to(flag, qlive.shape)
    part, gtot = gather_ladder(qkeys, mask, levels, gather_cap)
    lad_vals, lad_present = A._reduce_groups_impl(
        (part,), agg, q_cap, net=len(levels) > 1)
    return (qkeys, qlive, nq, old_vals, old_present, lad_vals, lad_present,
            d_vals, d_present, gtot.astype(jnp.int64))


def old_weights_ladder(delta: Batch, levels: Sequence[Batch]) -> jnp.ndarray:
    """Accumulated weight of each delta ROW (keys+vals) across ALL levels —
    the fused form of distinct's per-level probe-and-sum. Rows are unique
    within a consolidated level, so each (level, row) range is 0 or 1 wide;
    present weights sum across levels. ONE native custom call on CPU
    (``native_merge.old_weights_ladder_native``); the stitched probe pair
    below is the fallback and the ``DBSP_TPU_NATIVE=old_weights`` control."""
    assert levels, "old_weights_ladder: trace has no levels"
    if len(delta.cols) >= 1 and delta.weights.ndim == 1 and \
            kernels.native_kernel("old_weights"):
        from dbsp_tpu.zset import native_merge

        if native_merge.supports(_ladder_dtypes(delta, levels)):
            kernels.count_kernel_dispatch("old_weights", "native")
            return native_merge.old_weights_ladder_native(delta, levels)
    kernels.count_kernel_dispatch("old_weights", "xla")
    cols = delta.cols
    tables = [lvl.cols for lvl in levels]
    lo = lex_probe_ladder(tables, cols, side="left")
    hi = lex_probe_ladder(tables, cols, side="right")
    live = delta.weights != 0
    found = (hi > lo) & live[None, :]
    old = jnp.zeros_like(delta.weights)
    for k, lvl in enumerate(levels):
        w = lvl.weights[jnp.minimum(lo[k], lvl.cap - 1)]
        old = old + jnp.where(found[k], w, 0)
    return old
