"""Sharding-placement pass: keyed state must be hash-partitioned by key.

Under a multi-worker mesh every stateful keyed operator (trace→join/
aggregate/distinct, linear aggregate) owns a per-worker slice of state;
correctness requires its input stream to be hash-partitioned by the SAME
key (the reference re-shards stateful inputs for exactly this reason,
shard.rs:35-101). The builder sugar inserts exchanges automatically, but
hand-assembled graphs — and refactors that re-key a stream without
re-sharding — break the invariant silently: each worker then probes a
state slice that holds only a fraction of the matching rows.

Placement facts used here are build-time graph metadata, not runtime data:
``Node.key_sharded`` (set by ``shard()``/sources), the intent flags
``Node.shard_intent`` / ``Node.host_intent`` (the sugar recorded a
placement decision whose exchange/collapse was elided on a 1-worker mesh
— the same build at workers > 1 would have placed the stream, so what-if
analysis must not flag it), and "host-resident by construction" (the
output of an ``UnshardOp``). Only the root circuit is checked —
nested/recursive children are shard-lifted by their OWN sugar
(join/distinct/aggregate re-shard inside the child, recursive() shards
its imports), so their placement is correct by construction rather than
analyzable from root-level metadata.
"""

from __future__ import annotations

from typing import List

from dbsp_tpu.analysis.core import (AnalysisContext, Finding, make_finding,
                                    register_rule)

register_rule(
    "P001", "error", "missing-shard",
    "a stateful keyed operator (trace feeding join/aggregate/distinct, or "
    "a linear aggregate) whose input is neither key-sharded nor explicitly "
    "host-resident under a multi-worker runtime: each worker sees a "
    "fraction of every key's rows (wrong answers at worker count > 1).",
    "call .shard() on the input stream (the operator sugar does this — "
    "hand-built graphs must insert the ExchangeOp themselves)")
register_rule(
    "P002", "warn", "redundant-exchange",
    "an exchange over a stream that is already hash-partitioned on the "
    "same key: every row pays an all_to_all that cannot move it.",
    "drop the extra .shard(); the circuit cache shares one exchange per "
    "stream when built through the sugar")
register_rule(
    "P003", "warn", "mid-circuit-unshard",
    "an unshard() on a multi-worker mesh whose result is re-sharded or "
    "consumed by a shard-lifted operator (trace feeding join/aggregate/"
    "distinct/rolling, or a linear aggregate): the circuit collapses to "
    "one worker mid-graph — every downstream row pays an all-gather plus "
    "a re-distribution, and the W-way multiplier is lost for that "
    "subgraph. WARN by default; ERROR under --strict-shard (the "
    "machine-enforced zero-unshard invariant).",
    "drop the .unshard() — join/aggregate/distinct, recursive children "
    "and rolling (radix) aggregates are all shard-lifted; keep unshard "
    "only for genuinely host-resident consumers (topk/window order "
    "statistics) or waive with Stream.waive_lint('P003')")


def _placed(circuit, idx: int) -> bool:
    """True when node idx's output has a provable placement: key-sharded,
    placement-by-sugar-intent (elided exchange/collapse on a 1-worker
    build — either kind is a deliberate decision), or host-resident by
    construction (unshard output)."""
    from dbsp_tpu.operators.shard_op import UnshardOp

    node = circuit.nodes[idx]
    return (node.key_sharded or node.shard_intent or node.host_intent
            or isinstance(node.operator, UnshardOp))


def _p003_shardable_trace(circuit, trace_idx: int, consumers) -> bool:
    """True when the TraceOp at ``trace_idx`` feeds at least one
    shard-lifted consumer — i.e. a trace(shard=False) that exists only
    because its consumer USED to be host-bound. Order statistics (topk)
    and range partitioning (window / range join) are genuinely
    host-or-per-level shapes and stay legitimate."""
    from dbsp_tpu.operators.aggregate import AggregateOp
    from dbsp_tpu.operators.distinct import DistinctOp
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.timeseries.rolling import RollingAggregateOp

    lifted = (JoinOp, AggregateOp, DistinctOp, RollingAggregateOp)
    return any(isinstance(circuit.nodes[c].operator, lifted)
               for c in consumers[trace_idx])


def sharding_pass(ctx: AnalysisContext) -> List[Finding]:
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp
    from dbsp_tpu.operators.trace_op import TraceOp

    out: List[Finding] = []
    circuit = ctx.root
    nn = len(circuit.nodes)
    consumers = ctx.consumers(circuit)
    for n in circuit.nodes:
        op = n.operator
        # stale input indices are a W004 finding (wellformed pass); this
        # pass must not crash on them
        if any(not 0 <= i < nn for i in n.inputs):
            continue
        # P002 is a graph-shape smell at any worker count
        if isinstance(op, ExchangeOp) and n.inputs and \
                circuit.nodes[n.inputs[0]].key_sharded:
            out.append(make_finding(
                "P002", circuit, n,
                "exchange input is already key-sharded"))
        if ctx.workers <= 1:
            continue
        # P003 — the zero-unshard invariant: a mid-circuit collapse whose
        # result goes right back onto the mesh (re-exchange / linear
        # aggregate) or feeds a trace consumed by a shard-lifted operator.
        # Only ACTUAL UnshardOp nodes are judged (a workers>1 build);
        # host_intent markers from 1-worker builds stay exempt — a node
        # may legitimately carry both placement intents (dual consumption).
        if isinstance(op, UnshardOp):
            from dbsp_tpu.circuit.nested import SubcircuitOp
            # transitive: placement-preserving transforms between the
            # collapse and the re-distribution (unshard -> map -> shard)
            # carry the defect through — walk the consumer closure across
            # them instead of judging direct consumers only (the
            # pass-through predicate is SHARED with _schema_zero's
            # backward walk so the two checks cannot drift)
            from dbsp_tpu.operators.z1 import _placement_thru

            seen = {n.index}
            frontier = list(consumers[n.index])
            fired = False
            while frontier and not fired:
                c = frontier.pop()
                if c in seen:
                    continue
                seen.add(c)
                cop = circuit.nodes[c].operator
                # SubcircuitOp: recursive/nested children are shard-lifted
                # by construction — importing a collapsed stream is the
                # exact pre-lift regression shape
                fire = isinstance(cop, (ExchangeOp, LinearAggregateOp,
                                        SubcircuitOp)) or \
                    (isinstance(cop, TraceOp) and
                     _p003_shardable_trace(circuit, c, consumers))
                if fire:
                    out.append(make_finding(
                        "P003", circuit, n,
                        f"unshard() output feeds {cop.name!r} "
                        f"({ctx.workers} workers): the circuit collapses "
                        "to one worker mid-graph and immediately "
                        "re-distributes",
                        severity="error" if ctx.strict_shard else None))
                    fired = True
                elif _placement_thru(cop):
                    frontier.extend(consumers[c])
        if isinstance(op, (TraceOp, LinearAggregateOp)):
            if n.inputs and not _placed(circuit, n.inputs[0]):
                src = circuit.nodes[n.inputs[0]]
                out.append(make_finding(
                    "P001", circuit, n,
                    f"{op.name!r} consumes {src.operator.name!r} which is "
                    f"not key-sharded ({ctx.workers} workers)"))
        if isinstance(op, JoinOp) and len(n.inputs) == 2:
            a, b = (circuit.nodes[i] for i in n.inputs)
            # effective placement: really sharded, or WOULD be on a larger
            # mesh (host_intent means would-be-HOST, not co-sharded)
            ap = a.key_sharded or a.shard_intent
            bp = b.key_sharded or b.shard_intent
            if ap != bp:
                out.append(make_finding(
                    "P001", circuit, n,
                    f"join inputs disagree on placement: "
                    f"{a.operator.name!r} key_sharded={ap}, "
                    f"{b.operator.name!r} key_sharded={bp} — "
                    "not co-sharded"))
    return out
