"""Sharding-placement pass: keyed state must be hash-partitioned by key.

Under a multi-worker mesh every stateful keyed operator (trace→join/
aggregate/distinct, linear aggregate) owns a per-worker slice of state;
correctness requires its input stream to be hash-partitioned by the SAME
key (the reference re-shards stateful inputs for exactly this reason,
shard.rs:35-101). The builder sugar inserts exchanges automatically, but
hand-assembled graphs — and refactors that re-key a stream without
re-sharding — break the invariant silently: each worker then probes a
state slice that holds only a fraction of the matching rows.

Placement facts used here are build-time graph metadata, not runtime data:
``Node.key_sharded`` (set by ``shard()``/sources), the intent flags
``Node.shard_intent`` / ``Node.host_intent`` (the sugar recorded a
placement decision whose exchange/collapse was elided on a 1-worker mesh
— the same build at workers > 1 would have placed the stream, so what-if
analysis must not flag it), and "host-resident by construction" (the
output of an ``UnshardOp``). Only the root circuit is
checked — nested/recursive children are host-driven and unsharded by
construction (recursive() collapses its inputs first).
"""

from __future__ import annotations

from typing import List

from dbsp_tpu.analysis.core import (AnalysisContext, Finding, make_finding,
                                    register_rule)

register_rule(
    "P001", "error", "missing-shard",
    "a stateful keyed operator (trace feeding join/aggregate/distinct, or "
    "a linear aggregate) whose input is neither key-sharded nor explicitly "
    "host-resident under a multi-worker runtime: each worker sees a "
    "fraction of every key's rows (wrong answers at worker count > 1).",
    "call .shard() on the input stream (the operator sugar does this — "
    "hand-built graphs must insert the ExchangeOp themselves)")
register_rule(
    "P002", "warn", "redundant-exchange",
    "an exchange over a stream that is already hash-partitioned on the "
    "same key: every row pays an all_to_all that cannot move it.",
    "drop the extra .shard(); the circuit cache shares one exchange per "
    "stream when built through the sugar")


def _placed(circuit, idx: int) -> bool:
    """True when node idx's output has a provable placement: key-sharded,
    placement-by-sugar-intent (elided exchange/collapse on a 1-worker
    build — either kind is a deliberate decision), or host-resident by
    construction (unshard output)."""
    from dbsp_tpu.operators.shard_op import UnshardOp

    node = circuit.nodes[idx]
    return (node.key_sharded or node.shard_intent or node.host_intent
            or isinstance(node.operator, UnshardOp))


def sharding_pass(ctx: AnalysisContext) -> List[Finding]:
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.shard_op import ExchangeOp
    from dbsp_tpu.operators.trace_op import TraceOp

    out: List[Finding] = []
    circuit = ctx.root
    nn = len(circuit.nodes)
    for n in circuit.nodes:
        op = n.operator
        # stale input indices are a W004 finding (wellformed pass); this
        # pass must not crash on them
        if any(not 0 <= i < nn for i in n.inputs):
            continue
        # P002 is a graph-shape smell at any worker count
        if isinstance(op, ExchangeOp) and n.inputs and \
                circuit.nodes[n.inputs[0]].key_sharded:
            out.append(make_finding(
                "P002", circuit, n,
                "exchange input is already key-sharded"))
        if ctx.workers <= 1:
            continue
        if isinstance(op, (TraceOp, LinearAggregateOp)):
            if n.inputs and not _placed(circuit, n.inputs[0]):
                src = circuit.nodes[n.inputs[0]]
                out.append(make_finding(
                    "P001", circuit, n,
                    f"{op.name!r} consumes {src.operator.name!r} which is "
                    f"not key-sharded ({ctx.workers} workers)"))
        if isinstance(op, JoinOp) and len(n.inputs) == 2:
            a, b = (circuit.nodes[i] for i in n.inputs)
            # effective placement: really sharded, or WOULD be on a larger
            # mesh (host_intent means would-be-HOST, not co-sharded)
            ap = a.key_sharded or a.shard_intent
            bp = b.key_sharded or b.shard_intent
            if ap != bp:
                out.append(make_finding(
                    "P001", circuit, n,
                    f"join inputs disagree on placement: "
                    f"{a.operator.name!r} key_sharded={ap}, "
                    f"{b.operator.name!r} key_sharded={bp} — "
                    "not co-sharded"))
    return out
