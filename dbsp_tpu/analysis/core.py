"""Analyzer core: findings, the rule catalog, and the pass manager.

The analyzer runs over a BUILT circuit graph — between ``RootCircuit.build``
and the first step — so its subject is exactly what the scheduler/compiler
will execute: :class:`~dbsp_tpu.circuit.builder.Node` objects, their input
edges, and the node-level ``schema`` / ``key_sharded`` metadata the operator
sugar writes through :class:`~dbsp_tpu.circuit.builder.Stream` properties.
Passes are pure functions ``(AnalysisContext) -> [Finding]``; the
:class:`PassManager` fixes their order (well-formedness first — later passes
assume a sane graph) and aggregates findings.

Severity contract (enforced by the entry points in ``__init__``):
  ERROR — the circuit computes wrong answers or cannot run (refuse to start);
  WARN  — it runs correctly but violates the DBSP cost model (O(delta) work
          degrading to O(state)) or risks silent overflow (log + count).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from dbsp_tpu.circuit.builder import Circuit, CircuitError, Node

ERROR = "error"
WARN = "warn"

_SEV_ORDER = {ERROR: 0, WARN: 1}


@dataclasses.dataclass(frozen=True)
class Rule:
    """One catalog entry; the README's rule table renders from these."""

    rule_id: str
    severity: str
    title: str
    catches: str
    fix_hint: str


#: rule_id -> Rule; populated by the pass modules at import time
RULES: Dict[str, Rule] = {}


def register_rule(rule_id: str, severity: str, title: str, catches: str,
                  fix_hint: str) -> Rule:
    if rule_id in RULES:
        raise ValueError(f"duplicate analysis rule id {rule_id!r}")
    rule = Rule(rule_id, severity, title, catches, fix_hint)
    RULES[rule_id] = rule
    return rule


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: which rule fired, where, and how to fix it."""

    rule_id: str
    severity: str
    node_path: str
    message: str
    fix_hint: str

    def render(self) -> str:
        return (f"[{self.severity.upper()}] {self.rule_id} @ "
                f"{self.node_path}: {self.message}\n"
                f"    fix: {self.fix_hint}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def make_finding(rule_id: str, circuit: Circuit, node: Optional[Node],
                 message: str, fix_hint: Optional[str] = None,
                 severity: Optional[str] = None) -> Finding:
    """``severity`` overrides the rule's registered default — used by
    mode-escalated rules (P003 is WARN normally, ERROR under
    ``strict_shard``)."""
    rule = RULES[rule_id]
    return Finding(rule_id=rule_id,
                   severity=severity if severity is not None
                   else rule.severity,
                   node_path=node_path(circuit, node), message=message,
                   fix_hint=fix_hint if fix_hint is not None
                   else rule.fix_hint)


def node_path(circuit: Circuit, node: Optional[Node]) -> str:
    """Stable, human-readable node address: ``root/2/5:join`` — the global
    id joined with '/', suffixed with the operator name."""
    if node is None:
        gid: Tuple[int, ...] = circuit.path()
        name = "circuit"
    else:
        gid = circuit.global_id(node.index)
        name = node.operator.name
    return "root/" + "/".join(str(i) for i in gid) + ":" + name


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                 f.rule_id, f.node_path))


class AnalysisContext:
    """What every pass sees: the circuit forest plus derived graph views.

    ``schemas`` starts from the node metadata the builder persisted and is
    COMPLETED by the schema-inference pass (passes run in PassManager order,
    so sharding/incrementality passes read inferred entries too). Keys are
    ``(id(circuit), node_index)`` — node indices are only unique per
    circuit.
    """

    def __init__(self, circuit: Circuit, workers: int = 1,
                 strict_shard: bool = False):
        self.root = circuit
        self.workers = workers
        # --strict-shard: escalate the zero-unshard invariant (P003) from
        # WARN to ERROR — CI mode for circuits that must scale out
        self.strict_shard = strict_shard
        self.schemas: Dict[Tuple[int, int], Optional[tuple]] = {}
        self._consumers: Dict[int, List[List[int]]] = {}
        for c, n in self.walk():
            self.schemas[(id(c), n.index)] = n.schema

    # -- traversal -----------------------------------------------------------
    def circuits(self) -> Iterator[Circuit]:
        stack = [self.root]
        while stack:
            c = stack.pop()
            yield c
            for n in c.nodes:
                if n.child is not None:
                    stack.append(n.child)

    def walk(self) -> Iterator[Tuple[Circuit, Node]]:
        for c in self.circuits():
            for n in c.nodes:
                yield c, n

    def consumers(self, circuit: Circuit) -> List[List[int]]:
        """consumers[i] = node indices (same circuit) reading node i."""
        adj = self._consumers.get(id(circuit))
        if adj is None:
            adj = [[] for _ in circuit.nodes]
            for n in circuit.nodes:
                for i in n.inputs:
                    if 0 <= i < len(adj):
                        adj[i].append(n.index)
            self._consumers[id(circuit)] = adj
        return adj

    # -- schema helpers ------------------------------------------------------
    def schema_of(self, circuit: Circuit, index: int) -> Optional[tuple]:
        return self.schemas.get((id(circuit), index))

    def set_schema(self, circuit: Circuit, index: int, schema) -> None:
        self.schemas[(id(circuit), index)] = schema


AnalysisPass = Callable[[AnalysisContext], List[Finding]]


class AnalysisError(CircuitError):
    """Raised by verify entry points when ERROR findings exist; carries the
    full finding list so callers (manager HTTP surface, CLI) can render it."""

    def __init__(self, findings: List[Finding]):
        self.findings = findings
        errors = [f for f in findings if f.severity == ERROR]
        lines = "\n".join(f.render() for f in errors)
        super().__init__(
            f"circuit failed static analysis with {len(errors)} error(s):\n"
            f"{lines}")


class PassManager:
    """Runs registered passes in order over one context; order matters
    (schema inference feeds sharding/incrementality)."""

    def __init__(self, passes: Optional[List[AnalysisPass]] = None):
        self.passes: List[AnalysisPass] = list(passes or [])

    def add(self, p: AnalysisPass) -> "PassManager":
        self.passes.append(p)
        return self

    def run(self, circuit: Circuit, workers: int = 1,
            strict_shard: bool = False) -> List[Finding]:
        ctx = AnalysisContext(circuit, workers=workers,
                              strict_shard=strict_shard)
        # graph-level waivers (Stream.waive_lint): filtered centrally so
        # every rule honors them without each pass re-checking
        waived = {node_path(c, n): n.lint_waive
                  for c, n in ctx.walk() if n.lint_waive}
        findings: List[Finding] = []
        for p in self.passes:
            findings.extend(
                f for f in p(ctx)
                if f.rule_id not in waived.get(f.node_path, ()))
        return sort_findings(findings)
