"""Schema pass: propagate (key, val) dtypes along edges, then type-check.

Most streams already carry schema metadata (the operator sugar writes it
through to nodes — circuit/builder.py); this pass fills the gaps (operators
whose output schema is derivable: traces, joins, aggregates,
schema-preserving arithmetic) and then checks the dtype rules that the
runtime would otherwise "repair" with silent casts.

Why S001 is an ERROR and not a nicety: join kernels probe ``keys[:nk]``
lexicographically and the shard operator hash-partitions on the first key
column's BITS. A silently cast key column hashes differently on each side,
so matching keys land on different workers and the join quietly drops
matches — the worst kind of wrong answer (only at scale, only sharded).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from dbsp_tpu.analysis.core import (AnalysisContext, Finding, make_finding,
                                    register_rule)

register_rule(
    "S001", "error", "join-key-dtype-mismatch",
    "join/semijoin whose two input key column dtypes differ: the silent "
    "cast changes the hash shard and the lexicographic probe order, so "
    "matches are dropped (wrong answers, not an exception).",
    "cast one side's key columns (map_rows / index_by) so both join inputs "
    "share identical key dtypes")
register_rule(
    "S002", "warn", "narrow-accumulator",
    "an aggregator accumulating into an integer dtype narrower than 64 "
    "bits; long-running sums/counts overflow int32 after ~2.1e9 "
    "contributions and wrap silently on TPU.",
    "declare int64 acc/out dtypes on the aggregator (built-ins already do)")


def _dt(x) -> Optional[np.dtype]:
    try:
        return np.dtype(x)
    except TypeError:  # not a dtype-like (opaque schema entry)
        return None


def _key_dtypes(schema) -> Optional[tuple]:
    if not schema or not isinstance(schema, tuple) or len(schema) != 2:
        return None
    dts = tuple(_dt(d) for d in schema[0])
    return None if any(d is None for d in dts) else dts


def _infer(ctx: AnalysisContext) -> None:
    """Complete ctx.schemas from operator attributes + propagation."""
    from dbsp_tpu.operators.aggregate import AggregateOp
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.basic import Minus, Neg, Plus, SumN
    from dbsp_tpu.operators.distinct import DistinctOp, StreamDistinct
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp
    from dbsp_tpu.operators.trace_op import TraceOp
    from dbsp_tpu.operators.z1 import Z1, _PlusNamed

    preserving = (Plus, Minus, Neg, SumN, _PlusNamed, ExchangeOp, UnshardOp,
                  StreamDistinct, DistinctOp)
    for circuit, n in ctx.walk():
        if ctx.schema_of(circuit, n.index) is not None:
            continue
        op = n.operator
        if isinstance(op, TraceOp):
            ctx.set_schema(circuit, n.index,
                           (tuple(op.key_dtypes), tuple(op.val_dtypes)))
        elif isinstance(op, (JoinOp, AggregateOp, LinearAggregateOp)):
            ctx.set_schema(circuit, n.index, op.out_schema)
    # propagate through schema-preserving ops to a fixpoint (feedback
    # edges mean one forward sweep is not always enough); monotone —
    # schemas only move None -> known — so this terminates within
    # node-count sweeps
    while True:
        changed = False
        for circuit, n in ctx.walk():
            if ctx.schema_of(circuit, n.index) is not None:
                continue
            op = n.operator
            src: Optional[int] = None
            if isinstance(op, preserving) and n.inputs:
                src = n.inputs[0]
            elif isinstance(op, Z1) and n.kind == "strict_output" and \
                    n.partner is not None:
                inp = circuit.nodes[n.partner].inputs
                src = inp[0] if inp else None
            if src is not None:
                s = ctx.schema_of(circuit, src)
                if s is not None:
                    ctx.set_schema(circuit, n.index, s)
                    changed = True
        if not changed:
            break


def schema_pass(ctx: AnalysisContext) -> List[Finding]:
    from dbsp_tpu.operators.aggregate import AggregateOp
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.nested_ops import NestedJoinOp

    _infer(ctx)
    out: List[Finding] = []
    for circuit, n in ctx.walk():
        op = n.operator
        # S001 — join inputs must agree on the probed key columns
        if isinstance(op, (JoinOp, NestedJoinOp)) and len(n.inputs) == 2:
            ls = _key_dtypes(ctx.schema_of(circuit, n.inputs[0]))
            rs = _key_dtypes(ctx.schema_of(circuit, n.inputs[1]))
            if ls is None or rs is None:
                continue  # unknown side: nothing provable
            nk = int(getattr(op, "nk", 0)) or min(len(ls), len(rs))
            if len(ls) < nk or len(rs) < nk or ls[:nk] != rs[:nk]:
                out.append(make_finding(
                    "S001", circuit, n,
                    f"{op.name!r} joins key dtypes "
                    f"{tuple(str(d) for d in ls)} against "
                    f"{tuple(str(d) for d in rs)} (first {nk} must match "
                    "exactly)"))
        # S002 — narrow integer accumulators
        agg = None
        if isinstance(op, AggregateOp):
            agg = op.agg
        elif isinstance(op, LinearAggregateOp):
            agg = op.agg
        # order statistics (insert_combinable: Min/Max) select an existing
        # value rather than accumulate — a narrow out dtype there matches
        # the data and cannot overflow
        if agg is not None and not getattr(agg, "insert_combinable", False):
            acc = (*getattr(agg, "acc_dtypes", ()),
                   *getattr(agg, "out_dtypes", ()))
            narrow = sorted({str(d) for d in (_dt(x) for x in acc)
                             if d is not None and d.kind in "iu"
                             and d.itemsize < 8})
            if narrow:
                out.append(make_finding(
                    "S002", circuit, n,
                    f"aggregator {agg.name!r} accumulates into narrow "
                    f"integer dtype(s) {narrow}"))
    return out
