"""CLI: ``python -m dbsp_tpu.analysis <target>`` — analyze demo circuits.

Targets:
  q0 .. q22   one Nexmark query circuit (nexmark/queries.py)
  all         every Nexmark query, one report per query
  defects     a gallery of seeded-defect circuits, one per ERROR rule —
              shows what each rule's finding looks like

Exit status: 1 when any ERROR finding was produced (matching the
pipeline-start behavior), else 0.
"""

from __future__ import annotations

import argparse
import inspect
import sys

import jax


def _nexmark_query_names():
    from dbsp_tpu.nexmark import queries

    names = []
    for name in dir(queries):
        fn = getattr(queries, name)
        if name.startswith("q") and name[1:].isdigit() and callable(fn):
            required = [p for p in inspect.signature(fn).parameters.values()
                        if p.default is inspect.Parameter.empty]
            if len(required) == 3:
                names.append(name)
    return sorted(names, key=lambda s: int(s[1:]))


def _build_query(name: str):
    from dbsp_tpu.circuit import RootCircuit
    from dbsp_tpu.nexmark import build_inputs, queries

    def build(c):
        (p, a, b), handles = build_inputs(c)
        return getattr(queries, name)(p, a, b).output()

    circuit, _ = RootCircuit.build(build)
    return circuit


def _defect_circuits():
    """(label, circuit) pairs, one seeded defect per ERROR rule."""
    import jax.numpy as jnp

    from dbsp_tpu.circuit.builder import RootCircuit
    from dbsp_tpu.operators import Z1, add_input_zset
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.trace_op import TraceOp
    from dbsp_tpu.zset.batch import Batch

    gallery = []

    # W001 — dangling feedback (built WITHOUT RootCircuit.build, which
    # would refuse it at finalize)
    c = RootCircuit()
    s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
    c.add_feedback(Z1(lambda: Batch.empty((jnp.int64,), (jnp.int64,))))
    gallery.append(("W001 dangling feedback", c))

    # W002 — hand-wired cycle with no strict operator
    c = RootCircuit()
    s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
    a = s.plus(s)
    b = a.plus(s)
    a.node.inputs[1] = b.node_index  # close the loop around plus/plus
    gallery.append(("W002 non-strict cycle", c))

    # S001 — join over mismatched key dtypes (bypasses the sugar's check)
    c = RootCircuit()
    l, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
    r, _h = add_input_zset(c, [jnp.int32], [jnp.int64])
    lt = c.add_unary_operator(TraceOp((jnp.int64,), (jnp.int64,)), l)
    rt = c.add_unary_operator(TraceOp((jnp.int32,), (jnp.int64,)), r)
    lt.schema, rt.schema = l.schema, r.schema
    c.add_binary_operator(
        JoinOp(lambda k, lv, rv: (k, (*lv, *rv)), 1,
               ((jnp.int64,), (jnp.int64, jnp.int64))), lt, rt).output()
    gallery.append(("S001 join key dtype mismatch", c))

    # P001 — keyed aggregate with no shard (visible at workers > 1)
    from dbsp_tpu.operators.aggregate_linear import (LinearAggregateOp,
                                                     LinearCount)

    c = RootCircuit()
    s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
    # pretend the source does not hash-distribute (and never would)
    s.key_sharded = s.shard_intent = False
    c.add_unary_operator(LinearAggregateOp(LinearCount(), (jnp.int64,)),
                         s).output()
    gallery.append(("P001 missing shard (analyzed at workers=4)", c))

    # W004 — child circuit whose parent-index bookkeeping was hand-edited
    from dbsp_tpu.circuit.nested import subcircuit

    c = RootCircuit()
    subcircuit(c, lambda child: None)
    c.nodes[0].child._index_in_parent = 7  # re-parented by hand
    gallery.append(("W004 nested-clock inconsistency", c))

    # P003 — mid-circuit unshard immediately re-sharded (the zero-unshard
    # invariant; WARN by default, ERROR under --strict-shard). Hand-built:
    # the sugar elides both ops on a 1-worker build, so the gallery wires
    # the workers>1 node shapes directly.
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp

    c = RootCircuit()
    s, _h = add_input_zset(c, [jnp.int64], [jnp.int64])
    u = c.add_unary_operator(UnshardOp(), s)
    u.schema = s.schema
    c.add_unary_operator(ExchangeOp(4), u).output()
    gallery.append(("P003 mid-circuit unshard (analyzed at workers=4)", c))

    return gallery


def main(argv=None) -> int:
    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m dbsp_tpu.analysis",
        description="static-analyze demo circuits")
    ap.add_argument("target", help="q0..q22 | all | defects")
    ap.add_argument("--workers", type=int, default=1,
                    help="worker count to analyze for (default 1)")
    ap.add_argument("--strict-shard", action="store_true",
                    help="escalate P003 (mid-circuit unshard) to ERROR — "
                    "the machine-enforced zero-unshard invariant")
    args = ap.parse_args(argv)

    from dbsp_tpu.analysis import ERROR, analyze, format_findings

    if args.target == "defects":
        targets = [(label, c, 4 if label.startswith(("P001", "P003")) else
                    args.workers) for label, c in _defect_circuits()]
    elif args.target == "all":
        targets = [(n, _build_query(n), args.workers)
                   for n in _nexmark_query_names()]
    elif args.target in _nexmark_query_names():
        targets = [(args.target, _build_query(args.target), args.workers)]
    else:
        ap.error(f"unknown target {args.target!r}; expected one of "
                 f"{_nexmark_query_names()} or 'all' / 'defects'")

    any_error = False
    for label, circuit, workers in targets:
        findings = analyze(circuit, workers=workers,
                           strict_shard=args.strict_shard)
        any_error |= any(f.severity == ERROR for f in findings)
        print(f"== {label} ==")
        print(format_findings(findings))
        print()
    return 1 if any_error else 0


if __name__ == "__main__":
    sys.exit(main())
