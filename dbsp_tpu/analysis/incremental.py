"""Incrementality lint: patterns that turn O(delta) ticks into O(state).

DBSP's headline guarantee — per-tick cost proportional to the input change
— is a property of HOW a query is built, not just what it computes. Two
build patterns quietly forfeit it:

* a linear aggregate (count/sum/avg) routed through the general
  trace-gather path re-reads every touched group's full history per tick,
  where the linear path needs only a delta-sized segment sum;
* ``integrate()`` on the root clock accumulates a Z-set forever — without
  a downstream window (or any retention bound) per-tick consolidation cost
  grows with lifetime state, and at tick 1e6 the "incremental" pipeline is
  doing batch work. (Nested-circuit integrates reset each epoch and are
  exempt.)
"""

from __future__ import annotations

from typing import List

from dbsp_tpu.analysis.core import (AnalysisContext, Finding, make_finding,
                                    register_rule)

register_rule(
    "I001", "warn", "linear-aggregate-on-general-path",
    "aggregate(Count/Sum/Average) built on the general trace-gather path: "
    "per-tick work is O(touched group history) where the linear path is "
    "O(delta), and the input stream grows a trace it does not need.",
    "pass the linear aggregator (LinearCount/LinearSum/LinearAverage) so "
    "aggregate() dispatches to the delta-only fast path")
register_rule(
    "I002", "warn", "unbounded-integrate",
    "integrate() on the root clock with no downstream window: the running "
    "sum retains every key ever seen, so per-tick consolidation cost "
    "grows with lifetime state instead of the delta.",
    "bound the stream with .window(bounds, gc=True) (timeseries/window.py) "
    "or consume deltas directly instead of materializing the integral")


def incremental_pass(ctx: AnalysisContext) -> List[Finding]:
    from dbsp_tpu.operators.aggregate import (Average, Count, Sum,
                                              AggregateOp)
    from dbsp_tpu.operators.z1 import _PlusNamed
    from dbsp_tpu.timeseries.window import WindowOp

    out: List[Finding] = []
    for circuit, n in ctx.walk():
        op = n.operator
        # I001 — linear aggregators on the general gather path
        if isinstance(op, AggregateOp) and \
                isinstance(op.agg, (Count, Sum, Average)):
            out.append(make_finding(
                "I001", circuit, n,
                f"aggregate<{op.agg.name}> uses the general trace-gather "
                "path but is linear"))
        # I002 — root-clock integrate with no window anywhere downstream.
        # Serving layers that materialize a VIEW integral (state = live
        # view cardinality, not input history) opt out via waive_lint,
        # honored centrally by PassManager.run.
        if circuit is ctx.root and isinstance(op, _PlusNamed) and \
                op.name == "integrate":
            consumers = ctx.consumers(circuit)
            seen = {n.index}
            stack = [n.index]
            windowed = False
            while stack and not windowed:
                for c in consumers[stack.pop()]:
                    if isinstance(circuit.nodes[c].operator, WindowOp):
                        windowed = True
                        break
                    if c not in seen:
                        seen.add(c)
                        stack.append(c)
            if not windowed:
                out.append(make_finding(
                    "I002", circuit, n,
                    "integrate() accumulates unbounded state (no window "
                    "downstream)"))
    return out
