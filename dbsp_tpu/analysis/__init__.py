"""Static analysis for built circuits: a pass-manager-based verifier.

DBSP's correctness/cost guarantees only hold for *well-formed* circuits —
every cycle through a strict operator, joins over identical key dtypes,
keyed state co-sharded by key, state bounded by windows. None of that was
checked before this subsystem: a dangling feedback edge or a mis-typed
join key ran fine and produced wrong answers at tick 10^6. The analyzer
runs between ``RootCircuit.build`` and the first step, over the same graph
the scheduler executes.

Usage::

    from dbsp_tpu.analysis import analyze, verify_circuit
    findings = analyze(circuit)               # -> [Finding], no side effects
    verify_circuit(circuit, workers=8)        # ERROR -> AnalysisError
    python -m dbsp_tpu.analysis q4            # CLI over nexmark/demo circuits

Pipeline entry points (`compile_circuit`, ``CircuitServer``, the manager)
call :func:`verify_circuit` at start: ERROR findings refuse to start, WARN
findings are logged and counted on the obs registry as
``dbsp_tpu_analysis_findings_total{rule,severity}``.

The rule catalog (see README "Static analysis"):
  W001-W004 well-formedness   (wellformed.py)
  S001-S002 schema/dtypes     (schema.py)
  P001-P002 sharding placement (sharding.py)
  I001-I002 incrementality     (incremental.py)
"""

from __future__ import annotations

import logging
from typing import List, Optional

from dbsp_tpu.analysis.core import (ERROR, WARN, AnalysisContext,
                                    AnalysisError, Finding, PassManager,
                                    Rule, RULES, sort_findings)
from dbsp_tpu.analysis.incremental import incremental_pass
from dbsp_tpu.analysis.schema import schema_pass
from dbsp_tpu.analysis.sharding import sharding_pass
from dbsp_tpu.analysis.wellformed import wellformed_pass

__all__ = ["analyze", "verify_circuit", "rule_catalog", "format_findings",
           "AnalysisError", "Finding", "Rule", "RULES", "PassManager",
           "default_pass_manager", "ERROR", "WARN"]

logger = logging.getLogger(__name__)


def default_pass_manager() -> PassManager:
    """Pass order is a contract: well-formedness first (later passes assume
    a DAG), schema inference before the rules that read inferred schemas."""
    return PassManager([wellformed_pass, schema_pass, sharding_pass,
                        incremental_pass])


def analyze(circuit, workers: Optional[int] = None,
            strict_shard: bool = False) -> List[Finding]:
    """Run all passes over a built circuit; returns findings sorted by
    severity. Pure — no logging, no metrics, no raising.

    ``strict_shard=True`` escalates P003 (mid-circuit unshard) to ERROR —
    the CI form of the zero-unshard invariant."""
    if workers is None:
        from dbsp_tpu.circuit.runtime import Runtime

        workers = Runtime.worker_count()
    return default_pass_manager().run(circuit, workers=workers,
                                      strict_shard=strict_shard)


def verify_circuit(circuit, workers: Optional[int] = None, registry=None,
                   raise_on_error: bool = True) -> List[Finding]:
    """The pipeline-start entry point: analyze, log WARNs, count every
    finding on ``registry`` (obs.MetricsRegistry) as
    ``dbsp_tpu_analysis_findings_total{rule,severity}``, and raise
    :class:`AnalysisError` when ERROR findings exist."""
    if workers is None:
        from dbsp_tpu.circuit.runtime import Runtime

        workers = Runtime.worker_count()
    # One analysis (and one set of WARN log lines) per (circuit, workers):
    # the gates stack — compile_circuit inside try_compiled_driver, then
    # CircuitServer around the controller — and each would otherwise walk
    # the graph and log every WARN again. Counting still happens per call
    # so whichever gate carries the pipeline's registry gets the metrics.
    import os

    # DBSP_TPU_STRICT_SHARD=1: deploy-time form of --strict-shard. The
    # flag is part of the memo key — a cached non-strict analysis must
    # not be served after the env changes (a stale WARN-level result
    # would let a deploy proceed that strict mode should refuse).
    strict = os.environ.get("DBSP_TPU_STRICT_SHARD") == "1"
    cached = getattr(circuit, "_verify_cache", None)
    if cached is not None and cached[0] == (workers, strict):
        findings = cached[1]
    else:
        findings = analyze(circuit, workers=workers, strict_shard=strict)
        circuit._verify_cache = ((workers, strict), findings)
        for f in findings:
            if f.severity == WARN:
                logger.warning("%s", f.render())
    if registry is not None:
        counter = registry.counter(
            "dbsp_tpu_analysis_findings_total",
            "static-analysis findings at pipeline start",
            ("rule", "severity"))
        for f in findings:
            counter.labels(rule=f.rule_id, severity=f.severity).inc()
    errors = [f for f in findings if f.severity == ERROR]
    if errors and raise_on_error:
        raise AnalysisError(findings)
    return findings


def rule_catalog() -> List[Rule]:
    return [RULES[k] for k in sorted(RULES)]


def format_findings(findings: List[Finding]) -> str:
    if not findings:
        return "no findings"
    return "\n".join(f.render() for f in sort_findings(findings))
