"""Well-formedness pass: graph-shape invariants the scheduler assumes.

DBSP's semantics (VLDB'23 §3) are defined over circuits where every cycle
passes through a strict (z^-1) operator — that is what makes the per-tick
evaluation a DAG. The builder makes these hard to violate but not
impossible (dangling ``FeedbackConnector``, hand-wired graphs, a child
circuit grafted under the wrong parent), and a violation surfaces as wrong
answers, not an exception.
"""

from __future__ import annotations

from typing import List

from dbsp_tpu.analysis.core import (AnalysisContext, Finding, make_finding,
                                    register_rule)

register_rule(
    "W001", "error", "dangling-feedback",
    "add_feedback() whose FeedbackConnector.connect() was never called: the "
    "strict output half schedules as a source and emits the z^-1 zero "
    "forever on the open edge (silently wrong answers).",
    "call connector.connect(<input stream>) to close the feedback loop, or "
    "remove the add_feedback call")
register_rule(
    "W002", "error", "non-strict-cycle",
    "a dependency cycle that does not pass through a strict (z^-1) "
    "operator; per-tick evaluation is only defined on a DAG.",
    "break the cycle with .delay() / add_feedback(Z1) so the loop crosses "
    "a strict operator")
register_rule(
    "W003", "warn", "unreachable-node",
    "a node whose output reaches no sink, output handle, feedback input, "
    "export, or condition — dead weight that still evaluates every tick.",
    "consume the stream (.output()/.inspect()/export) or drop the operator")
register_rule(
    "W004", "error", "graph-link-inconsistency",
    "a node input index out of range, or a subcircuit whose parent/index "
    "links or import/export/condition node references are inconsistent — "
    "the executor would read the wrong (or no) streams.",
    "build graphs via the Stream sugar and children via "
    "parent.subcircuit()/recursive(); do not hand-edit node links")


def wellformed_pass(ctx: AnalysisContext) -> List[Finding]:
    from dbsp_tpu.operators.io_handles import ZSetInput
    from dbsp_tpu.operators.upsert import UpsertInput

    out: List[Finding] = []
    for circuit in ctx.circuits():
        nodes = circuit.nodes
        # W001 — dangling feedback connectors
        for n in nodes:
            if n.kind == "strict_output" and n.partner is None:
                out.append(make_finding(
                    "W001", circuit, n,
                    f"FeedbackConnector for {n.operator.name!r} was never "
                    "connected"))
        # W004 — nested clock consistency (pure link checks: valid on any
        # graph shape, so they run before the cycle bail-out below)
        for n in nodes:
            child = n.child
            if child is None:
                continue
            if child.parent is not circuit:
                out.append(make_finding(
                    "W004", circuit, n,
                    "child circuit's parent link does not point back at "
                    "the owning circuit"))
            if child._index_in_parent != n.index:
                out.append(make_finding(
                    "W004", circuit, n,
                    f"child circuit records parent index "
                    f"{child._index_in_parent}, but lives at node "
                    f"{n.index}"))
            nchild = len(child.nodes)
            for attr in ("exports", "conditions"):
                for i in getattr(child, attr, ()) or ():
                    if not (0 <= i < nchild):
                        out.append(make_finding(
                            "W004", circuit, n,
                            f"child {attr} references node {i}, out of "
                            f"range for {nchild} child nodes"))
            for pidx, _op in getattr(child, "imports", ()) or ():
                if not (0 <= pidx < len(nodes)):
                    out.append(make_finding(
                        "W004", circuit, n,
                        f"child import references parent node {pidx}, out "
                        f"of range for {len(nodes)} parent nodes"))
        # W004 — stale input indices; toposort/reachability math below is
        # meaningless over them (a dangling edge would read as a cycle)
        bad_inputs = False
        for n in nodes:
            for i in n.inputs:
                if not (0 <= i < len(nodes)):
                    bad_inputs = True
                    out.append(make_finding(
                        "W004", circuit, n,
                        f"{n.operator.name!r} input references node {i}, "
                        f"out of range for {len(nodes)} nodes"))
        if bad_inputs:
            continue
        # W002 — toposort leftovers are exactly the non-strict cycles
        # (strict operators are split into two nodes, so legal feedback is
        # already acyclic here)
        indeg = [0] * len(nodes)
        for n in nodes:
            for i in n.inputs:
                indeg[n.index] += 1
        ready = [n.index for n in nodes if indeg[n.index] == 0]
        seen = 0
        consumers = ctx.consumers(circuit)
        while ready:
            idx = ready.pop()
            seen += 1
            for c in consumers[idx]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if seen != len(nodes):
            stuck = [n for n in nodes if indeg[n.index] > 0]
            names = ", ".join(
                f"{n.index}:{n.operator.name}" for n in stuck)
            out.append(make_finding(
                "W002", circuit, stuck[0] if stuck else None,
                f"cycle through non-strict nodes [{names}]"))
            continue  # reachability below assumes a DAG
        # W003 — reverse reachability from effect nodes
        effect = set()
        for n in nodes:
            if n.kind in ("sink", "strict_input", "subcircuit"):
                effect.add(n.index)
        for attr in ("exports", "conditions"):
            effect.update(getattr(circuit, attr, ()) or ())
        live = set(effect)
        stack = list(effect)
        while stack:
            idx = stack.pop()
            for i in nodes[idx].inputs:
                if i not in live:
                    live.add(i)
                    stack.append(i)
        for n in nodes:
            if n.index not in live:
                # a declared-but-unconsumed input table is routine (one
                # table schema shared by pipelines that each read a
                # subset) and costs nothing per tick — flagging it on
                # every deploy would bury real unreachable operators
                if isinstance(n.operator, (ZSetInput, UpsertInput)):
                    continue
                out.append(make_finding(
                    "W003", circuit, n,
                    f"{n.operator.name!r} output reaches no sink/output/"
                    "feedback/export"))
    return out
