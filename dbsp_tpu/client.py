"""Python client for the pipeline manager + per-pipeline servers.

Reference: ``python/dbsp`` (DBSPConnection/Project/Pipeline wrapping the
manager REST API). Same shape: a connection object for the manager, pipeline
handles for data/control endpoints.
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Dict, List, Optional
from urllib.parse import quote


def default_timeout_s() -> float:
    """Per-request client timeout. Server-side work behind these routes is
    compile-bound (an overflow grow retraces + recompiles the whole step
    program inside one /step), and XLA:CPU compile latency scales with
    host cores — the historical flat 30 s fit an 8-core dev box but times
    out mid-recompile on a 2-core container. Budget for an 8-core-
    equivalent 30 s, scaled up on smaller hosts and floored at 30 s;
    ``DBSP_TPU_CLIENT_TIMEOUT_S`` overrides outright."""
    env = os.environ.get("DBSP_TPU_CLIENT_TIMEOUT_S")
    if env:
        return float(env)
    cores = os.cpu_count() or 1
    return 30.0 * max(1.0, 8.0 / cores)


def _lineage_qs(view: str, key) -> str:
    """?view=&key= query prefix for the /lineage routes, percent-encoded
    — bare-string key columns ('a b', 'x&y') are part of parse_key's
    contract and must survive URL interpolation."""
    key = key if isinstance(key, str) else ",".join(map(str, key))
    return f"?view={quote(view, safe='')}&key={quote(key, safe=',')}"


def _req(url: str, data: Optional[bytes] = None, method: str = "GET",
         timeout: Optional[float] = None,
         headers: Optional[dict] = None):
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(
                req, timeout=timeout or default_timeout_s()) as r:
            body = r.read()
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read()).get("error", str(e))
        except Exception:
            detail = str(e)
        raise RuntimeError(detail) from None
    return json.loads(body) if body else None


class PipelineHandle:
    """Talks to one running pipeline's embedded server."""

    def __init__(self, host: str, port: int):
        self.base = f"http://{host}:{port}"
        # e2e trace id of the most recent push() (None when tracing is off)
        self.last_trace: Optional[str] = None

    def status(self) -> dict:
        return _req(self.base + "/status")

    def stats(self) -> dict:
        return _req(self.base + "/stats")

    def metrics(self) -> str:
        with urllib.request.urlopen(self.base + "/metrics",
                                    timeout=default_timeout_s()) as r:
            return r.read().decode()

    def trace(self) -> dict:
        """Chrome-trace JSON of the recent step window (Perfetto-loadable;
        see README §Observability)."""
        return _req(self.base + "/trace")

    def mode(self) -> str:
        """Execution surface this pipeline runs on: ``compiled`` (one XLA
        program per tick) or ``host`` (the per-operator scheduler — check
        :meth:`status`'s ``fallback_reason`` when this says host for a
        pipeline you expected to compile)."""
        return self.status()["mode"]

    def flight(self, n: Optional[int] = None) -> dict:
        """The pipeline's flight-recorder ring (README §Observability):
        {"capacity", "dropped", "events": [...]} — per-tick latency with
        cause, host phases, drains, replays, fallbacks. ``n`` caps to the
        most recent events."""
        q = f"?n={n}" if n is not None else ""
        return _req(f"{self.base}/flight{q}")

    def timeline(self, since: int = 0, view: Optional[str] = None,
                 n: Optional[int] = None) -> dict:
        """The unified per-tick timeline (README §Observability):
        {"capacity", "enabled", "last_seq", "dropped", "truncated",
        "freshness", "records": [...]} — tick latency/rows/queue depth,
        flight events, freshness samples, and SLO incidents in one
        time-indexed ring. ``since`` (a record seq) makes polling
        incremental; ``view`` filters freshness records to one view;
        ``n`` caps to the most recent records. Quiesce-free server-side:
        the read never takes the pipeline's step lock."""
        qs = [f"since={since}"] if since else []
        if view is not None:
            qs.append(f"view={quote(view, safe='')}")
        if n is not None:
            qs.append(f"n={n}")
        q = ("?" + "&".join(qs)) if qs else ""
        return _req(f"{self.base}/timeline{q}")

    def explain_spike(self, n: Optional[int] = None) -> dict:
        """EXPLAIN SPIKE (``GET /spikes``): outlier ticks selected against
        a robust rolling baseline (median + MAD), each attributed to a
        cause from ``obs.timeline.SPIKE_CAUSES`` with ranked co-timed
        evidence (maintain drain, retrace, overflow replay, checkpoint
        write, residency fault, transport stall, GC). ``n`` caps to the
        most recent spikes."""
        q = f"?n={n}" if n is not None else ""
        return _req(f"{self.base}/spikes{q}")

    def incidents(self, with_window: bool = True) -> dict:
        """SLO status + captured incidents: {"status": {...},
        "incidents": [{slo, cause, observed, threshold, window, trace,
        ...}]}. ``with_window=False`` drops the frozen event windows and
        trace slices (summaries only)."""
        q = "" if with_window else "?window=0"
        return _req(f"{self.base}/incidents{q}")

    def profile(self, ticks: Optional[int] = None) -> dict:
        """Operator-level attribution report — the shared schema both
        engines emit (``opprofile.PROFILE_SCHEMA``; README §Observability
        profile-mode matrix). ``ticks=None`` is free: continuous
        measurement on a host pipeline, static per-node XLA cost analysis
        on a compiled one. ``ticks=N`` arms the compiled MEASURED mode —
        the server quiesces, runs N segmented ticks (per-node wall time +
        rows, asserted bit-identical to the fused program), rewinds, and
        reports; expect it to take N segmented ticks' worth of wall time
        plus per-node compiles on the first call."""
        q = f"?ticks={ticks}" if ticks is not None else ""
        return _req(f"{self.base}/profile{q}")

    def profile_dot(self, ticks: Optional[int] = None) -> str:
        """Graphviz rendering of :meth:`profile` (the reference's
        ``dump_profile`` .dot shape): nodes shaded by time share."""
        q = f"&ticks={ticks}" if ticks is not None else ""
        with urllib.request.urlopen(f"{self.base}/profile?format=dot{q}",
                                    timeout=default_timeout_s()) as r:
            return r.read().decode()

    def why(self, view: str, key, n: Optional[int] = None) -> dict:
        """Row-level lineage (EXPLAIN WHY, README §Observability): why is
        the row whose key columns start with ``key`` in ``view``? Returns
        the backward provenance DAG (``dbsp_tpu.lineage/v1``): per-hop
        supporting rows with Z-set weights down to concrete input-table
        rows (``report["inputs"]``). ``key`` is a tuple/list of column
        literals (or a preformatted csv string); ``n`` caps rows per hop.
        Read-only and quiesced server-side; resolving past untraced
        sources needs the pipeline's lineage taps
        (``DBSP_TPU_LINEAGE_TAP=1`` / config ``lineage_taps``)."""
        q = _lineage_qs(view, key) + (f"&n={n}" if n is not None else "")
        return _req(self.base + "/lineage" + q)

    def why_dot(self, view: str, key) -> str:
        """Graphviz rendering of :meth:`why`'s lineage DAG."""
        with urllib.request.urlopen(
                f"{self.base}/lineage{_lineage_qs(view, key)}&format=dot",
                timeout=default_timeout_s()) as r:
            return r.read().decode()

    def debug_bundle(self) -> dict:
        """The one-shot diagnostics bundle (``GET /debug``) — status +
        stats + SLO health + incident summaries + flight summary + the
        last profile/lineage reports + analysis findings, in one JSON:
        the "attach this to the bug report" artifact."""
        return _req(self.base + "/debug")

    def dump_profile(self) -> dict:
        """Legacy one-shot profiler dump (``/dump_profile``): per-operator
        totals on host pipelines, node inventory + tick latency on
        compiled ones. :meth:`profile` is the unified replacement."""
        return _req(self.base + "/dump_profile")

    def push(self, collection: str, rows: List[list], deletes: bool = False,
             trace: Optional[str] = None) -> int:
        """Push a batch. Pass ``trace`` to adopt a caller-minted e2e trace
        id (sent as ``X-Dbsp-Trace``); the id the server actually used —
        minted when none was supplied — lands in :attr:`last_trace` and can
        later be matched against ``/view`` responses' ``trace.ids``."""
        env = "delete" if deletes else "insert"
        body = "\n".join(json.dumps({env: list(r)}) for r in rows).encode()
        out = _req(f"{self.base}/input_endpoint/{collection}?format=json",
                   data=body, method="POST",
                   headers={"X-Dbsp-Trace": trace} if trace else None)
        self.last_trace = out.get("trace")
        return out["records"]

    def step(self) -> None:
        _req(self.base + "/step", data=b"", method="POST")

    def read(self, view: str) -> Dict[tuple, int]:
        """Latest tick's delta for ``view`` (the server re-serves it until
        the next tick; use :meth:`read_new` to poll without double counting)."""
        batch, _ = self._read_step(view)
        return batch

    def read_new(self, view: str, last_seen: int = -1
                 ) -> tuple[Dict[tuple, int], int]:
        """Dedup-polling read: returns ({}, last_seen) if the server still
        serves the tick already consumed, else (delta, new_cursor). Pass the
        returned cursor back on the next poll."""
        batch, step = self._read_step(view)
        if step == last_seen:
            return {}, last_seen
        return batch, step

    def _read_step(self, view: str) -> tuple[Dict[tuple, int], int]:
        with urllib.request.urlopen(
                f"{self.base}/output_endpoint/{view}?format=json",
                timeout=default_timeout_s()) as r:
            step = int(r.headers.get("X-Dbsp-Step", -1))
            out: Dict[tuple, int] = {}
            for line in r.read().decode().splitlines():
                if not line:
                    continue
                obj = json.loads(line)
                if "insert" in obj:
                    row = tuple(obj["insert"])
                    out[row] = out.get(row, 0) + 1
                else:
                    row = tuple(obj["delete"])
                    out[row] = out.get(row, 0) - 1
            return {r: w for r, w in out.items() if w != 0}, step

    # -- lock-free read plane (README §Serving read path) -------------------

    def get(self, view: str, key, limit: Optional[int] = None) -> dict:
        """Point lookup against the last PUBLISHED snapshot of ``view``
        (``GET /view/<view>?key=``): rows whose key columns start with
        ``key`` (a scalar, tuple, or csv string), each as ``[*row, w]``.
        Served lock-free server-side — never blocks or waits on ingest;
        staleness is bounded by the engine's validation interval. The
        response carries the snapshot's ``epoch``/``step``/``ts``."""
        key = key if isinstance(key, str) else (
            ",".join(map(str, key)) if isinstance(key, (tuple, list))
            else str(key))
        q = f"?key={quote(key, safe=',')}"
        if limit is not None:
            q += f"&limit={limit}"
        return _req(f"{self.base}/view/{quote(view, safe='')}{q}")

    def range(self, view: str, lo=None, hi=None,
              limit: Optional[int] = None) -> dict:
        """Inclusive range scan ``lo <= first-key-column <= hi`` against
        the last published snapshot (``GET /view/<view>?lo=&hi=``). Omit
        a bound for an open end; omit both for a full scan."""
        qs = []
        if lo is not None:
            qs.append(f"lo={lo}")
        if hi is not None:
            qs.append(f"hi={hi}")
        if limit is not None:
            qs.append(f"limit={limit}")
        q = ("?" + "&".join(qs)) if qs else ""
        return _req(f"{self.base}/view/{quote(view, safe='')}{q}")

    def subscribe(self, view: str, after_epoch: int = 0,
                  timeout: float = 0.0,
                  limit: Optional[int] = None) -> dict:
        """Changefeed poll (``GET /changefeed``): every per-interval delta
        record published after ``after_epoch``, exactly once. Pass the
        returned ``epoch`` back as the next ``after_epoch`` to resume. A
        cursor older than the feed's retention gets one synthesized
        ``kind="snapshot"`` record (full state) before the deltas.
        ``timeout`` long-polls until a newer epoch publishes."""
        q = f"?view={quote(view, safe='')}&after={after_epoch}"
        if timeout:
            q += f"&timeout={timeout}"
        if limit is not None:
            q += f"&limit={limit}"
        return _req(f"{self.base}/changefeed{q}",
                    timeout=timeout + default_timeout_s())

    def start(self) -> None:
        _req(self.base + "/start", data=b"", method="POST")

    def pause(self) -> None:
        _req(self.base + "/pause", data=b"", method="POST")

    def checkpoint(self) -> dict:
        """Write one durable checkpoint generation now (quiesced at a tick
        boundary). Returns {"tick", "generation", ...}; raises
        RuntimeError when the pipeline has no checkpoint directory
        configured (``checkpoint_dir`` / DBSP_TPU_CHECKPOINT_DIR). The
        restore position also rides ``status()["last_checkpoint_tick"]``."""
        return _req(self.base + "/checkpoint", data=b"", method="POST")


class Connection:
    """Manager-level API (reference: DBSPConnection)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8080):
        self.host = host
        self.base = f"http://{host}:{port}"

    def create_program(self, name: str, tables: dict, sql: Dict[str, str],
                       description: str = "") -> dict:
        """Create (or update — the manager bumps the version when the code
        changed). Returns the program descriptor (version/status)."""
        return _req(self.base + "/programs",
                    data=json.dumps({"name": name, "tables": tables,
                                     "sql": sql,
                                     "description": description}).encode(),
                    method="POST")

    def update_program(self, name: str, tables: dict, sql: Dict[str, str],
                       description: str = "") -> dict:
        return _req(f"{self.base}/programs/{name}",
                    data=json.dumps({"tables": tables, "sql": sql,
                                     "description": description}).encode(),
                    method="POST")

    def programs(self) -> List[str]:
        return _req(self.base + "/programs")

    def program(self, name: str) -> dict:
        """Full descriptor: {name, version, status, error, description}."""
        return _req(f"{self.base}/programs/{name}")

    def compile_program(self, name: str, version: Optional[int] = None
                        ) -> dict:
        """Enqueue a compile of ``version`` (409 -> RuntimeError if stale);
        poll :meth:`program` for the status to reach success/sql_error."""
        body = {} if version is None else {"version": version}
        return _req(f"{self.base}/programs/{name}/compile",
                    data=json.dumps(body).encode(), method="POST")

    def delete_program(self, name: str) -> None:
        _req(f"{self.base}/programs/{name}", method="DELETE")

    def delete_pipeline(self, name: str) -> None:
        _req(f"{self.base}/pipelines/{name}", method="DELETE")

    def start_pipeline(self, name: str, program: str,
                       config: Optional[dict] = None) -> PipelineHandle:
        """Deploy; ``config`` is a declarative pipeline config dict
        (io/config.py — ControllerConfig fields + inputs/outputs endpoint
        sections)."""
        body = {"name": name, "program": program}
        if config is not None:
            body["config"] = config
        desc = _req(self.base + "/pipelines", data=json.dumps(body).encode(),
                    method="POST")
        if desc.get("error"):
            raise RuntimeError(desc["error"])
        return PipelineHandle(self.host, desc["port"])

    def pipelines(self) -> List[dict]:
        return _req(self.base + "/pipelines")

    def metrics(self) -> str:
        """Fleet-wide Prometheus exposition: every deployed pipeline's
        registry under a ``pipeline="<name>"`` label."""
        with urllib.request.urlopen(self.base + "/metrics", timeout=default_timeout_s()) as r:
            return r.read().decode()

    def health(self) -> dict:
        """Fleet health: worst per-pipeline SLO state plus per-pipeline
        {health, status, mode, fallback_reason} detail."""
        return _req(self.base + "/health")

    def profile_pipeline(self, name: str,
                         ticks: Optional[int] = None) -> dict:
        """Manager-side attribution report: GET
        /pipelines/<name>/profile (same semantics as
        :meth:`PipelineHandle.profile`)."""
        q = f"?ticks={ticks}" if ticks is not None else ""
        return _req(f"{self.base}/pipelines/{name}/profile{q}")

    def lineage_pipeline(self, name: str, view: str, key,
                         n: Optional[int] = None) -> dict:
        """Manager-side lineage query: GET /pipelines/<name>/lineage
        (same semantics as :meth:`PipelineHandle.why`)."""
        q = _lineage_qs(view, key) + (f"&n={n}" if n is not None else "")
        return _req(f"{self.base}/pipelines/{name}/lineage{q}")

    def timeline_pipeline(self, name: str, since: int = 0,
                          view: Optional[str] = None,
                          n: Optional[int] = None) -> dict:
        """Manager-side timeline read: GET /pipelines/<name>/timeline
        (same semantics as :meth:`PipelineHandle.timeline`)."""
        qs = [f"since={since}"] if since else []
        if view is not None:
            qs.append(f"view={quote(view, safe='')}")
        if n is not None:
            qs.append(f"n={n}")
        q = ("?" + "&".join(qs)) if qs else ""
        return _req(f"{self.base}/pipelines/{name}/timeline{q}")

    def spikes_pipeline(self, name: str, n: Optional[int] = None) -> dict:
        """Manager-side EXPLAIN SPIKE: GET /pipelines/<name>/spikes (same
        semantics as :meth:`PipelineHandle.explain_spike`)."""
        q = f"?n={n}" if n is not None else ""
        return _req(f"{self.base}/pipelines/{name}/spikes{q}")

    def fleet_trace(self) -> dict:
        """One merged Chrome-trace JSON for the whole fleet (GET
        /fleet/trace): every pipeline's span ring plus every replica's,
        each on its own real pid/tid lane — load the result straight into
        Perfetto to see a cross-process delta journey end to end."""
        return _req(self.base + "/fleet/trace")

    def checkpoint_pipeline(self, name: str) -> dict:
        """Manager-side checkpoint trigger: POST
        /pipelines/<name>/checkpoint (same semantics as
        :meth:`PipelineHandle.checkpoint`)."""
        return _req(f"{self.base}/pipelines/{name}/checkpoint", data=b"",
                    method="POST")

    # -- read replicas (README §Serving read path) --------------------------

    def add_replicas(self, name: str, count: int = 1) -> dict:
        """Scale pipeline ``name``'s read-serving tier: start ``count``
        changefeed-fed snapshot replicas (POST /pipelines/<name>/replicas).
        Returns {"replicas": [...status...], "total": N}."""
        return _req(f"{self.base}/pipelines/{name}/replicas",
                    data=json.dumps({"count": count}).encode(),
                    method="POST")

    def replicas(self, name: str) -> List[dict]:
        """Per-replica freshness for pipeline ``name``: each status dict
        carries ``staleness_s`` (0.0 when caught up to the primary's
        published epochs) plus per-view cursor epochs."""
        return _req(f"{self.base}/pipelines/{name}/replicas")["replicas"]

    def remove_replicas(self, name: str) -> dict:
        """Stop every read replica of pipeline ``name``."""
        return _req(f"{self.base}/pipelines/{name}/replicas",
                    method="DELETE")

    def read_view(self, name: str, view: str, key=None, lo=None, hi=None,
                  limit: Optional[int] = None) -> dict:
        """Fan one snapshot read out over pipeline ``name``'s replica set
        (GET /pipelines/<name>/view/<view>, round-robin; falls back to the
        primary when no replica is up). Same query surface as
        :meth:`PipelineHandle.get` / :meth:`PipelineHandle.range`."""
        qs = []
        if key is not None:
            key = key if isinstance(key, str) else (
                ",".join(map(str, key)) if isinstance(key, (tuple, list))
                else str(key))
            qs.append(f"key={quote(key, safe=',')}")
        if lo is not None:
            qs.append(f"lo={lo}")
        if hi is not None:
            qs.append(f"hi={hi}")
        if limit is not None:
            qs.append(f"limit={limit}")
        q = ("?" + "&".join(qs)) if qs else ""
        return _req(
            f"{self.base}/pipelines/{name}/view/{quote(view, safe='')}{q}")

    def shutdown_pipeline(self, name: str) -> None:
        _req(f"{self.base}/pipelines/{name}/shutdown", data=b"",
             method="POST")
