"""Compilation-stability registry: every legal recompile, every donation.

The compiled engine's whole value proposition is that the steady-state
tick is ONE cached XLA program — BENCH r06 measured a ~12x throughput
decay from an oscillating-layout retrace-per-interval before it was
hand-fixed, and the donated-buffer aliasing class (a ``jnp.asarray``
zero-copy view riding into a ``donate_argnums`` pytree -> XLA frees the
memory under the view -> garbage int64s / SIGSEGV one tick later) has
been fixed by hand and re-documented in prose twice (checkpoint decoder,
residency tier movers). This module makes both disciplines declared data,
the way ``checkpoint.STATE_SCHEMA`` declares persistence and
``concurrency.CONCURRENCY_SCHEMA`` declares guards:

* :data:`RETRACE_SCHEMA` — every jitted program dispatched on the step /
  maintenance path, with the closed set of CAUSES under which it may
  legally (re)compile. A compile outside the declared set is a defect:
  on this CPU it costs ~12ms of trace+compile per occurrence; over a
  tunneled TPU it costs seconds.
* :data:`DONATION_SCHEMA` — every ``donate_argnums`` boundary, with the
  positions donated and the in-module names the donating callable is
  bound to (for the read-after-donation walk).
* :data:`DONATION_PRODUCERS` — every function whose results are allowed
  to feed a donated pytree, each with the owning-copy invariant it must
  uphold (the D001 escape walk starts from these).

Checked in both directions by ``tools/check_retrace.py`` (an undeclared
jit site in a registered module AND a stale schema entry are both
findings), enforced at runtime by ``dbsp_tpu/testing/retrace.py`` (jit
cache hooked per schema'd program; ``jax.transfer_guard`` armed over the
steady-state tick region), and gated at zero in tier-1 by
``tests/test_retrace.py``.

Deliberately NOT schema'd:

* operator / zset kernels (``zset/kernels.py``, ``operators/``,
  ``timeseries/``): on the compiled path they are traced INLINE into the
  step program and never dispatch as top-level programs — their
  static-config recompiles are the step program's, already declared
  here. The host engine dispatches them eagerly, but its per-dispatch
  overhead is the reason the compiled engine exists; retrace discipline
  for the host path would gate a cost model we do not claim.
* ``obs/flight.py`` / serving-plane modules: no jit sites; anything
  added there lands in a registered module or trips R005 when one of
  these modules grows a jit.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

#: waiver comment for the static pass (``tools/check_retrace.py``) — same
#: idiom as ``# hotpath: ok`` / ``# concurrency: ok``: suppresses the
#: finding on its line, MUST state the invariant that makes it safe, and
#: is itself audited (a waiver that no longer suppresses anything is a
#: W001 finding; see tools/schema_walk.py). Runtime sentinel violations
#: are NOT waivable.
WAIVER = "# retrace: ok"

#: the closed vocabulary of legal (re)compile causes. ``flight`` names
#: the flight-recorder cause annotation that accompanies the recompile on
#: the live path (dbsp_tpu/obs/flight.py event kinds), so the runtime
#: sentinel can join observed compiles against declared causes.
CAUSES: Dict[str, str] = {
    "first": "first dispatch after construction traces and compiles the "
             "program (flight cause 'retrace' — _dispatch notes it)",
    "retrace": "a capacity change invalidated the program: maintain tail "
               "growth, grow() after CompiledOverflow, presize() — each "
               "drops _step_jit/_scan_jits and notes flight cause "
               "'retrace'",
    "residency": "a tier transition changed the INPUT STRUCTURE (device "
                 "leaf -> numpy operand or back); jax.jit caches per "
                 "structure so the old program stays cached — flight "
                 "cause 'residency'",
    "chunk": "scanned dispatch compiles one program per chunk length n "
             "(_scan_jits is keyed by n); a growth run with a stable "
             "validation interval compiles exactly one",
    "grow": "a static-capacity operand (bucketed cap) changed — "
            "maintenance drains compile per (cap, structure) cache key",
    "structure": "the state pytree's structure changed (new levels after "
                 "a grow, cold levels interleaved) — snapshot copies and "
                 "requirement maxes re-specialize",
    "profile": "EXPLAIN ANALYZE segments AOT-compile per profile_ticks "
               "invocation and are discarded with it (obs/opprofile.py)",
    "config": "compiled once per static configuration key (mesh, kernel "
              "factory, static args) through a bounded lru_cache",
}

#: modules whose jit sites must ALL be declared below — tools/
#: check_retrace.py R005 fires on an undeclared ``jax.jit`` in any of
#: these, R006 on a schema entry whose site vanished. Paths relative to
#: the repo root.
RETRACE_MODULES: Tuple[str, ...] = (
    "dbsp_tpu/compiled/compiler.py",
    "dbsp_tpu/compiled/driver.py",
    "dbsp_tpu/residency.py",
    "dbsp_tpu/checkpoint.py",
    "dbsp_tpu/obs/opprofile.py",
    "dbsp_tpu/parallel/lift.py",
    "dbsp_tpu/parallel/exchange.py",
)

#: program -> {cause: why it applies to THIS program}. Keys are
#: ``<module basename>.<program name>`` where the program name is what
#: XLA's compile log reports: the function passed to ``jax.jit`` (its
#: ``__name__``) — for non-function jit operands, the enclosing def.
#: Causes must come from :data:`CAUSES`.
RETRACE_SCHEMA: Dict[str, Dict[str, str]] = {
    # -- the step path (hard-gated at zero undeclared by the sentinel) --
    "compiler.step_fn": {
        "first": "built lazily by _dispatch when _step_jit is None",
        "retrace": "maintain/grow/presize drop _step_jit; the overflow "
                   "replay in run_ticks notes the cause before replaying",
        "residency": "_enforce_residency changes hot/cold splits — "
                     "structure-keyed recompile, old program kept",
    },
    "compiler._scan_body": {
        "first": "built by step_scanned on the first chunk of length n",
        "chunk": "_scan_jits caches one program per chunk length",
        "retrace": "same invalidations as step_fn (caches cleared "
                   "together)",
        "residency": "structure-keyed like step_fn",
    },
    "compiler.scan_fn": {
        "first": "SPMD variant of _scan_body (mesh is not None)",
        "chunk": "same per-length cache",
        "retrace": "same invalidations as step_fn",
    },
    # -- maintenance / bookkeeping programs (counted, reported in bench
    #    detail; not hard-gated — their caches key on declared statics) --
    "compiler._copy_tree": {
        "first": "snapshot()/restore()/prewarm copy the state pytree",
        "structure": "one compile per state-pytree structure (levels "
                     "appear on grow, cold levels leave the hot tree)",
    },
    "compiler._drain_pair": {
        "first": "maintenance drain, full-source variant",
        "grow": "static cap operand — one compile per receiver bucket",
        "structure": "level layouts differ across (key dtypes, widths)",
    },
    "compiler._drain_slice": {
        "first": "maintenance drain, budgeted-slice variant",
        "grow": "static cap operand like _drain_pair",
        "structure": "level layouts differ across (key dtypes, widths)",
    },
    "compiler.maximum": {
        "first": "requirement running-max (jax.jit(jnp.maximum))",
        "structure": "re-specializes when the requirement vector length "
                     "changes (checks added on grow)",
    },
    # -- off-path programs --
    "opprofile.fn": {
        "profile": "per-node segments and the generator harness are "
                   "lowered+compiled per profile run, then dropped",
    },
    "lift._lifted_jit": {
        "config": "one SPMD callable per (mesh, factory, statics) via "
                  "lru_cache(1024); worker_scalar exists so VALUES ride "
                  "as operands instead of forcing per-value recompiles",
    },
    "exchange._shard_kernel": {
        "config": "static nworkers — one compile per worker count",
    },
    "exchange._sharded_consolidate": {
        "config": "one compile per mesh via lru_cache",
    },
}

#: the step-path subset the runtime sentinel hard-gates: in a
#: steady-state run EVERY compile of these must be attributable to a
#: declared cause noted on the handle; an unattributed compile is a
#: violation (NOT waivable at runtime).
SENTINEL_PROGRAMS: Tuple[str, ...] = (
    "step_fn", "_scan_body", "scan_fn")


class DonationSite(NamedTuple):
    """One ``donate_argnums`` boundary."""

    #: repo-relative file declaring the jit
    file: str
    #: donated argument positions, as written at the jit site
    argnums: Tuple[int, ...]
    #: in-module names the donating callable is bound to at call sites
    #: (the D002 read-after-donation walk tracks calls through these)
    call_names: Tuple[str, ...]
    #: the invariant making the donation safe
    why: str


#: program -> donation boundary. Every ``donate_argnums=`` occurrence in
#: a registered module must be declared here (D003 otherwise; stale
#: entries are D004).
DONATION_SCHEMA: Dict[str, DonationSite] = {
    "compiler.step_fn": DonationSite(
        "dbsp_tpu/compiled/compiler.py", (0,), ("_step_jit",),
        "donating the state pytree lets XLA alias untouched trace levels "
        "input->output instead of copying ~tens of MB per tick; cold "
        "(numpy) levels ride OUTSIDE the donated tree as per-call "
        "operands (_split_states), snapshots are real copies"),
    "compiler._scan_body": DonationSite(
        "dbsp_tpu/compiled/compiler.py", (0,), ("fn",),
        "same state donation as step_fn, per scanned chunk"),
    "compiler.scan_fn": DonationSite(
        "dbsp_tpu/compiled/compiler.py", (0,), ("fn",),
        "same state donation as step_fn, SPMD scanned chunk"),
    "compiler._drain_pair": DonationSite(
        "dbsp_tpu/compiled/compiler.py", (0, 1), ("_drain_pair",),
        "receiver and source levels are consumed; maintain() always "
        "feeds _copy_tree copies so handle state is never donated here"),
    "compiler._drain_slice": DonationSite(
        "dbsp_tpu/compiled/compiler.py", (0, 1), ("_drain_slice",),
        "same copy-in contract as _drain_pair"),
}

#: (file, qualname) -> the owning-copy invariant. These are the functions
#: whose RESULTS reach a donated pytree (trace state); the D001 escape
#: walk flags any return value produced by ``jnp.asarray`` /
#: ``np.frombuffer`` / another zero-copy view that is not wrapped in an
#: owning copy before it escapes. ``*.name`` matches the method in every
#: class of the file.
DONATION_PRODUCERS: Dict[Tuple[str, str], str] = {
    ("dbsp_tpu/checkpoint.py", "_Decoder._arr"):
        "restore decodes blob bytes into trace state the step program "
        "donates — jnp.array (a COPY) or XLA frees the decoder's buffer "
        "under it (observed: garbage int64 state one tick after restore, "
        "flaky SIGSEGV)",
    ("dbsp_tpu/residency.py", "to_device"):
        "a promoted level rejoins the donated hot pytree — jnp.array (a "
        "COPY), never asarray, or the donation frees host memory the "
        "residency bookkeeping still reads",
    ("dbsp_tpu/residency.py", "to_host"):
        "the demoted level must own its bytes: np.array (a COPY) — "
        "asarray could zero-copy-wrap the device buffer a later donation "
        "frees (the same hazard in reverse)",
    ("dbsp_tpu/compiled/compiler.py", "_copy_tree"):
        "jnp.copy per leaf: snapshots/restores must produce buffers the "
        "next donating dispatch can consume without invalidating the "
        "snapshot",
    ("dbsp_tpu/compiled/cnodes.py", "*.init_state"):
        "initial states are freshly materialized device buffers "
        "(jnp.zeros/full) — nothing upstream owns them",
}


class RetraceError(RuntimeError):
    """Schema violation raised by the runtime sentinel's ``check()``."""


def program_module(program: str) -> str:
    """'compiler.step_fn' -> 'compiler' (schema-key module basename)."""
    return program.split(".", 1)[0]


def module_basename(rel: str) -> str:
    """'dbsp_tpu/compiled/compiler.py' -> 'compiler'."""
    base = rel.replace("\\", "/").rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


def schema_for_module(rel: str) -> Dict[str, Dict[str, str]]:
    """The RETRACE_SCHEMA entries declared against one module file."""
    base = module_basename(rel)
    return {prog: causes for prog, causes in RETRACE_SCHEMA.items()
            if program_module(prog) == base}


def validate_schema() -> None:
    """Internal consistency: every declared cause is in the closed
    vocabulary; every donation entry names a registered module. Raises
    ``ValueError`` — called by the static pass and the sentinel."""
    for prog, causes in RETRACE_SCHEMA.items():
        if not causes:
            raise ValueError(f"RETRACE_SCHEMA[{prog!r}] declares no cause")
        for cause in causes:
            if cause not in CAUSES:
                raise ValueError(
                    f"RETRACE_SCHEMA[{prog!r}] uses undeclared cause "
                    f"{cause!r} (closed vocabulary: {sorted(CAUSES)})")
    for prog, site in DONATION_SCHEMA.items():
        if site.file not in RETRACE_MODULES:
            raise ValueError(
                f"DONATION_SCHEMA[{prog!r}] points at {site.file!r}, "
                "which is not in RETRACE_MODULES")
        if prog not in RETRACE_SCHEMA:
            raise ValueError(
                f"DONATION_SCHEMA[{prog!r}] has no RETRACE_SCHEMA entry "
                "— a donating program is always a compiled program")
    for prog in SENTINEL_PROGRAMS:
        if not any(p.split(".", 1)[1] == prog for p in RETRACE_SCHEMA):
            raise ValueError(
                f"SENTINEL_PROGRAMS names {prog!r} with no schema entry")
