"""Tiered trace residency: device (HBM) <- host RAM <- disk blob store.

The reference ships a RocksDB-backed ``PersistentTrace``
(``trace/persistent/trace.rs:34``) precisely so accumulated state is not
bounded by working memory; the classic LSM bet (O'Neil et al., Acta
Informatica '96) is the same — keep the hot small levels fast, let cold
deep levels live in a cheaper tier. This module is the ONE config point
both engines route through:

  * the host :class:`~dbsp_tpu.trace.spine.Spine` demotes its largest
    device levels to host numpy past ``device_rows`` and its coldest host
    levels to the disk blob store past ``host_rows`` (probes FAULT a disk
    level back to host, verified against its recorded digest);
  * the compiled engine (:class:`~dbsp_tpu.compiled.compiler
    .CompiledHandle`) applies the same two budgets to each leveled trace's
    deep levels between validated intervals — cold levels ride into the
    step program as per-call operands OUTSIDE the donated state pytree
    (numpy transfers per call and the buffers die with it; disk levels are
    ``np.memmap`` views the OS pages in on probe), so persistent device
    residency is bounded while every consumer still sees the identical
    Z-set.

Tier names are stable strings (metric label values): ``device`` — jax
arrays, persistent HBM/device buffers; ``host`` — process-resident numpy;
``disk`` — memmap views over content-addressed ``.npy`` blobs in a
:class:`ColdStore`.

The :class:`ColdStore` reuses the checkpoint store's per-blob SHA-256 +
hard-link discipline (``dbsp_tpu/checkpoint.py`` format v2) as the cold
format: a blob's name IS its content hash, so a checkpoint save of a
pipeline with disk-demoted levels hard-links the already-written blobs
instead of re-serializing them (O(hot state) saves), and a corrupted cold
blob read falls back to re-adopting the bytes from the newest checkpoint
generation that recorded the same digest — one SLO-visible incident, not
silent data corruption.

Knobs (env; a per-pipeline config key overrides each — see
``ControllerConfig.device_rows/host_rows/cold_dir``):

  DBSP_TPU_DEVICE_ROWS  per-trace device row budget (unset = unbounded)
  DBSP_TPU_HOST_ROWS    per-trace host-RAM row budget (unset = unbounded)
  DBSP_TPU_COLD_DIR     blob-store directory for the disk tier (unset =
                        a process-scoped temp directory, created lazily)
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import tempfile
import threading
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dbsp_tpu.zset.batch import Batch

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_DISK = "disk"
TIERS = (TIER_DEVICE, TIER_HOST, TIER_DISK)


def _env_rows(name: str) -> Optional[int]:
    v = os.environ.get(name)
    if not v:
        return None
    n = int(v)
    return n if n > 0 else None


#: module-level env defaults — read once at import like the spine's legacy
#: ``DEVICE_BUDGET_ROWS`` (which now aliases :data:`DEVICE_ROWS`); tests
#: monkeypatch these module attributes, so :meth:`ResidencyConfig.from_env`
#: reads the attributes rather than os.environ again
DEVICE_ROWS: Optional[int] = _env_rows("DBSP_TPU_DEVICE_ROWS")
HOST_ROWS: Optional[int] = _env_rows("DBSP_TPU_HOST_ROWS")
COLD_DIR: Optional[str] = os.environ.get("DBSP_TPU_COLD_DIR") or None


@dataclasses.dataclass(frozen=True)
class ResidencyConfig:
    """Per-pipeline residency budgets (both engines, one vocabulary).

    ``device_rows`` / ``host_rows`` bound the row CAPACITY each trace may
    keep resident in the respective tier (per trace, matching the host
    spine's historical ``DBSP_TPU_DEVICE_ROWS`` semantics — capacity is
    the static quantity the compiled programs actually allocate). Level 0
    is always exempt on the compiled path: the step program writes it
    every tick, so it is hot by construction — a budget below l0's
    capacity degrades to "everything deep is cold", bounded residency at
    bounded (transfer-per-probe) slowdown, the PersistentTrace contract.

    ``lru_intervals`` is the LRU clock: a level must go that many
    maintain intervals without a write before it may demote host -> disk,
    and a recently-written host level within that window is eligible for
    promotion back to device when budget headroom exists."""

    device_rows: Optional[int] = None
    host_rows: Optional[int] = None
    cold_dir: Optional[str] = None
    lru_intervals: int = 2

    @property
    def active(self) -> bool:
        return self.device_rows is not None or self.host_rows is not None

    @staticmethod
    def from_env() -> "ResidencyConfig":
        return ResidencyConfig(device_rows=DEVICE_ROWS, host_rows=HOST_ROWS,
                               cold_dir=COLD_DIR)


def resolve(device_rows=None, host_rows=None, cold_dir=None
            ) -> ResidencyConfig:
    """Merge explicit per-pipeline values over the env defaults — the one
    resolution rule both engines and the controller share. ``None`` =
    defer to env; an explicit value <= 0 = explicitly unbounded (a config
    key must be able to DISABLE an env-set budget, not only tighten it)."""

    def pick(v, env):
        if v is None:
            return env
        v = int(v)
        return v if v > 0 else None

    return ResidencyConfig(device_rows=pick(device_rows, DEVICE_ROWS),
                           host_rows=pick(host_rows, HOST_ROWS),
                           cold_dir=cold_dir or COLD_DIR)


# ---------------------------------------------------------------------------
# batch tier inspection / movement
# ---------------------------------------------------------------------------


def batch_tier(b: Batch) -> str:
    """Which tier a batch's buffers live in (weights column is
    representative — all columns of a batch move together)."""
    if isinstance(b.weights, np.memmap):
        return TIER_DISK
    if isinstance(b.weights, np.ndarray):
        return TIER_HOST
    return TIER_DEVICE


def to_host(b: Batch) -> Batch:
    """Copy a batch's columns to host memory (numpy). jnp kernels accept
    numpy operands and device_put them per call, so host-tier levels stay
    fully probe-able — each probe pays the transfer, nothing persists on
    device (the fetched operand buffers die with the call).

    ``np.array`` (a COPY), never ``np.asarray``: on the CPU backend
    asarray can zero-copy-wrap the device buffer, and the compiled step
    program DONATES its state pytree — a demoted level must own its
    bytes or a later donation frees them under the view (the same
    aliasing hazard checkpoint._Decoder documents, in reverse)."""
    return Batch(tuple(np.array(c) for c in b.keys),
                 tuple(np.array(c) for c in b.vals),
                 np.array(b.weights), b.runs)


def to_device(b: Batch) -> Batch:
    """Materialize a cold batch as persistent device arrays —
    ``jnp.array`` (a COPY), never ``asarray``: the result rejoins the
    DONATED hot pytree, so it must not alias host memory the residency
    bookkeeping (or a shared snapshot) still reads."""
    import jax.numpy as jnp

    return Batch(tuple(jnp.array(np.asarray(c)) for c in b.keys),
                 tuple(jnp.array(np.asarray(c)) for c in b.vals),
                 jnp.array(np.asarray(b.weights)), b.runs)


class ColdError(RuntimeError):
    """A disk-tier blob failed verification and could not be recovered."""


class ColdStore:
    """Content-addressed ``.npy`` blob store — the disk tier's format AND
    the hard-link source for checkpoint saves.

    A blob's filename is its SHA-256 (the same digest the checkpoint
    manifest records), written atomically (temp + ``os.replace``) and
    deduplicated by content. ``read_verified`` re-hashes on the promotion
    path; a mismatch consults ``recovery_dirs`` (checkpoint generation
    roots, newest generation first) for a blob whose MANIFEST records the
    wanted digest, verifies it, re-adopts the bytes into the store, and
    reports the episode via ``on_event`` — the cold tier can bit-rot
    without the pipeline silently serving garbage."""

    def __init__(self, path: str,
                 on_event: Optional[Callable[[dict], None]] = None):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.recovery_dirs: List[str] = []
        self.on_event = on_event
        self._lock = threading.Lock()
        # blob lifecycle: content-addressing means several levels (or
        # several spines sharing one store) can reference one blob file —
        # demotions RETAIN each column blob, promotions RELEASE them, and
        # zero-reference blobs land on a condemned list that sweep()
        # unlinks at a caller-chosen replay-safe point (the compiled
        # engine sweeps when a NEW snapshot supersedes the old one — an
        # overflow replay can never fault content older than the live
        # snapshot). Without this, every demote/promote churn leaked one
        # level-copy of .npy files until the cold dir filled the disk.
        self._refs: Dict[str, int] = {}
        self._condemned: List[str] = []

    @staticmethod
    def _meta_shas(meta: dict) -> List[str]:
        return [m["sha256"]
                for m in (*meta["keys"], *meta["vals"], meta["weights"])]

    def retain(self, meta: dict) -> None:
        """Take a reference on every column blob of one level meta."""
        with self._lock:
            for sha in self._meta_shas(meta):
                self._refs[sha] = self._refs.get(sha, 0) + 1

    def release(self, meta: dict) -> None:
        """Drop references; zero-ref blobs are CONDEMNED, not unlinked —
        :meth:`sweep` deletes them at a replay-safe point."""
        with self._lock:
            for sha in self._meta_shas(meta):
                if sha not in self._refs:
                    continue  # untracked (reconstructed meta): never ours
                self._refs[sha] -= 1
                if self._refs[sha] <= 0:
                    del self._refs[sha]
                    self._condemned.append(sha)

    def sweep(self) -> int:
        """Unlink condemned zero-reference blobs (checkpoint generations
        keep their own hard links — recovery is unaffected). Returns the
        number of files removed."""
        removed = 0
        with self._lock:
            condemned, self._condemned = self._condemned, []
            condemned = [s for s in condemned
                         if self._refs.get(s, 0) <= 0]
        for sha in condemned:
            try:
                os.unlink(self.blob_path(sha))
                removed += 1
            except OSError:
                pass
        return removed

    def note_recovery_dir(self, path: str) -> None:
        """Register a checkpoint store root as a corruption-recovery
        source (idempotent; called by checkpoint save/restore)."""
        with self._lock:
            if path not in self.recovery_dirs:
                self.recovery_dirs.append(path)

    def blob_path(self, sha: str) -> str:
        return os.path.join(self.path, sha + ".npy")

    def put_array(self, arr: np.ndarray) -> dict:
        """Serialize one array into the store (dedup by content). Returns
        the checkpoint-compatible blob meta ``{"sha256", "bytes"}``."""
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr), allow_pickle=False)
        data = buf.getvalue()
        sha = hashlib.sha256(data).hexdigest()
        dst = self.blob_path(sha)
        if not os.path.exists(dst):
            self._write_atomic(dst, data)
        return {"sha256": sha, "bytes": len(data)}

    @staticmethod
    def _write_atomic(dst: str, data: bytes) -> None:
        """Write-then-rename under a UNIQUE temp name: two threads
        landing the same content hash (a process-shared store, or two
        levels with identical columns) must not truncate each other's
        half-written temp file — pid alone does not disambiguate
        threads."""
        tmp = dst + f".tmp-{os.getpid()}-{threading.get_ident()}-" \
                    f"{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dst)

    def mmap(self, meta: dict) -> np.ndarray:
        """A disk-resident view of one blob (``np.load(mmap_mode='r')``):
        the OS pages content in on access — the compiled engine's probes
        fault exactly the bytes they touch. UNVERIFIED by design (per-page
        hashing would defeat the laziness); every promotion back to host
        goes through :meth:`read_verified`."""
        return np.load(self.blob_path(meta["sha256"]), mmap_mode="r",
                       allow_pickle=False)

    def _event(self, ev: dict) -> None:
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:  # noqa: BLE001 — observer must not break IO
                pass

    def verify_meta(self, meta: dict) -> bool:
        """Streaming-verify every column blob of one level meta IN PLACE
        (no materialization): hash the file in chunks against the
        recorded digest, healing a mismatch from the recovery dirs.
        The checkpoint save path uses this so serializing a disk-tier
        level never launders rotted bytes — without faulting the whole
        tier into RAM (O(1) memory, one extra read of data the encoder
        is about to read anyway). Returns True when any blob was HEALED
        (the caller must re-open memmaps: healing replaces the file, and
        an already-open memmap still maps the corrupted inode)."""
        healed = False
        for m in (*meta["keys"], *meta["vals"], meta["weights"]):
            p = self.blob_path(m["sha256"])
            h = hashlib.sha256()
            n = 0
            try:
                with open(p, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                        n += len(chunk)
            except OSError:
                pass
            if n != m["bytes"] or h.hexdigest() != m["sha256"]:
                self._recover(m)  # heals the file (or raises ColdError)
                healed = True
        return healed

    def read_verified(self, meta: dict) -> np.ndarray:
        """Read + verify one blob against its recorded digest; on failure
        recover the bytes from the newest checkpoint generation recording
        the same digest (one event either way)."""
        sha = meta["sha256"]
        p = self.blob_path(sha)
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        if len(data) == meta["bytes"] and \
                hashlib.sha256(data).hexdigest() == sha:
            return np.load(io.BytesIO(data), allow_pickle=False)
        return self._recover(meta)

    def _recover(self, meta: dict) -> np.ndarray:
        """Scan recovery dirs (checkpoint generation stores, newest
        generation first) for a blob whose manifest records the wanted
        digest; verify, re-adopt, report."""
        sha = meta["sha256"]
        with self._lock:
            dirs = list(self.recovery_dirs)
        for root in dirs:
            try:
                entries = sorted((e for e in os.listdir(root)
                                  if e.startswith("gen-")), reverse=True)
            except OSError:
                continue
            for gen in entries:
                gen_dir = os.path.join(root, gen)
                try:
                    with open(os.path.join(gen_dir, "manifest.json")) as f:
                        arrays = json.load(f).get(
                            "payload", {}).get("arrays", {})
                except (OSError, ValueError):
                    continue
                for name, m in arrays.items():
                    if m.get("sha256") != sha:
                        continue
                    try:
                        with open(os.path.join(gen_dir, name + ".npy"),
                                  "rb") as f:
                            data = f.read()
                    except OSError:
                        continue
                    if hashlib.sha256(data).hexdigest() != sha:
                        continue  # the generation's copy rotted too
                    # re-adopt: future mmaps/reads see the good bytes
                    self._write_atomic(self.blob_path(sha), data)
                    self._event({"kind": "cold_blob", "sha256": sha,
                                 "recovered": True,
                                 "source": os.path.join(gen, name)})
                    return np.load(io.BytesIO(data), allow_pickle=False)
        self._event({"kind": "cold_blob", "sha256": sha, "recovered": False})
        raise ColdError(
            f"cold blob {sha[:12]} failed verification and no checkpoint "
            f"generation under {dirs!r} records it")


_DEFAULT_STORE: Optional[ColdStore] = None
_DEFAULT_LOCK = threading.Lock()


def default_store() -> ColdStore:
    """Process-scoped fallback store (``DBSP_TPU_COLD_DIR`` or a temp
    directory) for spines/handles given budgets but no explicit store."""
    global _DEFAULT_STORE
    with _DEFAULT_LOCK:
        if _DEFAULT_STORE is None:
            path = COLD_DIR or os.path.join(
                tempfile.gettempdir(), f"dbsp-tpu-cold-{os.getpid()}")
            _DEFAULT_STORE = ColdStore(path)
        return _DEFAULT_STORE


# ---------------------------------------------------------------------------
# batch <-> disk
# ---------------------------------------------------------------------------


def demote_batch_to_disk(b: Batch, store: ColdStore
                         ) -> Tuple[Batch, dict]:
    """Write a batch's columns into the store and return (memmap-backed
    batch, blob metadata). The metadata is checkpoint-manifest-compatible
    per column (``keys``/``vals``/``weights`` lists of
    ``{"sha256", "bytes"}``) plus the batch's sorted-run aux. The blobs
    are RETAINED — the owner must :meth:`ColdStore.release` the meta when
    the level leaves the disk tier."""
    meta = {"keys": [store.put_array(c) for c in b.keys],
            "vals": [store.put_array(c) for c in b.vals],
            "weights": store.put_array(b.weights),
            "runs": list(b.runs) if b.runs is not None else None}
    store.retain(meta)
    return disk_batch(meta, store), meta


def meta_from_batch(b: Batch) -> dict:
    """Reconstruct a disk batch's blob metadata from its memmap filenames
    — the store is content-addressed, so the filename IS the expected
    digest. This is the verified-fault fallback when bookkeeping went
    stale (a restored overflow snapshot's cold level can outlive the
    ``_cold_meta`` entry that described it): faulting through the
    reconstructed meta still verifies against the content hash, where a
    raw memmap read would launder corruption."""

    def m(c):
        path = getattr(c, "filename", None)
        if not path or not path.endswith(".npy"):
            raise ColdError("not a blob-backed memmap batch")
        sha = os.path.basename(path)[:-4]
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = -1  # missing: read_verified goes straight to recovery
        return {"sha256": sha, "bytes": nbytes}

    return {"keys": [m(c) for c in b.keys],
            "vals": [m(c) for c in b.vals],
            "weights": m(b.weights),
            "runs": list(b.runs) if b.runs is not None else None}


def disk_batch(meta: dict, store: ColdStore) -> Batch:
    """Rehydrate a disk-tier batch as memmap views (lazy, unverified —
    see :meth:`ColdStore.mmap`)."""
    runs = tuple(meta["runs"]) if meta.get("runs") is not None else None
    return Batch(tuple(store.mmap(m) for m in meta["keys"]),
                 tuple(store.mmap(m) for m in meta["vals"]),
                 store.mmap(meta["weights"]), runs)


def fault_batch(meta: dict, store: ColdStore) -> Batch:
    """Promote a disk-tier batch to host: verified read of every column
    (the corruption-detection point; raises :class:`ColdError` only when
    recovery from checkpoint generations also fails)."""
    runs = tuple(meta["runs"]) if meta.get("runs") is not None else None
    return Batch(tuple(store.read_verified(m) for m in meta["keys"]),
                 tuple(store.read_verified(m) for m in meta["vals"]),
                 store.read_verified(meta["weights"]), runs)


# ---------------------------------------------------------------------------
# engine wiring (the one config point)
# ---------------------------------------------------------------------------


def circuit_spines(circuit) -> list:
    """Every Spine held by a circuit's operators (incl. nested children).

    Walks ALL instance attributes (plus one level of list/tuple/dict
    containers), not a fixed attr-name list: nested/recursive operators
    hold spines under names like ``prev_a``/``cur_b`` (operators/
    nested_ops.py) and lineage taps under ``lineage_tap`` — a budget (or
    an explicit disable) that silently skipped those would leave their
    levels un-governed, and the checkpoint save's verify pass would miss
    their disk tiers."""
    from dbsp_tpu.trace.spine import Spine

    out = []
    seen = set()

    def add(sp):
        if isinstance(sp, Spine) and id(sp) not in seen:
            seen.add(id(sp))
            out.append(sp)

    def walk(c):
        for node in c.nodes:
            for val in vars(node.operator).values():
                add(val)
                if isinstance(val, (list, tuple)):
                    for v in val:
                        add(v)
                elif isinstance(val, dict):
                    for v in val.values():
                        add(v)
            if node.child is not None:
                walk(node.child)

    walk(circuit)
    return out


def summary(driver) -> Optional[dict]:
    """One JSON-safe residency digest for a driver (either engine):
    per-tier resident rows, the configured budgets, and the cumulative
    transition count — the ``/status`` surface. None when no budget is
    configured and nothing ever demoted (the common unbudgeted case
    stays noise-free)."""
    ch = getattr(driver, "ch", None)
    if ch is not None and hasattr(ch, "tier_rows"):
        cfg = getattr(ch, "residency_cfg", None)
        if (cfg is None or not cfg.active) and not ch._tiers:
            return None
        return {"tier_rows": {k: int(v) for k, v in ch.tier_rows().items()},
                "device_rows_budget": cfg.device_rows if cfg else None,
                "host_rows_budget": cfg.host_rows if cfg else None,
                "transitions": int(sum(ch.residency_stats.values())),
                "cold_blob_events": len(getattr(ch, "cold_events", ()))}
    circuit = getattr(driver, "circuit", None)
    if circuit is None:
        return None
    spines = circuit_spines(circuit)
    budgeted = [sp for sp in spines
                if sp.device_budget_rows is not None
                or sp.host_budget_rows is not None]
    if not budgeted:
        return None
    tiers = {TIER_DEVICE: 0, TIER_HOST: 0, TIER_DISK: 0}
    transitions = 0
    for sp in spines:
        for k, v in sp.tier_rows().items():
            tiers[k] += v
        transitions += sum(sp.residency_stats.values())
    return {"tier_rows": tiers,
            "device_rows_budget": budgeted[0].device_budget_rows,
            "host_rows_budget": budgeted[0].host_budget_rows,
            "transitions": int(transitions)}


def apply_to_driver(driver, cfg: ResidencyConfig) -> None:
    """Route one residency config into whichever engine ``driver`` runs —
    the compiled handle's budget enforcement or every host spine's. This
    is the build_controller hook that makes the pipeline-config keys
    (``device_rows``/``host_rows``/``cold_dir``) ACTUALLY honored on both
    engines (an allowlist-accepted-but-ignored key is the silent failure
    the allowlist exists to prevent — the PR-10 lesson)."""
    ch = getattr(driver, "ch", None)
    if ch is not None and hasattr(ch, "set_residency"):
        ch.set_residency(cfg)
        return
    circuit = getattr(driver, "circuit", None)
    if circuit is None:
        return
    # the store is only materialized (mkdir) for ACTIVE budgets — an
    # inactive config must still be applied (it may be DISABLING env
    # knobs) but should leave no empty directories behind
    store = ColdStore(cfg.cold_dir) if cfg.cold_dir and cfg.active \
        else None
    for sp in circuit_spines(circuit):
        sp.device_budget_rows = cfg.device_rows
        sp.host_budget_rows = cfg.host_rows
        if store is not None:
            sp.cold_store = store
