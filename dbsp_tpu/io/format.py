"""Data formats: parsers (bytes -> weighted rows) and encoders (batches ->
bytes).

Reference: ``adapters/src/lib.rs:91-101`` (InputFormat/Parser/OutputFormat/
Encoder traits) and the CSV implementation (``adapters/src/format/csv.rs``).
JSON here is newline-delimited with explicit insert/delete envelopes, which
the reference gained later; CSV rows are inserts with an optional trailing
weight column.
"""

from __future__ import annotations

import csv
import io
import json
from typing import Iterable, List, Sequence, Tuple

import numpy as np

from dbsp_tpu.zset.batch import Batch, Row

WeightedRow = Tuple[Row, int]


class Parser:
    """Incremental parser: feed chunks, take parsed weighted rows."""

    def feed(self, chunk: bytes) -> None:
        raise NotImplementedError

    def take(self) -> List[WeightedRow]:
        raise NotImplementedError

    def eoi(self) -> None:
        """End of input: flush any buffered partial record."""


class _LineParser(Parser):
    def __init__(self):
        self._buf = b""
        self._rows: List[WeightedRow] = []

    def feed(self, chunk: bytes) -> None:
        self._buf += chunk
        *lines, self._buf = self._buf.split(b"\n")
        for line in lines:
            line = line.strip()
            if line:
                self._parse_line(line.decode())

    def eoi(self) -> None:
        if self._buf.strip():
            self._parse_line(self._buf.decode())
            self._buf = b""

    def take(self) -> List[WeightedRow]:
        rows, self._rows = self._rows, []
        return rows

    def _parse_line(self, line: str) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


def _coerce(values: Sequence[str], dtypes) -> Row:
    out = []
    for v, d in zip(values, dtypes):
        out.append(float(v) if np.issubdtype(np.dtype(d), np.floating)
                   else int(v))
    return tuple(out)


class CsvParser(_LineParser):
    """One record per line; columns ordered (keys..., vals...[, weight])."""

    def __init__(self, dtypes: Sequence):
        super().__init__()
        self.dtypes = tuple(dtypes)

    def _parse_line(self, line: str) -> None:
        fields = next(csv.reader([line]))
        n = len(self.dtypes)
        if len(fields) == n + 1:
            w = int(fields[n])
        elif len(fields) == n:
            w = 1
        else:
            raise ValueError(
                f"CSV record has {len(fields)} fields, schema has {n}")
        self._rows.append((_coerce(fields[:n], self.dtypes), w))


class JsonParser(_LineParser):
    """NDJSON with envelopes: {"insert": [..cols..]} or {"delete": [...]};
    a bare array is an insert."""

    def __init__(self, dtypes: Sequence):
        super().__init__()
        self.dtypes = tuple(dtypes)

    def _parse_line(self, line: str) -> None:
        obj = json.loads(line)
        if isinstance(obj, dict):
            if "insert" in obj:
                row, w = obj["insert"], 1
            elif "delete" in obj:
                row, w = obj["delete"], -1
            else:
                raise ValueError(f"JSON record needs insert/delete: {line}")
        else:
            row, w = obj, 1
        if len(row) != len(self.dtypes):
            raise ValueError(
                f"JSON record has {len(row)} fields, schema has "
                f"{len(self.dtypes)}")
        # coerce to schema dtypes NOW so type errors surface at the parse
        # boundary (HTTP 400 / endpoint error), not inside the circuit thread
        self._rows.append((_coerce(row, self.dtypes), w))


class Encoder:
    def encode(self, batch: Batch) -> bytes:
        raise NotImplementedError


class CsvEncoder(Encoder):
    def encode(self, batch: Batch) -> bytes:
        out = io.StringIO()
        writer = csv.writer(out, lineterminator="\n")
        for row, w in sorted(batch.to_dict().items()):
            writer.writerow([*row, w])
        return out.getvalue().encode()


class JsonEncoder(Encoder):
    def encode(self, batch: Batch) -> bytes:
        lines = []
        for row, w in sorted(batch.to_dict().items()):
            env = "insert" if w > 0 else "delete"
            for _ in range(abs(w)):
                lines.append(json.dumps({env: list(row)}))
        return ("\n".join(lines) + "\n").encode() if lines else b""


INPUT_FORMATS = {"csv": CsvParser, "json": JsonParser}
OUTPUT_FORMATS = {"csv": CsvEncoder, "json": JsonEncoder}
