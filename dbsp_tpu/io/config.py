"""Declarative pipeline configuration: YAML/JSON/dict -> controller wiring.

Reference: ``adapters/src/controller/config.rs:28-131`` — ``PipelineConfig``
with named input/output endpoint configs, each naming a transport and a
format, deserialized from YAML by the pipeline manager. Same shape here:

    min_batch_records: 500            # ControllerConfig fields (optional)
    flush_interval_s: 0.1
    inputs:
      prices_in:
        stream: bids                  # catalog collection to feed
        transport:
          name: file_input            # registry key (see TRANSPORTS)
          config: { path: bids.csv, follow: false }
        format: csv                   # csv | json
    outputs:
      counts_out:
        stream: by_auction
        transport: { name: kafka_output,
                     config: { brokers: "mini://127.0.0.1:9092",
                               topic: counts } }
        format: json

``build_controller(handle, catalog, cfg)`` constructs the controller and
attaches every endpoint; ``attach_endpoints(controller, cfg)`` wires an
existing one (the manager's deploy path). ``cfg`` may be a dict, a YAML/JSON
string, or a path to a ``.yaml``/``.json`` file.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict

from dbsp_tpu.io.controller import Controller, ControllerConfig
from dbsp_tpu.io.transport import (FileInputTransport, FileOutputTransport,
                                   KafkaInputTransport, KafkaOutputTransport)


class ConfigError(ValueError):
    pass


# transport registries: name -> ctor(config_dict) (config.rs's adapter
# factory registry, lib.rs:74-90)
INPUT_TRANSPORTS: Dict[str, Callable] = {
    "file_input": lambda c: FileInputTransport(
        c["path"], chunk_size=int(c.get("chunk_size", 1 << 16)),
        follow=bool(c.get("follow", False))),
    "kafka_input": lambda c: KafkaInputTransport(
        c["brokers"], c["topics"] if isinstance(c["topics"], list)
        else [c["topics"]],
        group_id=c.get("group_id", "dbsp_tpu")),
}
OUTPUT_TRANSPORTS: Dict[str, Callable] = {
    "file_output": lambda c: FileOutputTransport(c["path"]),
    "kafka_output": lambda c: KafkaOutputTransport(c["brokers"], c["topic"]),
}


def load_config(cfg) -> dict:
    """Normalize dict | YAML/JSON text | file path to a config dict."""
    if isinstance(cfg, dict):
        return cfg
    if not isinstance(cfg, str):
        raise ConfigError(f"unsupported config object {type(cfg).__name__}")
    text = cfg
    if cfg.endswith((".yaml", ".yml", ".json")) or os.path.exists(cfg):
        # an extension-named (or existing) string is a PATH: a missing file
        # is a config error with the path in it, not inline text fed to the
        # YAML parser
        try:
            with open(cfg) as f:
                text = f.read()
        except OSError as e:
            raise ConfigError(f"cannot read config file {cfg!r}: {e}") from e
    try:
        import yaml  # YAML is a JSON superset: one parser covers both

        out = yaml.safe_load(text)
    except ImportError:  # pragma: no cover — pyyaml is baked in
        out = json.loads(text)
    if not isinstance(out, dict):
        raise ConfigError("pipeline config must be a mapping")
    return out


def controller_config(cfg: dict) -> ControllerConfig:
    """The ControllerConfig subset of a pipeline config dict. Unknown
    top-level scalar keys are REJECTED (a typo'd tuning knob silently
    applied as the default is worse than an error)."""
    fields = {f.name for f in dataclasses.fields(ControllerConfig)}
    known_sections = {"inputs", "outputs", "name", "workers", "description",
                      "slo",  # watchdog objectives (obs/slo.py)
                      "lineage_taps"}  # raw-input provenance (obs/lineage.py)
    unknown = set(cfg) - fields - known_sections
    if unknown:
        raise ConfigError(
            f"unknown pipeline config keys {sorted(unknown)} "
            f"(tuning knobs: {sorted(fields)})")
    kwargs = {k: v for k, v in cfg.items() if k in fields}
    return ControllerConfig(**kwargs)


def _endpoint(section: str, registry: Dict[str, Callable], formats,
              name: str, spec: dict):
    if "stream" not in spec:
        raise ConfigError(f"{section} endpoint {name!r} needs a 'stream'")
    t = spec.get("transport")
    if not isinstance(t, dict) or "name" not in t:
        raise ConfigError(
            f"{section} endpoint {name!r} needs transport: {{name, config}}")
    if t["name"] not in registry:
        raise ConfigError(
            f"{section} endpoint {name!r}: unknown transport {t['name']!r} "
            f"(have {sorted(registry)})")
    fmt = spec.get("format", "csv")
    if fmt not in formats:
        raise ConfigError(
            f"{section} endpoint {name!r}: unknown format {fmt!r} "
            f"(have {sorted(formats)})")
    transport = registry[t["name"]](t.get("config", {}))
    return spec["stream"], transport, fmt


def attach_endpoints(controller: Controller, cfg) -> None:
    """Wire every configured endpoint onto an existing controller.

    Two phases: RESOLVE everything (unknown transports/formats/streams fail
    before any side effect), then attach — attaching starts input reader
    threads, and a validation error after a started tail-follow thread
    would leak it forever."""
    from dbsp_tpu.io.format import INPUT_FORMATS, OUTPUT_FORMATS

    cfg = load_config(cfg)
    ins, outs = [], []
    for name, spec in (cfg.get("inputs") or {}).items():
        stream, transport, fmt = _endpoint("input", INPUT_TRANSPORTS,
                                           INPUT_FORMATS, name, spec)
        try:
            controller.catalog.input(stream)
        except KeyError:
            raise ConfigError(
                f"input endpoint {name!r}: unknown stream {stream!r}")
        ins.append((name, stream, transport, fmt))
    for name, spec in (cfg.get("outputs") or {}).items():
        stream, transport, fmt = _endpoint("output", OUTPUT_TRANSPORTS,
                                           OUTPUT_FORMATS, name, spec)
        try:
            controller.catalog.output(stream)
        except KeyError:
            raise ConfigError(
                f"output endpoint {name!r}: unknown stream {stream!r}")
        outs.append((name, stream, transport, fmt))
    for name, stream, transport, fmt in ins:
        controller.add_input_endpoint(name, stream, transport, fmt=fmt)
    for name, stream, transport, fmt in outs:
        controller.add_output_endpoint(name, stream, transport, fmt=fmt)


def build_controller(handle, catalog, cfg) -> Controller:
    """Controller + endpoints from one declarative config."""
    cfg = load_config(cfg)
    ctl = Controller(handle, catalog, controller_config(cfg))
    # opt-in lineage taps honored HERE, not only on the manager deploy
    # path — a key the allowlist accepts but nothing applies is exactly
    # the silent failure controller_config's rejection exists to prevent
    # (enable_taps is idempotent; the manager path also calls it)
    circuit = getattr(handle, "circuit", None)
    if circuit is not None:
        from dbsp_tpu.obs import lineage

        if lineage.taps_env_enabled(cfg):
            lineage.enable_taps(circuit)
    attach_endpoints(ctl, cfg)
    return ctl
