"""Transports: byte sources/sinks feeding parsers/encoders.

Reference: ``adapters/src/lib.rs:74-90`` (factory traits) and the file /
Kafka / HTTP implementations under ``adapters/src/transport/``.

Kafka speaks through an installed client library (``confluent_kafka`` or
``kafka-python``) against real brokers, or — selected by a ``mini://``
address — through the in-repo broker/client (``io/minikafka.py``), which is
how the poll-thread -> parser -> controller wiring and the producer flush
path run for real in this environment's tests (reference CI does the same
against a containerized broker, ``adapters/src/test/kafka.rs:23-31``).
HTTP input/output endpoints live on the circuit server (``io/server.py``),
matching the reference's embedded HTTP transport.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

ChunkCallback = Callable[[bytes], None]


class InputTransport:
    name = "input"

    #: True when a restarted transport re-delivers its stream FROM THE
    #: BEGINNING (file reads). Restore-on-deploy then skips the
    #: checkpointed consumed-row prefix (Controller.restore_from) so the
    #: replay is exactly-once; position-keeping transports (broker
    #: consumer groups) leave this False and resume server-side.
    replays_from_start = False

    def start(self, on_chunk: ChunkCallback, on_eoi: Callable[[], None]) -> None:
        raise NotImplementedError

    def pause(self) -> None:
        """Backpressure hook: stop producing chunks until resume()."""

    def resume(self) -> None:
        pass

    def stop(self) -> None:
        pass


class OutputTransport:
    name = "output"

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


class FileInputTransport(InputTransport):
    """Streams a file in chunks on a reader thread; optional tail-follow."""

    name = "file_input"
    replays_from_start = True  # re-reads from byte 0 on every (re)start

    def __init__(self, path: str, chunk_size: int = 1 << 16,
                 follow: bool = False):
        self.path = path
        self.chunk_size = chunk_size
        self.follow = follow
        self._paused = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _tsan_hook(self)

    def start(self, on_chunk, on_eoi) -> None:
        def run():
            with open(self.path, "rb") as f:
                while not self._stop.is_set():
                    while self._paused.is_set() and not self._stop.is_set():
                        time.sleep(0.01)
                    chunk = f.read(self.chunk_size)
                    if chunk:
                        on_chunk(chunk)
                    elif self.follow:
                        time.sleep(0.05)
                    else:
                        break
            on_eoi()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"file-input-{self.path}")
        self._thread.start()

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout=None) -> None:
        if self._thread:
            self._thread.join(timeout)


class FileOutputTransport(OutputTransport):
    name = "file_output"

    def __init__(self, path: str):
        self._f = open(path, "ab")
        self._lock = threading.Lock()
        _tsan_hook(self)

    def write(self, data: bytes) -> None:
        with self._lock:
            self._f.write(data)

    def flush(self) -> None:
        with self._lock:
            self._f.flush()


def _kafka_client(brokers: str = ""):
    if brokers.startswith("mini://"):
        # in-repo broker/client (io/minikafka.py): same consumer/producer
        # call surface as kafka-python, selected by address scheme so the
        # transport wiring below runs for real without a Kafka install
        from dbsp_tpu.io import minikafka

        return ("kafka-python", minikafka)
    try:
        import confluent_kafka  # type: ignore

        return ("confluent", confluent_kafka)
    except ImportError:
        pass
    try:
        import kafka  # type: ignore

        return ("kafka-python", kafka)
    except ImportError:
        return None


class KafkaInputTransport(InputTransport):
    """Consumes topics and feeds message payloads to the parser (reference:
    adapters/src/transport/kafka/input.rs). Requires a Kafka client lib."""

    name = "kafka_input"

    def __init__(self, brokers: str, topics, group_id: str = "dbsp_tpu",
                 poll_timeout: float = 0.5):
        client = _kafka_client(brokers)
        if client is None:
            raise RuntimeError(
                "Kafka transport needs confluent_kafka or kafka-python "
                "installed; neither is available in this environment")
        self._kind, self._mod = client
        self.brokers = brokers
        self.topics = list(topics)
        self.group_id = group_id
        self.poll_timeout = poll_timeout
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._consumer = None
        self._retry_cfg: dict = {}
        self.error: str | None = None  # terminal transport failure, if any
        _tsan_hook(self)

    def configure_retry(self, timeout_s: float = 10.0, retries: int = 5,
                        backoff_s: float = 0.05) -> None:
        """Controller-config knobs (ControllerConfig.transport_*): applied
        to the underlying connection at/after consumer construction."""
        self._retry_cfg = {"timeout_s": timeout_s, "retries": retries,
                           "backoff_s": backoff_s}
        conn = getattr(self._consumer, "conn", None)
        if conn is not None and hasattr(conn, "configure_retry"):
            conn.configure_retry(**self._retry_cfg)

    @property
    def retries(self) -> int:
        """Transport-level retries performed (mini client); 0 for client
        libraries that retry internally."""
        return getattr(self._consumer, "retries", 0)

    def start(self, on_chunk, on_eoi) -> None:
        if self._kind == "confluent":
            consumer = self._mod.Consumer({
                "bootstrap.servers": self.brokers,
                "group.id": self.group_id,
                "auto.offset.reset": "earliest",
            })
            consumer.subscribe(self.topics)
            self._consumer = consumer

            def run():
                while not self._stop.is_set():
                    if self._paused.is_set():
                        time.sleep(0.05)
                        continue
                    msg = consumer.poll(self.poll_timeout)
                    if msg is not None and msg.error() is None:
                        on_chunk(msg.value() + b"\n")
                consumer.close()
                on_eoi()
        else:
            consumer = self._mod.KafkaConsumer(
                *self.topics, bootstrap_servers=self.brokers,
                group_id=self.group_id, auto_offset_reset="earliest")
            self._consumer = consumer
            if self._retry_cfg and hasattr(
                    getattr(consumer, "conn", None), "configure_retry"):
                consumer.conn.configure_retry(**self._retry_cfg)

            def run():
                while not self._stop.is_set():
                    if self._paused.is_set():
                        time.sleep(0.05)
                        continue
                    try:
                        polled = consumer.poll(
                            timeout_ms=int(self.poll_timeout * 1000))
                    except (ConnectionError, OSError) as e:
                        # dead broker past the retry budget: TERMINATE the
                        # endpoint (error + eoi -> controller sees a
                        # degraded pipeline) instead of hanging the reader
                        # thread in an unbounded reconnect loop
                        self.error = f"{type(e).__name__}: {e}"
                        break
                    for records in polled.values():
                        for r in records:
                            on_chunk(r.value + b"\n")
                try:
                    consumer.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                on_eoi()

        threading.Thread(target=run, daemon=True, name="kafka-input").start()

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def stop(self) -> None:
        self._stop.set()


class KafkaOutputTransport(OutputTransport):
    name = "kafka_output"

    def __init__(self, brokers: str, topic: str):
        client = _kafka_client(brokers)
        if client is None:
            raise RuntimeError(
                "Kafka transport needs confluent_kafka or kafka-python "
                "installed; neither is available in this environment")
        self._kind, self._mod = client
        self.topic = topic
        if self._kind == "confluent":
            self._producer = self._mod.Producer(
                {"bootstrap.servers": brokers})
        else:
            self._producer = self._mod.KafkaProducer(bootstrap_servers=brokers)
        _tsan_hook(self)

    def configure_retry(self, timeout_s: float = 10.0, retries: int = 5,
                        backoff_s: float = 0.05) -> None:
        """Controller-config knobs — bound the SYNCHRONOUS per-write stall
        a dead output broker can inflict on the circuit thread."""
        conn = getattr(self._producer, "conn", None)
        if conn is not None and hasattr(conn, "configure_retry"):
            conn.configure_retry(timeout_s=timeout_s, retries=retries,
                                 backoff_s=backoff_s)

    @property
    def retries(self) -> int:
        return getattr(self._producer, "retries", 0)

    def write(self, data: bytes) -> None:
        for line in data.splitlines():
            if not line:
                continue
            if self._kind == "confluent":
                self._producer.produce(self.topic, line)
            else:
                self._producer.send(self.topic, line)

    def flush(self) -> None:
        self._producer.flush()
