"""Catalog: named, schema'd registry of a circuit's input/output handles.

Reference: ``adapters/src/catalog.rs:15`` plus the serde bridge
(``DeCollectionHandle``, adapters/src/deinput.rs:128, and ``SerBatch``,
seroutput.rs:14): the untyped boundary where parsers push rows into typed
handles and encoders read batches out.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from dbsp_tpu.operators.io_handles import InputHandle, OutputHandle
from dbsp_tpu.io.format import WeightedRow


@dataclasses.dataclass
class InputCollection:
    name: str
    handle: InputHandle
    dtypes: Tuple  # (key..., val...) column dtypes, parser order

    def push_rows(self, rows: List[WeightedRow]) -> int:
        self.handle.extend(rows)
        return len(rows)


@dataclasses.dataclass
class OutputCollection:
    name: str
    handle: OutputHandle
    dtypes: Tuple


class Catalog:
    def __init__(self):
        self.inputs: Dict[str, InputCollection] = {}
        self.outputs: Dict[str, OutputCollection] = {}

    def register_input(self, name: str, handle: InputHandle,
                       dtypes: Sequence) -> None:
        if name in self.inputs:
            raise ValueError(f"duplicate input {name}")
        self.inputs[name] = InputCollection(name, handle, tuple(dtypes))

    def register_output(self, name: str, handle: OutputHandle,
                        dtypes: Sequence) -> None:
        if name in self.outputs:
            raise ValueError(f"duplicate output {name}")
        self.outputs[name] = OutputCollection(name, handle, tuple(dtypes))

    def input(self, name: str) -> InputCollection:
        if name not in self.inputs:
            raise KeyError(
                f"unknown input collection {name!r}; have {sorted(self.inputs)}")
        return self.inputs[name]

    def output(self, name: str) -> OutputCollection:
        if name not in self.outputs:
            raise KeyError(
                f"unknown output collection {name!r}; have {sorted(self.outputs)}")
        return self.outputs[name]
