"""Per-pipeline HTTP server: control, stats, metrics, data endpoints.

Reference: ``adapters/src/server/mod.rs:250-378`` — the actix service every
compiled pipeline embeds: /start /pause /shutdown /status /stats /metrics
/dump_profile plus push/pull data endpoints /input_endpoint/{name} and
/output_endpoint/{name} — and the Prometheus export
(``server/prometheus.rs``). stdlib ThreadingHTTPServer; no web framework.
"""

from __future__ import annotations

import json
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from dbsp_tpu.io.controller import Controller
from dbsp_tpu.io.format import INPUT_FORMATS, OUTPUT_FORMATS
from dbsp_tpu.obs import export as obs_export
from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook


class CircuitServer:
    def __init__(self, controller: Controller, host: str = "127.0.0.1",
                 port: int = 0, profiler=None, obs=None, findings=None):
        self.controller = controller
        self.profiler = profiler
        # obs: an obs.PipelineObs bundle — /metrics serves its registry
        # (plus the legacy names) and /trace its Chrome-trace span window
        self.obs = obs
        # Static-analysis gate (dbsp_tpu/analysis): ERROR findings refuse
        # to serve; WARNs are logged/counted and exposed at /analysis.
        # Callers that already verified (the manager) pass their findings
        # so the analyzer runs — and counts metrics — exactly once.
        if findings is None:
            circuit = getattr(controller.handle, "circuit", None)
            if circuit is not None:
                from dbsp_tpu.analysis import verify_circuit

                hh = getattr(controller.handle, "host_handle",
                             controller.handle)
                runtime = getattr(hh, "runtime", None)
                findings = verify_circuit(
                    circuit,
                    workers=getattr(runtime, "workers", 1),
                    registry=obs.registry if obs is not None else None)
        self.analysis_findings = findings or []
        # last served /profile and /lineage reports (for /debug)
        self._last_profile: Optional[dict] = None
        self._last_lineage: Optional[dict] = None
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, body: bytes,
                       ctype="application/json", headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                # the manager's console (another port) fetches these routes
                self.send_header("Access-Control-Allow-Origin", "*")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_OPTIONS(self):  # CORS preflight for the console
                self.send_response(204)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Access-Control-Allow-Methods",
                                 "GET, POST, OPTIONS")
                self.send_header("Access-Control-Allow-Headers",
                                 "Content-Type")
                self.end_headers()

            def _json(self, obj, code=200, headers=None):
                self._reply(code, json.dumps(obj).encode(),
                            headers=headers)

            def do_GET(self):
                url = urlparse(self.path)
                route = url.path.rstrip("/")
                c = server.controller
                if route == "/status":
                    self._json(server.status_dict())
                elif route == "/flight":
                    if server.obs is None:
                        self._json({"error": "flight recorder not "
                                             "enabled"}, 400)
                    else:
                        server.obs.watch()
                        qs = parse_qs(url.query)
                        limit = int(qs["n"][0]) if "n" in qs else None
                        self._json(server.obs.flight.to_dict(limit=limit))
                elif route == "/timeline":
                    # the unified per-tick timeline (obs/timeline.py):
                    # tick latency + flight events + freshness + incidents
                    # in one time-indexed ring. Quiesce-free: one watch()
                    # pass folds fresh flight events in, then the read is
                    # a ring snapshot under the timeline's own lock — the
                    # step lock is never taken on this path.
                    if server.obs is None:
                        self._json({"error": "timeline not enabled"}, 400)
                    else:
                        server.obs.watch()
                        qs = parse_qs(url.query)
                        since = int(qs["since"][0]) if "since" in qs else 0
                        view = qs["view"][0] if "view" in qs else None
                        limit = int(qs["n"][0]) if "n" in qs else None
                        self._json(server.obs.timeline.to_dict(
                            since=since, view=view, limit=limit))
                elif route == "/spikes":
                    # EXPLAIN SPIKE: outlier ticks vs the robust rolling
                    # baseline, each with ranked co-timed evidence. Same
                    # quiesce-free read discipline as /timeline.
                    if server.obs is None:
                        self._json({"error": "timeline not enabled"}, 400)
                    else:
                        server.obs.watch()
                        qs = parse_qs(url.query)
                        limit = int(qs["n"][0]) if "n" in qs else None
                        self._json(server.obs.timeline.explain_spikes(
                            limit=limit))
                elif route == "/incidents":
                    if server.obs is None:
                        self._json({"error": "SLO watchdog not enabled"},
                                   400)
                    else:
                        server.obs.watch()
                        qs = parse_qs(url.query)
                        full = qs.get("window", ["1"])[0] != "0"
                        self._json({
                            "status": server.obs.slo.status_dict(),
                            "incidents": server.obs.slo.incidents(
                                with_window=full)})
                elif route == "/stats":
                    self._json(c.stats())
                elif route == "/metrics":
                    self._reply(200, server.prometheus().encode(),
                                obs_export.CONTENT_TYPE)
                elif route == "/analysis":
                    self._json([f.to_dict()
                                for f in server.analysis_findings])
                elif route == "/trace":
                    if server.obs is None:
                        self._json({"error": "tracing not enabled"}, 400)
                    else:
                        self._reply(200,
                                    server.obs.spans.to_json().encode())
                elif route == "/dump_profile":
                    if server.profiler is None:
                        self._json({"error": "profiler not enabled"}, 400)
                    else:
                        self._reply(200, server.profiler.dump_json().encode())
                elif route == "/profile":
                    # operator-level EXPLAIN ANALYZE — the shared report
                    # schema both engines emit (obs/opprofile.py). ?ticks=N
                    # arms the compiled MEASURED mode (segmented per-node
                    # timing, bit-identity asserted, engine rewound);
                    # ?format=dot renders graphviz like the reference's
                    # dump_profile.
                    if server.profiler is None:
                        return self._json({"error": "profiler not enabled"},
                                          400)
                    from dbsp_tpu.obs.opprofile import (ProfileDivergence,
                                                        report_dot)

                    qs = parse_qs(url.query)
                    ticks = int(qs["ticks"][0]) if "ticks" in qs else None
                    try:
                        report = server.profile_report(ticks=ticks)
                    except ProfileDivergence as e:
                        # segmented != fused is a real engine bug — a 500,
                        # never silently degraded
                        return self._json(
                            {"error": f"ProfileDivergence: {e}"}, 500)
                    except Exception as e:  # noqa: BLE001 — API error
                        return self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 400)
                    if qs.get("format", ["json"])[0] == "dot":
                        self._reply(200, report_dot(report).encode(),
                                    "text/vnd.graphviz")
                    else:
                        self._json(report)
                elif route == "/lineage":
                    # row-level lineage (EXPLAIN WHY, obs/lineage.py):
                    # backward provenance slice of one output row —
                    # ?view=<output>&key=<csv> [&n=<rows/hop>]
                    # [&format=dot]; read-only, quiesced under the
                    # controller step lock.
                    from dbsp_tpu.obs import lineage as _lineage

                    code, payload, dot = _lineage.http_query(
                        server.lineage_report, parse_qs(url.query))
                    if dot:
                        self._reply(code, payload.encode(),
                                    "text/vnd.graphviz")
                    else:
                        self._json(payload, code)
                elif route == "/debug":
                    # the one-shot diagnostics bundle — "attach this to
                    # the bug report": status + SLO + incidents + flight
                    # summary + last profile/lineage + analysis findings,
                    # composed purely from the existing surfaces
                    self._json(server.debug_bundle())
                elif route.startswith("/view/"):
                    # point/range/scan read against the PUBLISHED snapshot
                    # (dbsp_tpu/serving.py): ?key=k1[,k2..] | ?lo=&hi= |
                    # no params = full scan; &limit=N caps rows. Lock-free
                    # like /timeline: resolves the current epoch's
                    # immutable snapshot with one atomic load — the step
                    # lock and quiesce() are NEVER taken on this path
                    # (C003). Staleness <= one validation interval. 503
                    # when the plane is off (DBSP_TPU_READPLANE=0).
                    t0 = _time.perf_counter()
                    plane = c.read_plane
                    if not plane.enabled:
                        return self._json(
                            {"error": "read plane disabled "
                                      "(DBSP_TPU_READPLANE=0)"}, 503)
                    name = route.rsplit("/", 1)[1]
                    qs = parse_qs(url.query)
                    try:
                        key = tuple(int(x) for x in
                                    qs["key"][0].split(",")) \
                            if "key" in qs else None
                        lo = int(qs["lo"][0]) if "lo" in qs else None
                        hi = int(qs["hi"][0]) if "hi" in qs else None
                        limit = int(qs["limit"][0]) if "limit" in qs \
                            else None
                        obj = plane.query(name, key=key, lo=lo, hi=hi,
                                          limit=limit)
                    except KeyError:
                        return self._json(
                            {"error": f"unknown view {name!r}; have "
                                      f"{sorted(plane.views())}"}, 404)
                    except ValueError as e:
                        return self._json({"error": str(e)}, 400)
                    plane.note_read(
                        "view_point" if key is not None else
                        "view_range" if (lo is not None or hi is not None)
                        else "view_scan", t0)
                    # e2e attribution: age_s + per-stage breakdown of the
                    # served epoch's delta path, and the trace ids echoed
                    # as a response header for cross-process correlation
                    c.e2e.annotate_read(obj, t0)
                    ids = (obj.get("trace") or {}).get("ids") or ()
                    self._json(obj, headers={"X-Dbsp-Trace":
                                             ",".join(ids)} if ids
                               else None)
                elif route == "/changefeed":
                    # changefeed read with a resume-from-epoch cursor:
                    # ?view=<name>&after=<epoch>[&timeout=<s>][&limit=N].
                    # Long-poll waits on the plane's wakeup condition —
                    # never the step lock (C003); a cursor behind the
                    # ring's retention gets a synthesized full-state
                    # snapshot record first.
                    t0 = _time.perf_counter()
                    plane = c.read_plane
                    if not plane.enabled:
                        return self._json(
                            {"error": "read plane disabled "
                                      "(DBSP_TPU_READPLANE=0)"}, 503)
                    qs = parse_qs(url.query)
                    if "view" not in qs:
                        return self._json({"error": "?view= required"}, 400)
                    name = qs["view"][0]
                    try:
                        obj = plane.changefeed(
                            name,
                            after_epoch=int(qs.get("after", ["0"])[0]),
                            timeout_s=float(qs.get("timeout", ["0"])[0]),
                            limit=int(qs["limit"][0]) if "limit" in qs
                            else None)
                    except KeyError:
                        return self._json(
                            {"error": f"unknown view {name!r}; have "
                                      f"{sorted(plane.views())}"}, 404)
                    except ValueError as e:
                        return self._json({"error": str(e)}, 400)
                    plane.note_read("changefeed", t0)
                    self._json(obj)
                elif route.startswith("/output_endpoint/"):
                    # Non-destructive sample of the latest emitted batch.
                    # Read plane ON (default): served from the last
                    # PUBLISHED snapshot — one atomic reference load, no
                    # step lock, no quiesce; the served batch is the very
                    # object the controller emitted at the last validation
                    # publish (bit-identical to a quiesced peek) and is at
                    # most ONE VALIDATION INTERVAL stale (host engine: one
                    # step). Read plane OFF (DBSP_TPU_READPLANE=0, the A/B
                    # control): the historical quiesced read — step lock
                    # held, open interval flushed, then peek.
                    # The X-Dbsp-Step tick id lets pollers dedup repeats
                    # (the same batch is re-served until the next publish).
                    t0 = _time.perf_counter()
                    name = route.rsplit("/", 1)[1]
                    try:
                        col = c.catalog.output(name)
                    except KeyError as e:
                        return self._json({"error": str(e)}, 404)
                    fmt = parse_qs(url.query).get("format", ["json"])[0]
                    plane = c.read_plane
                    epoch = None
                    if plane.enabled:
                        snap = plane.snapshot(name)
                        step, batch = str(snap.last_step), snap.last_batch
                        epoch = str(snap.epoch)
                    else:
                        with c.quiesce():
                            step = str(col.handle.step_id)
                            batch = col.handle.peek()
                    if batch is None:
                        self.send_response(200)
                        self.send_header("X-Dbsp-Step", step)
                        if epoch is not None:
                            self.send_header("X-Dbsp-Epoch", epoch)
                        self.send_header("Access-Control-Allow-Origin", "*")
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                    else:
                        body = OUTPUT_FORMATS[fmt]().encode(batch)
                        self.send_response(200)
                        self.send_header("X-Dbsp-Step", step)
                        if epoch is not None:
                            self.send_header("X-Dbsp-Epoch", epoch)
                        self.send_header("Access-Control-Allow-Origin", "*")
                        self.send_header("Content-Type", "text/plain")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    plane.note_read("output", t0)
                else:
                    self._json({"error": f"no route {route}"}, 404)

            def do_POST(self):
                url = urlparse(self.path)
                route = url.path.rstrip("/")
                c = server.controller
                if route == "/start":
                    c.start()
                    self._json({"state": c.state})
                elif route == "/pause":
                    c.pause()
                    self._json({"state": c.state})
                elif route == "/shutdown":
                    threading.Thread(target=c.stop, daemon=True).start()
                    self._json({"state": "shutdown"})
                elif route == "/step":
                    c.step()
                    self._json({"steps": c.steps})
                elif route == "/checkpoint":
                    # write one durable checkpoint generation now
                    # (quiesced under the step lock); 400 when no
                    # directory is configured
                    try:
                        info = c.checkpoint()
                    except Exception as e:  # noqa: BLE001 — API error
                        return self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 400)
                    self._json(info)
                elif route.startswith("/input_endpoint/"):
                    name = route.rsplit("/", 1)[1]
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    fmt = parse_qs(url.query).get("format", ["json"])[0]
                    try:
                        col = c.catalog.input(name)
                    except KeyError as e:
                        return self._json({"error": str(e)}, 404)
                    parser = INPUT_FORMATS[fmt](col.dtypes)
                    try:
                        parser.feed(body)
                        parser.eoi()
                        rows = parser.take()
                    except (ValueError, KeyError) as e:
                        return self._json({"error": f"parse error: {e}"}, 400)
                    col.push_rows(rows)
                    # HTTP pushes must wake the circuit loop like transport
                    # rows do — found by the console JS-path test: pushed
                    # rows sat unstepped until an explicit /step.
                    # An X-Dbsp-Trace request header is adopted as the
                    # batch's e2e trace id (cross-process propagation);
                    # otherwise one is minted — either way it is echoed.
                    trace_id = c.note_pushed(
                        len(rows),
                        trace_id=self.headers.get("X-Dbsp-Trace") or None)
                    resp = {"records": len(rows)}
                    if trace_id is not None:
                        resp["trace"] = trace_id
                    self._json(resp, headers={"X-Dbsp-Trace": trace_id}
                               if trace_id else None)
                else:
                    self._json({"error": f"no route {route}"}, 404)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        _tsan_hook(self)

    def status_dict(self) -> dict:
        """The /status body: serving state + mode + SLO health in one
        poll (the compiled->host fallback cliff must be visible here,
        not only in a counter); /debug embeds the same dict."""
        c = self.controller
        out = {"state": c.state,
               "mode": getattr(c.handle, "mode", "host"),
               # durability: the tick recovery would resume from
               # (None = no checkpoint yet/configured)
               "last_checkpoint_tick": getattr(
                   c, "last_checkpoint_tick", None),
               "checkpoints": getattr(c, "checkpoints", 0),
               # freshness: seconds the open deferred-validation interval
               # has been accumulating unpublished ticks (None = closed /
               # host engine, which publishes every step)
               "open_interval_age_s": getattr(
                   c.handle, "open_interval_age_s", None),
               # rows buffered per input endpoint awaiting the next drain
               # (endpoint locks only — never the step lock)
               "input_queue_depths": c.input_queue_depths()}
        ck_err = getattr(c, "checkpoint_error", None)
        if ck_err:
            out["checkpoint_error"] = ck_err
        # tiered trace residency (dbsp_tpu/residency.py): omitted when no
        # budget is configured and nothing ever demoted
        from dbsp_tpu import residency as _res

        rs = _res.summary(c.handle)
        if rs is not None:
            out["residency"] = rs
        if self.obs is not None:
            self.obs.watch()
            out["slo"] = self.obs.slo.status_dict()
            # the watchdog's latched copy, NOT a ring scan: the one-shot
            # deploy-time event ages out of the ring on a long-running
            # pipeline
            fb = self.obs.slo.fallback_reason
            if fb is not None:
                out["fallback_reason"] = fb
        return out

    def lineage_report(self, view: str, key, max_rows=None) -> dict:
        """The ``/lineage`` backward provenance slice, quiesced: holds
        the controller's step lock (no serving tick in flight — the
        compiled provider decodes a snapshot of the live states) and
        flushes any open deferred-validation interval first. Counts the
        gated lineage metrics and records one flight event per query;
        never mutates serving state."""
        from dbsp_tpu.obs import lineage

        kwargs = {} if max_rows is None else {"max_rows": max_rows}
        with self.controller.quiesce():
            report = lineage.slice_pipeline(
                self.controller.handle, self.controller.catalog, view, key,
                **kwargs)
        if self.obs is not None:
            lineage.observe_query(self.obs.registry, self.obs.flight,
                                  report)
        self._last_lineage = report
        return report

    def debug_bundle(self) -> dict:
        """One JSON for the bug report: status, stats, SLO health, the
        captured incidents (summaries), a flight-ring summary, the last
        profile/lineage reports served (None until one ran — composing
        a measured profile here would quiesce the pipeline unasked), and
        the static-analysis findings."""
        c = self.controller
        out = {"status": self.status_dict(),
               "stats": c.stats(),
               "analysis": [f.to_dict() for f in self.analysis_findings],
               "profile": getattr(self, "_last_profile", None),
               "lineage": getattr(self, "_last_lineage", None)}
        if self.obs is not None:
            # status_dict() already ran the watchdog and embedded the SLO
            # dict — alias it rather than polling + serializing it twice
            out["slo"] = out["status"].get("slo")
            out["incidents"] = self.obs.slo.incidents(with_window=False)
            out["flight"] = self.obs.flight.to_dict(limit=64)
            # span-ring drop accounting: a truncated /trace window must
            # announce itself in the bug-report bundle
            dropped = self.obs.spans.dropped_steps
            out["trace"] = {"dropped_steps": dropped,
                            "truncated": dropped > 0}
        return out

    def profile_report(self, ticks=None) -> dict:
        """The unified ``/profile`` report, quiesced: holds the
        controller's step lock (no serving tick in flight — the measured
        mode snapshots, runs hypothetical ticks, and rewinds) and flushes
        any open deferred-validation interval first. Spans land operator
        slices in the existing ``/trace`` window; the registry receives
        the gated per-node metric families only when a MEASURED profile
        actually runs (opprofile.export_node_metrics)."""
        with self.controller.quiesce():
            report = self.profiler.profile_report(
                ticks=ticks,
                spans=self.obs.spans if self.obs is not None else None,
                registry=self.obs.registry if self.obs is not None else None)
        self._last_profile = report  # /debug embeds the last served report
        return report

    def prometheus(self) -> str:
        """The /metrics payload: the obs registry's canonical exposition
        (when a PipelineObs is attached) followed by the legacy
        ``dbsp_steps``-era names — scrapers written against either surface
        keep working. All formatting lives in obs/export.py."""
        legacy = obs_export.legacy_controller_lines(self.controller.stats())
        body = "\n".join(legacy) + "\n"
        if self.obs is not None:
            body = obs_export.prometheus_text(self.obs.registry) + body
        return body

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="circuit-http")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
