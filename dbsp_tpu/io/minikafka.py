"""A minimal in-repo message broker + client for exercising the Kafka
transports without a Kafka installation.

The environment bakes no Kafka client library or broker, which would leave
``KafkaInputTransport``/``KafkaOutputTransport`` permanently unexecuted
(reference CI runs them against a real broker —
``adapters/src/test/kafka.rs:23-31``). This module provides the smallest
thing that makes the transport code REAL: a TCP broker with topics,
offsets, and consumer groups, plus a client exposing the exact call surface
the transports use (``MiniConsumer.poll/close``, ``MiniProducer.send/
flush``). Transports select it with a ``mini://host:port`` broker address;
real ``confluent_kafka`` / ``kafka-python`` addresses are untouched.

Protocol: newline-delimited JSON over TCP, payloads base64. One
request/response per line:
    {"op": "produce", "topic": t, "msgs": [b64, ...]}      -> {"ok": true}
    {"op": "fetch", "topic": t, "group": g, "max": n}      -> {"msgs": [...]}
Offsets advance on fetch (at-most-once per group — matching the transport's
auto-commit usage, not the full Kafka contract).
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import threading
from typing import Dict, List, Tuple


class MiniKafkaBroker:
    """Line-JSON TCP broker: topics are append-only lists of byte messages;
    each (topic, group) pair holds a read offset."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.topics: Dict[str, List[bytes]] = {}
        self.offsets: Dict[Tuple[str, str], int] = {}
        self.lock = threading.Lock()
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        resp = broker._handle(req)
                    except Exception as e:  # noqa: BLE001 — report + serve
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.host, self.port = self.server.server_address
        self.address = f"mini://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="minikafka")

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        with self.lock:
            if op == "produce":
                log = self.topics.setdefault(req["topic"], [])
                for m in req["msgs"]:
                    log.append(base64.b64decode(m))
                return {"ok": True, "end_offset": len(log)}
            if op == "fetch":
                log = self.topics.get(req["topic"], [])
                key = (req["topic"], req.get("group", ""))
                at = self.offsets.get(key, 0)
                upto = min(len(log), at + int(req.get("max", 100)))
                msgs = [base64.b64encode(m).decode() for m in log[at:upto]]
                self.offsets[key] = upto
                return {"msgs": msgs, "offset": upto}
            return {"error": f"unknown op {op!r}"}

    def start(self) -> "MiniKafkaBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()


class _Conn:
    """One line-JSON request/response TCP connection."""

    def __init__(self, address: str):
        if not address.startswith("mini://"):
            raise ValueError(
                f"minikafka address must start with 'mini://': {address!r}")
        host, port = address[len("mini://"):].rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=10)
        self.rfile = self.sock.makefile("rb")
        self.lock = threading.Lock()

    def request(self, req: dict) -> dict:
        with self.lock:
            self.sock.sendall(json.dumps(req).encode() + b"\n")
            line = self.rfile.readline()
        if not line:
            raise ConnectionError("minikafka broker closed the connection")
        resp = json.loads(line)
        if resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _Record:
    """Matches the attribute the transports read (kafka-python's record)."""

    __slots__ = ("value",)

    def __init__(self, value: bytes):
        self.value = value


class MiniConsumer:
    """kafka-python-shaped consumer over the mini protocol."""

    def __init__(self, *topics: str, bootstrap_servers: str = "",
                 group_id: str = "dbsp_tpu", **_ignored):
        self.topics = list(topics)
        self.group = group_id
        self.conn = _Conn(bootstrap_servers)

    def poll(self, timeout_ms: int = 500, max_records: int = 500) -> dict:
        """Fetch once per topic; when everything is empty, block up to
        ``timeout_ms`` like kafka-python does — the transport's poll loop
        has no sleep of its own and would otherwise busy-spin a core
        against the broker."""
        import time

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            out = {}
            for t in self.topics:
                resp = self.conn.request({"op": "fetch", "topic": t,
                                          "group": self.group,
                                          "max": max_records})
                if resp["msgs"]:
                    out[t] = [_Record(base64.b64decode(m))
                              for m in resp["msgs"]]
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(min(0.02, timeout_ms / 1000.0))

    def close(self) -> None:
        self.conn.close()


# the names KafkaInputTransport/KafkaOutputTransport construct for the
# "kafka-python" client kind
KafkaConsumer = MiniConsumer


class MiniProducer:
    """kafka-python-shaped producer over the mini protocol."""

    def __init__(self, bootstrap_servers: str = "", **_ignored):
        self.conn = _Conn(bootstrap_servers)
        self._pending: List[Tuple[str, bytes]] = []
        self.lock = threading.Lock()

    def send(self, topic: str, value: bytes) -> None:
        with self.lock:
            self._pending.append((topic, value))

    def flush(self) -> None:
        with self.lock:
            pending, self._pending = self._pending, []
        by_topic: Dict[str, List[bytes]] = {}
        for t, v in pending:
            by_topic.setdefault(t, []).append(v)
        for t, vs in by_topic.items():
            self.conn.request({"op": "produce", "topic": t,
                               "msgs": [base64.b64encode(v).decode()
                                        for v in vs]})

    def close(self) -> None:
        self.conn.close()


KafkaProducer = MiniProducer
