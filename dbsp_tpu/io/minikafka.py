"""A minimal in-repo message broker + client for exercising the Kafka
transports without a Kafka installation.

The environment bakes no Kafka client library or broker, which would leave
``KafkaInputTransport``/``KafkaOutputTransport`` permanently unexecuted
(reference CI runs them against a real broker —
``adapters/src/test/kafka.rs:23-31``). This module provides the smallest
thing that makes the transport code REAL: a TCP broker with topics,
offsets, and consumer groups, plus a client exposing the exact call surface
the transports use (``MiniConsumer.poll/close``, ``MiniProducer.send/
flush``). Transports select it with a ``mini://host:port`` broker address;
real ``confluent_kafka`` / ``kafka-python`` addresses are untouched.

Protocol: newline-delimited JSON over TCP, payloads base64. One
request/response per line:
    {"op": "produce", "topic": t, "msgs": [b64, ...]}      -> {"ok": true}
    {"op": "fetch", "topic": t, "group": g, "max": n}      -> {"msgs": [...]}
Offsets advance on fetch (at-most-once per group — matching the transport's
auto-commit usage, not the full Kafka contract).
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import threading
from typing import Dict, List, Optional, Tuple

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook


class MiniKafkaBroker:
    """Line-JSON TCP broker: topics are append-only lists of byte messages;
    each (topic, group) pair holds a read offset."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.topics: Dict[str, List[bytes]] = {}
        self.offsets: Dict[Tuple[str, str], int] = {}
        self.lock = threading.Lock()
        self._conns: List[socket.socket] = []  # live connections (stop()
        broker = self                          # severs them — a real death)

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with broker.lock:
                    broker._conns.append(self.connection)
                try:
                    for line in self.rfile:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            req = json.loads(line)
                            resp = broker._handle(req)
                        except Exception as e:  # noqa: BLE001 — report
                            resp = {"error": f"{type(e).__name__}: {e}"}
                        self.wfile.write(json.dumps(resp).encode() + b"\n")
                        self.wfile.flush()
                finally:
                    with broker.lock:
                        if self.connection in broker._conns:
                            broker._conns.remove(self.connection)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.host, self.port = self.server.server_address
        self.address = f"mini://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="minikafka")
        _tsan_hook(self)

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        with self.lock:
            if op == "produce":
                log = self.topics.setdefault(req["topic"], [])
                for m in req["msgs"]:
                    log.append(base64.b64decode(m))
                return {"ok": True, "end_offset": len(log)}
            if op == "fetch":
                log = self.topics.get(req["topic"], [])
                key = (req["topic"], req.get("group", ""))
                at = self.offsets.get(key, 0)
                upto = min(len(log), at + int(req.get("max", 100)))
                msgs = [base64.b64encode(m).decode() for m in log[at:upto]]
                self.offsets[key] = upto
                return {"msgs": msgs, "offset": upto}
            return {"error": f"unknown op {op!r}"}

    def start(self) -> "MiniKafkaBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Full broker death: stop accepting AND sever every established
        connection (a bare listener shutdown would leave existing handler
        threads serving — clients would never notice the 'death')."""
        self.server.shutdown()
        self.server.server_close()
        with self.lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class _Conn:
    """One line-JSON request/response TCP connection, hardened against a
    flaky/dead broker: connects and reads under a timeout, and
    :meth:`request` retries transient transport failures with bounded
    exponential backoff (reconnecting each attempt). Retries are counted
    (:attr:`retries` — surfaced as
    ``dbsp_tpu_io_transport_retries_total{endpoint}``); when the budget is
    exhausted a :class:`ConnectionError` propagates so the endpoint
    TERMINATES (degraded pipeline) instead of hanging the controller
    thread forever. Delivery note: a retried ``produce`` whose response
    was lost may duplicate (at-least-once); a retried ``fetch`` may skip
    messages whose offsets the broker already advanced — the transport's
    auto-commit contract, unchanged."""

    def __init__(self, address: str, timeout_s: float = 10.0,
                 retries: int = 5, backoff_s: float = 0.05):
        if not address.startswith("mini://"):
            raise ValueError(
                f"minikafka address must start with 'mini://': {address!r}")
        host, port = address[len("mini://"):].rsplit(":", 1)
        self.addr = (host, int(port))
        self.timeout_s = float(timeout_s)
        self.max_retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.retries = 0  # transient failures retried (monotone counter)
        self.lock = threading.Lock()
        self.sock = None
        self.rfile = None
        self._connect()
        _tsan_hook(self)

    def _connect(self) -> None:  # holds: lock
        self._close_locked()
        self.sock = socket.create_connection(self.addr,
                                             timeout=self.timeout_s)
        self.sock.settimeout(self.timeout_s)  # read timeout
        self.rfile = self.sock.makefile("rb")

    def configure_retry(self, timeout_s: Optional[float] = None,
                        retries: Optional[int] = None,
                        backoff_s: Optional[float] = None) -> None:
        # under the connection lock: the reader thread may be mid-request
        # when the controller applies its transport knobs at endpoint
        # wiring (found by tools/check_concurrency.py C001 — sock is
        # claimed lock(lock))
        with self.lock:
            if timeout_s is not None:
                self.timeout_s = float(timeout_s)
                if self.sock is not None:
                    self.sock.settimeout(self.timeout_s)
            if retries is not None:
                self.max_retries = int(retries)
            if backoff_s is not None:
                self.backoff_s = float(backoff_s)

    def _roundtrip(self, payload: bytes) -> bytes:  # holds: lock
        if self.sock is None:
            self._connect()
        self.sock.sendall(payload)
        line = self.rfile.readline()
        if not line:
            raise ConnectionError("minikafka broker closed the connection")
        return line

    def request(self, req: dict) -> dict:
        import time

        payload = json.dumps(req).encode() + b"\n"
        last: Optional[Exception] = None
        with self.lock:
            for attempt in range(self.max_retries + 1):
                if attempt:
                    self.retries += 1
                    # bounded exponential backoff, capped at 2s per wait
                    time.sleep(min(2.0,
                                   self.backoff_s * (2 ** (attempt - 1))))
                    try:
                        self._connect()
                    except OSError as e:
                        last = e
                        continue
                try:
                    line = self._roundtrip(payload)
                    break
                except (ConnectionError, socket.timeout, OSError) as e:
                    last = e
                    self._close_locked()
            else:
                raise ConnectionError(
                    f"minikafka broker {self.addr} unreachable after "
                    f"{self.max_retries} retries: {last}") from last
        resp = json.loads(line)
        if resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp

    def close(self) -> None:
        """Public close: serialized against an in-flight request (waits
        out its retry loop rather than yanking the socket mid-read)."""
        with self.lock:
            self._close_locked()

    def _close_locked(self) -> None:  # holds: lock
        for f in (self.rfile, self.sock):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self.sock = None
        self.rfile = None


class _Record:
    """Matches the attribute the transports read (kafka-python's record)."""

    __slots__ = ("value",)

    def __init__(self, value: bytes):
        self.value = value


class MiniConsumer:
    """kafka-python-shaped consumer over the mini protocol."""

    def __init__(self, *topics: str, bootstrap_servers: str = "",
                 group_id: str = "dbsp_tpu", **_ignored):
        self.topics = list(topics)
        self.group = group_id
        self.conn = _Conn(bootstrap_servers)
        _tsan_hook(self)

    @property
    def retries(self) -> int:
        """Transport retries this consumer's connection has performed."""
        return self.conn.retries

    def poll(self, timeout_ms: int = 500, max_records: int = 500) -> dict:
        """Fetch once per topic; when everything is empty, block up to
        ``timeout_ms`` like kafka-python does — the transport's poll loop
        has no sleep of its own and would otherwise busy-spin a core
        against the broker."""
        import time

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            out = {}
            for t in self.topics:
                resp = self.conn.request({"op": "fetch", "topic": t,
                                          "group": self.group,
                                          "max": max_records})
                if resp["msgs"]:
                    out[t] = [_Record(base64.b64decode(m))
                              for m in resp["msgs"]]
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(min(0.02, timeout_ms / 1000.0))

    def close(self) -> None:
        self.conn.close()


# the names KafkaInputTransport/KafkaOutputTransport construct for the
# "kafka-python" client kind
KafkaConsumer = MiniConsumer


class MiniProducer:
    """kafka-python-shaped producer over the mini protocol."""

    def __init__(self, bootstrap_servers: str = "", **_ignored):
        self.conn = _Conn(bootstrap_servers)
        self._pending: List[Tuple[str, bytes]] = []
        self.lock = threading.Lock()
        _tsan_hook(self)

    @property
    def retries(self) -> int:
        return self.conn.retries

    def send(self, topic: str, value: bytes) -> None:
        with self.lock:
            self._pending.append((topic, value))

    def flush(self) -> None:
        with self.lock:
            pending, self._pending = self._pending, []
        by_topic: Dict[str, List[bytes]] = {}
        for t, v in pending:
            by_topic.setdefault(t, []).append(v)
        for t, vs in by_topic.items():
            self.conn.request({"op": "produce", "topic": t,
                               "msgs": [base64.b64encode(v).decode()
                                        for v in vs]})

    def close(self) -> None:
        self.conn.close()


KafkaProducer = MiniProducer
