"""Controller: drives a circuit from transport endpoints with backpressure.

Reference: ``adapters/src/controller/mod.rs`` — ``Controller::with_config``
(:119), the circuit thread ("calls dbsp.step() when input buffered", :1-14),
the backpressure thread (pauses endpoints over threshold, :11-15),
``start/pause/stop`` (:196-246) — and the stats module
(``controller/stats.rs:129``: per-endpoint + global atomic counters).

One difference by design: the reference needs a separate backpressure thread
because endpoints buffer inside foreign-threaded callbacks; here endpoint
buffers are checked on the same circuit loop that drains them (pause/resume
transitions happen at drain points), which keeps the protocol identical
(pause over threshold, resume at half) with one fewer moving thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from dbsp_tpu.circuit.runtime import CircuitHandle
from dbsp_tpu.io.catalog import Catalog
from dbsp_tpu.io.format import INPUT_FORMATS, OUTPUT_FORMATS
from dbsp_tpu.io.transport import InputTransport, OutputTransport


@dataclasses.dataclass
class ControllerConfig:
    """Reference: ``PipelineConfig`` (controller/config.rs:28-131)."""

    min_batch_records: int = 1_000     # step as soon as this many buffered
    max_buffered_records: int = 100_000  # pause endpoint above this
    flush_interval_s: float = 0.25     # step at least this often when idle


class _InputEndpoint:
    def __init__(self, name: str, collection, transport: InputTransport,
                 parser):
        self.name = name
        self.collection = collection
        self.transport = transport
        self.parser = parser
        self.lock = threading.Lock()
        self.rows: List = []
        self.eoi = False
        self.paused = False
        self.error = None
        self.total_records = 0
        self.total_bytes = 0

    def on_chunk(self, chunk: bytes) -> None:
        with self.lock:
            self.total_bytes += len(chunk)
            try:
                self.parser.feed(chunk)
                self.rows.extend(self.parser.take())
            except Exception as e:  # bad data must not kill the reader
                # record, surface via stats, and terminate the endpoint so
                # eoi_reached() cannot hang on a dead feed
                self.error = f"{type(e).__name__}: {e}"
                self.rows.extend(self.parser.take())
                self.eoi = True
                self.transport.stop()

    def on_eoi(self) -> None:
        with self.lock:
            try:
                self.parser.eoi()
                self.rows.extend(self.parser.take())
            except Exception as e:
                self.error = f"{type(e).__name__}: {e}"
            self.eoi = True

    def drain(self) -> List:
        with self.lock:
            rows, self.rows = self.rows, []
            self.total_records += len(rows)
            return rows

    def buffered(self) -> int:
        with self.lock:
            return len(self.rows)


class _OutputEndpoint:
    def __init__(self, name: str, collection, transport: OutputTransport,
                 encoder):
        self.name = name
        self.collection = collection
        self.transport = transport
        self.encoder = encoder
        self.total_records = 0
        self.total_bytes = 0
        # private delta queue: endpoints never race other handle consumers
        self.cursor = collection.handle.register_consumer()


class Controller:
    """Owns the circuit thread; endpoints feed it, outputs drain from it."""

    def __init__(self, handle: CircuitHandle, catalog: Catalog,
                 config: ControllerConfig = ControllerConfig()):
        self.handle = handle
        self.catalog = catalog
        self.config = config
        self.inputs: Dict[str, _InputEndpoint] = {}
        self.outputs: Dict[str, _OutputEndpoint] = {}
        self.state = "initializing"  # reference PipelineState
        self.steps = 0
        self._stop = threading.Event()
        self._pushed = 0              # host-pushed rows awaiting a step
        self.total_pushed = 0         # lifetime counter (stats)
        self._pushed_lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_lock = threading.Lock()
        # monitor hooks (the SLO watchdog's evaluation site): run after
        # every step and on idle loop passes, on the circuit thread
        self._monitors: List = []

    # -- endpoint wiring ----------------------------------------------------
    def add_input_endpoint(self, name: str, collection: str,
                           transport: InputTransport,
                           fmt: str = "csv") -> None:
        col = self.catalog.input(collection)
        parser = INPUT_FORMATS[fmt](col.dtypes)
        ep = _InputEndpoint(name, col, transport, parser)
        self.inputs[name] = ep
        transport.start(ep.on_chunk, ep.on_eoi)

    def add_output_endpoint(self, name: str, collection: str,
                            transport: OutputTransport,
                            fmt: str = "csv") -> None:
        col = self.catalog.output(collection)
        self.outputs[name] = _OutputEndpoint(name, col, transport,
                                             OUTPUT_FORMATS[fmt]())

    def add_monitor(self, fn) -> None:
        """Register a zero-arg callable run by the circuit loop after each
        step and while idling (obs.PipelineObs.watch registers here — the
        controller loop is where SLOs evaluate). Exceptions are swallowed:
        a watchdog must never take the pipeline down."""
        self._monitors.append(fn)

    def _run_monitors(self) -> None:
        for fn in self._monitors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass

    # push-style input (HTTP endpoints on the server use this)
    def push(self, collection: str, rows) -> int:
        col = self.catalog.input(collection)
        n = col.push_rows(rows)
        self.note_pushed(n)
        return n

    def note_pushed(self, n: int) -> None:
        """Record host-pushed rows (HTTP endpoints / client API) so the
        circuit loop's batching sees them alongside transport buffers —
        without this, pushed rows waited for an explicit /step."""
        with self._pushed_lock:
            self._pushed += int(n)
            self.total_pushed += int(n)

    # -- lifecycle (reference: start/pause/stop, controller/mod.rs:196-246) -
    def start(self) -> None:
        self.state = "running"
        self._running.set()
        if self._thread is None:
            self._thread = threading.Thread(target=self._circuit_loop,
                                            daemon=True, name="circuit")
            self._thread.start()

    def pause(self) -> None:
        self.state = "paused"
        self._running.clear()
        with self._step_lock:  # quiesce: wait out any in-flight step
            self._flush_driver_locked()

    def stop(self) -> None:
        self.state = "shutdown"
        self._stop.set()
        self._running.set()  # unblock
        for ep in self.inputs.values():
            ep.transport.stop()
        if self._thread:
            self._thread.join(timeout=10)
        with self._step_lock:
            self._flush_driver_locked()

    def _flush_driver_locked(self) -> None:
        """Validate + deliver a compiled driver's open interval (no-op for
        host handles and at the default serve cadence of 1). Called with
        the step lock held, at quiesce points and when the loop idles, so
        a validation cadence > 1 never strands buffered outputs."""
        flush = getattr(self.handle, "flush", None)
        if flush is not None:
            flush()
            self._emit_outputs()

    def eoi_reached(self) -> bool:
        """All inputs exhausted AND fully processed.

        Buffers drain at the START of a step, so emptiness alone races with
        an in-flight step (its results aren't visible yet); taking the step
        lock serializes against it.
        """
        if not all(ep.eoi and ep.buffered() == 0
                   for ep in self.inputs.values()):
            return False
        with self._step_lock:
            # "fully processed" includes a compiled driver's open deferred-
            # validation interval — validate + deliver it before answering,
            # or a cadence > 1 strands the final ticks' outputs
            self._flush_driver_locked()
            return all(ep.eoi and ep.buffered() == 0
                       for ep in self.inputs.values())

    # -- the circuit thread ---------------------------------------------------
    def _circuit_loop(self) -> None:
        last_flush = time.monotonic()
        while not self._stop.is_set():
            if not self._running.wait(timeout=0.1):
                continue
            if self._stop.is_set():
                break
            stepped = False
            # the running re-check happens UNDER the step lock: once pause()
            # holds the lock, no new step can slip in after it returns
            with self._step_lock:
                if self._running.is_set():
                    buffered = sum(ep.buffered()
                                   for ep in self.inputs.values())
                    with self._pushed_lock:
                        buffered += self._pushed
                    now = time.monotonic()
                    if buffered >= self.config.min_batch_records or (
                            buffered > 0 and
                            now - last_flush >= self.config.flush_interval_s):
                        self._step_locked()
                        last_flush = now
                        stepped = True
            if not stepped:
                with self._step_lock:
                    self._flush_driver_locked()
                self._run_monitors()
                time.sleep(0.005)
            self._backpressure()

    def step(self) -> None:
        """One controller-driven tick: drain buffers -> step -> emit outputs."""
        with self._step_lock:
            self._step_locked()

    def _step_locked(self) -> None:
        with self._pushed_lock:
            self._pushed = 0  # this step consumes all pushed rows
        for ep in self.inputs.values():
            rows = ep.drain()
            if rows:
                ep.collection.push_rows(rows)
        self.handle.step()
        self.steps += 1
        self._emit_outputs()
        self._run_monitors()

    def _emit_outputs(self) -> None:
        for out in self.outputs.values():
            # per-consumer queue: the HTTP server's /read peeks the same
            # handle, so a destructive take() here would race it
            batch = out.collection.handle.read_consumer(out.cursor)
            if batch is not None and int(batch.live_count()) > 0:
                data = out.encoder.encode(batch)
                out.transport.write(data)
                out.transport.flush()
                out.total_bytes += len(data)
                out.total_records += len(batch.to_dict())

    def _backpressure(self) -> None:
        for ep in self.inputs.values():
            n = ep.buffered()
            if not ep.paused and n > self.config.max_buffered_records:
                ep.paused = True
                ep.transport.pause()
            elif ep.paused and n < self.config.max_buffered_records // 2:
                ep.paused = False
                ep.transport.resume()

    # -- stats (reference: ControllerStatus, controller/stats.rs) -----------
    def stats(self) -> dict:
        return {
            "state": self.state,
            "steps": self.steps,
            "pushed_records": self.total_pushed,
            "inputs": {
                name: {
                    "total_records": ep.total_records,
                    "total_bytes": ep.total_bytes,
                    "buffered_records": ep.buffered(),
                    "paused": ep.paused,
                    "eoi": ep.eoi,
                    "error": ep.error,
                } for name, ep in self.inputs.items()
            },
            "outputs": {
                name: {
                    "total_records": out.total_records,
                    "total_bytes": out.total_bytes,
                } for name, out in self.outputs.items()
            },
        }
