"""Controller: drives a circuit from transport endpoints with backpressure.

Reference: ``adapters/src/controller/mod.rs`` — ``Controller::with_config``
(:119), the circuit thread ("calls dbsp.step() when input buffered", :1-14),
the backpressure thread (pauses endpoints over threshold, :11-15),
``start/pause/stop`` (:196-246) — and the stats module
(``controller/stats.rs:129``: per-endpoint + global atomic counters).

One difference by design: the reference needs a separate backpressure thread
because endpoints buffer inside foreign-threaded callbacks; here endpoint
buffers are checked on the same circuit loop that drains them (pause/resume
transitions happen at drain points), which keeps the protocol identical
(pause over threshold, resume at half) with one fewer moving thread.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional

from dbsp_tpu.circuit.runtime import CircuitHandle
from dbsp_tpu.io.catalog import Catalog
from dbsp_tpu.io.format import INPUT_FORMATS, OUTPUT_FORMATS
from dbsp_tpu.io.transport import InputTransport, OutputTransport
from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook


@dataclasses.dataclass
class ControllerConfig:
    """Reference: ``PipelineConfig`` (controller/config.rs:28-131)."""

    min_batch_records: int = 1_000     # step as soon as this many buffered
    max_buffered_records: int = 100_000  # pause endpoint above this
    flush_interval_s: float = 0.25     # step at least this often when idle
    # durability (dbsp_tpu.checkpoint): directory for periodic checkpoint
    # generations and the cadence in controller ticks. 0/None defer to the
    # env knobs DBSP_TPU_CHECKPOINT_EVERY_TICKS / DBSP_TPU_CHECKPOINT_DIR;
    # a configured directory with no interval uses the default cadence.
    checkpoint_dir: Optional[str] = None
    checkpoint_every_ticks: int = 0
    # transport hardening (io/minikafka.py): connect/read timeout, retry
    # attempts, and exponential-backoff base for broker-backed endpoints
    transport_timeout_s: float = 10.0
    transport_retries: int = 5
    transport_backoff_s: float = 0.05
    # tiered trace residency (dbsp_tpu/residency.py) — the per-pipeline
    # override of the DBSP_TPU_DEVICE_ROWS / _HOST_ROWS / _COLD_DIR env
    # knobs, honored by BOTH engines (compiled leveled traces and host
    # spines). None = env default; <= 0 = explicitly unbounded; cold_dir
    # unset defaults to <checkpoint_dir>/cold when checkpointing is on.
    device_rows: Optional[int] = None
    host_rows: Optional[int] = None
    cold_dir: Optional[str] = None


class _InputEndpoint:
    def __init__(self, name: str, collection, transport: InputTransport,
                 parser, notify_arrival=None):
        self.name = name
        self.collection = collection
        self.transport = transport
        self.parser = parser
        # freshness stamp hook (Controller._note_arrival): called with the
        # row count of each arriving chunk, outside the endpoint lock
        self.notify_arrival = notify_arrival
        self.lock = threading.Lock()
        self.rows: List = []
        self.eoi = False
        self.paused = False
        self.error = None
        self.total_records = 0
        self.total_bytes = 0
        # rows to DROP before feeding the circuit: restore-on-deploy sets
        # this to the checkpointed consumed count for transports that
        # replay their stream from the beginning, so replayed rows the
        # restored state already contains are not double-applied
        self.skip_rows = 0
        _tsan_hook(self)

    def on_chunk(self, chunk: bytes) -> None:
        n_new = 0
        with self.lock:
            self.total_bytes += len(chunk)
            try:
                self.parser.feed(chunk)
                taken = self.parser.take()
                self.rows.extend(taken)
                n_new = len(taken)
            except Exception as e:  # bad data must not kill the reader
                # record, surface via stats, and terminate the endpoint so
                # eoi_reached() cannot hang on a dead feed
                self.error = f"{type(e).__name__}: {e}"
                taken = self.parser.take()
                self.rows.extend(taken)
                n_new = len(taken)
                self.eoi = True
                self.transport.stop()
        # arrival wall-time stamp for freshness tracking — OUTSIDE the
        # endpoint lock (the timeline has its own guard; no nesting)
        if n_new and self.notify_arrival is not None:
            self.notify_arrival(n_new)

    def on_eoi(self) -> None:
        with self.lock:
            try:
                self.parser.eoi()
                self.rows.extend(self.parser.take())
            except Exception as e:
                self.error = f"{type(e).__name__}: {e}"
            self.eoi = True

    def drain(self) -> List:
        with self.lock:
            rows, self.rows = self.rows, []
            if self.skip_rows:
                k = min(self.skip_rows, len(rows))
                self.skip_rows -= k
                rows = rows[k:]  # already counted in the restored totals
            self.total_records += len(rows)
            return rows

    def buffered(self) -> int:
        with self.lock:
            return len(self.rows)


class _OutputEndpoint:
    def __init__(self, name: str, collection, transport: OutputTransport,
                 encoder):
        self.name = name
        self.collection = collection
        self.transport = transport
        self.encoder = encoder
        self.total_records = 0
        self.total_bytes = 0
        self.error = None  # terminal sink failure (dead output broker)
        self.pending = None  # batch whose write failed, awaiting retry
        # private delta queue: endpoints never race other handle consumers
        self.cursor = collection.handle.register_consumer()
        _tsan_hook(self)


class Controller:
    """Owns the circuit thread; endpoints feed it, outputs drain from it."""

    def __init__(self, handle: CircuitHandle, catalog: Catalog,
                 config: ControllerConfig = ControllerConfig()):
        self.handle = handle
        self.catalog = catalog
        self.config = config
        self.inputs: Dict[str, _InputEndpoint] = {}
        self.outputs: Dict[str, _OutputEndpoint] = {}
        self.state = "initializing"  # reference PipelineState
        self.steps = 0
        self._stop = threading.Event()
        self._pushed = 0              # host-pushed rows awaiting a step
        self.total_pushed = 0         # lifetime counter (stats)
        self._pushed_lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step_lock = threading.Lock()
        self._lifecycle_lock = threading.Lock()  # stop()/pause() idempotency
        # monitor hooks (the SLO watchdog's evaluation site): run after
        # every step and on idle loop passes, on the circuit thread
        self._monitors: List = []
        # durability: periodic checkpointing into a generation store
        # (dbsp_tpu.checkpoint). Enabled when a directory is configured
        # (config field or DBSP_TPU_CHECKPOINT_DIR); the cadence defaults
        # to checkpoint.DEFAULT_EVERY_TICKS when unset.
        from dbsp_tpu import checkpoint as _ckpt

        self.checkpoint_dir = config.checkpoint_dir or \
            os.environ.get("DBSP_TPU_CHECKPOINT_DIR") or None
        every = config.checkpoint_every_ticks or \
            int(os.environ.get("DBSP_TPU_CHECKPOINT_EVERY_TICKS", "0"))
        self.checkpoint_every = every or (
            _ckpt.DEFAULT_EVERY_TICKS if self.checkpoint_dir else 0)
        self.last_checkpoint_tick: Optional[int] = None
        self.checkpoints = 0
        self.checkpoint_error: Optional[str] = None
        self._last_ckpt_step = 0
        # optional obs.FlightRecorder (PipelineObs.attach_controller wires
        # it) — checkpoint/restore events become SLO-visible through it
        self.flight = None
        # optional obs.Timeline (same wiring site): per-tick latency /
        # rows / queue-depth records plus freshness stamps (arrival at
        # push sites, visibility at validation publish)
        self.timeline = None
        # tiered trace residency: route the unified budgets into whichever
        # engine this controller drives (compiled handle or host spines).
        # Applying HERE — not only on the manager deploy path — is what
        # makes the allowlist-accepted config keys honored everywhere a
        # controller is built (an accepted-but-ignored key is the silent
        # failure the allowlist exists to prevent).
        from dbsp_tpu import residency as _res

        rcfg = _res.resolve(
            device_rows=config.device_rows, host_rows=config.host_rows,
            cold_dir=config.cold_dir or (
                os.path.join(self.checkpoint_dir, "cold")
                if self.checkpoint_dir else None))
        # applied UNCONDITIONALLY: an explicit <= 0 config key resolves to
        # an INACTIVE config that must still reach the engine to DISABLE
        # the env budget it read at construction (gating on rcfg.active
        # here would be the accepted-but-ignored key again, in reverse).
        # Kept on the controller: restore_from re-applies it — a host
        # restore rebuilds spines from decoded state, which would
        # otherwise silently drop the per-pipeline budgets.
        self._residency_cfg = rcfg
        _res.apply_to_driver(handle, rcfg)
        # lock-free read serving plane (dbsp_tpu/serving.py): every
        # catalog output becomes a served view; the step path publishes
        # immutable snapshots at each validation publish and readers
        # never touch _step_lock. DBSP_TPU_READPLANE=0 disables
        # publication (reads fall back to the quiesced control path).
        from dbsp_tpu.serving import ReadPlane

        self.read_plane = ReadPlane()
        for vname, vcol in self.catalog.outputs.items():
            self.read_plane.add_view(vname, vcol.handle)
        # fleet-wide delta tracing (obs/tracing.py): every ingested batch
        # gets a trace context that flows push -> tick -> publish ->
        # changefeed -> replica -> read; DBSP_TPU_TRACE_E2E=0 disables.
        from dbsp_tpu.obs.tracing import E2ETracer

        self.e2e = E2ETracer()
        _tsan_hook(self)

    # -- endpoint wiring ----------------------------------------------------
    def add_input_endpoint(self, name: str, collection: str,
                           transport: InputTransport,
                           fmt: str = "csv") -> None:
        col = self.catalog.input(collection)
        parser = INPUT_FORMATS[fmt](col.dtypes)
        ep = _InputEndpoint(name, col, transport, parser,
                            notify_arrival=self._note_arrival)
        self.inputs[name] = ep
        configure = getattr(transport, "configure_retry", None)
        if configure is not None:  # broker-backed transports honor the
            configure(timeout_s=self.config.transport_timeout_s,  # knobs
                      retries=self.config.transport_retries,
                      backoff_s=self.config.transport_backoff_s)
        transport.start(ep.on_chunk, ep.on_eoi)

    def add_output_endpoint(self, name: str, collection: str,
                            transport: OutputTransport,
                            fmt: str = "csv") -> None:
        col = self.catalog.output(collection)
        configure = getattr(transport, "configure_retry", None)
        if configure is not None:
            # sinks retry SYNCHRONOUSLY on the circuit thread (the parked
            # pending batch re-sends next step), so the retry budget here
            # bounds per-step stall time under a dead output broker
            configure(timeout_s=self.config.transport_timeout_s,
                      retries=self.config.transport_retries,
                      backoff_s=self.config.transport_backoff_s)
        self.outputs[name] = _OutputEndpoint(name, col, transport,
                                             OUTPUT_FORMATS[fmt]())

    def add_monitor(self, fn) -> None:
        """Register a zero-arg callable run by the circuit loop after each
        step and while idling (obs.PipelineObs.watch registers here — the
        controller loop is where SLOs evaluate). Exceptions are swallowed:
        a watchdog must never take the pipeline down."""
        self._monitors.append(fn)

    def _run_monitors(self) -> None:
        for fn in self._monitors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — monitoring is best-effort
                pass

    # push-style input (HTTP endpoints on the server use this)
    def push(self, collection: str, rows) -> int:
        col = self.catalog.input(collection)
        n = col.push_rows(rows)
        self.note_pushed(n)
        return n

    def _note_arrival(self, n: int,
                      trace_id: Optional[str] = None) -> Optional[str]:
        """Freshness: stamp the wall-time a batch of rows reached this
        controller (push sites and transport chunk callbacks both land
        here). Visibility is stamped when the batch's results publish —
        the gap is the freshness sample. Also mints (or adopts, when the
        pusher sent ``X-Dbsp-Trace``) the batch's e2e trace context;
        returns its id."""
        tl = self.timeline
        if n and tl is not None:
            tl.note_arrival(n)
        if n:
            return self.e2e.note_ingest(n, trace_id=trace_id)
        return None

    def note_pushed(self, n: int,
                    trace_id: Optional[str] = None) -> Optional[str]:
        """Record host-pushed rows (HTTP endpoints / client API) so the
        circuit loop's batching sees them alongside transport buffers —
        without this, pushed rows waited for an explicit /step. Returns
        the batch's e2e trace id (None when tracing is off)."""
        with self._pushed_lock:
            self._pushed += int(n)
            self.total_pushed += int(n)
        return self._note_arrival(n, trace_id=trace_id)

    # -- durability (dbsp_tpu.checkpoint) -----------------------------------
    def _controller_state(self) -> dict:
        """The controller-side section of a checkpoint manifest: the step
        counter plus each input endpoint's consumed high-water mark — the
        replay position recovery resumes feeds from (exactly-once: rows
        counted here were fully stepped; rows past them must be re-fed)."""
        return {
            "steps": self.steps,
            "pushed_records": self.total_pushed,
            # read-plane epoch at checkpoint time: restore republishes the
            # checkpointed view state under this epoch, so changefeed
            # cursors from before the restore resume exactly (older
            # cursors get a synthesized snapshot record)
            "read_epoch": self.read_plane.epoch,
            "inputs": {name: {"total_records": ep.total_records,
                              "total_bytes": ep.total_bytes}
                       for name, ep in self.inputs.items()},
        }

    def checkpoint(self, path: Optional[str] = None) -> dict:
        """Write one checkpoint generation (quiesced under the step lock).
        Uses the configured directory when ``path`` is omitted."""
        with self._step_lock:
            return self._checkpoint_locked(path)

    def _checkpoint_locked(self, path=None) -> dict:  # holds: _step_lock
        from dbsp_tpu import checkpoint as _ckpt

        path = path or self.checkpoint_dir
        if not path:
            raise ValueError(
                "no checkpoint directory configured (set checkpoint_dir "
                "in the pipeline config or DBSP_TPU_CHECKPOINT_DIR)")
        tick = getattr(self.handle, "_tick", None)
        info = _ckpt.save(self.handle, path,
                          controller=self._controller_state(),
                          tick=self.steps if tick is None else None,
                          output_pending={
                              name: out.pending
                              for name, out in self.outputs.items()
                              if out.pending is not None},
                          read_plane=(self.read_plane.state_batches()
                                      if self.read_plane.enabled else None))
        self.checkpoints += 1
        self.last_checkpoint_tick = info["tick"]
        self.checkpoint_error = None
        self._last_ckpt_step = self.steps
        if self.flight is not None:
            self.flight.record("checkpoint", tick=info["tick"],
                               generation=info["generation"],
                               linked=info["linked_arrays"],
                               bytes=info["bytes"])
        return info

    def _maybe_checkpoint_locked(self) -> None:  # holds: _step_lock
        """Periodic-cadence hook on the circuit thread: a checkpoint
        failure is recorded (flight + stats) but never takes the pipeline
        down — serving continues at reduced durability."""
        if not self.checkpoint_every or not self.checkpoint_dir:
            return
        if self.steps - self._last_ckpt_step < self.checkpoint_every:
            return
        try:
            self._checkpoint_locked()
        except Exception as e:  # noqa: BLE001 — durability is best-effort
            self.checkpoint_error = f"{type(e).__name__}: {e}"
            self._last_ckpt_step = self.steps  # back off a full interval
            if self.flight is not None:
                self.flight.record("checkpoint",
                                   error=self.checkpoint_error[:200])

    def restore_from(self, path: Optional[str] = None) -> dict:
        """Restore the newest valid generation into this controller's
        driver and adopt the checkpointed controller counters. Call before
        :meth:`start` (deploy-time recovery).

        Input replay position: each endpoint's checkpointed consumed-row
        count becomes its SKIP prefix when the transport replays its
        stream from the beginning (``transport.replays_from_start`` —
        file inputs), so replayed rows the restored state already
        contains are dropped, not double-applied. Broker-backed inputs
        own their position server-side (consumer-group offsets) and
        resume there; rows fetched-but-unstepped at a crash follow the
        transport's own at-most-once auto-commit contract."""
        from dbsp_tpu import checkpoint as _ckpt

        path = path or self.checkpoint_dir
        if not path:
            raise ValueError("no checkpoint directory configured")
        with self._step_lock:
            info = _ckpt.restore(self.handle, path)
            # a HOST restore rebuilds spines from decoded state (fresh
            # Spine objects, module-default budgets) — re-apply the
            # pipeline's resolved residency config so the budgets survive
            # recovery; no-op for the compiled driver (its handle keeps
            # residency_cfg across restore)
            from dbsp_tpu import residency as _res

            _res.apply_to_driver(self.handle, self._residency_cfg)
            c = info.get("controller") or {}
            self.steps = int(c.get("steps", info["tick"]))
            with self._pushed_lock:  # writes join note_pushed's guard
                self.total_pushed = int(c.get("pushed_records", 0))
            for name, d in (c.get("inputs") or {}).items():
                ep = self.inputs.get(name)
                if ep is not None:
                    with ep.lock:  # counters share the endpoint's guard
                        ep.total_records = int(d.get("total_records", 0))
                        ep.total_bytes = int(d.get("total_bytes", 0))
                        if getattr(ep.transport, "replays_from_start",
                                   False):
                            ep.skip_rows = ep.total_records
            for name, batch in (info.get("output_pending") or {}).items():
                out = self.outputs.get(name)
                if out is not None:  # undelivered sink deltas re-send on
                    out.pending = batch  # the first post-restore emission
            if self.read_plane.enabled:
                # republish the checkpointed view state under the
                # checkpointed epoch; pre-restore changefeed cursors
                # resume via a synthesized snapshot record
                self.read_plane.restore(
                    int(c.get("read_epoch", 0)),
                    info.get("read_plane") or {})
            self.last_checkpoint_tick = info["tick"]
            self._last_ckpt_step = self.steps
        return info

    # -- lifecycle (reference: start/pause/stop, controller/mod.rs:196-246) -
    def start(self) -> None:
        # under the lifecycle lock like pause()/stop(): a start() racing
        # a stop() must not resurrect "running" state or spawn a second
        # circuit thread (found by tools/check_concurrency.py C001 —
        # state/_thread are claimed writelock(_lifecycle_lock))
        with self._lifecycle_lock:
            if self.state == "shutdown":
                return
            self.state = "running"
            self._running.set()
            if self._thread is None:
                self._thread = threading.Thread(target=self._circuit_loop,
                                                daemon=True, name="circuit")
                self._thread.start()

    def pause(self) -> None:
        with self._lifecycle_lock:
            if self.state in ("paused", "shutdown"):
                return  # idempotent under double-call
            self.state = "paused"
            # clear the run gate INSIDE the lifecycle lock: a racing
            # start() otherwise interleaves its _running.set() before
            # this clear, leaving state=="running" with the gate down —
            # a healthy-looking pipeline that never steps
            self._running.clear()
        with self._step_lock:  # quiesce: wait out any in-flight step
            self._flush_driver_locked()

    def stop(self) -> None:
        with self._lifecycle_lock:
            already = self.state == "shutdown"
            self.state = "shutdown"
            self._stop.set()
            self._running.set()  # unblock
            if already:
                # second stop(): the first one owns teardown — just wait
                # it out instead of racing the circuit thread join and
                # re-running the flush/checkpoint sequence
                if self._thread:
                    self._thread.join(timeout=10)
                return
        for ep in self.inputs.values():
            ep.transport.stop()
        if self._thread:
            self._thread.join(timeout=10)
        with self._step_lock:
            # graceful shutdown: flush any open deferred-validation
            # interval, then persist a final checkpoint so a clean stop
            # is always resumable from its exact last tick. ONLY when
            # there is progress past the last checkpoint: a no-progress
            # save would be a redundant generation, and on an
            # aborted/refused deploy it would overwrite a store the
            # operator may still want to inspect with FRESH-EMPTY state
            # (turning a strict-mode refusal into a silent reset).
            self._flush_driver_locked()
            if self.checkpoint_dir and self.steps > self._last_ckpt_step:
                try:
                    self._checkpoint_locked()
                except Exception as e:  # noqa: BLE001 — still shut down
                    self.checkpoint_error = f"{type(e).__name__}: {e}"

    def _flush_driver_locked(self) -> None:  # holds: _step_lock
        """Validate + deliver a compiled driver's open interval (no-op for
        host handles and at the default serve cadence of 1). Called with
        the step lock held, at quiesce points and when the loop idles, so
        a validation cadence > 1 never strands buffered outputs."""
        flush = getattr(self.handle, "flush", None)
        if flush is not None:
            was_open = getattr(self.handle, "interval_open", False)
            flush()
            self._emit_outputs()
            # snapshot publication rides every validation publish (cheap
            # no-op when no output's step_id advanced)
            self.read_plane.publish(tracer=self.e2e)
            tl = self.timeline
            if was_open and tl is not None:
                # a deferred-validation interval just closed: its buffered
                # ticks' results became visible now, not at their steps
                tl.note_visible(list(self.catalog.outputs))

    @contextlib.contextmanager
    def quiesce(self):
        """Public quiesce point: hold the step lock (no serving tick in
        flight) with any open deferred-validation interval flushed, for
        the duration of the ``with`` block. The sanctioned way for other
        components (the HTTP server's ``/lineage`` and ``/profile``
        handlers) to get a consistent, non-advancing view of the engine —
        reaching through to ``_step_lock`` directly is a C003 lint
        violation (tools/check_concurrency.py).

        Lock order: ``_step_lock`` is the OUTERMOST engine lock; nested
        inside it are ``_pushed_lock`` (static C002 graph) and the
        per-endpoint ``_InputEndpoint.lock`` (drain/restore — a
        cross-class edge the static graph does not model; the runtime
        sanitizer's lock-order tracking covers it). Never acquire an
        endpoint lock and THEN call into a step-lock-taking controller
        method — that is the ABBA inversion. Do not call ``step()``,
        ``checkpoint()`` or another ``quiesce()`` from inside the block —
        the step lock is not reentrant."""
        with self._step_lock:
            self._flush_driver_locked()
            yield self

    def eoi_reached(self) -> bool:
        """All inputs exhausted AND fully processed.

        Buffers drain at the START of a step, so emptiness alone races with
        an in-flight step (its results aren't visible yet); taking the step
        lock serializes against it.
        """
        if not all(ep.eoi and ep.buffered() == 0
                   for ep in self.inputs.values()):
            return False
        with self._step_lock:
            # "fully processed" includes a compiled driver's open deferred-
            # validation interval — validate + deliver it before answering,
            # or a cadence > 1 strands the final ticks' outputs
            self._flush_driver_locked()
            return all(ep.eoi and ep.buffered() == 0
                       for ep in self.inputs.values())

    # -- the circuit thread ---------------------------------------------------
    def _circuit_loop(self) -> None:
        last_flush = time.monotonic()
        while not self._stop.is_set():
            if not self._running.wait(timeout=0.1):
                continue
            if self._stop.is_set():
                break
            stepped = False
            # the running re-check happens UNDER the step lock: once pause()
            # holds the lock, no new step can slip in after it returns
            with self._step_lock:
                if self._running.is_set():
                    buffered = sum(ep.buffered()
                                   for ep in self.inputs.values())
                    with self._pushed_lock:
                        buffered += self._pushed
                    now = time.monotonic()
                    if buffered >= self.config.min_batch_records or (
                            buffered > 0 and
                            now - last_flush >= self.config.flush_interval_s):
                        self._step_locked()
                        last_flush = now
                        stepped = True
            if not stepped:
                with self._step_lock:
                    self._flush_driver_locked()
                self._run_monitors()
                time.sleep(0.005)
            self._backpressure()

    def step(self) -> None:
        """One controller-driven tick: drain buffers -> step -> emit outputs."""
        with self._step_lock:
            self._step_locked()

    def _step_locked(self) -> None:  # holds: _step_lock
        t0 = time.perf_counter_ns()
        # queue_wait ends for every batch stamped so far: contexts noted
        # BEFORE this point have their rows in the buffers drained below
        # (push sites append rows before stamping the context)
        self.e2e.tick_begin()
        with self._pushed_lock:
            rows_in = self._pushed
            self._pushed = 0  # this step consumes all pushed rows
        for ep in self.inputs.values():
            rows = ep.drain()
            if rows:
                ep.collection.push_rows(rows)
                rows_in += len(rows)
        self.handle.step()
        self.steps += 1
        rows_out = self._emit_outputs()
        trace_ids = self.e2e.tick_end()
        if not getattr(self.handle, "interval_open", False):
            # validation publish: swap in immutable read-plane snapshots
            # (host engine: every step; compiled: when the deferred-
            # validation interval closed this tick). BEFORE the periodic
            # checkpoint so a checkpoint captures this tick's publication.
            self.read_plane.publish(tracer=self.e2e)
        self._maybe_checkpoint_locked()
        self._run_monitors()
        # the tick record is stamped LAST so checkpoint writes and in-tick
        # monitor work (everything inside the step lock) count toward the
        # tick's wall latency — that is what a serving client waits on
        tl = self.timeline
        if tl is not None:
            tl.note_tick(self.steps, time.perf_counter_ns() - t0,
                         rows_in=rows_in, rows_out=rows_out,
                         queue_depth=sum(ep.buffered()
                                         for ep in self.inputs.values()),
                         trace_ids=trace_ids)
            if not getattr(self.handle, "interval_open", False):
                # this step's results validated and published (host engine:
                # every step; compiled: when no deferred interval remains)
                tl.note_visible(list(self.catalog.outputs))

    def _emit_outputs(self) -> int:
        from dbsp_tpu.zset.batch import concat_batches

        emitted = 0
        for out in self.outputs.values():
            # per-consumer queue: the HTTP server's /read peeks the same
            # handle, so a destructive take() here would race it
            batch = out.collection.handle.read_consumer(out.cursor)
            if out.pending is not None:
                # deltas whose write failed fold into this emission (Z-set
                # sum — exactly what the consumer queue does for laggards)
                batch = out.pending if batch is None else concat_batches(
                    [out.pending, batch]).consolidate().shrink_to_fit()
                out.pending = None
            if batch is not None and int(batch.live_count()) > 0:
                data = out.encoder.encode(batch)
                try:
                    out.transport.write(data)
                    out.transport.flush()
                except Exception as e:  # noqa: BLE001 — a dead SINK must
                    # not kill the circuit thread: record the failure (the
                    # flight source latches it as degraded), retain the
                    # batch for the next emission, and keep serving — a
                    # recovered sink misses nothing
                    out.error = f"{type(e).__name__}: {e}"
                    out.pending = batch
                    continue
                out.error = None
                out.total_bytes += len(data)
                n = len(batch.to_dict())
                out.total_records += n
                emitted += n
        return emitted

    def _backpressure(self) -> None:
        for ep in self.inputs.values():
            n = ep.buffered()
            if not ep.paused and n > self.config.max_buffered_records:
                ep.paused = True
                ep.transport.pause()
            elif ep.paused and n < self.config.max_buffered_records // 2:
                ep.paused = False
                ep.transport.resume()

    def input_queue_depths(self) -> Dict[str, int]:
        """Rows buffered per input endpoint, awaiting the next drain —
        the /status queue-depth section. Each read takes only the
        endpoint's own lock; never the step lock."""
        return {name: ep.buffered() for name, ep in self.inputs.items()}

    # -- stats (reference: ControllerStatus, controller/stats.rs) -----------
    def stats(self) -> dict:
        return {
            "state": self.state,
            "steps": self.steps,
            "pushed_records": self.total_pushed,
            "checkpoints": self.checkpoints,
            "last_checkpoint_tick": self.last_checkpoint_tick,
            "checkpoint_error": self.checkpoint_error,
            "read_plane": self.read_plane.stats(),
            "e2e": self.e2e.stats(),
            "inputs": {
                name: {
                    "total_records": ep.total_records,
                    "total_bytes": ep.total_bytes,
                    "buffered_records": ep.buffered(),
                    "paused": ep.paused,
                    "eoi": ep.eoi,
                    # a transport's terminal failure (dead broker past the
                    # retry budget) surfaces as the endpoint's error too
                    "error": ep.error or getattr(ep.transport, "error",
                                                 None),
                    "transport_retries": getattr(ep.transport, "retries",
                                                 0),
                } for name, ep in self.inputs.items()
            },
            "outputs": {
                name: {
                    "total_records": out.total_records,
                    "total_bytes": out.total_bytes,
                    "error": out.error,
                } for name, out in self.outputs.items()
            },
        }
