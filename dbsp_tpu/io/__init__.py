from dbsp_tpu.io.catalog import Catalog
from dbsp_tpu.io.config import (ConfigError, attach_endpoints,
                                build_controller, load_config)
from dbsp_tpu.io.controller import Controller, ControllerConfig
from dbsp_tpu.io.format import (CsvEncoder, CsvParser, JsonEncoder,
                                JsonParser)
from dbsp_tpu.io.server import CircuitServer
from dbsp_tpu.io.transport import (FileInputTransport, FileOutputTransport,
                                   KafkaInputTransport, KafkaOutputTransport)

__all__ = [
    "Catalog", "Controller", "ControllerConfig", "CircuitServer",
    "ConfigError", "attach_endpoints", "build_controller", "load_config",
    "CsvParser", "CsvEncoder", "JsonParser", "JsonEncoder",
    "FileInputTransport", "FileOutputTransport",
    "KafkaInputTransport", "KafkaOutputTransport",
]
