from dbsp_tpu.circuit.builder import (
    Circuit, CircuitError, CircuitEvent, FeedbackConnector, RootCircuit,
    SchedulerEvent, Stream)
from dbsp_tpu.circuit.operator import (
    BinaryOperator, ImportOperator, NaryOperator, Operator, SinkOperator,
    SourceOperator, StrictOperator, UnaryOperator)
from dbsp_tpu.circuit.runtime import CircuitHandle, Runtime

__all__ = [
    "Circuit", "CircuitError", "CircuitEvent", "FeedbackConnector",
    "RootCircuit",
    "SchedulerEvent", "Stream", "Operator", "SourceOperator", "SinkOperator",
    "UnaryOperator", "BinaryOperator", "NaryOperator", "StrictOperator",
    "ImportOperator", "CircuitHandle", "Runtime",
]
