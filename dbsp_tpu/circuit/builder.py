"""Circuit construction: streams, nodes, edges, feedback, caching, events.

The host-side equivalent of the reference's circuit builder
(``crates/dbsp/src/circuit/circuit_builder.rs``): a DAG of operators connected
by streams, built once, then evaluated tick-by-tick by a scheduler. The graph
lives on the host (graph construction is control flow, not compute); the data
flowing on streams is device-resident :class:`~dbsp_tpu.zset.Batch` pytrees or
host scalars, and each operator drives its own jitted kernels.

Key surface parity (reference file:line):
  Stream                circuit_builder.rs:92
  Circuit node insert   circuit_builder.rs:1943-2224 (add_*_operator)
  add_feedback          circuit_builder.rs:2225 (FeedbackConnector :3490)
  RootCircuit.build     circuit_builder.rs:1403
  circuit cache         circuit/cache.rs:59
  event handlers        circuit_builder.rs:1474-1516
  step                  circuit_builder.rs:3658
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dbsp_tpu.circuit.operator import (
    BinaryOperator, ImportOperator, NaryOperator, Operator, SinkOperator,
    SourceOperator, StrictOperator, UnaryOperator)

class CircuitError(RuntimeError):
    """A malformed circuit construction or use (typed — unlike ``assert``,
    these survive ``python -O``; tools/check_hotpath.py enforces that
    user-input validation in circuit/ and io/ never relies on assert)."""


# ---------------------------------------------------------------------------
# Construction / scheduler events (reference: circuit/trace.rs:44,496)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CircuitEvent:
    kind: str           # "operator" | "subcircuit" | "edge"
    node_id: Tuple[int, ...] | None = None
    name: str | None = None
    from_id: Tuple[int, ...] | None = None
    to_id: Tuple[int, ...] | None = None


@dataclasses.dataclass
class SchedulerEvent:
    kind: str           # "step_start" | "step_end" | "eval_start" | "eval_end"
    #                     | "clock_start" | "clock_end"
    node_id: Tuple[int, ...] | None = None
    name: str | None = None
    time_ns: int = 0


class Stream:
    """A typed edge in the circuit carrying one value per clock tick.

    Operator sugar (``map``/``join``/``aggregate``/...) is attached by the
    ``dbsp_tpu.operators`` package, mirroring how the reference implements
    operators as extension methods on ``Stream``.

    ``schema`` / ``key_sharded`` metadata lives on the underlying
    :class:`Node` (a Stream is a light wrapper; several wrappers may point
    at one node, and the static analyzer reads the graph, not the
    wrappers), so setting it through any wrapper is visible to all.
    """

    def __init__(self, circuit: "Circuit", node_index: int):
        self.circuit = circuit
        self.node_index = node_index

    @property
    def node(self) -> "Node":
        return self.circuit.nodes[self.node_index]

    def _touch_metadata(self) -> None:
        """Node metadata feeds the static analyzer; a memoized verification
        of the old metadata must not gate the mutated graph."""
        self.circuit.root()._verify_cache = None

    # (key_dtypes, val_dtypes) of the Z-set batches on this edge, or None
    # for non-batch payloads / unknown
    @property
    def schema(self):
        return self.node.schema

    @schema.setter
    def schema(self, value) -> None:
        self.node.schema = value
        self._touch_metadata()

    # True when rows are provably hash-partitioned over the worker mesh by
    # the current first key column (set by shard()/sources; reset by
    # re-keying operators simply by being absent on their output node)
    @property
    def key_sharded(self) -> bool:
        return self.node.key_sharded

    @key_sharded.setter
    def key_sharded(self, value: bool) -> None:
        self.node.key_sharded = bool(value)
        self._touch_metadata()

    # Placement decisions the builder sugar made here when its exchange/
    # collapse was elided on a 1-worker mesh (shard()/unshard()/sources
    # no-op at workers == 1). The same build on a larger mesh would have
    # placed the stream accordingly, so what-if analysis at workers > 1
    # must treat it as placed. Two independent flags — one node may feed
    # both a sharded and a host consumer, each of which would get its own
    # exchange/collapse node on a larger mesh.
    @property
    def shard_intent(self) -> bool:
        return self.node.shard_intent

    @shard_intent.setter
    def shard_intent(self, value: bool) -> None:
        self.node.shard_intent = bool(value)
        self._touch_metadata()

    @property
    def host_intent(self) -> bool:
        return self.node.host_intent

    @host_intent.setter
    def host_intent(self, value: bool) -> None:
        self.node.host_intent = bool(value)
        self._touch_metadata()

    def waive_lint(self, *rule_ids: str) -> "Stream":
        """Mark this stream's node as an intentional exception to the given
        static-analysis rules (dbsp_tpu/analysis) — the graph-level analog
        of the AST lint's ``# hotpath: ok`` waiver. Returns self so it
        chains inside builder expressions."""
        self.node.lint_waive = (*self.node.lint_waive, *rule_ids)
        self._touch_metadata()
        return self

    def get(self) -> Any:
        """Value produced this tick (valid during a step)."""
        return self.circuit._values[self.node_index]

    # local (per-worker) id, unique within the circuit
    @property
    def stream_id(self) -> int:
        return self.node_index

    def __repr__(self):
        return f"Stream({self.circuit.path()}/{self.node_index}:{self.node.operator.name})"


@dataclasses.dataclass
class Node:
    """One scheduled unit: an operator plus its input streams.

    A :class:`StrictOperator` contributes TWO nodes — an output half (acts as
    a source; scheduled first) and an input half (acts as a sink; scheduled
    after its input is produced). This is how feedback cycles become a DAG.
    """

    index: int
    operator: Operator
    kind: str  # "source" | "unary" | "binary" | "nary" | "sink"
    #            | "strict_output" | "strict_input" | "subcircuit" | "import"
    inputs: List[int] = dataclasses.field(default_factory=list)
    # for strict halves: the index of the partner node
    partner: Optional[int] = None
    # subcircuit payload
    child: Optional["Circuit"] = None
    # stream metadata (see Stream.schema / Stream.key_sharded /
    # Stream.shard_intent)
    schema: Optional[Tuple] = None
    key_sharded: bool = False
    shard_intent: bool = False  # sugar would hash-shard this on a larger mesh
    host_intent: bool = False  # sugar would host-collapse this on a larger mesh
    # static-analysis rule ids this node is an intentional exception to
    # (see Stream.waive_lint) — the graph-level '# hotpath: ok'
    lint_waive: Tuple[str, ...] = ()


class FeedbackConnector:
    """Handle returned by :meth:`Circuit.add_feedback`; closing the loop with
    :meth:`connect` schedules the strict operator's input half."""

    def __init__(self, circuit: "Circuit", output_node: int, op: StrictOperator):
        self.circuit = circuit
        self.output_node = output_node
        self.op = op
        self.stream = Stream(circuit, output_node)

    def connect(self, input_stream: Stream) -> None:
        if input_stream.circuit is not self.circuit:
            raise CircuitError(
                f"feedback across circuits: {input_stream} belongs to "
                f"circuit {input_stream.circuit.path()}, the connector to "
                f"{self.circuit.path()}")
        if self.circuit.nodes[self.output_node].partner is not None:
            raise CircuitError(
                f"feedback connector for node "
                f"{self.circuit.global_id(self.output_node)} is already "
                "connected")
        node = self.circuit._add_node(self.op, "strict_input",
                                      [input_stream.node_index])
        node.partner = self.output_node
        self.circuit.nodes[self.output_node].partner = node.index


class Circuit:
    """A (possibly nested) dataflow circuit under one logical clock."""

    def __init__(self, parent: Optional["Circuit"] = None,
                 iterative: bool = False):
        self.parent = parent
        self.iterative = iterative
        self.nodes: List[Node] = []
        self._values: Dict[int, Any] = {}
        self.cache: Dict[Any, Any] = {}
        self._executor = None
        self._circuit_handlers: List[Callable[[CircuitEvent], None]] = []
        self._scheduler_handlers: List[Callable[[SchedulerEvent], None]] = []
        self._index_in_parent: Optional[int] = None

    # -- identity -----------------------------------------------------------
    def root(self) -> "Circuit":
        return self if self.parent is None else self.parent.root()

    def scope_depth(self) -> int:
        return 0 if self.parent is None else 1 + self.parent.scope_depth()

    def path(self) -> Tuple[int, ...]:
        if self.parent is None:
            return ()
        return (*self.parent.path(), self._index_in_parent)

    def global_id(self, node_index: int) -> Tuple[int, ...]:
        return (*self.path(), node_index)

    # -- events -------------------------------------------------------------
    def register_circuit_event_handler(self, h) -> None:
        self.root()._circuit_handlers.append(h)

    def register_scheduler_event_handler(self, h) -> None:
        self.root()._scheduler_handlers.append(h)

    def _emit_circuit_event(self, ev: CircuitEvent) -> None:
        for h in self.root()._circuit_handlers:
            h(ev)

    def _emit_scheduler_event(self, ev: SchedulerEvent) -> None:
        for h in self.root()._scheduler_handlers:
            h(ev)

    # -- node insertion (reference: circuit_builder.rs:1943-2224) -----------
    def _add_node(self, op: Operator, kind: str, inputs: List[int],
                  child: Optional["Circuit"] = None) -> Node:
        node = Node(index=len(self.nodes), operator=op, kind=kind,
                    inputs=list(inputs), child=child)
        self.nodes.append(node)
        self._executor = None  # invalidate schedule
        # graph changed: a memoized verification (analysis/verify_circuit)
        # of the old graph must not gate the new one
        self.root()._verify_cache = None
        self._emit_circuit_event(CircuitEvent(
            kind="operator", node_id=self.global_id(node.index), name=op.name))
        for i in inputs:
            self._emit_circuit_event(CircuitEvent(
                kind="edge", from_id=self.global_id(i),
                to_id=self.global_id(node.index)))
        return node

    def add_source(self, op: SourceOperator) -> Stream:
        return Stream(self, self._add_node(op, "source", []).index)

    def add_unary_operator(self, op: UnaryOperator, s: Stream) -> Stream:
        self._check_stream(s)
        return Stream(self, self._add_node(op, "unary", [s.node_index]).index)

    def add_binary_operator(self, op: BinaryOperator, a: Stream, b: Stream
                            ) -> Stream:
        self._check_stream(a), self._check_stream(b)
        return Stream(self, self._add_node(
            op, "binary", [a.node_index, b.node_index]).index)

    def add_nary_operator(self, op: NaryOperator, streams: Sequence[Stream]
                          ) -> Stream:
        for s in streams:
            self._check_stream(s)
        return Stream(self, self._add_node(
            op, "nary", [s.node_index for s in streams]).index)

    def add_sink(self, op: SinkOperator, s: Stream) -> None:
        self._check_stream(s)
        self._add_node(op, "sink", [s.node_index])

    def add_feedback(self, op: StrictOperator) -> FeedbackConnector:
        node = self._add_node(op, "strict_output", [])
        return FeedbackConnector(self, node.index, op)

    def _check_stream(self, s: Stream) -> None:
        if s.circuit is not self:
            raise CircuitError(
                f"stream {s} belongs to a different circuit; use "
                "delta0/import to move values across clock domains")

    def check_wellformed(self) -> None:
        """Build-finalize validation: raise :class:`CircuitError` on
        structurally broken circuits (recursing into children).

        The cheap, always-on subset of the static analyzer
        (dbsp_tpu/analysis/): a dangling ``FeedbackConnector`` (``connect``
        never called) would otherwise SCHEDULE — its strict-output half is
        a source — and yield the z^-1 zero forever on the open edge,
        surfacing as silently wrong answers instead of an error."""
        for n in self.nodes:
            if n.kind == "strict_output" and n.partner is None:
                raise CircuitError(
                    f"dangling FeedbackConnector at node "
                    f"{self.global_id(n.index)} ({n.operator.name}): "
                    "add_feedback was never connect()ed to an input stream")
            if n.child is not None:
                n.child.check_wellformed()

    # -- stepping -----------------------------------------------------------
    def step(self) -> None:
        """Evaluate every node exactly once (one tick of this clock).

        Reference: ``CircuitHandle::step`` (circuit_builder.rs:3658) via the
        static scheduler (schedule/static_scheduler.rs:52).
        """
        from dbsp_tpu.circuit.scheduler import OnceExecutor

        if self._executor is None:
            self._executor = OnceExecutor(self)
        self._executor.run(self)

    def clock_start(self, scope: int = 0) -> None:
        if self.parent is None:
            # child clocks start once per parent tick — only the root clock
            # is a monitor-visible event (reference: one clock per scope)
            self._emit_scheduler_event(SchedulerEvent(kind="clock_start"))
        for n in self.nodes:
            if n.kind != "strict_input":  # one call per operator instance
                n.operator.clock_start(scope)
            if n.child is not None:
                n.child.clock_start(scope + 1)

    def clock_end(self, scope: int = 0) -> None:
        for n in self.nodes:
            if n.kind != "strict_input":
                n.operator.clock_end(scope)
            if n.child is not None:
                n.child.clock_end(scope + 1)
        if self.parent is None:
            self._emit_scheduler_event(SchedulerEvent(kind="clock_end"))


class RootCircuit(Circuit):
    """Top-level circuit under the root clock (one tick == one input delta).

    ``RootCircuit.build(f)`` constructs the dataflow from ``f`` and returns
    the circuit plus ``f``'s result (typically input/output handles) —
    reference: ``circuit_builder.rs:1403``.
    """

    @staticmethod
    def build(constructor: Callable[["RootCircuit"], Any]
              ) -> Tuple["RootCircuit", Any]:
        circuit = RootCircuit()
        result = constructor(circuit)
        circuit.check_wellformed()
        circuit.clock_start(0)
        return circuit, result
