"""Nested circuits: subcircuits, cross-clock imports, iterative execution.

Reference: ``circuit_builder.rs:2287`` (``subcircuit``), ``:2307`` (``iterate``),
``:2332`` (``fixedpoint``), ``operator/delta0.rs`` (cross-clock import),
``schedule/mod.rs:100-139`` (``IterativeExecutor``) and the fixedpoint
contract (``operator_traits.rs:148-196``).

Scope note (deliberate round-1 simplification): the reference's nested
circuits are *incremental across parent ticks* via nested timestamps
(``time/nested_ts32.rs``) — child state persists and per-parent-tick work is
proportional to the parent delta. Here child state RESETS each parent tick
(``clock_start``), so recursion is re-evaluated per parent tick, incremental
only within the iteration (semi-naive). The exported results are identical;
the cross-epoch incrementality is an optimization planned for the nested-
timestamp round. Each child evaluation is still pure device work.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from dbsp_tpu.circuit.builder import (Circuit, CircuitError, CircuitEvent,
                                      Stream)
from dbsp_tpu.circuit.operator import ImportOperator, Operator


class Delta0(ImportOperator):
    """Emits the parent value on the child's first tick, zero afterwards
    (operator/delta0.rs). ``hold=True`` re-emits the value EVERY child tick
    instead — the constant-import shape per-tick operators (stream_join /
    stream_aggregate) need in iterate()-style children."""

    name = "delta0"

    def __init__(self, zero_factory: Callable[[], Any], hold: bool = False):
        self.zero_factory = zero_factory
        self.hold = hold
        self.value: Any = None
        self.first = True

    def import_value(self, value: Any) -> None:
        self.value = value
        self.first = True

    def eval(self) -> Any:
        if self.first or self.hold:
            self.first = False
            return self.value
        return self.zero_factory()


class SubcircuitOp(Operator):
    """Parent-side node owning a child circuit; value = tuple of exports."""

    name = "subcircuit"

    def __init__(self, child: "ChildCircuit"):
        self.child = child


class ChildCircuit(Circuit):
    """A circuit one clock level below its parent.

    Construction: ``parent.subcircuit(constructor)`` — the constructor adds
    child operators and declares imports (``child.import_stream``), exports
    (``child.export``) and termination conditions (``child.add_condition``).
    """

    def __init__(self, parent: Circuit, iterative: bool):
        super().__init__(parent=parent, iterative=iterative)
        self.imports: List[Tuple[int, Delta0]] = []   # (parent node, import op)
        self.exports: List[int] = []                   # child node indices
        self.conditions: List[int] = []                # child node indices
        self.max_iterations = 10_000
        self.iteration = 0            # current child tick (set per step)
        self.run_exact: Optional[int] = None  # fixed iteration count (e.g.
        #                               PageRank-style loops), no fixedpoint
        # True (set by recursive()): child operators are incremental ACROSS
        # parent ticks via nested (epoch, iteration) timestamps — imports are
        # parent DELTAS, join/distinct dispatch to nested variants, and
        # per-epoch work is proportional to the parent delta. False: the
        # round-1 regime — child state resets per epoch, imports must be
        # integrals (iterate()-style children with aggregates use this).
        self.nested_incremental = False

    def import_stream(self, parent_stream: Stream,
                      zero_factory: Optional[Callable[[], Any]] = None,
                      hold: bool = False) -> Stream:
        """delta0 import of a parent stream into this clock domain
        (``hold=True``: re-emit the value every child tick)."""
        if parent_stream.circuit is not self.parent:
            raise CircuitError(
                "import_stream takes a stream of the immediate parent")
        op = Delta0(zero_factory, hold=hold)
        if zero_factory is None:
            schema = getattr(parent_stream, "schema", None)
            if schema is None:
                raise CircuitError(
                    "import_stream needs schema metadata or zero_factory")
            # placement-following zero: the zeros emitted on later child
            # ticks must carry the SAME placement as the imported parent
            # value (a mixed sharded/unsharded merge downstream is a build
            # error), so the default zero copies the lead axis off the
            # value itself — an unsharded host-resident import on a
            # multi-worker mesh (P003-waived shapes) stays unsharded
            key_dtypes, val_dtypes = schema

            def zero_factory():
                from dbsp_tpu.zset.batch import Batch

                v = op.value
                if v is not None and hasattr(v, "weights"):
                    lead = ((v.weights.shape[0],) if v.sharded else ())
                else:
                    from dbsp_tpu.circuit.runtime import Runtime

                    w = Runtime.worker_count()
                    lead = (w,) if w > 1 else ()
                return Batch.empty(key_dtypes, val_dtypes, lead=lead)

            op.zero_factory = zero_factory
        node = self._add_node(op, "import", [])
        self.imports.append((parent_stream.node_index, op))
        s = Stream(self, node.index)
        s.schema = getattr(parent_stream, "schema", None)
        # placement survives the clock-domain crossing: the import emits the
        # parent's batches (or same-placement zeros) unchanged
        s.key_sharded = getattr(parent_stream, "key_sharded", False)
        return s

    def export(self, child_stream: Stream) -> int:
        """Mark a child stream for export; returns its export slot index.

        The exported value is the stream's value on the FINAL child tick
        (reference: ``subcircuit``'s export streams)."""
        if child_stream.circuit is not self:
            raise CircuitError("export takes a stream of this child circuit")
        self.exports.append(child_stream.node_index)
        # exports feed the analyzer's reachability/link checks: a memoized
        # verification of the old graph must not gate the new one
        self.root()._verify_cache = None
        return len(self.exports) - 1

    def add_condition(self, child_stream: Stream) -> None:
        """Register a termination condition: a stream of Z-set batches; the
        iteration stops when ALL condition batches are empty on the same tick
        (reference: ``operator/condition.rs``)."""
        if child_stream.circuit is not self:
            raise CircuitError(
                "add_condition takes a stream of this child circuit")
        self.conditions.append(child_stream.node_index)
        self.root()._verify_cache = None  # see export()


def subcircuit(parent: Circuit, constructor: Callable[[ChildCircuit], Any],
               iterative: bool = True) -> Tuple[Stream, Any]:
    """Build a nested circuit; returns (exports stream, constructor result).

    The exports stream carries a tuple of the child's exported values, one
    entry per ``child.export`` call, produced after the child clock reaches
    its fixedpoint each parent tick.

    The parent node is created BEFORE the constructor runs so child nodes
    have their global path (monitor/profiler event ids depend on it); import
    edges are attached — and their edge events emitted — once the
    constructor has declared them.
    """
    child = ChildCircuit(parent, iterative)
    node = parent._add_node(SubcircuitOp(child), "subcircuit", [], child=child)
    child._index_in_parent = node.index
    result = constructor(child)
    node.inputs = [pidx for (pidx, _) in child.imports]
    parent.root()._verify_cache = None  # inputs changed after _add_node
    for pidx in node.inputs:
        parent._emit_circuit_event(CircuitEvent(
            kind="edge", from_id=parent.global_id(pidx),
            to_id=parent.global_id(node.index)))
    parent._executor = None  # inputs changed; rebuild the schedule
    return Stream(parent, node.index), result
