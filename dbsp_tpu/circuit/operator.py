"""Operator traits — the plugin boundary every circuit node implements.

Equivalent surface to the reference's operator traits
(``crates/dbsp/src/circuit/operator_traits.rs:18-363``): lifecycle hooks
(``clock_start``/``clock_end``), fixedpoint reporting for nested circuits, and
arity-specific ``eval`` signatures. Differences by design:

* No ``is_async``/``ready`` machinery. The reference needs async operators so
  its thread scheduler can overlap exchange communication with compute; here
  cross-worker communication is an XLA collective *inside* a jitted kernel —
  overlap is the compiler's job, so every operator is synchronous on the host.
* ``eval`` takes and returns host Python values (usually :class:`Batch` pytrees
  holding device buffers); device work happens in jitted kernels the operator
  owns. Operators are free to keep device-side state (e.g. spines).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence


class Operator:
    """Base: naming, clock lifecycle, fixedpoint contract."""

    name: str = "operator"

    def clock_start(self, scope: int) -> None:
        """A (possibly nested) clock this operator belongs to started."""

    def clock_end(self, scope: int) -> None:
        """The clock ended (an epoch of the nested circuit completed)."""

    def fixedpoint(self, scope: int) -> bool:
        """True if, fed the same inputs forever, outputs will not change.

        Used by iterative executors to detect quiescence of nested circuits
        (reference contract: ``operator_traits.rs:148-196``). Stateless
        operators are trivially at a fixedpoint.
        """
        return True

    def metadata(self) -> dict:
        """Profiling metadata (sizes, counts) — reference: ``circuit/metadata.rs``."""
        return {}

    # -- checkpoint protocol (no reference analog; SURVEY.md §5 notes the
    # reference only has RocksDB state *spilling*, not restartability) -----
    def state_dict(self) -> dict:
        """Serializable operator state; stateless operators return {}."""
        return {}

    def load_state_dict(self, state: dict) -> None:
        if state:
            raise ValueError(
                f"{self.name} got unexpected checkpoint state "
                f"(keys: {sorted(state)})")


class SourceOperator(Operator):
    """Produces one value per tick (reference: ``operator_traits.rs:202``)."""

    def eval(self) -> Any:
        raise NotImplementedError


class SinkOperator(Operator):
    def eval(self, value: Any) -> None:
        raise NotImplementedError


class UnaryOperator(Operator):
    def eval(self, value: Any) -> Any:
        raise NotImplementedError


class BinaryOperator(Operator):
    def eval(self, a: Any, b: Any) -> Any:
        raise NotImplementedError


class NaryOperator(Operator):
    def eval(self, *values: Any) -> Any:
        raise NotImplementedError


class StrictOperator(Operator):
    """Feedback operator (z^-1): output at t must not depend on input at t.

    The scheduler reads :meth:`get_output` *before* the rest of the circuit
    runs, and feeds the tick's input to :meth:`eval_strict` afterwards
    (reference: ``operator_traits.rs:363`` + ``operator/z1.rs``).
    """

    def get_output(self) -> Any:
        raise NotImplementedError

    def eval_strict(self, value: Any) -> None:
        raise NotImplementedError


class ImportOperator(Operator):
    """Imports a value across a clock-domain boundary into a child circuit
    (reference: ``operator_traits.rs:411``, ``operator/delta0.rs``): receives
    the parent value once per parent tick, emits into the child clock.
    """

    def import_value(self, value: Any) -> None:
        raise NotImplementedError

    def eval(self) -> Any:
        raise NotImplementedError
