"""Schedulers: decide node evaluation order per tick.

Reference: ``crates/dbsp/src/circuit/schedule/`` — a static toposort scheduler
plus a dynamic work-stealing one. Only the static scheduler exists here, by
design: the reference's dynamic scheduler earns its keep by overlapping async
exchange I/O across threads, but in this engine cross-worker communication is
an XLA collective inside a jitted kernel, so the host-side order is a pure
toposort and XLA owns all overlap. (See SURVEY.md §7 "Operators stay a
host-side circuit graph".)

The executor hierarchy mirrors ``schedule/mod.rs:91-143``:
  OnceExecutor      — run the schedule once per tick (root circuits)
  IterativeExecutor — run the child clock to a fixedpoint (nested circuits)
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, List

from dbsp_tpu.circuit.builder import Circuit, CircuitError, Node, \
    SchedulerEvent

if TYPE_CHECKING:
    pass


class CircuitGraphError(CircuitError):
    pass


def static_schedule(circuit: Circuit) -> List[Node]:
    """Topological order; strict-output halves act as sources, so feedback
    cycles are already broken (reference: schedule/static_scheduler.rs:17-88).

    Refuses dangling feedback before ordering (via
    ``Circuit.check_wellformed`` — one shared scan with build-finalize): a
    never-connected FeedbackConnector's output half schedules fine on its
    own (it is a source) and silently emits the z^-1 zero forever — the
    schedule is the last line of defense for circuits not built via
    ``RootCircuit.build``.
    """
    circuit.check_wellformed()
    nodes = circuit.nodes
    indeg = [0] * len(nodes)
    consumers: List[List[int]] = [[] for _ in nodes]
    for n in nodes:
        for i in n.inputs:
            indeg[n.index] += 1
            consumers[i].append(n.index)
    ready = [n.index for n in nodes if indeg[n.index] == 0]
    order: List[Node] = []
    while ready:
        # FIFO keeps sources first and sinks last within ties (stable order
        # aids debugging and profiling diffs).
        idx = ready.pop(0)
        order.append(nodes[idx])
        for c in consumers[idx]:
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
    if len(order) != len(nodes):
        stuck = [n.index for n in nodes if n not in order]
        raise CircuitGraphError(
            f"circuit has a non-strict cycle through nodes {stuck}; every "
            "feedback loop must pass through a strict (z^-1) operator")
    return order


def _eval_node(circuit: Circuit, node: Node) -> None:
    op = node.operator
    gid = circuit.global_id(node.index)
    circuit._emit_scheduler_event(SchedulerEvent(
        kind="eval_start", node_id=gid, name=op.name,
        time_ns=time.perf_counter_ns()))
    vals = [circuit._values[i] for i in node.inputs]
    if node.kind == "source":
        circuit._values[node.index] = op.eval()
    elif node.kind == "import":
        circuit._values[node.index] = op.eval()
    elif node.kind == "unary":
        circuit._values[node.index] = op.eval(vals[0])
    elif node.kind == "binary":
        circuit._values[node.index] = op.eval(vals[0], vals[1])
    elif node.kind == "nary":
        circuit._values[node.index] = op.eval(*vals)
    elif node.kind == "sink":
        op.eval(vals[0])
    elif node.kind == "strict_output":
        circuit._values[node.index] = op.get_output()
    elif node.kind == "strict_input":
        op.eval_strict(vals[0])
    elif node.kind == "subcircuit":
        circuit._values[node.index] = IterativeExecutor.run_child(
            node.child, vals, scope=circuit.scope_depth() + 1)
    else:  # pragma: no cover
        raise AssertionError(f"unknown node kind {node.kind}")
    circuit._emit_scheduler_event(SchedulerEvent(
        kind="eval_end", node_id=gid, name=op.name,
        time_ns=time.perf_counter_ns()))


class OnceExecutor:
    """Evaluate each node exactly once per tick (reference: schedule/mod.rs:143)."""

    def __init__(self, circuit: Circuit):
        self.order = static_schedule(circuit)

    def run(self, circuit: Circuit) -> None:
        circuit._emit_scheduler_event(SchedulerEvent(
            kind="step_start", time_ns=time.perf_counter_ns()))
        for node in self.order:
            _eval_node(circuit, node)
        circuit._values.clear()
        circuit._emit_scheduler_event(SchedulerEvent(
            kind="step_end", time_ns=time.perf_counter_ns()))


class IterativeExecutor:
    """Run a child circuit's clock to a fixedpoint once per parent tick
    (reference: schedule/mod.rs:100-139).

    Termination: every registered condition stream produced an empty batch on
    the tick (host-checked scalar), matching the reference's Condition
    operator; operators additionally report ``fixedpoint()`` which guards
    against dirty traces.
    """

    @staticmethod
    def run_child(child, parent_vals, scope: int):
        # fresh epoch: reset child state (see nested.py scope note)
        child.clock_start(scope)
        for (_, op), v in zip(child.imports, parent_vals):
            op.import_value(v)
        if child._executor is None:
            child._executor = OnceExecutor(child)

        exports = None
        limit = child.run_exact if child.run_exact is not None \
            else child.max_iterations
        for it in range(limit):
            child.iteration = it  # nested ops read the (epoch, i) clock
            # evaluate one child tick, capturing export/condition values
            child._emit_scheduler_event(SchedulerEvent(kind="step_start"))
            for node in child._executor.order:
                _eval_node(child, node)
            exports = tuple(child._values[i] for i in child.exports)
            done = all(
                int(child._values[i].live_count()) == 0
                for i in child.conditions) if child.conditions else True
            child._values.clear()
            child._emit_scheduler_event(SchedulerEvent(kind="step_end"))
            if child.run_exact is None and done and all(
                    n.operator.fixedpoint(scope) for n in child.nodes):
                break
        else:
            if child.run_exact is None:
                raise RuntimeError(
                    f"nested circuit did not reach a fixedpoint within "
                    f"{child.max_iterations} iterations")
        child.clock_end(scope)
        return exports
