"""Runtime + handle: client-facing entry points for running circuits.

Reference surface: ``Runtime::init_circuit`` / ``DBSPHandle``
(``crates/dbsp/src/circuit/dbsp_handle.rs:36,175,246``) and the worker pool in
``circuit/runtime.rs:137``. The execution model differs fundamentally — and
deliberately:

* The reference runs N OS threads, each with a clone of the circuit,
  exchanging data through shared-memory mailboxes. Here there is ONE host
  circuit whose batches are device arrays laid out over a
  ``jax.sharding.Mesh`` of N workers (TPU cores/chips); sharded operators run
  SPMD via ``shard_map`` and exchange data with XLA collectives over ICI.
  The reference's per-step worker barrier (exchange is a synchronization
  point) is exactly the SPMD step semantics, so the programming models agree.
* There is no client/worker command channel: the host thread IS the driver,
  and ``step()`` dispatches device work directly.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Tuple

from dbsp_tpu.circuit.builder import Circuit, RootCircuit


class RuntimeError_(RuntimeError):
    pass


class _CurrentRuntime(threading.local):
    rt: Optional["Runtime"] = None


class Runtime:
    """Execution context: worker (mesh) configuration for a circuit.

    The ambient "current runtime" is THREAD-LOCAL: circuit builds and steps
    happen concurrently on manager handler threads, controller flush
    threads, and the compiler service's queue worker — a process-global
    slot would let one thread's save/restore clobber another's mid-build
    (a multi-worker circuit would then silently build with worker_count()
    == 1 and no sharding)."""

    _tls = _CurrentRuntime()

    def __init__(self, workers: int = 1, mesh=None,
                 build_only: bool = False):
        """``build_only=True`` skips device-mesh construction: the runtime
        can BUILD a workers-N circuit graph (the sugar only reads
        ``workers``) but not step it. Static analysis uses this to
        materialize the real N-worker node shapes — exchanges, unshards —
        on hosts with fewer than N devices (the P003 sweep in
        tools/lint_all.py builds every query this way)."""
        from dbsp_tpu.parallel.mesh import make_mesh

        self.workers = workers
        self.mesh = mesh if mesh is not None else (
            make_mesh(workers) if workers > 1 and not build_only else None)

    @staticmethod
    def current() -> Optional["Runtime"]:
        return Runtime._tls.rt

    @staticmethod
    def _swap(rt: Optional["Runtime"]) -> Optional["Runtime"]:
        """Install ``rt`` as this THREAD's current runtime; returns the
        previous one for the caller's finally-restore."""
        prev, Runtime._tls.rt = Runtime._tls.rt, rt
        return prev

    @staticmethod
    def worker_count() -> int:
        rt = Runtime._tls.rt
        return rt.workers if rt is not None else 1

    @staticmethod
    def init_circuit(workers: int,
                     constructor: Callable[[RootCircuit], Any]
                     ) -> Tuple["CircuitHandle", Any]:
        """Build a circuit configured for ``workers`` SPMD workers and return
        a stepping handle plus the constructor's result (the I/O handles)."""
        runtime = Runtime(workers)
        prev = Runtime._swap(runtime)
        try:
            circuit, result = RootCircuit.build(constructor)
        finally:
            Runtime._swap(prev)
        return CircuitHandle(circuit, runtime), result


class CircuitHandle:
    """Steps a built circuit; collects per-step latency for the profiler.

    Reference: ``DBSPHandle::step`` (dbsp_handle.rs:246). ``kill``/worker-panic
    machinery has no analog — failures surface as Python exceptions on the
    driving thread, synchronously.
    """

    # execution-surface tag (CompiledCircuitDriver says "compiled"); the
    # server's /status and the manager's describe() report it
    mode = "host"

    def __init__(self, circuit: Circuit, runtime: Runtime):
        self.circuit = circuit
        self.runtime = runtime
        self.step_times_ns: list[int] = []

    def step(self) -> None:
        prev = Runtime._swap(self.runtime)
        t0 = time.perf_counter_ns()
        try:
            self.circuit.step()
        finally:
            Runtime._swap(prev)
        self.step_times_ns.append(time.perf_counter_ns() - t0)
