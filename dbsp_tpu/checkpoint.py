"""Checkpoint / restore: durable, crash-safe snapshots of pipeline state.

Designed fresh — the reference has NO checkpointing; its closest capability
is the RocksDB ``PersistentTrace`` (``trace/persistent/mod.rs:40-45``,
SURVEY.md §5: "state spilling, not restartability"). The durability model
here is Flink's asynchronous barrier snapshotting (Carbone et al., "State
Management in Apache Flink", VLDB'17) collapsed to our single-clock
setting: the tick number IS the barrier, so a checkpoint is one consistent
cut — engine state at a validated tick plus the retained (not yet
validated) input feeds past it — and recovery replays those retained
inputs deterministically for exactly-once resumption (the same
high-water-mark semantics the compiled engine's overflow replay already
relies on).

Format (version 2) — versioned, checksummed, atomically written:

    <dir>/CURRENT               name of the newest valid generation
    <dir>/gen-00000007/
        manifest.json           {"payload": {...}, "sha256": <hex>}
        <blob>.npy              one numpy array per state-tree leaf

Every blob's SHA-256 (and the manifest payload's own) is recorded and
verified on load; a generation is written under a temp name and
``os.replace``d into place, then CURRENT is atomically swapped — a
PROCESS crash (SIGKILL included) at ANY point leaves the previous
generation intact and loadable. A corrupted/truncated CURRENT generation
falls back to the newest older generation that still verifies (callers
surface this as a ``restore`` flight event / SLO incident).
``DBSP_TPU_CHECKPOINT_FSYNC=1`` additionally fsyncs every write for
power-loss durability (see :data:`FSYNC` for why it defaults off).

Incremental across generations: deep trace levels of a compiled handle are
version-counted by maintenance drains (the same counters PR 3's
incremental ``snapshot()`` uses). A level untouched since the previous
generation is HARD-LINKED into the new one instead of re-serialized, so
steady-state checkpoint cost is O(level 0 + small states), not O(trace).

Three targets share the format (``engine`` field): a host
:class:`~dbsp_tpu.circuit.runtime.CircuitHandle` (operator ``state_dict``
walk), a bare :class:`~dbsp_tpu.compiled.compiler.CompiledHandle`, and a
serving :class:`~dbsp_tpu.compiled.driver.CompiledCircuitDriver` (engine
states + caps + slotted-l0 geometry + maintain cursors + tick counter +
retained-feed replay window). The circuit must be rebuilt by the same
constructor before ``restore`` — structure is checked and a mismatch
rejected.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from dbsp_tpu.zset.batch import Batch

FORMAT_VERSION = 2

#: generations retained on disk (older ones pruned after a successful
#: write); >= 2 so a corrupted CURRENT always has a fallback
KEEP_GENERATIONS = max(2, int(os.environ.get("DBSP_TPU_CHECKPOINT_KEEP",
                                             "3")))

#: default periodic-checkpoint cadence (controller ticks) when a
#: checkpoint directory is configured but no explicit interval is set
DEFAULT_EVERY_TICKS = 64

#: fsync policy (DBSP_TPU_CHECKPOINT_FSYNC=1 to enable). Default OFF:
#: the crash model checkpoints exist for is PROCESS death (SIGKILL —
#: the fault harness's induced crash), which the page cache survives, so
#: the atomic write/rename ordering alone makes restores exact; fsync
#: buys durability against POWER/kernel loss at ~170 ms per save on a
#: typical fs (measured: ~85% of warm-save cost), and even without it a
#: torn post-power-loss generation is caught by the checksums and falls
#: back one generation — the same default posture as RocksDB WAL writes
#: and Kafka's page-cache flush policy.
FSYNC = os.environ.get("DBSP_TPU_CHECKPOINT_FSYNC", "0") == "1"


def _maybe_fsync(f) -> None:
    if FSYNC:
        f.flush()
        os.fsync(f.fileno())


class CheckpointError(AssertionError):
    """Unloadable/mismatched checkpoint. Subclasses AssertionError for
    backwards compatibility with pre-v2 callers that caught the structure
    check's assert."""


# ---------------------------------------------------------------------------
# State-schema registry (tools/check_state.py lints against this)
# ---------------------------------------------------------------------------

#: Every instance attribute of the stateful serving classes must be claimed
#: here, keyed by class, as one of:
#:   "persisted"  — captured in the checkpoint manifest/blobs
#:   "derived"    — reconstructible from persisted state (caches, stats,
#:                  observability samples); safe to lose on crash
#:   "config"     — rebuilt from the program/config at deploy, not state
#:   "runtime"    — process-local machinery (locks, threads, sockets)
#: ``tools/check_state.py`` walks the class bodies and fails when an
#: attribute is missing here (state growth can never silently break
#: restore) or when a claimed attribute vanished (stale schema).
STATE_SCHEMA: Dict[str, Dict[str, str]] = {
    "CompiledHandle": {
        "states": "persisted",
        "maintain_pending": "persisted",
        "_level_versions": "persisted",
        "circuit": "config",
        "runtime": "config",
        "mesh": "config",
        "workers": "config",
        "order": "config",
        "cnodes": "config",      # caps + _slot_cap persisted per cnode
        "by_index": "config",
        "deferred_consolidations": "config",
        "_op_to_index": "config",
        "_gen_fn": "config",
        "_step_jit": "derived",
        "_scan_jits": "derived",
        # device-resident tick cursor (program output, re-uploaded on any
        # discontinuity) + the transfer-guard level testing/retrace.py arms
        "_tick_dev": "derived",
        "_tick_host": "derived",
        "_steady_guard": "runtime",
        "_checks": "derived",
        "_req": "derived",
        "_max_jit": "derived",
        "last_req": "derived",
        "last_outputs": "derived",
        "step_times_ns": "derived",
        "overflow_replays": "derived",
        # exchange-bucket overflow subset of the replays (skew hazard
        # observability; mirrored process-wide in parallel/exchange.py)
        "exchange_overflows": "derived",
        "host_overhead_ns": "derived",
        "tick_causes": "derived",
        "_pending_causes": "derived",
        "maintain_stats": "derived",
        "_snap_levels": "derived",
        "_ckpt_salt": "derived",  # hard-link scope marker, per process
        # tiered trace residency (dbsp_tpu/residency.py): the tier map and
        # disk blob metadata are persisted (payload "residency" /
        # "cold_blobs") so restore can leave disk-demoted levels on disk;
        # the LRU clock, transition observability, and the store handle
        # rebuild from a fresh run
        "residency_cfg": "config",
        "_tiers": "persisted",
        "_cold_meta": "persisted",
        "_cold_store": "runtime",
        "_lru": "derived",
        "_interval": "derived",
        "residency_stats": "derived",
        "residency_log": "derived",
        "cold_events": "derived",
    },
    "CompiledCircuitDriver": {
        "mode": "config",
        "_tick": "persisted",
        "_retained": "persisted",
        "host_handle": "config",
        "circuit": "config",
        "ch": "config",           # its own persisted parts listed above
        "validate_every": "config",
        "_inputs": "config",
        "_outputs": "config",
        "_snap": "derived",       # rebuilt from restored state on resume
        "_out_buffer": "derived",  # rebuilt by replaying _retained
        "_interval_open_ts": "derived",  # wall-clock restamped on resume
        "spans": "runtime",
    },
    "Controller": {
        "steps": "persisted",
        "total_pushed": "persisted",
        "handle": "config",
        "catalog": "config",
        "config": "config",
        "checkpoint_dir": "config",
        "checkpoint_every": "config",
        "_residency_cfg": "config",  # resolved residency budgets,
                                     # re-applied after a host restore
        "inputs": "config",       # endpoint counters persisted via
        "outputs": "config",      # _controller_state() (see _InputEndpoint)
        "state": "runtime",
        "_stop": "runtime",
        "_pushed": "derived",     # buffered-not-yet-stepped rows replay
        "_pushed_lock": "runtime",
        "_running": "runtime",
        "_thread": "runtime",
        "_step_lock": "runtime",
        "_lifecycle_lock": "runtime",
        "_monitors": "runtime",
        "flight": "runtime",
        "timeline": "runtime",   # obs wiring; its ring is rebuilt live
        "checkpoints": "derived",
        "checkpoint_error": "derived",
        "last_checkpoint_tick": "persisted",
        "_last_ckpt_step": "derived",
        "read_plane": "persisted",  # per-view merged state rides the
                                    # "read_plane" payload; epoch in the
                                    # manifest ("read_epoch")
        "e2e": "runtime",   # delta-trace contexts die with the process:
                            # a restored pipeline mints fresh trace ids
    },
    "_InputEndpoint": {
        "total_records": "persisted",   # consumed high-water mark: the
        "total_bytes": "persisted",     # replay position recovery resumes
        "name": "config",               # input feeds from
        "collection": "config",
        "transport": "config",
        "parser": "config",
        "notify_arrival": "config",  # freshness stamp hook (controller)
        "lock": "runtime",
        "rows": "derived",    # in-flight rows not yet stepped: upstream
        "eoi": "derived",     # replays them past the checkpoint tick
        "paused": "derived",
        "error": "derived",
        "skip_rows": "derived",  # set from the persisted total_records at
    },                           # restore (replay-from-start transports)
    "_OutputEndpoint": {
        "name": "config",
        "collection": "config",
        "transport": "config",
        "encoder": "config",
        "total_records": "derived",  # at-least-once on the output side:
        "total_bytes": "derived",    # sinks dedup by tick (X-Dbsp-Step)
        "cursor": "derived",
        "error": "derived",
        "pending": "persisted",  # failed-write retry batch rides the
    },                           # manifest (output_pending) so a crash
                                 # cannot drop an undelivered delta
    "ReadPlane": {
        "enabled": "config",
        "capacity": "config",
        "compact_after": "config",
        "_lock": "runtime",
        "_wakeup": "runtime",
        "_views": "persisted",   # each view's merged snapshot state is a
                                 # consolidated Batch in the "read_plane"
                                 # payload (state_batches()/restore())
        "epoch": "persisted",    # manifest "read_epoch" via
                                 # Controller._controller_state()
        "publishes": "derived",
        "last_publish_ts": "derived",
        "flight": "runtime",
        "_read_qps": "runtime",
        "_read_seconds": "runtime",
        "_publish_total": "runtime",
    },
    "_ViewState": {
        "name": "config",
        "handle": "config",
        "mode": "config",
        "nkeys": "derived",      # recomputed from the restored batch
        "cid": "runtime",        # consumer re-registered on restore
        "snap": "persisted",     # the merged rows ARE the read_plane blob
        "prev_rows": "derived",  # rebuilt from the restored snapshot
        "feed": "derived",       # reset; old cursors resume through a
        "dropped_epoch": "derived",  # synthesized kind="snapshot" record
        "seen_step": "derived",
    },
    "ReplicaServer": {
        # stateless by contract: the whole state is the changefeed fold,
        # reconstructible from epoch 0 (or any snapshot record) — nothing
        # to checkpoint, which is what makes replicas free to scale
        "primary": "config",
        "views_served": "config",
        "name": "config",
        "poll_timeout_s": "config",
        "_lock": "runtime",
        "_state": "derived",
        "_cursor": "derived",
        "_nkeys": "derived",
        "_applied_ts": "derived",
        "_sorted": "derived",
        "applied": "derived",
        "stalled": "runtime",
        "_stop": "runtime",
        "_httpd": "runtime",
        "port": "runtime",
        "_serve_thread": "runtime",
        "_feed_thread": "runtime",
        "e2e": "runtime",     # shared tracer wiring (writer-owned)
        "spans": "runtime",   # this process's span ring — trace surface
        "_trace": "derived",  # per-view applied trace annotations: the
                              # changefeed fold re-derives them
    },
}


# ---------------------------------------------------------------------------
# State-tree encoding (arrays out-of-line as named blobs)
# ---------------------------------------------------------------------------


class _Encoder:
    def __init__(self):
        self.arrays: Dict[str, np.ndarray] = {}
        self.counter = 0
        self._hint = "a"

    def _store(self, arr) -> str:
        key = f"{self._hint}{self.counter}"
        self.counter += 1
        self.arrays[key] = np.asarray(arr)
        return key

    def encode(self, v: Any, hint: Optional[str] = None) -> Any:
        """Encode a state pytree; ``hint`` prefixes this subtree's blob
        names (deterministic names are what lets an unchanged trace level
        hard-link its previous generation's blobs)."""
        if hint is not None:
            prev_hint, prev_counter = self._hint, self.counter
            self._hint, self.counter = hint + "_", 0
            try:
                return self.encode(v)
            finally:
                self._hint, self.counter = prev_hint, prev_counter
        if isinstance(v, Batch):
            return {"__batch__": {
                "keys": [self._store(c) for c in v.keys],
                "vals": [self._store(c) for c in v.vals],
                "weights": self._store(v.weights),
                # sorted-run aux metadata: part of the batch's identity
                # (consolidation regime dispatch + compiled pytree aux)
                "runs": list(v.runs) if v.runs is not None else None,
            }}
        from dbsp_tpu.trace.spine import Spine

        if isinstance(v, Spine):
            return {"__spine__": {
                "key_dtypes": [str(d) for d in v.key_dtypes],
                "val_dtypes": [str(d) for d in v.val_dtypes],
                "batches": [self.encode(b) for b in v.batches],
                "dirty": v.dirty,
            }}
        if isinstance(v, (jnp.ndarray, np.ndarray)):
            return {"__array__": self._store(v)}
        if isinstance(v, np.generic):  # numpy scalar (int64(3), bool_, ...)
            return {"__scalar__": v.item(), "dtype": str(v.dtype)}
        if isinstance(v, dict):
            return {"__dict__": {k: self.encode(x) for k, x in v.items()}}
        if isinstance(v, (list, tuple)):
            return {"__seq__": [self.encode(x) for x in v],
                    "tuple": isinstance(v, tuple)}
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        raise TypeError(f"unsupported checkpoint value type {type(v)}")


class _NpDecoder:
    """Variant decoder materializing HOST numpy copies — used for
    residency-demoted (host-tier) trace levels at restore, which must not
    round-trip through device memory just to come back off it. Built on
    the same loader; only ``_arr`` differs."""

    def __init__(self, load_array):
        self.load = load_array

    def _arr(self, name: str) -> np.ndarray:
        return np.array(self.load(name))  # copy: the loader cache is shared

    decode = None  # assigned below (shares _Decoder.decode)


class _Decoder:
    """Decodes against a blob loader (verifying checksums lazily).

    Every array materializes through :meth:`_arr` — ``jnp.array`` (a
    COPY), never ``jnp.asarray``: on the CPU backend ``asarray`` can
    zero-copy-wrap the numpy buffer, and the compiled step program
    DONATES its state inputs — XLA would then alias/free memory the
    decoder still owns (observed: garbage int64 state one tick after
    restore, heap corruption, flaky SIGSEGV)."""

    def __init__(self, load_array):
        self.load = load_array

    def _arr(self, name: str) -> jnp.ndarray:
        return jnp.array(self.load(name))

    def decode(self, v: Any) -> Any:
        if isinstance(v, dict):
            if "__batch__" in v:
                b = v["__batch__"]
                runs = tuple(b["runs"]) if b.get("runs") is not None else None
                return Batch(
                    tuple(self._arr(k) for k in b["keys"]),
                    tuple(self._arr(k) for k in b["vals"]),
                    self._arr(b["weights"]), runs)
            if "__spine__" in v:
                from dbsp_tpu.trace.spine import Spine

                s = v["__spine__"]
                spine = Spine([jnp.dtype(d) for d in s["key_dtypes"]],
                              [jnp.dtype(d) for d in s["val_dtypes"]])
                spine.batches = [self.decode(b) for b in s["batches"]]
                spine.dirty = s["dirty"]
                return spine
            if "__array__" in v:
                return self._arr(v["__array__"])
            if "__scalar__" in v:
                return np.dtype(v["dtype"]).type(v["__scalar__"])
            if "__dict__" in v:
                return {k: self.decode(x) for k, x in v["__dict__"].items()}
            if "__seq__" in v:
                seq = [self.decode(x) for x in v["__seq__"]]
                return tuple(seq) if v["tuple"] else seq
        return v


_NpDecoder.decode = _Decoder.decode  # same walk, numpy leaves


# ---------------------------------------------------------------------------
# Generation store: atomic writes, checksums, fallback scan
# ---------------------------------------------------------------------------


def _gen_name(n: int) -> str:
    return f"gen-{n:08d}"


def _gen_number(name: str) -> Optional[int]:
    if name.startswith("gen-"):
        try:
            return int(name[4:])
        except ValueError:
            return None
    return None


def _list_generations(path: str) -> List[Tuple[int, str]]:
    """(number, name) of every generation directory, newest first."""
    out = []
    try:
        entries = os.listdir(path)
    except OSError:
        return []
    for name in entries:
        n = _gen_number(name)
        if n is not None and os.path.isdir(os.path.join(path, name)):
            out.append((n, name))
    out.sort(reverse=True)
    return out


def exists(path: str) -> bool:
    """True when ``path`` holds at least one checkpoint generation."""
    return bool(path) and os.path.isdir(path) and \
        bool(_list_generations(path))


def _sha256_file(p: str) -> str:
    h = hashlib.sha256()
    with open(p, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _payload_digest(payload: dict) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()


def _read_manifest(gen_dir: str) -> dict:
    """Load + verify one generation's manifest; raises CheckpointError."""
    mpath = os.path.join(gen_dir, "manifest.json")
    try:
        with open(mpath) as f:
            wrapper = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable manifest {mpath}: {e}") from e
    payload = wrapper.get("payload")
    if not isinstance(payload, dict) or \
            wrapper.get("sha256") != _payload_digest(payload):
        raise CheckpointError(f"manifest checksum mismatch in {gen_dir}")
    if payload.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint format {payload.get('format')} != {FORMAT_VERSION}")
    return payload


def _verify_blobs(gen_dir: str, payload: dict,
                  bytes_cache: Optional[Dict[str, bytes]] = None) -> None:
    """Verify every blob's size+digest up front (restore must not get
    halfway through mutating engine state before hitting corruption).
    ``bytes_cache`` keeps the verified bytes for the loader so the
    restore path reads each blob from disk exactly once."""
    for name, meta in payload.get("arrays", {}).items():
        p = os.path.join(gen_dir, name + ".npy")
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointError(
                f"blob {name} unreadable in {gen_dir}: {e}") from e
        if len(data) != meta["bytes"]:
            raise CheckpointError(f"blob {name} truncated in {gen_dir}")
        if hashlib.sha256(data).hexdigest() != meta["sha256"]:
            raise CheckpointError(
                f"blob {name} checksum mismatch in {gen_dir}")
        if bytes_cache is not None:
            bytes_cache[name] = data


def _make_loader(gen_dir: str, payload: dict,
                 bytes_cache: Optional[Dict[str, bytes]] = None):
    cache: Dict[str, np.ndarray] = {}
    bytes_cache = bytes_cache if bytes_cache is not None else {}

    def load(name: str) -> np.ndarray:
        if name not in cache:
            data = bytes_cache.pop(name, None)  # verified read, if any
            if data is None:
                p = os.path.join(gen_dir, name + ".npy")
                with open(p, "rb") as f:
                    data = f.read()
            cache[name] = np.load(io.BytesIO(data), allow_pickle=False)
        return cache[name]

    return load


def load_manifest(path: str, verify_blobs: bool = True,
                  bytes_cache: Optional[Dict[str, bytes]] = None
                  ) -> Tuple[str, dict, Optional[str]]:
    """(generation name, verified payload, fallback_from) for the newest
    loadable generation. Tries CURRENT first, then older generations —
    ``fallback_from`` names the corrupt generation that was skipped (the
    caller's cue to emit a ``restore`` incident). Raises
    :class:`CheckpointError` when nothing verifies.

    ``verify_blobs=False`` checks only the manifest (its own checksum):
    the SAVE path uses it to find the previous generation for hard-link
    reuse — re-hashing the whole previous state per periodic checkpoint
    would make saves O(state) again, and a bit-rotted linked blob is
    still caught at RESTORE time (the recorded digest rides along)."""
    if not os.path.isdir(path):
        raise CheckpointError(f"no checkpoint directory {path!r}")
    current = None
    try:
        with open(os.path.join(path, "CURRENT")) as f:
            current = f.read().strip() or None
    except OSError:
        pass
    gens = [name for _, name in _list_generations(path)]
    if current in gens:  # CURRENT first, then the rest newest-first
        gens.remove(current)
        gens.insert(0, current)
    if not gens:
        raise CheckpointError(f"no checkpoint generations under {path!r}")
    fallback_from: Optional[str] = None
    last_err: Optional[Exception] = None
    for name in gens:
        gen_dir = os.path.join(path, name)
        try:
            payload = _read_manifest(gen_dir)
            if verify_blobs:
                _verify_blobs(gen_dir, payload, bytes_cache)
            return name, payload, fallback_from
        except CheckpointError as e:
            if bytes_cache is not None:
                bytes_cache.clear()  # partial reads of a bad generation
            if fallback_from is None:
                fallback_from = name
            last_err = e
    raise CheckpointError(
        f"no valid checkpoint generation under {path!r}: {last_err}")


def _fsync_dir(path: str) -> None:
    if not FSYNC:
        return
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # fsync on directories is best-effort on some filesystems


def _write_generation(path: str, payload: dict, enc: _Encoder,
                      linked: Dict[str, str],
                      linked_meta: Optional[Dict[str, dict]] = None,
                      copied: Optional[Dict[str, str]] = None
                      ) -> Tuple[str, dict]:
    """Write one generation atomically: blobs + manifest land in a temp
    dir, which is renamed into place before CURRENT is swapped. ``linked``
    maps blob name -> absolute source path to hard-link instead of
    serializing (clean deep levels); ``linked_meta`` carries their
    already-recorded digests so a linked blob is never re-hashed (saves
    stay O(dirty state), not O(state)). ``copied`` maps blob name ->
    source path to COPY (new inode): the first generation's capture of a
    cold-store blob must not share the store file's inode, or in-place
    bit-rot would take the recovery copy down with the store (subsequent
    generations hard-link the generation copy). Returns
    (gen name, stats)."""
    os.makedirs(path, exist_ok=True)
    # sweep orphaned temp dirs from writers that died mid-save (SIGKILL
    # mid-serialization leaves up to a full state copy under .tmp-*; a
    # crash-looping pipeline would otherwise fill the disk one orphan per
    # crash — the store has one writer by design, so any .tmp-* is dead)
    for entry in os.listdir(path):
        if entry.startswith(".tmp-"):
            shutil.rmtree(os.path.join(path, entry), ignore_errors=True)
    gens = _list_generations(path)
    gen_no = (gens[0][0] + 1) if gens else 1
    name = _gen_name(gen_no)
    payload = dict(payload, format=FORMAT_VERSION, generation=gen_no,
                   created_ts=time.time())
    tmp = os.path.join(path, f".tmp-{name}-{os.getpid()}")
    os.makedirs(tmp)
    arrays: Dict[str, dict] = {}
    nbytes = 0
    linked_meta = linked_meta or {}
    for blob, src in linked.items():
        dst = os.path.join(tmp, blob + ".npy")
        try:
            os.link(src, dst)
        except OSError:  # cross-device / FS without hard links
            shutil.copy2(src, dst)
        meta = linked_meta.get(blob)
        if meta is None:  # unexpected: fall back to hashing the file
            meta = {"sha256": _sha256_file(dst),
                    "bytes": os.path.getsize(dst)}
        arrays[blob] = meta
        nbytes += meta["bytes"]
    for blob, src in (copied or {}).items():
        dst = os.path.join(tmp, blob + ".npy")
        shutil.copy2(src, dst)
        meta = linked_meta.get(blob)
        if meta is None:
            meta = {"sha256": _sha256_file(dst),
                    "bytes": os.path.getsize(dst)}
        arrays[blob] = meta
        nbytes += meta["bytes"]
    for key, arr in enc.arrays.items():
        # serialize to memory, hash the bytes, write ONCE — hashing the
        # file after np.save would re-read every fresh blob from disk,
        # doubling save-path I/O on the periodic hot path
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        data = buf.getvalue()
        with open(os.path.join(tmp, key + ".npy"), "wb") as f:
            f.write(data)
            _maybe_fsync(f)
        arrays[key] = {"sha256": hashlib.sha256(data).hexdigest(),
                       "bytes": len(data)}
        nbytes += len(data)
    payload["arrays"] = arrays
    wrapper = {"payload": payload, "sha256": _payload_digest(payload)}
    mpath = os.path.join(tmp, "manifest.json")
    with open(mpath, "w") as f:
        json.dump(wrapper, f)
        _maybe_fsync(f)
    final = os.path.join(path, name)
    shutil.rmtree(final, ignore_errors=True)  # stale dir from a dead writer
    os.replace(tmp, final)
    _fsync_dir(path)
    # CURRENT swap: readers always see either the old or the new pointer
    cur_tmp = os.path.join(path, ".CURRENT.tmp")
    with open(cur_tmp, "w") as f:
        f.write(name)
        _maybe_fsync(f)
    os.replace(cur_tmp, os.path.join(path, "CURRENT"))
    _fsync_dir(path)
    # retention: prune old generations (hard-linked blobs stay alive via
    # the new generation's directory entries)
    for n, gname in _list_generations(path)[KEEP_GENERATIONS:]:
        shutil.rmtree(os.path.join(path, gname), ignore_errors=True)
    return name, {"generation": gen_no,
                  "arrays": len(arrays),
                  "linked_arrays": len(linked),
                  "copied_arrays": len(copied or {}),
                  "bytes": nbytes}


# ---------------------------------------------------------------------------
# Host circuit walking (engine = "host")
# ---------------------------------------------------------------------------


def _walk(circuit, prefix=()):
    for node in circuit.nodes:
        if node.kind == "strict_input":
            continue  # same operator instance as its strict_output partner
        yield (*prefix, node.index), node
        if node.child is not None:
            yield from _walk(node.child, (*prefix, node.index))


def _host_structure(circuit) -> list:
    return [[list(gid), node.operator.name, node.kind]
            for gid, node in _walk(circuit)]


def _save_host(handle, enc: _Encoder) -> dict:
    from dbsp_tpu import residency as _res

    # disk-tier spine levels are streaming-VERIFIED in place before they
    # are serialized: encoding raw memmap bytes would stamp a bit-rotted
    # blob with a fresh valid checksum — corruption laundered into a
    # checkpoint that verifies clean forever. verify_meta (not a fault):
    # no whole-tier materialization in RAM, no spine mutation, no
    # release/sweep churn — the tiers survive the save untouched.
    for sp in _res.circuit_spines(handle.circuit):
        batches = getattr(sp, "batches", None)
        if not batches:
            continue
        for i, b in enumerate(list(batches)):
            if not isinstance(b.weights, np.memmap):
                continue
            meta = getattr(sp, "_disk_meta", {}).get(id(b)) or \
                _res.meta_from_batch(b)
            if sp._store().verify_meta(meta):
                # a blob was healed: the open memmap still maps the OLD
                # corrupted inode — re-open so the encoder reads the
                # recovered bytes, and re-key the meta to the new object
                fresh = _res.disk_batch(meta, sp._store())
                sp.batches[i] = fresh
                if sp._disk_meta.pop(id(b), None) is not None:
                    sp._disk_meta[id(fresh)] = meta
    states = {}
    for gid, node in _walk(handle.circuit):
        sd = node.operator.state_dict()
        if sd:
            states[json.dumps(list(gid))] = enc.encode(sd)
    return {"engine": "host",
            "structure": _host_structure(handle.circuit),
            "states": states,
            "tick": len(handle.step_times_ns)}


def _restore_host(handle, payload: dict, dec: _Decoder) -> None:
    structure = _host_structure(handle.circuit)
    if structure != payload["structure"]:
        raise CheckpointError(
            "circuit structure differs from the checkpointed circuit — "
            "rebuild with the same constructor before restoring")
    states = payload["states"]
    # two-phase: decode everything BEFORE the first load_state_dict, so a
    # decode failure cannot leave a half-restored circuit
    decoded = {key: dec.decode(st) for key, st in states.items()}
    for gid, node in _walk(handle.circuit):
        key = json.dumps(list(gid))
        if key in decoded:
            node.operator.load_state_dict(decoded[key])


# ---------------------------------------------------------------------------
# Compiled engine (engine = "compiled")
# ---------------------------------------------------------------------------


def _compiled_structure(ch) -> list:
    return [[cn.node.index, cn.op.name, type(cn).__name__]
            for cn in ch.cnodes]


def _level_fingerprint(ch, key: str, i: int, cap: int) -> str:
    vers = ch._level_versions.get(key)
    v = vers[i] if vers is not None and i < len(vers) else 0
    salt = getattr(ch, "_ckpt_salt", None)
    if salt is None:
        # scopes hard-link reuse to THIS handle instance: two handles
        # checkpointing into one directory must never alias each other's
        # blobs on coincidentally equal version counters
        salt = ch._ckpt_salt = uuid.uuid4().hex[:12]
    return f"{salt}/{key}/{i}/v{v}/c{cap}/w{ch.workers}"


def _save_compiled(ch, enc: _Encoder, states: Dict[str, Any],
                   prev: Optional[Tuple[str, dict]],
                   path: str) -> Tuple[dict, Dict[str, str],
                                       Dict[str, dict]]:
    """Encode a CompiledHandle's engine state. ``states`` is the state
    dict to persist (live states, or the interval-start snapshot when a
    replay window is open). Returns (payload fragment, linked blobs,
    linked blob digests carried over from the previous manifest)."""
    from dbsp_tpu.compiled import cnodes as _cn

    prev_payload = prev[1] if prev is not None else None
    prev_dir = os.path.join(path, prev[0]) if prev is not None else None
    prev_levels = (prev_payload or {}).get("level_blobs", {})
    prev_arrays = (prev_payload or {}).get("arrays", {})
    enc_states: Dict[str, Any] = {}
    level_blobs: Dict[str, dict] = {}
    linked: Dict[str, str] = {}
    linked_meta: Dict[str, dict] = {}
    copied: Dict[str, str] = {}
    residency: Dict[str, list] = {}
    cold_blobs: Dict[str, Dict[str, dict]] = {}
    for key, st in states.items():
        cn = ch.by_index.get(int(key))
        leveled = isinstance(cn, _cn._Leveled) and isinstance(st, tuple) \
            and len(st) == 2 and isinstance(st[0], tuple)
        if not leveled:
            enc_states[key] = enc.encode(st, hint=f"s{key}")
            continue
        levels, base = st
        tiers = getattr(ch, "_tiers", {}).get(key)
        if tiers:
            residency[key] = list(tiers)
        enc_levels = []
        for i, lvl in enumerate(levels):
            hint = f"s{key}_l{i}"
            fp = _level_fingerprint(ch, key, i, lvl.cap)
            ent = getattr(ch, "_cold_meta", {}).get(key, {}).get(i)
            disk_ent = ent if (i > 0 and ent is not None
                               and ent.get("batch") is lvl) else None
            reuse = prev_levels.get(fp) if i > 0 else None
            if reuse is not None and prev_dir is not None and all(
                    os.path.exists(os.path.join(prev_dir, b + ".npy"))
                    for b in reuse["blobs"]):
                # clean deep level: reuse the previous generation's encoded
                # node verbatim and hard-link its blobs (same names — the
                # hint is deterministic per (state, level)). Disk-demoted
                # levels take this path on every save AFTER the first: the
                # generation chain links its OWN first copy, whose inode is
                # deliberately independent of the cold store's (see below)
                enc_levels.append(reuse["node"])
                for b in reuse["blobs"]:
                    linked[b] = os.path.join(prev_dir, b + ".npy")
                    if b in prev_arrays:
                        linked_meta[b] = prev_arrays[b]
                level_blobs[fp] = reuse
                if disk_ent is not None:
                    cold_blobs.setdefault(key, {})[str(i)] = \
                        disk_ent["blob"]
                    ch._store().note_recovery_dir(path)
                continue
            # disk-demoted level, first generation capture: its columns
            # ALREADY live as content-addressed blobs in the cold store —
            # verified COPY into the generation (no serialization from
            # memory; the recorded digests ride along). A hard link here
            # would share the store file's INODE, and in-place bit-rot
            # would corrupt the recovery copy together with the store —
            # defeating the fallback the cold tier's corruption contract
            # depends on. Subsequent saves hard-link the generation copy
            # (fp reuse above), so warm saves stay O(hot state).
            if disk_ent is not None:
                store = ch._store()
                blob = disk_ent["blob"]
                cols = [*blob["keys"], *blob["vals"], blob["weights"]]
                nk = len(blob["keys"])
                names = [f"{hint}_c{j}" for j in range(len(cols))]
                if all(os.path.exists(store.blob_path(m["sha256"]))
                       for m in cols):
                    if store.verify_meta(blob):  # never launder rot —
                        # and a HEAL replaced the file: re-point every
                        # live holder off the corrupted inode
                        lvl = _reheal_level(ch, states, key, i, lvl, blob)
                    node = {"__batch__": {
                        "keys": names[:nk],
                        "vals": names[nk:-1],
                        "weights": names[-1],
                        "runs": blob.get("runs")}}
                    for name, m in zip(names, cols):
                        copied[name] = store.blob_path(m["sha256"])
                        linked_meta[name] = {"sha256": m["sha256"],
                                             "bytes": m["bytes"]}
                    enc_levels.append(node)
                    level_blobs[fp] = {"node": node, "blobs": names}
                    cold_blobs.setdefault(key, {})[str(i)] = blob
                    store.note_recovery_dir(path)
                    continue
            if isinstance(lvl.weights, np.memmap):
                # disk level with stale/missing meta (identity guard
                # failed): streaming-VERIFY (and heal) before serializing
                # — encoding raw memmap bytes would launder a corrupted
                # blob into a clean-checksummed checkpoint
                from dbsp_tpu import residency as _res

                stale_meta = _res.meta_from_batch(lvl)
                ch._store().verify_meta(stale_meta)
                # re-open regardless (a heal replaced the file under the
                # open memmap; a fresh view is free either way) AND swap
                # the fresh batch into the live holders so the engine
                # stops reading the old inode too
                lvl = _reheal_level(ch, states, key, i, lvl, stale_meta)
            before = set(enc.arrays)
            node = enc.encode(lvl, hint=hint)
            blobs = sorted(set(enc.arrays) - before)
            enc_levels.append(node)
            if i > 0:
                level_blobs[fp] = {"node": node, "blobs": blobs}
        enc_states[key] = {"__levels__": enc_levels,
                           "base": enc.encode(base, hint=f"s{key}_base")}
    caps = {str(cn.node.index): dict(cn.caps)
            for cn in ch.cnodes if cn.caps}
    slots = {str(cn.node.index): cn._slot_cap
             for cn in ch.cnodes
             if getattr(cn, "_slot_cap", None) is not None}
    return {
        "engine": "compiled",
        "structure": _compiled_structure(ch),
        "workers": ch.workers,
        "states": enc_states,
        "caps": caps,
        "slots": slots,
        "level_versions": {k: list(v)
                           for k, v in ch._level_versions.items()},
        "maintain_pending": bool(ch.maintain_pending),
        "level_blobs": level_blobs,
        "residency": residency,
        "cold_blobs": cold_blobs,
    }, linked, linked_meta, copied


def _adopt_cold_blobs(store, blob: dict, enc_node: dict,
                      gen_dir: str) -> None:
    """Ensure every column blob of one disk-tier level exists in the cold
    store, hard-linking (or copying) the generation's verified files in
    by content hash — restore never re-serializes cold state."""
    names = []
    if isinstance(enc_node, dict) and "__batch__" in enc_node:
        b = enc_node["__batch__"]
        names = [*b["keys"], *b["vals"], b["weights"]]
    metas = [*blob["keys"], *blob["vals"], blob["weights"]]
    for j, m in enumerate(metas):
        dst = store.blob_path(m["sha256"])
        if os.path.exists(dst):
            continue
        src = os.path.join(gen_dir, (names[j] if j < len(names)
                                     else "") + ".npy")
        if not os.path.exists(src):
            continue  # fault_batch will surface/recover the miss later
        try:
            os.link(src, dst)
        except OSError:
            shutil.copy2(src, dst)


def _reheal_level(ch, states: Dict[str, Any], key: str, i: int,
                  old: Batch, blob: dict) -> Batch:
    """After ``verify_meta`` healed a blob on disk, any OPEN memmap still
    maps the corrupted inode (the heal is an ``os.replace``): re-open a
    fresh view and swap it into every live holder whose level IS the
    healed object — the engine states (so subsequent step programs stop
    reading rotted bytes), the states dict being saved, and the blob
    bookkeeping's identity anchor."""
    from dbsp_tpu import residency as _res

    fresh = _res.disk_batch(blob, ch._store())
    for holder in (ch.states, states):
        st = holder.get(key)
        if isinstance(st, tuple) and len(st) == 2 and \
                isinstance(st[0], tuple) and i < len(st[0]) and \
                st[0][i] is old:
            lv = list(st[0])
            lv[i] = fresh
            holder[key] = (tuple(lv), st[1])
    ent = getattr(ch, "_cold_meta", {}).get(key, {}).get(i)
    if ent is not None and ent.get("batch") is old:
        ent["batch"] = fresh
    return fresh


def _restore_compiled(ch, payload: dict, dec: _Decoder,
                      gen_dir: Optional[str] = None,
                      path: Optional[str] = None) -> Dict[str, Any]:
    """Apply a compiled payload onto a freshly compiled handle: caps, slot
    geometry, maintain cursors, and the decoded states (re-placed over
    the worker mesh when sharded). TWO-PHASE: everything is decoded and
    device-placed BEFORE the first mutation, so a decode/placement
    failure leaves the handle exactly as built (a half-mutated engine
    served as 'fresh' would double-apply replayed inputs). Returns the
    decoded state dict.

    Residency: when the restoring handle runs with active budgets
    (``residency_cfg.active``), the payload's persisted tier map is
    honored — disk-demoted levels are re-adopted into the cold store by
    content hash and come back as memmap views (the restore that leaves
    cold state on disk), host-tier levels decode straight to numpy. A
    handle with no budgets decodes everything device-resident (legacy
    behavior, bit-identical either way)."""
    from dbsp_tpu import residency as _res

    if _compiled_structure(ch) != payload["structure"]:
        raise CheckpointError(
            "compiled circuit structure differs from the checkpointed "
            "circuit — rebuild with the same constructor before restoring")
    if payload.get("workers", 1) != ch.workers:
        raise CheckpointError(
            f"checkpoint was taken at workers={payload.get('workers')} != "
            f"this runtime's {ch.workers}")
    honor_tiers = getattr(ch, "residency_cfg", None) is not None and \
        ch.residency_cfg.active and ch.workers == 1
    residency = payload.get("residency") or {}
    cold_blobs = payload.get("cold_blobs") or {}
    npdec = _NpDecoder(dec.load)
    # phase 1: decode + place (no mutation of ch/cnodes yet)
    states: Dict[str, Any] = {}
    tiers_out: Dict[str, list] = {}
    cold_meta_out: Dict[str, Dict[int, dict]] = {}
    for key, enc_st in payload["states"].items():
        if isinstance(enc_st, dict) and "__levels__" in enc_st:
            tiers = residency.get(key) if honor_tiers else None
            levels = []
            for i, lv in enumerate(enc_st["__levels__"]):
                tier = tiers[i] if tiers and i < len(tiers) \
                    else _res.TIER_DEVICE
                blob = cold_blobs.get(key, {}).get(str(i))
                if tier == _res.TIER_DISK and blob is not None and \
                        gen_dir is not None:
                    store = ch._store()
                    _adopt_cold_blobs(store, blob, lv, gen_dir)
                    lvl = _res.disk_batch(blob, store)
                    store.retain(blob)  # sweep-protect the restored level
                    cold_meta_out.setdefault(key, {})[i] = {
                        "blob": blob, "batch": lvl}
                    if path is not None:
                        store.note_recovery_dir(path)
                elif tier == _res.TIER_HOST:
                    lvl = npdec.decode(lv)
                else:
                    tier = _res.TIER_DEVICE
                    lvl = dec.decode(lv)
                levels.append(lvl)
                if tiers:
                    tiers[i] = tier  # downgraded disk->device when no dir
            if tiers and any(t != _res.TIER_DEVICE for t in tiers):
                tiers_out[key] = list(tiers)
            states[key] = (tuple(levels), dec.decode(enc_st["base"]))
        else:
            states[key] = dec.decode(enc_st)
    if ch.workers > 1:
        import jax

        from dbsp_tpu.parallel.mesh import worker_sharding

        states = jax.device_put(states, worker_sharding(ch.mesh))
    # phase 2: apply
    for cn in ch.cnodes:
        key = str(cn.node.index)
        saved = payload["caps"].get(key)
        if saved:
            cn.caps.update({k: int(v) for k, v in saved.items()})
        if key in payload.get("slots", {}):
            cn._slot_cap = int(payload["slots"][key])
        if key in tiers_out:
            cn.residency_tiers = tuple(tiers_out[key])
        cn._live_cache = None
    ch.states = states
    ch._tiers = tiers_out
    ch._cold_meta = cold_meta_out
    ch._level_versions = {k: list(v)
                          for k, v in payload["level_versions"].items()}
    ch.maintain_pending = bool(payload.get("maintain_pending", False))
    ch._snap_levels.clear()
    ch._step_jit = None
    ch._scan_jits = {}
    ch._req = None
    # tick discontinuity: the next dispatch re-uploads the cursor
    # explicitly (compiler._tick_operand)
    ch._tick_dev = None
    ch._tick_host = None
    ch._ckpt_salt = uuid.uuid4().hex[:12]  # new buffers, new link scope
    return states


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _driver_of(target):
    """(driver, compiled_handle, host_handle) for any supported target."""
    from dbsp_tpu.compiled.compiler import CompiledHandle
    from dbsp_tpu.compiled.driver import CompiledCircuitDriver

    if isinstance(target, CompiledCircuitDriver):
        return target, target.ch, None
    if isinstance(target, CompiledHandle):
        return None, target, None
    return None, None, target


def save(target, path: str, controller: Optional[dict] = None,
         tick: Optional[int] = None,
         output_pending: Optional[Dict[str, Batch]] = None,
         read_plane: Optional[Dict[str, Batch]] = None) -> dict:
    """Write one checkpoint generation of ``target`` under ``path``.

    ``target`` is a host ``CircuitHandle``, a ``CompiledHandle``, or a
    serving ``CompiledCircuitDriver`` (which also persists its tick counter
    and the retained-feed replay window of an open validation interval).
    ``controller`` is an opaque JSON-safe dict persisted alongside (the
    Controller stores step/endpoint counters there); ``output_pending``
    maps output-endpoint names to delta batches whose sink write failed —
    persisting them keeps the output stream at-least-once across a crash
    (the input high-water marks cover the step that produced them, so a
    restore would otherwise never re-emit them); ``read_plane`` maps
    served view names to their compacted published state so a restored
    controller republishes snapshots (and answers changefeed resume
    cursors) without waiting for new traffic. Returns
    ``{"tick", "generation", "path", ...}``."""
    driver, ch, host = _driver_of(target)
    enc = _Encoder()
    linked: Dict[str, str] = {}
    linked_meta: Dict[str, dict] = {}
    copied: Dict[str, str] = {}
    if host is not None:
        payload = _save_host(host, enc)
    else:
        prev = None
        try:
            # manifest-only verification: the save path must stay
            # O(dirty state) — see load_manifest
            name, prev_payload, _ = load_manifest(path,
                                                  verify_blobs=False)
            if prev_payload.get("engine") == "compiled":
                prev = (name, prev_payload)
        except CheckpointError:
            prev = None
        if driver is not None and driver._retained:
            # open validation interval: persist the VALIDATED interval-
            # start snapshot plus the retained feeds — recovery replays
            # them deterministically past the checkpoint tick
            states = driver._snap
            base_tick = driver._retained[0][0]
            retained = [
                [t, {str(ch._op_to_index[id(op)]):
                     enc.encode(b, hint=f"r{t}i{ch._op_to_index[id(op)]}")
                     for op, b in feeds.items()}]
                for t, feeds in driver._retained]
        else:
            states = ch.states
            base_tick = driver._tick if driver is not None else 0
            retained = []
        payload, linked, linked_meta, copied = _save_compiled(
            ch, enc, states, prev, path)
        payload["retained"] = retained
        payload["tick"] = base_tick
    if tick is not None:
        payload["tick"] = int(tick)
    if controller is not None:
        payload["controller"] = controller
    if output_pending:
        payload["output_pending"] = {
            n: enc.encode(b, hint=f"op_{i}")
            for i, (n, b) in enumerate(sorted(output_pending.items()))}
    if read_plane:
        payload["read_plane"] = {
            n: enc.encode(b, hint=f"rp_{i}")
            for i, (n, b) in enumerate(sorted(read_plane.items()))}
    name, stats = _write_generation(path, payload, enc, linked,
                                    linked_meta, copied)
    return dict(stats, tick=payload["tick"], path=path, name=name)


def restore(target, path: str) -> dict:
    """Restore the newest valid generation under ``path`` into ``target``
    (a freshly rebuilt circuit / freshly compiled driver of the same
    structure). Returns ``{"tick", "generation", "fallback_from",
    "controller"}`` — ``fallback_from`` names a corrupted newer generation
    that was skipped (surface it as a ``restore`` incident)."""
    bytes_cache: Dict[str, bytes] = {}
    name, payload, fallback_from = load_manifest(path,
                                                 bytes_cache=bytes_cache)
    gen_dir = os.path.join(path, name)
    dec = _Decoder(_make_loader(gen_dir, payload, bytes_cache))
    driver, ch, host = _driver_of(target)
    engine = payload.get("engine")
    if host is not None:
        if engine != "host":
            raise CheckpointError(
                f"checkpoint engine {engine!r} cannot restore into a host "
                "circuit handle — rebuild the matching driver first")
        _restore_host(host, payload, dec)
        tick = payload.get("tick", 0)
    else:
        if engine != "compiled":
            raise CheckpointError(
                f"checkpoint engine {engine!r} cannot restore into a "
                "compiled handle")
        _restore_compiled(ch, payload, dec, gen_dir=gen_dir, path=path)
        tick = int(payload.get("tick", 0))
        if driver is not None:
            retained = [
                (int(t), {int(i): dec.decode(b) for i, b in feeds.items()})
                for t, feeds in (payload.get("retained") or [])]
            driver.restore_checkpoint(tick, retained)
    return {"tick": tick,
            "generation": payload.get("generation"),
            "name": name,
            "fallback_from": fallback_from,
            "controller": payload.get("controller"),
            "output_pending": {
                n: dec.decode(b)
                for n, b in (payload.get("output_pending") or {}).items()},
            "read_plane": {
                n: dec.decode(b)
                for n, b in (payload.get("read_plane") or {}).items()}}
