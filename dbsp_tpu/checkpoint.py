"""Checkpoint / resume: durable snapshots of a circuit's operator state.

Designed fresh — the reference has NO checkpointing; its closest capability
is the RocksDB ``PersistentTrace`` (``trace/persistent/mod.rs:40-45``) which
spills state to a fresh temp DB per run (SURVEY.md §5: "state spilling, not
restartability"). This module provides what that leaves missing: suspend a
running pipeline, restart the process, rebuild the same circuit, restore, and
continue from the exact tick.

Format: one ``.npz`` (all device buffers, pulled to host numpy) plus a JSON
manifest describing each operator's state tree (batches carry their column
split and dtypes; spines are lists of batches). Dependency-free and
inspectable; device placement/sharding is re-established lazily on first use
after restore.

The circuit must be rebuilt by the same constructor before ``restore`` —
operator state is addressed by global node id, and a structural mismatch is
detected and rejected.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Circuit
from dbsp_tpu.circuit.runtime import CircuitHandle
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset.batch import Batch

FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# State-tree encoding
# ---------------------------------------------------------------------------


class _Encoder:
    def __init__(self):
        self.arrays: Dict[str, np.ndarray] = {}
        self.counter = 0

    def _store(self, arr) -> str:
        key = f"a{self.counter}"
        self.counter += 1
        self.arrays[key] = np.asarray(arr)
        return key

    def encode(self, v: Any) -> Any:
        if isinstance(v, Batch):
            return {"__batch__": {
                "keys": [self._store(c) for c in v.keys],
                "vals": [self._store(c) for c in v.vals],
                "weights": self._store(v.weights),
            }}
        if isinstance(v, Spine):
            return {"__spine__": {
                "key_dtypes": [str(d) for d in v.key_dtypes],
                "val_dtypes": [str(d) for d in v.val_dtypes],
                "batches": [self.encode(b) for b in v.batches],
                "dirty": v.dirty,
            }}
        if isinstance(v, (jnp.ndarray, np.ndarray)):
            return {"__array__": self._store(v)}
        if isinstance(v, dict):
            return {"__dict__": {k: self.encode(x) for k, x in v.items()}}
        if isinstance(v, (list, tuple)):
            return {"__seq__": [self.encode(x) for x in v],
                    "tuple": isinstance(v, tuple)}
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        raise TypeError(f"unsupported checkpoint value type {type(v)}")


class _Decoder:
    def __init__(self, arrays):
        self.arrays = arrays

    def decode(self, v: Any) -> Any:
        if isinstance(v, dict):
            if "__batch__" in v:
                b = v["__batch__"]
                return Batch(
                    tuple(jnp.asarray(self.arrays[k]) for k in b["keys"]),
                    tuple(jnp.asarray(self.arrays[k]) for k in b["vals"]),
                    jnp.asarray(self.arrays[b["weights"]]))
            if "__spine__" in v:
                s = v["__spine__"]
                spine = Spine([jnp.dtype(d) for d in s["key_dtypes"]],
                              [jnp.dtype(d) for d in s["val_dtypes"]])
                spine.batches = [self.decode(b) for b in s["batches"]]
                spine.dirty = s["dirty"]
                return spine
            if "__array__" in v:
                return jnp.asarray(self.arrays[v["__array__"]])
            if "__dict__" in v:
                return {k: self.decode(x) for k, x in v["__dict__"].items()}
            if "__seq__" in v:
                seq = [self.decode(x) for x in v["__seq__"]]
                return tuple(seq) if v["tuple"] else seq
        return v


# ---------------------------------------------------------------------------
# Circuit walking
# ---------------------------------------------------------------------------


def _walk(circuit: Circuit, prefix=()):
    for node in circuit.nodes:
        if node.kind == "strict_input":
            continue  # same operator instance as its strict_output partner
        yield (*prefix, node.index), node
        if node.child is not None:
            yield from _walk(node.child, (*prefix, node.index))


def save(handle: CircuitHandle, path: str) -> None:
    """Snapshot every operator's state under ``path`` (a directory)."""
    os.makedirs(path, exist_ok=True)
    enc = _Encoder()
    states = {}
    structure = []
    for gid, node in _walk(handle.circuit):
        structure.append([list(gid), node.operator.name, node.kind])
        sd = node.operator.state_dict()
        if sd:
            states[json.dumps(list(gid))] = enc.encode(sd)
    manifest = {
        "version": FORMAT_VERSION,
        "structure": structure,
        "states": states,
        "step_times_len": len(handle.step_times_ns),
    }
    np.savez_compressed(os.path.join(path, "state.npz"), **enc.arrays)
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f)


def restore(handle: CircuitHandle, path: str) -> None:
    """Load a snapshot into a freshly rebuilt identical circuit."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["version"] == FORMAT_VERSION, (
        f"checkpoint format {manifest['version']} != {FORMAT_VERSION}")
    structure = [[list(gid), node.operator.name, node.kind]
                 for gid, node in _walk(handle.circuit)]
    assert structure == manifest["structure"], (
        "circuit structure differs from the checkpointed circuit — rebuild "
        "with the same constructor before restoring")
    arrays = np.load(os.path.join(path, "state.npz"))
    dec = _Decoder(arrays)
    states = manifest["states"]
    for gid, node in _walk(handle.circuit):
        key = json.dumps(list(gid))
        if key in states:
            node.operator.load_state_dict(dec.decode(states[key]))
