from dbsp_tpu.nexmark.generator import GeneratorConfig, NexmarkGenerator
from dbsp_tpu.nexmark import model, queries

__all__ = ["GeneratorConfig", "NexmarkGenerator", "model", "queries"]


def build_inputs(circuit):
    """Create the three Nexmark relation inputs; returns (streams, handles)."""
    from dbsp_tpu.operators import add_input_zset
    from dbsp_tpu.nexmark import model as M

    persons, hp = add_input_zset(circuit, M.PERSON_KEY, M.PERSON_VALS)
    auctions, ha = add_input_zset(circuit, M.AUCTION_KEY, M.AUCTION_VALS)
    bids, hb = add_input_zset(circuit, M.BID_KEY, M.BID_VALS)
    return (persons, auctions, bids), (hp, ha, hb)
