"""Nexmark event generator — vectorized, columnar, deterministic.

Behavioral equivalent of the reference's Flink-compatible generator
(``crates/nexmark/src/generator/mod.rs:20-45`` and ``src/config.rs:133-140``)
re-thought for a columnar engine: instead of producing one ``Event`` struct at
a time from an iterator, it emits *column batches* (numpy arrays) ready for
device upload — no per-record host work anywhere.

Semantics preserved from the spec:
  * event mix: out of every 50 consecutive events, 1 is a person, 3 are
    auctions, 46 are bids (model.PROPORTION_DENOMINATOR);
  * dense monotone ids: person i is the i-th person event overall
    (FIRST_PERSON_ID + i), auctions likewise;
  * event time advances at a configured rate (``first_event_rate`` events/s
    => inter-event gap of 10^9/rate ns, stored as ms);
  * skew: bids prefer recent ("hot") auctions and bidders with configured
    probabilities; auction expiry a bounded random horizon.
Deterministic per seed + event index: the whole column batch for events
[n0, n1) can be (re)generated independently — that also makes generation
trivially parallel across processes, and replaces the reference's
wallclock-throttled multi-threaded source (``nexmark/src/lib.rs:40-160``)
with pure functions of the event index.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import numpy as np

from dbsp_tpu.nexmark import model as M


def _mix64(seed: int, x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 counters — the per-event RNG."""
    z = x.astype(np.uint64) + np.uint64((seed * 0x9E3779B97F4A7C15) % 2**64)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


@dataclasses.dataclass(frozen=True)
class GeneratorConfig:
    """Mirrors the knobs of the reference bench config (nexmark/src/config.rs)."""

    seed: int = 1
    base_time_ms: int = 1_651_000_000_000  # arbitrary fixed epoch start
    first_event_rate: int = 10_000_000     # events/sec of *event time*
    hot_auction_ratio: float = 0.85        # P(bid goes to a recent auction)
    hot_bidder_ratio: float = 0.85
    hot_window: int = 100                  # "recent" = last N auctions/persons
    num_channels: int = 16
    num_name_codes: int = 512
    num_city_codes: int = 64
    num_state_codes: int = 50
    auction_expire_min_ms: int = 1_000
    auction_expire_max_ms: int = 60_000


# Host-side decode tables for dictionary-coded string columns. Kept tiny and
# synthesized on demand; real adapters (io/) would own real dictionaries.
def decode_tables(cfg: GeneratorConfig) -> Dict[str, list]:
    return {
        "name": [f"person-{i}" for i in range(cfg.num_name_codes)],
        "city": [f"city-{i}" for i in range(cfg.num_city_codes)],
        "state": [f"ST{i}" for i in range(cfg.num_state_codes)],
        "channel": [f"channel-{i}" for i in range(cfg.num_channels)],
    }


class NexmarkGenerator:
    """Columnar batch generator over a half-open event-index range."""

    def __init__(self, cfg: GeneratorConfig = GeneratorConfig()):
        self.cfg = cfg

    # -- index arithmetic (pure) -------------------------------------------
    @staticmethod
    def _epoch_offset(n: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        return (n // M.PROPORTION_DENOMINATOR,
                n % M.PROPORTION_DENOMINATOR)

    @staticmethod
    def person_count(n: int) -> int:
        """Number of person events among events [0, n)."""
        ep, off = divmod(n, M.PROPORTION_DENOMINATOR)
        return ep + min(off, M.PERSON_PROPORTION)

    @staticmethod
    def auction_count(n: int) -> int:
        ep, off = divmod(n, M.PROPORTION_DENOMINATOR)
        extra = min(max(off - M.PERSON_PROPORTION, 0), M.AUCTION_PROPORTION)
        return ep * M.AUCTION_PROPORTION + extra

    def timestamps(self, n: np.ndarray) -> np.ndarray:
        step_ns = 1_000_000_000 // self.cfg.first_event_rate
        return self.cfg.base_time_ms + (n.astype(np.int64) * step_ns) // 1_000_000


    # -- batch generation ---------------------------------------------------
    def generate(self, n0: int, n1: int) -> Dict[str, Dict[str, np.ndarray]]:
        """Columns for events [n0, n1), split per relation.

        Returns {"persons": {...}, "auctions": {...}, "bids": {...}} where
        each inner dict maps column name -> numpy array. Deterministic in
        (seed, n0, n1-partitioning-independent): uses counter-based Philox
        streams keyed by absolute event index so any batching yields
        identical events.
        """
        n = np.arange(n0, n1, dtype=np.int64)
        ep, off = self._epoch_offset(n)
        ts = self.timestamps(n)
        is_person = off < M.PERSON_PROPORTION
        is_auction = (~is_person) & (off < M.PERSON_PROPORTION +
                                     M.AUCTION_PROPORTION)
        is_bid = ~is_person & ~is_auction

        # Stateless counter-based randomness: draw j for absolute event index
        # i is splitmix64(seed, i*8+j) — batch-invariant by construction (any
        # [n0,n1) partitioning yields identical events) and embarrassingly
        # parallel, unlike a sequential RNG stream.
        r32 = np.stack([_mix64(self.cfg.seed, n * 8 + j) >> np.uint64(33)
                        for j in range(5)]).astype(np.int64)

        out = {
            "persons": self._persons(n[is_person], ep[is_person],
                                     ts[is_person], r32[:, is_person]),
            "auctions": self._auctions(n[is_auction], ep[is_auction],
                                       off[is_auction], ts[is_auction],
                                       r32[:, is_auction]),
            "bids": self._bids(n[is_bid], ts[is_bid], r32[:, is_bid]),
        }
        return out

    def _persons(self, n, ep, ts, r):
        pid = M.FIRST_PERSON_ID + ep  # one person per epoch, dense ids
        return {
            "id": pid,
            "name": (r[0] % self.cfg.num_name_codes).astype(np.int32),
            "city": (r[1] % self.cfg.num_city_codes).astype(np.int32),
            "state": (r[2] % self.cfg.num_state_codes).astype(np.int32),
            "email": (r[3] % self.cfg.num_name_codes).astype(np.int32),
            "date_time": ts,
        }

    def _auctions(self, n, ep, off, ts, r):
        aid = (M.FIRST_AUCTION_ID + ep * M.AUCTION_PROPORTION +
               (off - M.PERSON_PROPORTION))
        # seller: usually a recent person, sometimes any existing one
        max_person = np.maximum(ep, 0)  # persons 0..ep exist (epoch ep just added one)
        hot = (r[0] % 1000) < int(self.cfg.hot_bidder_ratio * 1000)
        recent = np.maximum(max_person - self.cfg.hot_window, 0)
        seller_idx = np.where(
            hot, recent + r[1] % np.maximum(max_person - recent + 1, 1),
            r[1] % np.maximum(max_person + 1, 1))
        price0 = 1 + (r[2] % 10_000)
        span = self.cfg.auction_expire_max_ms - self.cfg.auction_expire_min_ms
        return {
            "id": aid,
            "item": (r[3] % self.cfg.num_name_codes).astype(np.int32),
            "seller": M.FIRST_PERSON_ID + seller_idx,
            "category": M.FIRST_CATEGORY_ID + r[4] % M.NUM_CATEGORIES,
            "initial_bid": price0,
            "reserve": price0 + (r[2] >> 16) % 10_000,
            "date_time": ts,
            "expires": ts + self.cfg.auction_expire_min_ms + r[0] % span,
        }

    def _bids(self, n, ts, r):
        ep = n // M.PROPORTION_DENOMINATOR
        max_auction = np.maximum((ep + 1) * M.AUCTION_PROPORTION - 1, 0)
        max_person = ep
        hot_a = (r[0] % 1000) < int(self.cfg.hot_auction_ratio * 1000)
        recent_a = np.maximum(max_auction - self.cfg.hot_window, 0)
        auction_idx = np.where(
            hot_a, recent_a + r[1] % np.maximum(max_auction - recent_a + 1, 1),
            r[1] % np.maximum(max_auction + 1, 1))
        hot_b = (r[2] % 1000) < int(self.cfg.hot_bidder_ratio * 1000)
        recent_b = np.maximum(max_person - self.cfg.hot_window, 0)
        bidder_idx = np.where(
            hot_b, recent_b + r[3] % np.maximum(max_person - recent_b + 1, 1),
            r[3] % np.maximum(max_person + 1, 1))
        # log-uniform price in [1, 10^7)
        price = np.exp(np.log(10_000_000) * ((r[4] % 65536) / 65536.0))
        return {
            "auction": M.FIRST_AUCTION_ID + auction_idx,
            "bidder": M.FIRST_PERSON_ID + bidder_idx,
            "price": np.maximum(price.astype(np.int64), 1),
            "channel": (r[0] % self.cfg.num_channels).astype(np.int32),
            "date_time": ts,
        }

    def generate_fast(self, n0: int, n1: int):
        """Native C++ data-loader when buildable (bit-identical to
        :meth:`generate` — tested), numpy otherwise. ~12x faster; keeps the
        host side ahead of the reference protocol's 10M events/s."""
        try:
            from dbsp_tpu.nexmark import native

            return native.generate(self.cfg, n0, n1)
        except Exception:
            return self.generate(n0, n1)

    # -- circuit feeding ----------------------------------------------------
    def feed(self, handles, n0: int, n1: int) -> None:
        """Push events [n0, n1) into (persons, auctions, bids) input handles
        as device batches (the zero-copy push_batch path)."""
        from dbsp_tpu.zset.batch import Batch

        cols = self.generate_fast(n0, n1)
        hp, ha, hb = handles
        # persons/auctions arrive sorted by their dense monotone id with
        # weight 1 — already consolidated, no sort needed on either side of
        # the push; bids are keyed by (random) auction id and do need one
        p = cols["persons"]
        if len(p["id"]):
            hp.push_batch(Batch.from_columns(
                [p["id"]], [p["name"], p["city"], p["state"], p["email"],
                            p["date_time"]],
                np.ones(len(p["id"]), np.int64), consolidated=True),
                consolidated=True)
        a = cols["auctions"]
        if len(a["id"]):
            ha.push_batch(Batch.from_columns(
                [a["id"]], [a["item"], a["seller"], a["category"],
                            a["initial_bid"], a["reserve"], a["date_time"],
                            a["expires"]],
                np.ones(len(a["id"]), np.int64), consolidated=True),
                consolidated=True)
        b = cols["bids"]
        if len(b["auction"]):
            # from_columns consolidates (sorts by auction id) by default
            hb.push_batch(Batch.from_columns(
                [b["auction"]], [b["bidder"], b["price"], b["channel"],
                                 b["date_time"]],
                np.ones(len(b["auction"]), np.int64)), consolidated=True)
