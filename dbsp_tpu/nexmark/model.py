"""Nexmark data model, columnar (device schemas + string dictionaries).

Reference: the generator's row models (``crates/nexmark/src/model.rs:14-69``:
Person/Auction/Bid). TPU-native change: variable-length strings (names,
cities, channels, urls) are dictionary-encoded on the host into int32 codes
(SURVEY.md §7 "variable-length keys"); the decode tables live host-side in
:mod:`dbsp_tpu.nexmark.generator`.

Device schemas (key columns index the Z-set; joins/aggregates group by them):
  persons:  key (id:i64)        vals (name:i32, city:i32, state:i32, email:i32, date_time:i64)
  auctions: key (id:i64)        vals (item:i32, seller:i64, category:i64, initial_bid:i64,
                                      reserve:i64, date_time:i64, expires:i64)
  bids:     key (auction:i64)   vals (bidder:i64, price:i64, channel:i32, date_time:i64)
"""

import jax.numpy as jnp

PERSON_KEY = (jnp.int64,)
PERSON_VALS = (jnp.int32, jnp.int32, jnp.int32, jnp.int32, jnp.int64)
# person val column order: name, city, state, email, date_time
P_NAME, P_CITY, P_STATE, P_EMAIL, P_DATE = range(5)

AUCTION_KEY = (jnp.int64,)
AUCTION_VALS = (jnp.int32, jnp.int64, jnp.int64, jnp.int64, jnp.int64,
                jnp.int64, jnp.int64)
# auction val column order: item, seller, category, initial_bid, reserve,
# date_time, expires
A_ITEM, A_SELLER, A_CATEGORY, A_INITIAL, A_RESERVE, A_DATE, A_EXPIRES = range(7)

BID_KEY = (jnp.int64,)
BID_VALS = (jnp.int64, jnp.int64, jnp.int32, jnp.int64)
# bid val column order: bidder, price, channel, date_time
B_BIDDER, B_PRICE, B_CHANNEL, B_DATE = range(4)

# Generator constants (same universe as the Nexmark spec: first ids, the
# 1 person : 3 auctions : 46 bids mix per 50 events, category base 10).
FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10
NUM_CATEGORIES = 5
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
PROPORTION_DENOMINATOR = 50  # 1 + 3 + 46
