"""Device-side Nexmark event generation — the input pipeline for compiled
(fully-jitted) benchmark runs.

The host generator (:mod:`dbsp_tpu.nexmark.generator`) is counter-based:
every column of event ``i`` is a pure function of ``(seed, i)`` via the
splitmix64 finalizer. That design pays off twice — it made the host path
batch-invariant and parallel, and it means the SAME arithmetic runs on the
TPU as a jitted kernel, so a benchmark tick needs **zero host→device
transfer** (the reference streams events over memory from generator threads,
``crates/nexmark/src/lib.rs:40-160``; under the axon tunnel a 100k-event
host batch costs ~140ms of PCIe-over-network, which would dominate every
other cost in the engine).

Bit-compatibility with the host path is tested (``tests/test_device_gen.py``):
integer columns are identical arithmetic; the one transcendental (the
log-uniform bid price) is replaced on both paths' terms by an exact 65536-entry
lookup table computed once with numpy, so device and host prices agree bit
for bit.

Static shapes: a tick of ``n`` events with ``n % 50 == 0`` contains exactly
``n/50`` persons, ``3n/50`` auctions and ``46n/50`` bids (the spec's fixed
event mix), so every tick compiles to the same shapes and the whole run is
one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.nexmark import model as M
from dbsp_tpu.nexmark.generator import GeneratorConfig
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import WEIGHT_DTYPE, Batch


def _mix64(seed: int, x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — same constants as the host/native paths."""
    z = x.astype(jnp.uint64) + jnp.uint64((seed * 0x9E3779B97F4A7C15) % 2**64)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def price_table() -> np.ndarray:
    """All 65536 possible bid prices, exactly as the host generator computes
    them (log-uniform in [1, 10^7)); numpy-evaluated once so host and device
    agree bit for bit."""
    r = np.arange(65536, dtype=np.float64)
    p = np.exp(np.log(10_000_000) * (r / 65536.0))
    return np.maximum(p.astype(np.int64), 1)


def _draws(seed: int, n: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """The five 31-bit draws for each absolute event index (int64)."""
    return tuple((_mix64(seed, n * 8 + j) >> jnp.uint64(33)).astype(jnp.int64)
                 for j in range(5))


def _timestamps(cfg: GeneratorConfig, n: jnp.ndarray) -> jnp.ndarray:
    step_ns = 1_000_000_000 // cfg.first_event_rate
    return cfg.base_time_ms + (n.astype(jnp.int64) * step_ns) // 1_000_000


@partial(jax.jit, static_argnames=("cfg", "epochs"))
def generate_tick(cfg: GeneratorConfig, e0: jnp.ndarray, epochs: int
                  ) -> Tuple[Batch, Batch, Batch]:
    """Device batches for epochs [e0, e0+epochs) == events [50*e0, 50*(e0+epochs)).

    ``e0`` is a traced scalar, ``epochs`` static — every tick of a run reuses
    one compiled program. Returns consolidated (persons, auctions, bids)
    batches at their natural capacities (epochs, 3*epochs, 46*epochs).
    """
    e0 = jnp.asarray(e0, jnp.int64)
    ep = e0 + jnp.arange(epochs, dtype=jnp.int64)

    # -- persons: event n = 50*ep --------------------------------------------
    n_p = ep * M.PROPORTION_DENOMINATOR
    r = _draws(cfg.seed, n_p)
    persons = Batch(
        keys=(M.FIRST_PERSON_ID + ep,),
        vals=((r[0] % cfg.num_name_codes).astype(jnp.int32),
              (r[1] % cfg.num_city_codes).astype(jnp.int32),
              (r[2] % cfg.num_state_codes).astype(jnp.int32),
              (r[3] % cfg.num_name_codes).astype(jnp.int32),
              _timestamps(cfg, n_p)),
        weights=jnp.ones((epochs,), WEIGHT_DTYPE),
        runs=(epochs,))

    # -- auctions: events n = 50*ep + 1 + i, i in 0..3 -----------------------
    epa = jnp.repeat(ep, M.AUCTION_PROPORTION)
    off = jnp.tile(jnp.arange(M.AUCTION_PROPORTION, dtype=jnp.int64), epochs)
    n_a = epa * M.PROPORTION_DENOMINATOR + M.PERSON_PROPORTION + off
    ts = _timestamps(cfg, n_a)
    r = _draws(cfg.seed, n_a)
    aid = M.FIRST_AUCTION_ID + epa * M.AUCTION_PROPORTION + off
    max_person = jnp.maximum(epa, 0)
    hot = (r[0] % 1000) < int(cfg.hot_bidder_ratio * 1000)
    recent = jnp.maximum(max_person - cfg.hot_window, 0)
    seller_idx = jnp.where(
        hot, recent + r[1] % jnp.maximum(max_person - recent + 1, 1),
        r[1] % jnp.maximum(max_person + 1, 1))
    price0 = 1 + (r[2] % 10_000)
    span = cfg.auction_expire_max_ms - cfg.auction_expire_min_ms
    auctions = Batch(
        keys=(aid,),
        vals=((r[3] % cfg.num_name_codes).astype(jnp.int32),
              M.FIRST_PERSON_ID + seller_idx,
              M.FIRST_CATEGORY_ID + r[4] % M.NUM_CATEGORIES,
              price0,
              price0 + (r[2] >> 16) % 10_000,
              ts,
              ts + cfg.auction_expire_min_ms + r[0] % span),
        weights=jnp.ones((epochs * M.AUCTION_PROPORTION,), WEIGHT_DTYPE),
        runs=(epochs * M.AUCTION_PROPORTION,))

    # -- bids: events n = 50*ep + 4 + i, i in 0..46 --------------------------
    epb = jnp.repeat(ep, M.BID_PROPORTION)
    offb = jnp.tile(jnp.arange(M.BID_PROPORTION, dtype=jnp.int64), epochs)
    n_b = (epb * M.PROPORTION_DENOMINATOR + M.PERSON_PROPORTION +
           M.AUCTION_PROPORTION + offb)
    ts = _timestamps(cfg, n_b)
    r = _draws(cfg.seed, n_b)
    max_auction = jnp.maximum((epb + 1) * M.AUCTION_PROPORTION - 1, 0)
    max_person = epb
    hot_a = (r[0] % 1000) < int(cfg.hot_auction_ratio * 1000)
    recent_a = jnp.maximum(max_auction - cfg.hot_window, 0)
    auction_idx = jnp.where(
        hot_a, recent_a + r[1] % jnp.maximum(max_auction - recent_a + 1, 1),
        r[1] % jnp.maximum(max_auction + 1, 1))
    hot_b = (r[2] % 1000) < int(cfg.hot_bidder_ratio * 1000)
    recent_b = jnp.maximum(max_person - cfg.hot_window, 0)
    bidder_idx = jnp.where(
        hot_b, recent_b + r[3] % jnp.maximum(max_person - recent_b + 1, 1),
        r[3] % jnp.maximum(max_person + 1, 1))
    prices = jnp.asarray(price_table())[r[4] % 65536]
    bids = Batch(
        keys=(M.FIRST_AUCTION_ID + auction_idx,),
        vals=(M.FIRST_PERSON_ID + bidder_idx,
              prices,
              (r[0] % cfg.num_channels).astype(jnp.int32),
              ts),
        weights=jnp.ones((epochs * M.BID_PROPORTION,), WEIGHT_DTYPE))

    # persons/auctions arrive sorted by their dense ids (consolidated);
    # bids are keyed by a random auction id and need the one sort
    return persons, auctions, bids.consolidate()
