"""Nexmark queries as circuit builders.

Reference: ``crates/nexmark/src/queries/*.rs`` (hand-built on the Stream
API, q0-q9 + q12-q22). Each builder takes the three relation streams
(persons, auctions, bids — see model.py schemas) and returns the query's
output stream. Queries are added here stage by stage as the operator
library grows; q3+ use incremental join/aggregate (operators/join.py,
operators/aggregate.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.nexmark import model as M
from dbsp_tpu.operators.aggregate import Max, Min  # noqa: F401
# Count/Average take the linear fast path (delta segment-sums, no input
# trace); Min/Max need the general group-gather path
from dbsp_tpu.operators.aggregate_linear import (  # noqa: F401
    LinearAverage as Average, LinearCount as Count)


def q0(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Passthrough — measures raw engine overhead (queries/q0.rs)."""
    return bids.map_rows(lambda k, v: (k, v), M.BID_KEY, M.BID_VALS,
                         name="q0", preserves_order=True)


def q1(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Currency conversion: price dollars -> euros * 0.908 (queries/q1.rs).

    Integer semantics: price * 908 / 1000 (the reference uses f32; integer
    milli-euros keep the Z-set exactly comparable across backends).
    """
    def conv(k, v):
        bidder, price, channel, ts = v
        return k, (bidder, price * 908 // 1000, channel, ts)

    return bids.map_rows(conv, M.BID_KEY, M.BID_VALS, name="q1",
                         preserves_order=True)


def q2(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Bids on a sampled set of auctions: auction % 123 == 0, project
    (auction, price) (queries/q2.rs)."""
    filt = bids.filter_rows(lambda k, v: k[0] % 123 == 0, name="q2-filter")
    return filt.map_rows(lambda k, v: (k, (v[M.B_PRICE],)),
                         M.BID_KEY, (jnp.int64,), name="q2-project")


# State codes standing in for the reference's 'OR','ID','CA' literals
# (states are dictionary-encoded, generator.py).
Q3_STATES = (0, 1, 2)
Q3_CATEGORY = 10


def q3(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Who is selling in OR/ID/CA in category 10? (queries/q3.rs:35)

    filter(persons by state) ⋈ filter(auctions by category) on seller ->
    (name, city, state, auction id), keyed by auction id. Incremental
    equi-join (operators/join.py).
    """
    sellers = persons.filter_rows(
        lambda k, v: (v[M.P_STATE] == Q3_STATES[0])
        | (v[M.P_STATE] == Q3_STATES[1]) | (v[M.P_STATE] == Q3_STATES[2]),
        name="q3-sellers")
    cat = auctions.filter_rows(
        lambda k, v: v[M.A_CATEGORY] == Q3_CATEGORY, name="q3-category")
    # re-key auctions by seller (person id)
    by_seller = cat.index_by(
        lambda k, v: (v[M.A_SELLER],), M.PERSON_KEY,
        val_fn=lambda k, v: (k[0],), val_dtypes=(jnp.int64,),
        name="q3-by-seller")
    return sellers.join_index(
        by_seller,
        lambda k, pv, av: ((av[0],), (pv[0], pv[1], pv[2])),
        [jnp.int64], [jnp.int32, jnp.int32, jnp.int32], name="q3-join")


Q5_WINDOW_MS = 10_000
Q5_HOP_MS = 2_000
Q5_RETAIN_MS = 4 * Q5_WINDOW_MS  # completed windows linger this long


def q5(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Hot items: auctions with the most bids per hopping window
    (10s window, 2s hop — queries/q5.rs). Hopping windows are expressed
    TPU-style as a static flat_map: each bid belongs to exactly
    window/hop = 5 windows, so fan-out is a fixed [5, cap] expansion instead
    of a data-dependent iterator. Output: (window_start, auction) for
    auctions whose bid count equals the window maximum."""
    fanout = Q5_WINDOW_MS // Q5_HOP_MS

    def assign(k, v):
        ts = v[M.B_DATE]
        first = (ts // Q5_HOP_MS) * Q5_HOP_MS - (fanout - 1) * Q5_HOP_MS
        starts = jnp.stack([first + i * Q5_HOP_MS for i in range(fanout)])
        auction = jnp.broadcast_to(k[0], starts.shape)
        keep = jnp.ones(starts.shape, bool)
        return (starts, auction), (), keep

    per_window = bids.flat_map_rows(
        assign, fanout, (jnp.int64, jnp.int64), (), name="q5-windows")
    # retire old windows (queries/q5.rs keeps state bounded the same way):
    # a watermark on bid time drives monotone bounds; windows whose start
    # falls below wm - retention are retracted AND their trace state GC'd
    wm = bids.watermark_monotonic(lambda k, v: v[M.B_DATE], lateness=0)
    bounds = wm.apply(
        lambda w: None if w is None else (w - Q5_RETAIN_MS, 1 << 62),
        name="q5-bounds")
    per_window = per_window.window(bounds, gc=True)
    counts = per_window.aggregate(Count(), name="q5-count")
    # counts: key=(window, auction) val=(n). Max n per window:
    by_window = counts.index_by(
        lambda k, v: (k[0],), (jnp.int64,),
        val_fn=lambda k, v: (k[1], v[0]), val_dtypes=(jnp.int64, jnp.int64),
        name="q5-by-window", preserves_first_key=True)
    maxes = by_window.aggregate(Max(1), name="q5-max")
    hot = by_window.join_index(
        maxes,
        lambda k, cv, mv: (k, (cv[0], cv[1], mv[0])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64), name="q5-join",
        preserves_first_key=True)
    winners = hot.filter_rows(lambda k, v: v[1] == v[2], name="q5-winners")
    return winners.map_rows(lambda k, v: ((k[0], v[0]), ()),
                            (jnp.int64, jnp.int64), (), name="q5-project",
                            preserves_first_key=True)


Q7_WINDOW_MS = 10_000


def q7(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Highest bid of the latest completed tumbling window (queries/q7.rs):
    a watermark on bid event time drives monotone window bounds; the window
    operator maintains the bids of the last complete period, and a Max
    aggregate reduces them. Output: (window_end, max_price)."""
    wm = bids.watermark_monotonic(lambda k, v: v[M.B_DATE], lateness=0)

    def to_bounds(w):
        if w is None:
            return None
        end = (w // Q7_WINDOW_MS) * Q7_WINDOW_MS
        return (end - Q7_WINDOW_MS, end)

    bounds = wm.apply(to_bounds, name="q7-bounds")
    by_time = bids.index_by(
        lambda k, v: (v[M.B_DATE],), (jnp.int64,),
        val_fn=lambda k, v: (v[M.B_PRICE],), val_dtypes=(jnp.int64,),
        name="q7-by-time")
    windowed = by_time.window(bounds)
    # all rows of the (single-period) window share a window end — key by it
    keyed = windowed.map_rows(
        lambda k, v: (((k[0] // Q7_WINDOW_MS) * Q7_WINDOW_MS + Q7_WINDOW_MS,),
                      (v[0],)),
        (jnp.int64,), (jnp.int64,), name="q7-rekey")
    return keyed.aggregate(Max(0), name="q7-max")


Q8_WINDOW_MS = 10_000


def q8(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Monitor new users (queries/q8.rs:48-70): persons who created an
    auction in the same tumbling 10s window they registered in. The
    reference builds this from watermark_monotonic + window + join; the
    tumbling-window equality is expressed by making the window start a join
    key component. Output: (person_id, window_start, name)."""
    p_keyed = persons.index_by(
        lambda k, v: (k[0], (v[M.P_DATE] // Q8_WINDOW_MS) * Q8_WINDOW_MS),
        (jnp.int64, jnp.int64),
        val_fn=lambda k, v: (v[M.P_NAME],), val_dtypes=(jnp.int32,),
        name="q8-persons", preserves_first_key=True)
    a_keyed = auctions.index_by(
        lambda k, v: (v[M.A_SELLER],
                      (v[M.A_DATE] // Q8_WINDOW_MS) * Q8_WINDOW_MS),
        (jnp.int64, jnp.int64),
        val_fn=lambda k, v: (), val_dtypes=(),
        name="q8-auctions")
    joined = p_keyed.join_index(
        a_keyed, lambda k, pv, av: (k, (pv[0],)),
        (jnp.int64, jnp.int64), (jnp.int32,), name="q8-join",
        preserves_first_key=True)
    return joined.distinct()


def q4(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Average final (max) bid price per category over closed auctions
    (queries/q4.rs:43): bids within [auction.date_time, auction.expires]
    joined on auction id -> max price per (auction, category) -> average per
    category. Exercises join + two incremental aggregates."""
    by_auction = auctions.index_by(
        lambda k, v: (k[0],), M.AUCTION_KEY,
        val_fn=lambda k, v: (v[M.A_CATEGORY], v[M.A_DATE], v[M.A_EXPIRES]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64), name="q4-auctions",
        preserves_first_key=True)
    joined = bids.join_index(
        by_auction,
        lambda k, bv, av: (
            (k[0], av[0]),
            (bv[M.B_PRICE], bv[M.B_DATE], av[1], av[2])),
        [jnp.int64, jnp.int64], [jnp.int64, jnp.int64, jnp.int64, jnp.int64],
        name="q4-join", preserves_first_key=True)
    in_window = joined.filter_rows(
        lambda k, v: (v[1] >= v[2]) & (v[1] <= v[3]), name="q4-window")
    # max price per (auction, category)
    per_auction = in_window.map_rows(
        lambda k, v: (k, (v[0],)), (jnp.int64, jnp.int64), (jnp.int64,),
        name="q4-price", preserves_first_key=True).aggregate(Max(0), name="q4-max")
    # average of those maxima per category
    by_category = per_auction.index_by(
        lambda k, v: (k[1],), (jnp.int64,),
        val_fn=lambda k, v: (v[0],), val_dtypes=(jnp.int64,),
        name="q4-by-category")
    return by_category.aggregate(Average(0), name="q4-avg")


# ---------------------------------------------------------------------------
# q6 / q9: winning bids (join + in-window max with tie-break) and rolling
# per-seller averages (top-K by close time)
# ---------------------------------------------------------------------------


def _winning_bids(auctions: Stream, bids: Stream) -> Stream:
    """(auction) -> (price, neg_ts, bidder, seller, expires) for the winning
    (highest-price, earliest-time) in-window bid of each auction — the core
    of q9/q6 (queries/q9.rs). Tie-break encoded by ranking on
    (price, -ts): lexicographic top-1 picks max price then min ts."""
    by_auction = auctions.index_by(
        lambda k, v: (k[0],), M.AUCTION_KEY,
        val_fn=lambda k, v: (v[M.A_SELLER], v[M.A_DATE], v[M.A_EXPIRES]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64), name="q9-auctions",
        preserves_first_key=True)
    joined = bids.join_index(
        by_auction,
        lambda k, bv, av: (
            (k[0],),
            (bv[M.B_PRICE], -bv[M.B_DATE], bv[M.B_BIDDER], av[0],
             bv[M.B_DATE], av[1], av[2])),
        (jnp.int64,),
        (jnp.int64, jnp.int64, jnp.int64, jnp.int64, jnp.int64, jnp.int64,
         jnp.int64), name="q9-join", preserves_first_key=True)
    in_window = joined.filter_rows(
        lambda k, v: (v[4] >= v[5]) & (v[4] <= v[6]), name="q9-window")
    ranked = in_window.map_rows(
        lambda k, v: (k, (v[0], v[1], v[2], v[3], v[6])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64, jnp.int64, jnp.int64),
        name="q9-rank")
    return ranked.topk(1, largest=True, name="q9-top1")


def q9(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Winning bid of each auction: (auction, price, ts, bidder)."""
    return _winning_bids(auctions, bids).map_rows(
        lambda k, v: (k, (v[0], -v[1], v[2])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64), name="q9-project",
        preserves_first_key=True)


def q6(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Average winning price of each seller's last 10 closed auctions
    (queries/q6.rs): winning bids -> per-seller top-10 by expiry -> average.
    Output: (seller, avg_price)."""
    winners = _winning_bids(auctions, bids)
    by_seller = winners.map_rows(
        lambda k, v: ((v[3],), (v[4], k[0], v[0])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64), name="q6-by-seller")
    last10 = by_seller.topk(10, largest=True, name="q6-last10")
    prices = last10.map_rows(lambda k, v: (k, (v[2],)),
                             (jnp.int64,), (jnp.int64,), name="q6-prices")
    return prices.aggregate(Average(0), name="q6-avg")


# ---------------------------------------------------------------------------
# q12-q22
# ---------------------------------------------------------------------------

Q12_WINDOW_TICKS = 10


def q12(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Bid count per bidder per PROCESSING-time window (queries/q12.rs).

    Processing time on a deterministic engine is the tick index: each
    circuit step is one processing unit, windows span 10 ticks. The tick
    counter is a stream_fold (no wall clock — reproducible runs)."""
    import jax.numpy as _jnp

    from dbsp_tpu.operators.basic import Apply2
    from dbsp_tpu.zset.batch import Batch

    tick = bids.stream_fold(0, lambda acc, b: acc + 1)

    def attach(batch: Batch, t: int) -> Batch:
        win = (t - 1) // Q12_WINDOW_TICKS
        bidder = batch.vals[M.B_BIDDER]
        wcol = _jnp.full((batch.cap,), win, _jnp.int64)
        return Batch((bidder, wcol), (), batch.weights).consolidate()

    keyed = bids.circuit.add_binary_operator(
        Apply2(attach, "q12-procwin"), bids, tick)
    keyed.schema = ((jnp.int64, jnp.int64), ())
    return keyed.aggregate(Count(), name="q12-count")


def q13(persons: Stream, auctions: Stream, bids: Stream,
        side: Stream = None) -> Stream:
    """Bounded side-input join (queries/q13.rs): enrich bids from a static
    keyed table. Default side input: channel -> boosted id table."""
    from dbsp_tpu.operators.basic import Generator
    from dbsp_tpu.zset.batch import Batch

    c = bids.circuit
    if side is None:
        table = Batch.from_tuples(
            [((ch, 1000 + ch), 1) for ch in range(16)],
            (jnp.int64,), (jnp.int64,))
        side = c.add_source(Generator(
            [table], default=Batch.empty((jnp.int64,), (jnp.int64,))))
        side.schema = ((jnp.int64,), (jnp.int64,))
    by_channel = bids.index_by(
        lambda k, v: (v[M.B_CHANNEL].astype(jnp.int64),), (jnp.int64,),
        val_fn=lambda k, v: (k[0], v[M.B_BIDDER], v[M.B_PRICE], v[M.B_DATE]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64, jnp.int64),
        name="q13-by-channel")
    return by_channel.join_index(
        side, lambda k, bv, sv: ((bv[0],), (bv[1], bv[2], bv[3], sv[0])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64, jnp.int64),
        name="q13-join")


def q14(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Calculation + filter (queries/q14.rs): euro price > 1M, bucketed
    bid-time-of-day. Output key (auction), vals (bidder, eur, timetype, ts);
    timetype: 0=day [8,18), 1=night [0,6)|[20,24), 2=other."""
    def conv(k, v):
        eur = v[M.B_PRICE] * 908 // 1000
        hour = (v[M.B_DATE] // 3_600_000) % 24
        night = ((hour < 6) | (hour >= 20)).astype(jnp.int64)
        day = ((hour >= 8) & (hour < 18)).astype(jnp.int64)
        timetype = jnp.where(day == 1, 0, jnp.where(night == 1, 1, 2))
        return k, (v[M.B_BIDDER], eur, timetype, v[M.B_DATE])

    mapped = bids.map_rows(conv, M.BID_KEY,
                           (jnp.int64, jnp.int64, jnp.int64, jnp.int64),
                           name="q14-calc")
    return mapped.filter_rows(lambda k, v: v[1] > 1_000_000, name="q14-filter")


DAY_MS = 86_400_000


def q15(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Distinct bidders per day (queries/q15.rs): (day, n_distinct)."""
    day_bidder = bids.map_rows(
        lambda k, v: ((v[M.B_DATE] // DAY_MS, v[M.B_BIDDER]), ()),
        (jnp.int64, jnp.int64), (), name="q15-daybidder")
    uniq = day_bidder.distinct()
    by_day = uniq.index_by(lambda k, v: (k[0],), (jnp.int64,),
                           val_fn=lambda k, v: (k[1],),
                           val_dtypes=(jnp.int64,), name="q15-by-day")
    return by_day.aggregate(Count(), name="q15-count")


Q16_RANK1 = 10_000
Q16_RANK2 = 1_000_000
Q16_NSTATS = 12


import dataclasses as _dc

from dbsp_tpu.operators.aggregate_linear import LinearAggregator


@_dc.dataclass(frozen=True)
class _Q16Stats(LinearAggregator):
    """12-column linear sum: each input row is a one-hot stat contribution;
    summing per (channel, day) assembles the full stat row with zeros for
    absent ranks — the left-join-with-default-0 the reference's SQL
    `count(*) filter (...)` columns imply."""

    acc_dtypes = (jnp.int64,) * Q16_NSTATS
    out_dtypes = (jnp.int64,) * Q16_NSTATS
    name = "q16stats"

    def weigh(self, val_cols):
        return tuple(val_cols[:Q16_NSTATS])

    def finalize(self, acc_cols, count):
        return acc_cols


def q16(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Channel statistics per day (queries/q16.rs, the FULL stat set):
    (channel, day) -> (total_bids, rank1/2/3_bids, total_bidders,
    rank1/2/3_bidders, total_auctions, rank1/2/3_auctions), where rank
    buckets split on price < 10_000 / < 1_000_000 / >= (q16.rs:55-66).

    Shape: one Count per bid rank (4 streams), one distinct+Count per
    (bidder x rank) and (auction x rank) (8 streams); each stat maps to a
    one-hot 12-column row and a single 12-column linear sum per
    (channel, day) assembles the output with 0 for empty buckets."""
    def rank_of(price):
        return jnp.where(price < Q16_RANK1, 1,
                         jnp.where(price < Q16_RANK2, 2, 3))

    base = bids.map_rows(
        lambda k, v: ((v[M.B_CHANNEL].astype(jnp.int64),
                       v[M.B_DATE] // DAY_MS),
                      (k[0], v[M.B_BIDDER], rank_of(v[M.B_PRICE]))),
        (jnp.int64, jnp.int64), (jnp.int64, jnp.int64, jnp.int64),
        name="q16-base")  # (channel, day) -> (auction, bidder, rank)

    def rank_filter(s, r, name):
        return s if r == 0 else s.filter_rows(
            lambda k, v, _r=r: v[2] == _r, name=name)

    stats = []  # (slot, stream of (channel, day) -> count)
    for r in range(4):  # bids counts: slots 0..3
        stats.append((r, rank_filter(base, r, f"q16-bids-r{r}")
                      .aggregate(Count(), name=f"q16-nbids-r{r}")))
    for col, what in ((1, "bidder"), (0, "auction")):
        for r in range(4):  # bidders: slots 4..7; auctions: slots 8..11
            slot = (4 if what == "bidder" else 8) + r
            uniq = rank_filter(base, r, f"q16-{what}-r{r}-f").map_rows(
                lambda k, v, _c=col: ((k[0], k[1], v[_c]), ()),
                (jnp.int64, jnp.int64, jnp.int64), (),
                name=f"q16-{what}-r{r}-key").distinct()
            cnt = uniq.index_by(
                lambda k, v: (k[0], k[1]), (jnp.int64, jnp.int64),
                val_fn=lambda k, v: (k[2],), val_dtypes=(jnp.int64,),
                name=f"q16-{what}-r{r}-by").aggregate(
                    Count(), name=f"q16-n{what}-r{r}")
            stats.append((slot, cnt))

    # one-hot each stat into the 12-column layout and sum
    onehot = []
    for slot, s in stats:
        def mk(slot):
            def f(k, v):
                z = jnp.zeros_like(v[0])
                return k, tuple(v[0] if i == slot else z
                                for i in range(Q16_NSTATS))
            return f

        oh = s.map_rows(mk(slot), (jnp.int64, jnp.int64),
                        (jnp.int64,) * Q16_NSTATS, name=f"q16-oh{slot}")
        onehot.append(oh)
    combined = onehot[0].sum_with(onehot[1:])
    combined.schema = ((jnp.int64, jnp.int64), (jnp.int64,) * Q16_NSTATS)
    return combined.aggregate(_Q16Stats(), name="q16-stats")


def q17(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Auction bid statistics per day (queries/q17.rs): (auction, day) ->
    (count, min, max, avg price)."""
    keyed = bids.map_rows(
        lambda k, v: ((k[0], v[M.B_DATE] // DAY_MS), (v[M.B_PRICE],)),
        (jnp.int64, jnp.int64), (jnp.int64,), name="q17-key",
        preserves_first_key=True)
    cnt = keyed.aggregate(Count(), name="q17-count")
    mn = keyed.aggregate(Min(0), name="q17-min")
    mx = keyed.aggregate(Max(0), name="q17-max")
    avg = keyed.aggregate(Average(0), name="q17-avg")
    j1 = cnt.join_index(mn, lambda k, a, b: (k, (a[0], b[0])),
                        (jnp.int64, jnp.int64), (jnp.int64, jnp.int64),
                        name="q17-j1", preserves_first_key=True)
    j2 = j1.join_index(mx, lambda k, a, b: (k, (a[0], a[1], b[0])),
                       (jnp.int64, jnp.int64),
                       (jnp.int64, jnp.int64, jnp.int64), name="q17-j2", preserves_first_key=True)
    return j2.join_index(avg, lambda k, a, b: (k, (a[0], a[1], a[2], b[0])),
                         (jnp.int64, jnp.int64),
                         (jnp.int64, jnp.int64, jnp.int64, jnp.int64),
                         name="q17-j3", preserves_first_key=True)


def q18(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Last bid of each bidder (queries/q18.rs): (bidder, ts, auction, price)."""
    by_bidder = bids.index_by(
        lambda k, v: (v[M.B_BIDDER],), (jnp.int64,),
        val_fn=lambda k, v: (v[M.B_DATE], k[0], v[M.B_PRICE]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64), name="q18-by-bidder")
    return by_bidder.topk(1, largest=True, name="q18-last")


def q19(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Top-10 bids by price per auction (queries/q19.rs): the window-function
    query; ranking = (price, ts) lexicographic."""
    ranked = bids.index_by(
        lambda k, v: (k[0],), M.BID_KEY,
        val_fn=lambda k, v: (v[M.B_PRICE], v[M.B_DATE], v[M.B_BIDDER]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64), name="q19-rank",
        preserves_first_key=True)
    return ranked.topk(10, largest=True, name="q19-top10")


def q20(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Expand bids with their auction's info, category 10 only
    (queries/q20.rs): (auction) -> (bidder, price, item, seller)."""
    cat = auctions.filter_rows(lambda k, v: v[M.A_CATEGORY] == Q3_CATEGORY,
                               name="q20-cat")
    by_id = cat.index_by(
        lambda k, v: (k[0],), M.AUCTION_KEY,
        val_fn=lambda k, v: (v[M.A_ITEM].astype(jnp.int64), v[M.A_SELLER]),
        val_dtypes=(jnp.int64, jnp.int64), name="q20-auctions",
        preserves_first_key=True)
    return bids.join_index(
        by_id, lambda k, bv, av: (k, (bv[M.B_BIDDER], bv[M.B_PRICE],
                                      av[0], av[1])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64, jnp.int64),
        name="q20-join", preserves_first_key=True)


def q21(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Channel id classification (queries/q21.rs): channels 0-3 map to fixed
    ids (the reference's apple/google/facebook/baidu CASE), others extract
    channel_id from the url. Strings are dictionary codes; the host-side
    dictionary (``nexmark/strings.py``) is constructed so this arithmetic
    EQUALS the CASE/regex over the decoded strings (fidelity-tested)."""
    def classify(k, v):
        ch = v[M.B_CHANNEL].astype(jnp.int64)
        chan_id = jnp.where(ch < 4, ch, 100 + ch)
        return k, (v[M.B_BIDDER], v[M.B_PRICE], ch, chan_id)

    return bids.map_rows(classify, M.BID_KEY,
                         (jnp.int64, jnp.int64, jnp.int64, jnp.int64),
                         name="q21")


def q22(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """URL split (queries/q22.rs): dir1/dir2/dir3 of the bid url. URLs are
    dictionary-coded; ``nexmark/strings.py`` owns the real strings, built so
    this mod/div arithmetic EQUALS split_part over the decoded url
    (fidelity-tested)."""
    def split(k, v):
        url = v[M.B_CHANNEL].astype(jnp.int64)  # channel doubles as url code
        dir1 = url % 7
        dir2 = (url // 7) % 11
        dir3 = (url // 77) % 13
        return k, (v[M.B_BIDDER], v[M.B_PRICE], dir1, dir2, dir3)

    return bids.map_rows(split, M.BID_KEY,
                         (jnp.int64, jnp.int64, jnp.int64, jnp.int64,
                          jnp.int64), name="q22")
