"""Nexmark queries as circuit builders.

Reference: ``crates/nexmark/src/queries/*.rs`` (hand-built on the Stream
API, q0-q9 + q12-q22). Each builder takes the three relation streams
(persons, auctions, bids — see model.py schemas) and returns the query's
output stream. Queries are added here stage by stage as the operator
library grows; q3+ use incremental join/aggregate (operators/join.py,
operators/aggregate.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.nexmark import model as M
from dbsp_tpu.operators.aggregate import Average, Count, Max  # noqa: F401 (queries use all three)


def q0(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Passthrough — measures raw engine overhead (queries/q0.rs)."""
    return bids.map_rows(lambda k, v: (k, v), M.BID_KEY, M.BID_VALS,
                         name="q0", preserves_order=True)


def q1(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Currency conversion: price dollars -> euros * 0.908 (queries/q1.rs).

    Integer semantics: price * 908 / 1000 (the reference uses f32; integer
    milli-euros keep the Z-set exactly comparable across backends).
    """
    def conv(k, v):
        bidder, price, channel, ts = v
        return k, (bidder, price * 908 // 1000, channel, ts)

    return bids.map_rows(conv, M.BID_KEY, M.BID_VALS, name="q1",
                         preserves_order=True)


def q2(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Bids on a sampled set of auctions: auction % 123 == 0, project
    (auction, price) (queries/q2.rs)."""
    filt = bids.filter_rows(lambda k, v: k[0] % 123 == 0, name="q2-filter")
    return filt.map_rows(lambda k, v: (k, (v[M.B_PRICE],)),
                         M.BID_KEY, (jnp.int64,), name="q2-project")


# State codes standing in for the reference's 'OR','ID','CA' literals
# (states are dictionary-encoded, generator.py).
Q3_STATES = (0, 1, 2)
Q3_CATEGORY = 10


def q3(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Who is selling in OR/ID/CA in category 10? (queries/q3.rs:35)

    filter(persons by state) ⋈ filter(auctions by category) on seller ->
    (name, city, state, auction id), keyed by auction id. Incremental
    equi-join (operators/join.py).
    """
    sellers = persons.filter_rows(
        lambda k, v: (v[M.P_STATE] == Q3_STATES[0])
        | (v[M.P_STATE] == Q3_STATES[1]) | (v[M.P_STATE] == Q3_STATES[2]),
        name="q3-sellers")
    cat = auctions.filter_rows(
        lambda k, v: v[M.A_CATEGORY] == Q3_CATEGORY, name="q3-category")
    # re-key auctions by seller (person id)
    by_seller = cat.index_by(
        lambda k, v: (v[M.A_SELLER],), M.PERSON_KEY,
        val_fn=lambda k, v: (k[0],), val_dtypes=(jnp.int64,),
        name="q3-by-seller")
    return sellers.join_index(
        by_seller,
        lambda k, pv, av: ((av[0],), (pv[0], pv[1], pv[2])),
        [jnp.int64], [jnp.int32, jnp.int32, jnp.int32], name="q3-join")


Q5_WINDOW_MS = 10_000
Q5_HOP_MS = 2_000


def q5(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Hot items: auctions with the most bids per hopping window
    (10s window, 2s hop — queries/q5.rs). Hopping windows are expressed
    TPU-style as a static flat_map: each bid belongs to exactly
    window/hop = 5 windows, so fan-out is a fixed [5, cap] expansion instead
    of a data-dependent iterator. Output: (window_start, auction) for
    auctions whose bid count equals the window maximum."""
    fanout = Q5_WINDOW_MS // Q5_HOP_MS

    def assign(k, v):
        ts = v[M.B_DATE]
        first = (ts // Q5_HOP_MS) * Q5_HOP_MS - (fanout - 1) * Q5_HOP_MS
        starts = jnp.stack([first + i * Q5_HOP_MS for i in range(fanout)])
        auction = jnp.broadcast_to(k[0], starts.shape)
        keep = jnp.ones(starts.shape, bool)
        return (starts, auction), (), keep

    per_window = bids.flat_map_rows(
        assign, fanout, (jnp.int64, jnp.int64), (), name="q5-windows")
    counts = per_window.aggregate(Count(), name="q5-count")
    # counts: key=(window, auction) val=(n). Max n per window:
    by_window = counts.index_by(
        lambda k, v: (k[0],), (jnp.int64,),
        val_fn=lambda k, v: (k[1], v[0]), val_dtypes=(jnp.int64, jnp.int64),
        name="q5-by-window")
    maxes = by_window.aggregate(Max(1), name="q5-max")
    hot = by_window.join_index(
        maxes,
        lambda k, cv, mv: (k, (cv[0], cv[1], mv[0])),
        (jnp.int64,), (jnp.int64, jnp.int64, jnp.int64), name="q5-join")
    winners = hot.filter_rows(lambda k, v: v[1] == v[2], name="q5-winners")
    return winners.map_rows(lambda k, v: ((k[0], v[0]), ()),
                            (jnp.int64, jnp.int64), (), name="q5-project")


Q7_WINDOW_MS = 10_000


def q7(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Highest bid of the latest completed tumbling window (queries/q7.rs):
    a watermark on bid event time drives monotone window bounds; the window
    operator maintains the bids of the last complete period, and a Max
    aggregate reduces them. Output: (window_end, max_price)."""
    wm = bids.watermark_monotonic(lambda k, v: v[M.B_DATE], lateness=0)

    def to_bounds(w):
        if w is None:
            return None
        end = (w // Q7_WINDOW_MS) * Q7_WINDOW_MS
        return (end - Q7_WINDOW_MS, end)

    bounds = wm.apply(to_bounds, name="q7-bounds")
    by_time = bids.index_by(
        lambda k, v: (v[M.B_DATE],), (jnp.int64,),
        val_fn=lambda k, v: (v[M.B_PRICE],), val_dtypes=(jnp.int64,),
        name="q7-by-time")
    windowed = by_time.window(bounds)
    # all rows of the (single-period) window share a window end — key by it
    keyed = windowed.map_rows(
        lambda k, v: (((k[0] // Q7_WINDOW_MS) * Q7_WINDOW_MS + Q7_WINDOW_MS,),
                      (v[0],)),
        (jnp.int64,), (jnp.int64,), name="q7-rekey")
    return keyed.aggregate(Max(0), name="q7-max")


Q8_WINDOW_MS = 10_000


def q8(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Monitor new users (queries/q8.rs:48-70): persons who created an
    auction in the same tumbling 10s window they registered in. The
    reference builds this from watermark_monotonic + window + join; the
    tumbling-window equality is expressed by making the window start a join
    key component. Output: (person_id, window_start, name)."""
    p_keyed = persons.index_by(
        lambda k, v: (k[0], (v[M.P_DATE] // Q8_WINDOW_MS) * Q8_WINDOW_MS),
        (jnp.int64, jnp.int64),
        val_fn=lambda k, v: (v[M.P_NAME],), val_dtypes=(jnp.int32,),
        name="q8-persons")
    a_keyed = auctions.index_by(
        lambda k, v: (v[M.A_SELLER],
                      (v[M.A_DATE] // Q8_WINDOW_MS) * Q8_WINDOW_MS),
        (jnp.int64, jnp.int64),
        val_fn=lambda k, v: (), val_dtypes=(),
        name="q8-auctions")
    joined = p_keyed.join_index(
        a_keyed, lambda k, pv, av: (k, (pv[0],)),
        (jnp.int64, jnp.int64), (jnp.int32,), name="q8-join")
    return joined.distinct()


def q4(persons: Stream, auctions: Stream, bids: Stream) -> Stream:
    """Average final (max) bid price per category over closed auctions
    (queries/q4.rs:43): bids within [auction.date_time, auction.expires]
    joined on auction id -> max price per (auction, category) -> average per
    category. Exercises join + two incremental aggregates."""
    by_auction = auctions.index_by(
        lambda k, v: (k[0],), M.AUCTION_KEY,
        val_fn=lambda k, v: (v[M.A_CATEGORY], v[M.A_DATE], v[M.A_EXPIRES]),
        val_dtypes=(jnp.int64, jnp.int64, jnp.int64), name="q4-auctions")
    joined = bids.join_index(
        by_auction,
        lambda k, bv, av: (
            (k[0], av[0]),
            (bv[M.B_PRICE], bv[M.B_DATE], av[1], av[2])),
        [jnp.int64, jnp.int64], [jnp.int64, jnp.int64, jnp.int64, jnp.int64],
        name="q4-join")
    in_window = joined.filter_rows(
        lambda k, v: (v[1] >= v[2]) & (v[1] <= v[3]), name="q4-window")
    # max price per (auction, category)
    per_auction = in_window.map_rows(
        lambda k, v: (k, (v[0],)), (jnp.int64, jnp.int64), (jnp.int64,),
        name="q4-price").aggregate(Max(0), name="q4-max")
    # average of those maxima per category
    by_category = per_auction.index_by(
        lambda k, v: (k[1],), (jnp.int64,),
        val_fn=lambda k, v: (v[0],), val_dtypes=(jnp.int64,),
        name="q4-by-category")
    return by_category.aggregate(Average(0), name="q4-avg")
