"""ctypes bridge to the native C++ Nexmark generator (native/nexmark_gen.cpp).

Builds the shared library on first use (g++ -O3; no pybind11 in this image —
plain C ABI + ctypes, per the repo's native-binding policy). The native path
must be bit-identical to the numpy implementation — the test suite compares
them column by column, so either can generate any sub-range of the stream.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

import numpy as np

from dbsp_tpu.nexmark.generator import GeneratorConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "nexmark_gen.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libnexmark_gen.so")

_lib: Optional[ctypes.CDLL] = None


class _CConfig(ctypes.Structure):
    _fields_ = [(name, ctypes.c_int64) for name in (
        "seed", "base_time_ms", "first_event_rate", "hot_auction_pm",
        "hot_bidder_pm", "hot_window", "num_channels", "num_name_codes",
        "num_city_codes", "num_state_codes", "expire_min_ms",
        "expire_max_ms")]


_build_error: Optional[str] = None


def build_library(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path.

    A failed build is cached (raised again without re-spawning g++) so hot
    paths with a numpy fallback don't fork a failing compiler per batch."""
    global _build_error
    if _build_error is not None and not force:
        raise RuntimeError(_build_error)
    if force or not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        # stamped build chokepoint (tools/build_native) — dev rebuilds
        # embed the source SHA-256 for the staleness lint
        import sys

        if _REPO_ROOT not in sys.path:
            sys.path.insert(0, _REPO_ROOT)
        from tools.build_native import compile_so

        try:
            compile_so(_SRC, _SO,
                       ["-O3", "-march=native", "-shared", "-fPIC"])
        except RuntimeError as e:
            _build_error = f"native generator: {e}"
            raise RuntimeError(_build_error) from None
    return _SO


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_library())
        lib.nx_counts.argtypes = [ctypes.c_int64] * 2 + \
            [ctypes.POINTER(ctypes.c_int64)] * 3
        # explicit argtypes: without them ctypes truncates int args to
        # 32-bit C ints, desynchronizing the generated range from the
        # nx_counts-sized buffers
        lib.nx_generate.argtypes = [
            ctypes.POINTER(_CConfig), ctypes.c_int64, ctypes.c_int64,
        ] + [ctypes.c_void_p] * 19
        lib.nx_generate.restype = None
        _lib = lib
    return _lib


def counts(n0: int, n1: int):
    lib = _load()
    np_, na, nb = (ctypes.c_int64(), ctypes.c_int64(), ctypes.c_int64())
    lib.nx_counts(n0, n1, ctypes.byref(np_), ctypes.byref(na),
                  ctypes.byref(nb))
    return np_.value, na.value, nb.value


def generate(cfg: GeneratorConfig, n0: int, n1: int
             ) -> Dict[str, Dict[str, np.ndarray]]:
    """Columnar events [n0, n1), same layout as NexmarkGenerator.generate."""
    lib = _load()
    n_p, n_a, n_b = counts(n0, n1)
    c = _CConfig(
        seed=cfg.seed, base_time_ms=cfg.base_time_ms,
        first_event_rate=cfg.first_event_rate,
        hot_auction_pm=int(cfg.hot_auction_ratio * 1000),
        hot_bidder_pm=int(cfg.hot_bidder_ratio * 1000),
        hot_window=cfg.hot_window, num_channels=cfg.num_channels,
        num_name_codes=cfg.num_name_codes, num_city_codes=cfg.num_city_codes,
        num_state_codes=cfg.num_state_codes,
        expire_min_ms=cfg.auction_expire_min_ms,
        expire_max_ms=cfg.auction_expire_max_ms)

    def buf(n, dt):
        return np.empty((n,), dt)

    p = {"id": buf(n_p, np.int64), "name": buf(n_p, np.int32),
         "city": buf(n_p, np.int32), "state": buf(n_p, np.int32),
         "email": buf(n_p, np.int32), "date_time": buf(n_p, np.int64)}
    a = {"id": buf(n_a, np.int64), "item": buf(n_a, np.int32),
         "seller": buf(n_a, np.int64), "category": buf(n_a, np.int64),
         "initial_bid": buf(n_a, np.int64), "reserve": buf(n_a, np.int64),
         "date_time": buf(n_a, np.int64), "expires": buf(n_a, np.int64)}
    b = {"auction": buf(n_b, np.int64), "bidder": buf(n_b, np.int64),
         "price": buf(n_b, np.int64), "channel": buf(n_b, np.int32),
         "date_time": buf(n_b, np.int64)}

    def ptr(arr):
        return arr.ctypes.data_as(ctypes.c_void_p)

    lib.nx_generate(
        ctypes.byref(c), n0, n1,
        ptr(p["id"]), ptr(p["name"]), ptr(p["city"]), ptr(p["state"]),
        ptr(p["email"]), ptr(p["date_time"]),
        ptr(a["id"]), ptr(a["item"]), ptr(a["seller"]), ptr(a["category"]),
        ptr(a["initial_bid"]), ptr(a["reserve"]), ptr(a["date_time"]),
        ptr(a["expires"]),
        ptr(b["auction"]), ptr(b["bidder"]), ptr(b["price"]),
        ptr(b["channel"]), ptr(b["date_time"]))
    return {"persons": p, "auctions": a, "bids": b}
