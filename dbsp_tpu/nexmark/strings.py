"""Host-side string dictionaries for Nexmark's channel/URL columns.

SURVEY §7 hard parts: variable-length strings are dictionary-encoded on the
host; fixed-width codes flow on device. This module OWNS the dictionary —
the real strings — and is constructed so that the device-side arithmetic in
q21/q22 (``queries.py``) is EXACTLY the string operation the reference
performs on the decoded text:

* q21 (queries/q21.rs): ``CASE channel WHEN 'apple'/'google'/'facebook'/
  'baidu' -> fixed ids ELSE regex-extract channel_id from the url``. Codes
  0-3 decode to the four named channels; any other code decodes to a URL
  whose ``channel_id`` query parameter IS ``100 + code`` — so the circuit's
  ``where(code < 4, code, 100 + code)`` equals regex extraction over the
  decoded string.
* q22 (queries/q22.rs): ``split_part(url, '/', 5..7)`` — dir1/dir2/dir3.
  URLs decode to ``https://b1.com/d<a>/d<b>/d<c>`` with a/b/c the same
  mod/div arithmetic the circuit applies, so splitting the decoded string
  reproduces the device output.

Encode at ingestion (`encode_channel`), decode at the serving boundary
(`decode_channel` / `channel_url` / `url_dirs`, used by output formatting
and the fidelity tests).
"""

from __future__ import annotations

from typing import Tuple

NAMED_CHANNELS = ("apple", "google", "facebook", "baidu")

# q21's CASE arm ids for the named channels are their codes (0..3); other
# channels get ids extracted from their URL's channel_id parameter
URL_CHANNEL_BASE = 100

# q22 splits (see url_dirs)
_D1, _D2, _D3 = 7, 11, 13


def decode_channel(code: int) -> str:
    """The channel STRING a code stands for."""
    if 0 <= code < len(NAMED_CHANNELS):
        return NAMED_CHANNELS[code]
    return f"channel-{code}"


def channel_url(code: int) -> str:
    """The bid URL for a channel code (the reference attaches one per bid)."""
    a, b, c3 = url_dirs_arith(code)
    return (f"https://b1.com/d{a}/d{b}/d{c3}"
            f"?channel_id={URL_CHANNEL_BASE + code}")


def encode_channel(name: str) -> int:
    if name in NAMED_CHANNELS:
        return NAMED_CHANNELS.index(name)
    assert name.startswith("channel-"), f"unknown channel {name!r}"
    return int(name.split("-", 1)[1])


# -- the string operations the queries model --------------------------------


def channel_id_of(code: int) -> int:
    """q21's CASE, evaluated over the REAL strings: named channels map to
    their fixed ids; others regex-extract channel_id from the URL."""
    if 0 <= code < len(NAMED_CHANNELS):
        return code
    url = channel_url(code)
    # the reference's `SPLIT(url, 'channel_id=')[2]`
    return int(url.split("channel_id=")[1])


def url_dirs_arith(code: int) -> Tuple[int, int, int]:
    """The dir1/dir2/dir3 codes embedded in the URL (and computed on device)."""
    return code % _D1, (code // _D1) % _D2, (code // (_D1 * _D2)) % _D3


def url_dirs_of(code: int) -> Tuple[str, str, str]:
    """q22's split_part over the REAL url string."""
    url = channel_url(code)
    path = url.split("?")[0]
    parts = path.split("/")  # ['https:', '', 'b1.com', d1, d2, d3]
    return parts[3], parts[4], parts[5]
