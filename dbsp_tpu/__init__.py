"""dbsp_tpu: a TPU-native framework for incremental view maintenance over data streams.

A brand-new JAX/XLA design with the capabilities of DBSP
(vmware/database-stream-processor): computations are dataflow circuits of
operators over Z-sets (multisets with signed integer weights), evaluated
incrementally so each clock tick costs in proportion to the input delta, not
the accumulated state.

Architecture (TPU-first, not a port):
  - Z-set batches are columnar struct-of-arrays device buffers with static
    capacities, zero-weight padding, and sort-based consolidation kernels
    (``dbsp_tpu.zset``).
  - Traces are LSM-style spines of geometric size classes with amortized
    device merges (``dbsp_tpu.trace``).
  - The circuit is a host-side DAG driving jitted per-operator kernels
    (``dbsp_tpu.circuit``, ``dbsp_tpu.operators``).
  - Worker parallelism is SPMD over a ``jax.sharding.Mesh``: the reference's
    key-hash shard()/exchange maps to an all_to_all over ICI
    (``dbsp_tpu.parallel``).
  - Observability is one registry-backed subsystem: labeled metrics with
    Prometheus exposition, per-operator latency histograms, spine residency
    gauges, and Chrome-trace span export (``dbsp_tpu.obs``).

64-bit integers are enabled globally: stream timestamps (ms since epoch) and
SQL BIGINT semantics require them.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from dbsp_tpu.zset.batch import Batch  # noqa: E402

__version__ = "0.1.0"

__all__ = ["Batch", "__version__"]
