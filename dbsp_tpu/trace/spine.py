"""Spine: the LSM-style trace of a stream — accumulated state as a small set
of consolidated batches in geometric size classes.

TPU-native rethink of the reference's fueled spine
(``crates/dbsp/src/trace/spine_fueled.rs:107``): the reference amortizes merge
work by carrying "fuel" through partially-completed merges; here a merge is a
single fused device kernel (concat + sort + segment-sum + compact), so instead
of fuel we bound *when* merges fire — two batches in the same power-of-two
capacity bucket merge immediately, giving the same O(log n) level structure
and O(1) amortized merges per insert, with no partially-merged state to track.

Host-side bookkeeping (which batches exist, their buckets) is Python; all data
movement is jitted device work. Capacities are power-of-two buckets so the set
of compiled kernel shapes stays logarithmic.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, Row, bucket_cap, concat_batches

# Device-residency budget (rows) for EACH spine: levels beyond it live in
# HOST memory as numpy-backed batches and transfer on probe, and — one
# tier further (HOST_BUDGET_ROWS) — as content-addressed blobs in the
# disk ColdStore, faulted back to host on probe with digest verification.
# None = no cap. The larger-than-device-memory story (reference: the
# RocksDB-backed PersistentTrace, trace/persistent/trace.rs:34 — a
# drop-in Spine whose cold levels spill to disk): the hierarchy is
# HBM <- host RAM <- disk, and the transfer unit is a whole cold level.
# BOTH knobs (and the store directory) are owned by dbsp_tpu.residency —
# the one config point the compiled engine shares — and aliased here for
# backward compatibility (tests monkeypatch these module attributes).
from dbsp_tpu import residency as _res  # noqa: E402

DEVICE_BUDGET_ROWS: Optional[int] = _res.DEVICE_ROWS
HOST_BUDGET_ROWS: Optional[int] = _res.HOST_ROWS

# Maintenance budget (rows one maintenance call may move/merge) — the ONE
# owner of the DBSP_TPU_MAINTAIN_BUDGET_ROWS knob; the compiled engine
# (compiled/compiler.py) imports it so both engines stay in lockstep. An
# equal-bucket compaction whose pair cost exceeds the budget defers to a
# later insert/maintain call instead of landing its whole merge in one
# tick. The trace is the union of its batches at every point, so deferral
# changes only WHEN compaction happens, never any consumer result
# (tests/test_maintenance.py proves bit-identity). 0/negative = unbounded
# (None); unset defaults to 131072 rows.
_env_maintain = os.environ.get("DBSP_TPU_MAINTAIN_BUDGET_ROWS")
if _env_maintain:
    MAINTAIN_BUDGET_ROWS: Optional[int] = (
        int(_env_maintain) if int(_env_maintain) > 0 else None)
else:
    MAINTAIN_BUDGET_ROWS = 1 << 17


def _to_cold(batch: Batch) -> Batch:
    """Move a batch's columns to host memory (numpy). jnp kernels accept
    numpy operands and device_put them per call, so cold levels stay fully
    probe-able — each probe pays the transfer, nothing persists on device
    (the fetched operand buffers die with the call)."""
    return _res.to_host(batch)


def _is_cold(batch: Batch) -> bool:
    return isinstance(batch.weights, np.ndarray)


def _is_disk(batch: Batch) -> bool:
    return isinstance(batch.weights, np.memmap)


class Spine:
    """An append-only Z-set trace with amortized device merges.

    Reference behaviors covered (``trace/mod.rs:86``): ``insert`` (:meth:`insert`),
    the dirty flag (:attr:`dirty`), lower-bound GC ``truncate_keys_below``
    (:meth:`truncate_keys_below`), and cursor-style key probes
    (:meth:`probe_ranges`).
    """

    def __init__(self, key_dtypes: Sequence, val_dtypes: Sequence = (),
                 device_budget_rows: Optional[int] = None,
                 maintain_budget_rows: Optional[int] = None,
                 host_budget_rows: Optional[int] = None,
                 cold_store=None):
        self.key_dtypes = tuple(jnp.dtype(d) for d in key_dtypes)
        self.val_dtypes = tuple(jnp.dtype(d) for d in val_dtypes)
        self.batches: List[Batch] = []
        self.dirty = False  # any insert since last clear (fixedpoint checks)
        self._consolidated: Optional[Batch] = None
        self.device_budget_rows = (device_budget_rows
                                   if device_budget_rows is not None
                                   else DEVICE_BUDGET_ROWS)
        self.host_budget_rows = (host_budget_rows
                                 if host_budget_rows is not None
                                 else HOST_BUDGET_ROWS)
        # disk tier (residency.ColdStore); lazily defaulted when the host
        # budget first forces a demotion and no store was configured
        self.cold_store = cold_store
        # per-batch disk blob metadata, keyed by batch object identity
        # (the batch object stays referenced in self.batches while listed,
        # so ids are stable for the entry's lifetime)
        self._disk_meta: Dict[int, dict] = {}
        # residency observability: transition counts keyed
        # (tier_from, tier_to, cause) and a bounded transition log —
        # exported as dbsp_tpu_trace_residency_transitions_total and
        # polled into `residency` flight events
        self.residency_stats: Dict[Tuple[str, str, str], int] = {}
        self.residency_log: List[dict] = []
        self.maintain_budget_rows = (maintain_budget_rows
                                     if maintain_budget_rows is not None
                                     else MAINTAIN_BUDGET_ROWS)
        # amortization bookkeeping: last_slice_rows is the row capacity the
        # most recent insert/maintain call actually merged (what the
        # cascade test bounds); pending_compaction flags deferred merges
        self.maintain_stats = {"merged_rows": 0, "max_slice_rows": 0,
                               "merges": 0, "forced_merges": 0}
        self.last_slice_rows = 0
        self.pending_compaction = False

    def device_resident_rows(self) -> int:
        """Capacity currently held in DEVICE memory (cold levels excluded)
        — what the budget bounds; tests and the ``dbsp_tpu_trace_device_
        resident_rows`` gauge read this. Sharded batches count their
        per-worker capacity (each worker holds ``cap`` rows of HBM), the
        same capacity :meth:`_enforce_budget` charges against the budget."""
        return sum(b.cap for b in self.batches if not _is_cold(b))

    def host_offloaded_rows(self) -> int:
        """Row capacity living in HOST memory (cold levels, disk-tier
        memmaps excluded) — exported as
        ``dbsp_tpu_trace_host_offloaded_rows``."""
        return sum(b.cap for b in self.batches
                   if _is_cold(b) and not _is_disk(b))

    def disk_resident_rows(self) -> int:
        """Row capacity living as disk blobs (memmap-backed levels)."""
        return sum(b.cap for b in self.batches if _is_disk(b))

    def tier_rows(self) -> Dict[str, int]:
        """Resident row capacity per tier (metric label values)."""
        return {_res.TIER_DEVICE: self.device_resident_rows(),
                _res.TIER_HOST: self.host_offloaded_rows(),
                _res.TIER_DISK: self.disk_resident_rows()}

    def _note_transition(self, tier_from: str, tier_to: str, rows: int,
                         cause: str) -> None:
        key = (tier_from, tier_to, cause)
        self.residency_stats[key] = self.residency_stats.get(key, 0) + 1
        if len(self.residency_log) < 512:  # bounded; stats stay exact
            self.residency_log.append(
                {"tier_from": tier_from, "tier_to": tier_to,
                 "rows": int(rows), "cause": cause})

    def _store(self):
        if self.cold_store is None:
            self.cold_store = _res.default_store()
        return self.cold_store

    def _fault(self, b: Batch, cause: str = "probe") -> Batch:
        """Fault one disk-tier batch back to host (verified read — the
        corruption-detection point; recovery + incident semantics in
        :meth:`dbsp_tpu.residency.ColdStore.read_verified`), replacing it
        in the level list. Demand-driven promotion: a probe touching a
        disk level pays exactly this."""
        meta = self._disk_meta.get(id(b))
        if meta is None:
            # untracked memmap (bookkeeping went stale): the store is
            # content-addressed, so the filenames still carry the
            # expected digests — reconstruct and VERIFY; never read raw
            hot = _res.fault_batch(_res.meta_from_batch(b), self._store())
        else:
            # meta is dropped (and its blobs released toward the sweep)
            # only AFTER the verified read succeeds: a failed fault
            # (ColdError before a recovery dir exists) must leave the
            # level tracked for the retry
            hot = _res.fault_batch(meta, self._store())
            del self._disk_meta[id(b)]
            self._store().release(meta)
            self._store().sweep()  # host engine: no replay window to wait for
        i = next(i for i, x in enumerate(self.batches) if x is b)
        self.batches[i] = hot
        self._note_transition(_res.TIER_DISK, _res.TIER_HOST, b.cap, cause)
        return hot

    def _fault_all(self, cause: str = "probe") -> None:
        for b in list(self.batches):
            if _is_disk(b):
                self._fault(b, cause)

    def _enforce_budget(self) -> None:
        """Offload the largest device levels to host until the device
        residency fits the budget. Largest-first: deep levels are the
        coldest (probed identically but re-merged the least), so one
        offload buys the most headroom per transfer.

        Budget semantics on multichip spines: SHARDED batches count toward
        the resident total (they occupy HBM and the residency gauge counts
        them) but are never offload candidates — a cold (numpy) operand
        cannot participate in the SPMD collectives that probe sharded
        levels. The budget is therefore enforced where it can be (unsharded
        levels), and a spine whose sharded levels alone exceed the budget
        stays over it — visibly, since metric and enforcement now agree."""
        if self.device_budget_rows is not None:
            hot = sorted((b for b in self.batches
                          if not _is_cold(b) and not b.sharded),
                         key=lambda b: b.cap, reverse=True)
            resident = sum(b.cap for b in self.batches if not _is_cold(b))
            # hard cap, largest level first (deep levels are re-merged the
            # least, so one offload buys the most headroom per transfer); a
            # budget below the delta size degrades to offload-every-insert —
            # bounded residency at bounded (transfer-per-probe) slowdown,
            # which is the PersistentTrace contract
            for b in hot:
                if resident <= self.device_budget_rows:
                    break
                # identity lookup: dataclass == would compare columns
                i = next(i for i, x in enumerate(self.batches) if x is b)
                self.batches[i] = _to_cold(b)
                self._note_transition(_res.TIER_DEVICE, _res.TIER_HOST,
                                      b.cap, "budget")
                resident -= b.cap
        if self.host_budget_rows is None:
            return
        # second tier: host levels past the host budget demote to the disk
        # blob store, largest-first for the same headroom-per-transfer
        # argument; probes FAULT them back (verified) on demand
        warm = sorted((b for b in self.batches
                       if _is_cold(b) and not _is_disk(b)),
                      key=lambda b: b.cap, reverse=True)
        resident = sum(b.cap for b in warm)
        for b in warm:
            if resident <= self.host_budget_rows:
                break
            cold, meta = _res.demote_batch_to_disk(b, self._store())
            i = next(i for i, x in enumerate(self.batches) if x is b)
            self.batches[i] = cold
            self._disk_meta[id(cold)] = meta
            self._note_transition(_res.TIER_HOST, _res.TIER_DISK,
                                  b.cap, "budget")
            resident -= b.cap

    # -- maintenance --------------------------------------------------------
    def insert(self, batch: Batch) -> None:
        """Insert a consolidated delta batch; merge equal-sized levels
        (amortized — see :meth:`maintain`)."""
        batch = _shrink(batch)
        if batch is None:
            return
        self.dirty = True
        self._consolidated = None
        self.batches.append(batch)
        self.batches.sort(key=lambda b: b.cap, reverse=True)
        self.maintain()
        self._enforce_budget()

    def maintain(self, budget_rows: Optional[int] = None) -> bool:
        """One bounded compaction slice: merge levels sharing a capacity
        bucket (LSM compaction) until the per-call budget is spent.

        Levels are consolidated (sorted), so each merge is one rank-based
        sorted-merge kernel, not a re-sort of the combined rows. The budget
        (default: the spine's ``maintain_budget_rows``) bounds the summed
        row capacity merged per call — the host-path analog of the
        reference's merge fuel (spine_fueled.rs:107) and of the compiled
        engine's drain budget: a cascade (merge chains re-bucketing into
        the next class) spreads over subsequent insert/maintain calls
        instead of one tick absorbing it. Deferred pairs are correct
        merely-uncompacted state (probes fan over all batches); a bucket
        holding MORE than two batches force-merges regardless of budget so
        a budget below one pair's cost degrades to late compaction, never
        to unbounded batch growth. Returns True while work remains
        (``pending_compaction``)."""
        budget = (budget_rows if budget_rows is not None
                  else self.maintain_budget_rows)
        left = budget if budget and budget > 0 else None
        sliced = 0
        merged = True
        deferred = False
        while merged:
            merged = False
            buckets: Dict[int, int] = {}
            for b in self.batches:
                buckets[b.cap] = buckets.get(b.cap, 0) + 1
            for i in range(len(self.batches) - 1):
                if self.batches[i].cap != self.batches[i + 1].cap:
                    continue
                cost = self.batches[i].cap + self.batches[i + 1].cap
                over = left is not None and cost > left - sliced
                forced = buckets.get(self.batches[i].cap, 0) > 2
                if over and not forced:
                    deferred = True
                    continue
                # a merge READS both sides: disk-tier operands fault to
                # host first (verified — the write path must never fold
                # unverified bytes into the trace)
                for b in (self.batches[i], self.batches[i + 1]):
                    if _is_disk(b):
                        self._fault(b, cause="maintain")
                a = self.batches.pop(i + 1)
                b = self.batches.pop(i)
                m = _shrink(a.merge_with(b))
                if m is not None:
                    self.batches.insert(i, m)
                    self.batches.sort(key=lambda b: b.cap, reverse=True)
                sliced += cost
                self.maintain_stats["merged_rows"] += cost
                self.maintain_stats["merges"] += 1
                if over:
                    self.maintain_stats["forced_merges"] += 1
                merged = True
                break
        self.last_slice_rows = sliced
        self.maintain_stats["max_slice_rows"] = max(
            self.maintain_stats["max_slice_rows"], sliced)
        self.pending_compaction = deferred
        return deferred

    def is_empty(self) -> bool:
        return not self.batches

    def clear_dirty(self) -> None:
        self.dirty = False

    @property
    def total_cap(self) -> int:
        return sum(b.cap for b in self.batches)

    def consolidated(self) -> Batch:
        """All levels merged into one canonical batch (cached until insert).

        O(total state) when (re)built — use :meth:`probe_ranges` /
        per-level access in per-step hot paths; this is for aggregation
        snapshots, output handles, and tests.
        """
        if self._consolidated is None:
            self._fault_all(cause="probe")  # reads every level anyway
            if not self.batches:
                self._consolidated = Batch.empty(self.key_dtypes, self.val_dtypes)
            elif len(self.batches) == 1:
                self._consolidated = self.batches[0]
            else:
                # fold small->large so each rank-merge probes the smaller side
                acc = None
                for b in sorted(self.batches, key=lambda b: b.cap):
                    acc = b if acc is None else acc.merge_with(b)
                c = _shrink(acc)
                self._consolidated = c if c is not None else Batch.empty(
                    self.key_dtypes, self.val_dtypes)
        return self._consolidated

    # -- GC (reference: TraceBound truncation, operator/trace.rs:29-120) ----
    def truncate_keys_below(self, bound_key: Tuple) -> None:
        """Drop all rows whose key tuple is lexicographically < ``bound_key``.

        Consumers (windows, GC) declare monotone lower bounds; state below
        them can never affect future outputs and is reclaimed here.
        """
        self._fault_all(cause="gc")  # truncation rewrites every level
        new: List[Batch] = []
        for b in self.batches:
            kept = _shrink(_truncate_batch(b, bound_key))
            if kept is not None:
                new.append(kept)
        self._disk_meta.clear()  # every batch object was replaced
        self.batches = sorted(new, key=lambda b: b.cap, reverse=True)
        self._consolidated = None
        self._enforce_budget()

    # -- probes (cursor equivalents) ----------------------------------------
    def probe_ranges(self, query_keys: Tuple[jnp.ndarray, ...]
                     ) -> List[Tuple[Batch, jnp.ndarray, jnp.ndarray]]:
        """Per-level [lo, hi) ranges of rows matching each query key.

        Delta-proportional (O(m log n) binary-search probes per level); the
        replacement for the reference's per-batch cursors + CursorList k-way
        merge (``trace/cursor/cursor_list.rs``) — consumers fan out over the
        O(log n) levels and combine with segment reductions.
        """
        nk = len(self.key_dtypes)
        out = []
        for b in list(self.batches):
            if _is_disk(b):
                # demand-driven promotion: a probe touching a disk level
                # faults it to host (verified read; stays host until the
                # budget demotes it again)
                b = self._fault(b, cause="probe")
            tk = b.keys[:nk]
            lo = kernels.lex_probe(tk, query_keys, side="left")
            hi = kernels.lex_probe(tk, query_keys, side="right")
            out.append((b, lo, hi))
        return out

    # -- host views ----------------------------------------------------------
    def to_dict(self) -> Dict[Row, int]:
        self._fault_all(cause="probe")
        out: Dict[Row, int] = {}
        for b in self.batches:
            for r, w in b.to_dict().items():
                out[r] = out.get(r, 0) + w
                if out[r] == 0:
                    del out[r]
        return out


@jax.jit
def _truncate_weights(keys, weights, bound):
    ge = jnp.zeros(weights.shape, jnp.bool_)
    all_eq = jnp.ones(weights.shape, jnp.bool_)
    for k, bv in zip(keys, bound):
        kv = jnp.asarray(bv, k.dtype)
        ge = ge | (all_eq & (k > kv))
        all_eq = all_eq & (k == kv)
    ge = ge | all_eq
    return jnp.where(ge, weights, 0)


def _truncate_batch(b: Batch, bound_key: Tuple) -> Batch:
    nk = len(bound_key)
    w = _truncate_weights(b.keys[:nk], b.weights, tuple(bound_key))
    return Batch(b.keys, b.vals, w).consolidate()


def _shrink(batch: Batch) -> Optional[Batch]:
    """Shrink a consolidated batch to its tight capacity bucket; None if empty.

    The one host<->device sync per insert (a scalar live-row count); keeps
    level capacities proportional to live data so probe/merge cost tracks
    actual state size.
    """
    live = int(batch.live_count())
    if live == 0:
        return None
    return batch.with_cap(bucket_cap(live))
