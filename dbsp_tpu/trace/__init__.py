from dbsp_tpu.trace.spine import Spine

__all__ = ["Spine"]
