"""Timeline: always-on per-tick history, EXPLAIN SPIKE, freshness tracking.

The sensors grown over PRs 5-15 are rich but disjoint: the flight
recorder names causes, the per-node profiler attributes shares, the SLO
watchdog latches incidents, and the residency layer exports tier
transitions — yet answering "why was p99 high five minutes ago?" still
required a human to join four surfaces by hand, and nothing measured the
quantity readers actually feel: ingest-to-visible freshness. This module
is the join. It follows the Dapper / "Tail at Scale" pattern — always-on,
low-overhead, cause-attributed telemetry — applied to incremental view
maintenance:

* **One time-indexed ring.** Both engines feed it: the flight stream
  (tick latency + causes, host phase overheads, maintain drains,
  overflow replays, exchange deltas, residency transitions, checkpoint
  saves, transport errors) is ingested incrementally by ``seq`` cursor,
  the controller stamps wall-clock tick records (``note_tick``) that
  include everything inside the step lock (validate, maintain, snapshot,
  checkpoint write), and SLO incidents land as records too. Bounded
  (configurable retention via ``DBSP_TPU_TIMELINE_CAPACITY``), append-
  only under its own lock — readers never touch the step lock.

* **EXPLAIN SPIKE** (:meth:`Timeline.explain_spikes`): outlier ticks are
  selected against a robust rolling baseline (trailing median + MAD —
  means would let the spike poison its own threshold) and each is
  explained with ranked evidence drawn from the co-timed records:
  maintain drain, retrace, overflow replay, checkpoint write, residency
  demotion/promotion fault, transport stall, GC. Co-timing is by wall
  clock against the tick's span, so flight events ingested late (at the
  next scrape) still attach to the tick they happened inside.

* **Freshness tracking.** The controller stamps arrival wall-time per
  pushed batch (``note_arrival``) and records visibility at validation
  publish (``note_visible``); the delta is exported as the
  ``dbsp_tpu_freshness_seconds{view}`` histogram plus a per-view
  staleness gauge — snapshot staleness becomes a measured, gateable
  quantity (tests/test_timeline.py gates it at validation interval + one
  tick budget on both engines).

Overhead discipline mirrors the flight recorder: every note_* call is
one dict build + deque append under a short lock; the always-on cost is
gated by the ``timeline`` front in ``tools/lint_all.py`` and the
interleaved A/B in ``BENCH_local_timeline[_off].json``. ``DBSP_TPU_
TIMELINE=0`` disables the feed entirely (the A/B control).

This is deliberately the sensor substrate for the ROADMAP item 2
governor: every future adaptation decision should land as a timeline
record, so oscillation is attributable by construction.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

__all__ = ["Timeline", "SPIKE_CAUSES", "timeline_enabled"]

#: Closed vocabulary of spike-attribution causes (ranked-evidence keys;
#: METRICS.md documents this as the `cause` label set of
#: dbsp_tpu_timeline_spikes_total).
SPIKE_CAUSES = ("maintain", "retrace", "overflow_replay", "checkpoint",
                "residency", "transport", "gc", "unattributed")

#: flight/record kind -> spike cause bucket
_KIND_CAUSE = {
    "maintain": "maintain",
    "compile": "retrace",
    "overflow_replay": "overflow_replay",
    "checkpoint": "checkpoint",
    "residency": "residency",
    "transport": "transport",
    "gc": "gc",
}

#: tick-cause annotation -> spike cause bucket (engine tick records carry
#: `causes` lists with their own vocabulary)
_ANNOTATION_CAUSE = {"maintain": "maintain", "retrace": "retrace",
                     "snapshot": "checkpoint", "gc": "gc"}

# spike selection: a tick is an outlier when its latency exceeds BOTH the
# multiplicative bound (MULT x rolling median) and the additive robust
# bound (median + max(MAD_K x MAD, FLOOR)). The floor keeps sub-ms jitter
# on fast ticks from ever flagging; both knobs are env-tunable so the
# artifact generator and the lint front share one detector.
_SPIKE_MULT = float(os.environ.get("DBSP_TPU_SPIKE_MULT", "3.0"))
_SPIKE_MAD_K = 8.0
_SPIKE_FLOOR_NS = float(os.environ.get("DBSP_TPU_SPIKE_FLOOR_MS", "10")) * 1e6
_MIN_BASELINE = 8      # never flag before the baseline has this many ticks
_BASELINE_WINDOW = 64  # trailing window the median/MAD roll over

# e2e stage spikes (obs/tracing.py feeds per-stage `e2e_stage` records)
# use the same robust detector but a much higher floor: stage timings mix
# queue dwell and HTTP long-poll scheduling, so sub-100ms wiggle is normal
# operation — only a genuine stall (seeded transport delay, stuck apply)
# should ever flag, and the unperturbed control must flag nothing.
_STAGE_SPIKE_FLOOR_NS = float(os.environ.get(
    "DBSP_TPU_STAGE_SPIKE_FLOOR_MS", "250")) * 1e6

#: freshness histogram bounds: 1ms .. ~2000s, x2 per bucket — staleness
#: spans sub-tick (host engine, validate_every=1) to long deferred
#: intervals and seeded stalls
_FRESHNESS_BUCKETS = tuple(1e-3 * 2 ** i for i in range(22))


def timeline_enabled(env=None) -> bool:
    """The always-on default; ``DBSP_TPU_TIMELINE=0`` is the A/B control
    (BENCH_local_timeline_off.json) and the kill switch."""
    return (env if env is not None else os.environ).get(
        "DBSP_TPU_TIMELINE", "1") != "0"


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    m = n // 2
    return s[m] if n % 2 else (s[m - 1] + s[m]) / 2.0


class Timeline:
    """Bounded, time-indexed ring joining tick history, flight events,
    freshness samples, and SLO incidents; thread-safe, append-only under
    its own lock (never the step lock)."""

    def __init__(self, capacity: Optional[int] = None, registry=None,
                 pipeline: str = "", enabled: Optional[bool] = None):
        self.capacity = int(capacity if capacity is not None else
                            os.environ.get("DBSP_TPU_TIMELINE_CAPACITY",
                                           "4096"))
        self.enabled = timeline_enabled() if enabled is None else \
            bool(enabled)
        self.pipeline = pipeline
        self._lock = threading.Lock()
        self._records: Deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0  # records aged out of the ring
        self._flight_seen = 0  # seq cursor into the flight ring
        # freshness state: one pending pool (arrivals not yet visible) —
        # visibility publishes every registered view at once, so the
        # oldest unpublished arrival bounds staleness for all of them
        self._pending_rows = 0
        self._oldest_pending_ts: Optional[float] = None
        self._last_visible_ts: Optional[float] = None
        self._freshness: Dict[str, dict] = {}  # view -> last sample state
        self._spike_metric_seen = 0  # tick-record seq already counted
        self._fresh_hist = None
        self._stale_gauge = None
        self._spike_counter = None
        if registry is not None:
            self._fresh_hist = registry.histogram(
                "dbsp_tpu_freshness_seconds",
                "Ingest-to-visible latency per view: arrival wall-time of "
                "the oldest unpublished batch to its validation publish",
                labels=("view",), buckets=_FRESHNESS_BUCKETS)
            self._stale_gauge = registry.gauge(
                "dbsp_tpu_freshness_staleness_seconds",
                "Current staleness per view: age of the oldest arrived-"
                "but-not-yet-visible batch (0 when fully published)",
                labels=("view",))
            self._spike_counter = registry.counter(
                "dbsp_tpu_timeline_spikes_total",
                "Outlier ticks flagged by EXPLAIN SPIKE, by attributed "
                "cause (closed set: obs.timeline.SPIKE_CAUSES)",
                labels=("cause",))
            registry.register_collector(self._export)
        _tsan_hook(self)

    # -- feed (writers) -----------------------------------------------------

    def _append_locked(self, rec: dict) -> int:  # holds: _lock
        self._seq += 1
        rec["seq"] = self._seq
        if len(self._records) == self.capacity:
            self.dropped += 1
        self._records.append(rec)
        return self._seq

    def note_tick(self, tick: int, latency_ns: int, rows_in: int = 0,
                  rows_out: int = 0, causes: Sequence[str] = (),
                  queue_depth: int = 0,
                  trace_ids: Sequence[str] = ()) -> None:
        """One controller-level tick: wall latency of everything inside
        the step lock (engine step + validate/maintain/snapshot +
        checkpoint write + monitors). ``trace_ids`` links the tick to the
        e2e trace contexts it drained, so a flagged spike names the
        deltas it delayed."""
        if not self.enabled:
            return
        rec = {"kind": "tick", "src": "ctl", "ts": time.time(),
               "t_ns": time.perf_counter_ns(), "tick": int(tick),
               "latency_ns": int(latency_ns), "rows_in": int(rows_in),
               "rows_out": int(rows_out), "causes": list(causes),
               "queue_depth": int(queue_depth)}
        if trace_ids:
            rec["trace"] = list(trace_ids)
        with self._lock:
            self._append_locked(rec)

    def note_e2e_stage(self, stage: str, seconds: float,
                       trace_ids: Sequence[str] = ()) -> None:
        """One measured stage of the end-to-end delta path (fed by
        :class:`dbsp_tpu.obs.tracing.E2ETracer`): writer stages per
        published epoch, replica stages per applied changefeed batch.
        EXPLAIN SPIKE baselines these per stage, so a stalled hop is
        named — with its trace ids — in ``stage_spikes``."""
        if not self.enabled:
            return
        rec = {"kind": "e2e_stage", "src": "e2e", "ts": time.time(),
               "t_ns": time.perf_counter_ns(), "stage": str(stage),
               "seconds": float(seconds), "trace": list(trace_ids)}
        with self._lock:
            self._append_locked(rec)

    def note_arrival(self, rows: int, ts: Optional[float] = None) -> None:
        """Stamp arrival wall-time of one pushed batch (controller push
        path and input-endpoint chunks)."""
        if not self.enabled:
            return
        now = time.time() if ts is None else ts
        with self._lock:
            self._pending_rows += int(rows)
            if self._oldest_pending_ts is None:
                self._oldest_pending_ts = now
            self._append_locked({"kind": "arrival", "ts": now,
                                 "t_ns": time.perf_counter_ns(),
                                 "rows": int(rows)})

    def note_visible(self, views: Sequence[str],
                     ts: Optional[float] = None) -> None:
        """Record visibility at validation publish: every pending arrival
        is now readable through each view; the oldest pending arrival's
        age is the freshness sample."""
        if not self.enabled:
            return
        now = time.time() if ts is None else ts
        with self._lock:
            oldest = self._oldest_pending_ts
            sample = max(0.0, now - oldest) if oldest is not None else None
            self._pending_rows = 0
            self._oldest_pending_ts = None
            self._last_visible_ts = now
            if sample is None:
                return  # nothing new became visible — no sample
            for view in views:
                st = self._freshness.setdefault(
                    view, {"samples": 0, "last_s": 0.0, "max_s": 0.0})
                st["samples"] += 1
                st["last_s"] = sample
                st["max_s"] = max(st["max_s"], sample)
            self._append_locked({"kind": "freshness", "ts": now,
                                 "t_ns": time.perf_counter_ns(),
                                 "views": list(views),
                                 "seconds": sample})
        if self._fresh_hist is not None:
            for view in views:
                self._fresh_hist.labels(view=view).observe(sample)

    def note_incident(self, incident: dict) -> None:
        """One opened SLO incident (PipelineObs.watch feeds these)."""
        if not self.enabled:
            return
        rec = {"kind": "incident", "ts": time.time(),
               "t_ns": time.perf_counter_ns(),
               "slo": incident.get("slo"),
               "cause": incident.get("cause")}
        with self._lock:
            self._append_locked(rec)

    def ingest_flight(self, flight) -> int:
        """Incrementally join the flight ring in by ``seq`` cursor: every
        new flight event becomes a timeline record (src="flight"), the
        engine-level tick/phase/maintain/residency/checkpoint/transport
        stream time-indexed next to the controller's own records. Returns
        the number of records ingested. Lock order: Timeline._lock ->
        FlightRecorder._lock (never the reverse)."""
        if not self.enabled:
            return 0
        with self._lock:
            events = flight.events(since_seq=self._flight_seen)
            n = 0
            for ev in events:
                rec = dict(ev)
                rec["src"] = "flight"
                rec["flight_seq"] = rec.pop("seq")
                self._append_locked(rec)
                self._flight_seen = max(self._flight_seen,
                                        rec["flight_seq"])
                n += 1
            return n

    # -- read surface (never takes the step lock) ---------------------------

    def records(self, since: int = 0, view: Optional[str] = None,
                kinds: Optional[Sequence[str]] = None,
                limit: Optional[int] = None) -> List[dict]:
        """Snapshot of records (oldest first), filtered by ``seq >
        since`` (incremental pollers), by view binding, and by kind."""
        with self._lock:
            out = list(self._records)
        if since:
            out = [r for r in out if r["seq"] > since]
        if kinds is not None:
            ks = set(kinds)
            out = [r for r in out if r["kind"] in ks]
        if view is not None:
            out = [r for r in out
                   if r.get("view") == view or view in r.get("views", ())]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def staleness(self) -> Dict[str, float]:
        """Current per-view staleness: age of the oldest arrived-but-not-
        visible batch, 0.0 when fully published."""
        now = time.time()
        with self._lock:
            pending = self._oldest_pending_ts
            views = list(self._freshness)
        age = max(0.0, now - pending) if pending is not None else 0.0
        return {v: age for v in views} if views else \
            ({"_pipeline": age} if pending is not None else {})

    def freshness_summary(self) -> Dict[str, dict]:
        now = time.time()
        with self._lock:
            pending = self._oldest_pending_ts
            out = {v: dict(st) for v, st in self._freshness.items()}
        age = max(0.0, now - pending) if pending is not None else 0.0
        for st in out.values():
            st["staleness_s"] = age
        return out

    def to_dict(self, since: int = 0, view: Optional[str] = None,
                limit: Optional[int] = None) -> dict:
        with self._lock:
            last_seq = self._seq
            dropped = self.dropped
        return {"capacity": self.capacity, "enabled": self.enabled,
                "last_seq": last_seq, "dropped": dropped,
                "truncated": dropped > 0,
                "freshness": self.freshness_summary(),
                "records": self.records(since=since, view=view,
                                        limit=limit)}

    # -- EXPLAIN SPIKE ------------------------------------------------------

    def _tick_stream(self, recs: List[dict]) -> List[dict]:
        """The tick records to baseline over: controller-level wall ticks
        when a controller feeds us (they include checkpoint/maintain time
        inside the step lock), engine-level flight ticks otherwise."""
        ticks = [r for r in recs if r["kind"] == "tick"]
        ctl = [r for r in ticks if r.get("src") == "ctl"]
        return ctl or ticks

    def _evidence(self, recs: List[dict], tick: dict) -> List[dict]:
        """Ranked co-timed evidence for one spike tick: records whose
        wall-clock stamp falls inside the tick's span, bucketed into the
        closed cause set and ranked by contributed time (ns fields),
        then by count."""
        t1 = tick["ts"]
        t0 = t1 - tick.get("latency_ns", 0) / 1e9 - 0.005
        scores: Dict[str, dict] = {}

        def add(cause, weight_ns, ev):
            st = scores.setdefault(cause, {"cause": cause, "score_ns": 0,
                                           "count": 0, "events": []})
            st["score_ns"] += int(weight_ns)
            st["count"] += 1
            if len(st["events"]) < 8:
                st["events"].append(ev)

        for r in recs:
            if r["kind"] == "tick" or not (t0 <= r["ts"] <= t1 + 0.005):
                continue
            cause = _KIND_CAUSE.get(r["kind"])
            if r["kind"] == "phase" and r.get("phase") == "maintain":
                cause = "maintain"
            if cause is None:
                continue
            weight = r.get("ns") or r.get("duration_ns") or 0
            ev = {k: v for k, v in r.items()
                  if k not in ("seq", "t_ns", "src", "flight_seq")}
            add(cause, weight, ev)
        for c in tick.get("causes") or ():
            mapped = _ANNOTATION_CAUSE.get(c)
            if mapped:
                add(mapped, 0, {"kind": "tick_annotation", "cause": c})
        ranked = sorted(scores.values(),
                        key=lambda s: (s["score_ns"], s["count"]),
                        reverse=True)
        return ranked

    def explain_spikes(self, limit: Optional[int] = None) -> dict:
        """Attribution pass: outlier ticks against the robust rolling
        baseline, each explained with ranked co-timed evidence."""
        with self._lock:
            recs = list(self._records)
        ticks = self._tick_stream(recs)
        spikes: List[dict] = []
        history: List[int] = []
        new_spike_seqs: List[Tuple[int, str]] = []
        for t in ticks:
            lat = t.get("latency_ns", 0)
            if len(history) >= _MIN_BASELINE:
                base = history[-_BASELINE_WINDOW:]
                med = _median(base)
                mad = _median([abs(x - med) for x in base])
                thr = max(_SPIKE_MULT * med,
                          med + max(_SPIKE_MAD_K * mad, _SPIKE_FLOOR_NS))
                if lat > thr:
                    evidence = self._evidence(recs, t)
                    cause = evidence[0]["cause"] if evidence else \
                        "unattributed"
                    spikes.append({
                        "tick": t.get("tick"), "ts": t["ts"],
                        "latency_ns": int(lat), "baseline_ns": int(med),
                        "mad_ns": int(mad), "threshold_ns": int(thr),
                        "cause": cause, "trace": list(t.get("trace", ())),
                        "evidence": evidence})
                    new_spike_seqs.append((t["seq"], cause))
                    continue  # a flagged outlier must not poison history
            history.append(lat)
        stage_spikes = self._stage_spikes(recs)
        if self._spike_counter is not None and new_spike_seqs:
            with self._lock:
                fresh = [(s, c) for s, c in new_spike_seqs
                         if s > self._spike_metric_seen]
                if fresh:
                    self._spike_metric_seen = max(s for s, _ in fresh)
            for _, cause in fresh:
                self._spike_counter.labels(cause=cause).inc()
        if limit is not None and len(spikes) > limit:
            spikes = spikes[-limit:]
        return {"spikes": spikes, "ticks_seen": len(ticks),
                "stage_spikes": stage_spikes,
                "baseline": {"min_samples": _MIN_BASELINE,
                             "window": _BASELINE_WINDOW,
                             "mult": _SPIKE_MULT,
                             "floor_ns": int(_SPIKE_FLOOR_NS),
                             "stage_floor_ns": int(_STAGE_SPIKE_FLOOR_NS)}}

    def _stage_spikes(self, recs: List[dict]) -> List[dict]:
        """The e2e-stage detector: same robust median+MAD selection as
        ticks, rolled independently per stage over the ``e2e_stage``
        records, with the higher _STAGE_SPIKE_FLOOR_NS floor. Each spike
        carries a human-readable evidence line that NAMES the slow stage
        and the trace ids it delayed."""
        stage_spikes: List[dict] = []
        history: Dict[str, List[float]] = {}
        for r in recs:
            if r["kind"] != "e2e_stage":
                continue
            ns = float(r.get("seconds", 0.0)) * 1e9
            hist = history.setdefault(r["stage"], [])
            if len(hist) >= _MIN_BASELINE:
                base = hist[-_BASELINE_WINDOW:]
                med = _median(base)
                mad = _median([abs(x - med) for x in base])
                thr = max(_SPIKE_MULT * med,
                          med + max(_SPIKE_MAD_K * mad,
                                    _STAGE_SPIKE_FLOOR_NS))
                if ns > thr:
                    ids = list(r.get("trace", ()))
                    stage_spikes.append({
                        "stage": r["stage"], "ts": r["ts"],
                        "seconds": float(r.get("seconds", 0.0)),
                        "baseline_s": med / 1e9,
                        "threshold_s": thr / 1e9,
                        "trace": ids,
                        "evidence": "e2e stage '%s' took %.3fs against a "
                                    "%.3fs baseline (trace %s)" % (
                                        r["stage"], ns / 1e9, med / 1e9,
                                        ",".join(ids) or "-")})
                    continue  # flagged outliers stay out of the baseline
            hist.append(ns)
        return stage_spikes

    # -- scrape-time collector ----------------------------------------------

    def _export(self) -> None:
        """Refresh the per-view staleness gauge at scrape time."""
        if self._stale_gauge is None:
            return
        for view, age in self.staleness().items():
            if view != "_pipeline":
                self._stale_gauge.labels(view=view).set(age)
