"""Metric primitives + the registry that owns them.

Reference: the per-pipeline metric surface of ``server/prometheus.rs`` and
``controller/stats.rs:129`` (global + per-endpoint atomic counters). Here
the primitives are host-side and lock-protected — they sit on control-plane
paths (scheduler event handlers, scrape-time collectors), never inside
jitted kernels.

Types:
  Counter    — monotone; ``_total`` names.
  Gauge      — set/inc/dec; scrape-time collectors usually drive these.
  Histogram  — log-bucketed (geometric bucket bounds); renders cumulative
               ``_bucket{le=...}`` series plus ``_sum``/``_count`` and can
               answer :meth:`Histogram.quantile` host-side.
  Summary    — same sketch as Histogram but renders ``{quantile=...}``
               lines (p50/p95/p99) — for step latency, where operators want
               the quantiles directly in the scrape.

Every metric is labeled: ``metric.labels(worker="0").inc()``. An empty
label set is the common case and needs no ``labels()`` call.

Naming convention (enforced at registration): metric names look like
``dbsp_tpu_<subsystem>_<name>_<unit>`` — lowercase snake_case, prefix
``dbsp_tpu_``, final segment one of the allowed units. Counters must end in
``_total``. ``tools/check_metrics.py`` re-checks the convention over the
tree as a tier-1 lint.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

# final name segment must be a unit (prometheus naming conventions; "total"
# is the counter suffix, "info" the build-info idiom; "timestamp" covers
# event-time domains whose unit the engine cannot know)
ALLOWED_UNITS = ("total", "seconds", "rows", "bytes", "count", "ratio",
                 "info", "timestamp")

_NAME_RE = re.compile(r"^dbsp_tpu_[a-z0-9]+(_[a-z0-9]+)+$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# The closed label-name allowlist for engine metrics. Label VALUES drive
# time-series cardinality, so label names are restricted to dimensions
# with enumerable value sets (operators, nodes, phases, causes, ...) —
# never per-key, per-row, or per-tick identities. tools/check_metrics.py
# lints every in-tree registration against this list (tier-1 via
# tests/test_obs.py); grow it deliberately, with the value set in mind.
# ("le"/"quantile" are exposition-internal, reserved for obs/export.py.)
ALLOWED_LABEL_NAMES = frozenset((
    "operator", "node", "endpoint", "phase", "cause", "reason", "path",
    "rule", "severity", "slo", "pipeline", "worker", "mode", "state",
    "query", "kind",
    # kernel dispatch attribution: "kernel" names a Z-set kernel entry
    # point (merge/probe/expand/...), "backend" the implementation it
    # dispatched to (native/xla/pallas) — both closed, enumerable sets
    # (zset/native_merge.py::KERNELS x three backends)
    "kernel", "backend",
    # tiered trace residency (dbsp_tpu/residency.py): "tier" and the
    # transition endpoints draw from the closed {device, host, disk} set
    "tier", "tier_from", "tier_to",
    # freshness tracking (obs/timeline.py): "view" names a registered
    # output view of the pipeline's catalog — the value set is the
    # pipeline's declared views, fixed at program deploy time
    "view",
    # flight-recorder drop accounting (obs/flight.py): "source" is the
    # event kind group that was evicted from the bounded ring — drawn
    # from the closed FlightRecorder event-kind vocabulary
    "source",
    # read serving plane (dbsp_tpu/serving.py): "route" is the read API
    # surface served (closed set: serving.READ_ROUTES); "replica" names
    # a manager-orchestrated read replica — the value set is the
    # deployment's replica topology, fixed at orchestration time like
    # "pipeline"/"worker"
    "route", "replica",
    # end-to-end delta tracing (obs/tracing.py): "stage" is one hop of
    # the ingest→tick→publish→changefeed→replica→read path — the closed
    # set obs.tracing.E2E_STAGES (queue_wait, tick, publish, transport,
    # apply, serve)
    "stage",
))


class MetricNameError(ValueError):
    pass


def validate_metric_name(name: str, kind: Optional[str] = None) -> None:
    """Raise :class:`MetricNameError` unless ``name`` follows the
    ``dbsp_tpu_<subsystem>_<name>_<unit>`` convention (and, for counters,
    ends in ``_total``)."""
    if not _NAME_RE.match(name):
        raise MetricNameError(
            f"metric name {name!r} must match "
            "dbsp_tpu_<subsystem>_<name>_<unit> (lowercase snake_case)")
    if kind == "counter" and not name.endswith("_total"):
        raise MetricNameError(
            f"counter {name!r} must end in '_total'")
    unit = name.rsplit("_", 1)[1]
    if unit not in ALLOWED_UNITS:
        raise MetricNameError(
            f"metric name {name!r} must end in a unit suffix "
            f"{ALLOWED_UNITS}, got {unit!r}")
    if kind in ("histogram", "summary") and name.endswith("_total"):
        raise MetricNameError(
            f"{kind} {name!r} must not end in '_total' (reserved for "
            "counters)")


def default_latency_buckets() -> Tuple[float, ...]:
    """Geometric (log-spaced) latency bounds: 100us .. ~100s, x2 per
    bucket — 21 buckets, enough resolution for p50/p95/p99 over anything
    from a fused XLA tick to a tunneled-TPU compile."""
    return tuple(100e-6 * 2 ** i for i in range(21))


class _Child:
    """One label-set instance of a metric; holds the actual value(s)."""

    __slots__ = ("value", "sum", "count", "buckets")

    def __init__(self, nbuckets: int = 0):
        self.value = 0.0
        self.sum = 0.0
        self.count = 0
        self.buckets = [0] * nbuckets if nbuckets else None

    def snapshot(self) -> "_Child":
        """Deep-enough copy for consistent reads; take under the owning
        metric's lock (samples()/quantile() both go through this — one
        copy site, so a new field cannot be copied in one and torn in
        the other)."""
        s = _Child()
        s.value, s.sum, s.count = self.value, self.sum, self.count
        s.buckets = list(self.buckets) if self.buckets is not None else None
        return s


class Metric:
    """Base: a named family of children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: Sequence[str] = ()):
        validate_metric_name(name, self.kind)
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise MetricNameError(f"bad label name {ln!r} on {name!r}")
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()

    def _child(self, key: Tuple[str, ...]) -> _Child:
        # fully under the lock — no lock-free fast path. The old
        # check-then-act (a naked dict read before a locked setdefault)
        # could hand out a child that clear_children() had just detached,
        # silently dropping updates into a dead cell; the schema claims
        # _children as lock(_lock), and these are control-plane metrics
        # where an uncontended acquire costs nothing measurable.
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._new_child()
            return c

    def _new_child(self) -> _Child:
        return _Child()

    def labels(self, **labels: str) -> "_Bound":
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[n]) for n in self.label_names)
        return _Bound(self, self._child(key))

    @property
    def _default(self) -> _Child:
        return self._child(())

    def clear_children(self) -> None:
        """Drop every label-set child. For gauge families whose HELP
        contract is "the LAST <event>" (e.g. the per-node profile
        gauges): re-exporting without clearing would leave children from
        the previous event serving stale values next to fresh ones.
        Never call on counters — monotone families must not regress."""
        with self._lock:
            self._children.clear()

    def samples(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        """(label values, child-SNAPSHOT) pairs in insertion order. Copies
        are taken under the metric lock so a scrape concurrent with
        observe()/inc() renders internally consistent values (sum/count/
        buckets from one moment), never torn mid-update state."""
        with self._lock:
            return [(key, c.snapshot())
                    for key, c in self._children.items()]


class _Bound:
    """A metric bound to one label set; forwards the value API."""

    __slots__ = ("_metric", "_c")

    def __init__(self, metric: Metric, child: _Child):
        self._metric = metric
        self._c = child

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._c, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._metric._inc(self._c, -amount)

    def set(self, value: float) -> None:
        self._metric._set(self._c, value)

    def set_total(self, value: float) -> None:
        # collector API (counters): mirror an external monotone total
        self._metric._set(self._c, value)

    def observe(self, value: float) -> None:
        self._metric._observe(self._c, value)

    @property
    def value(self) -> float:
        return self._c.value


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._inc(self._default, amount)

    def _inc(self, c: _Child, amount: float) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            c.value += amount

    def _set(self, c: _Child, value: float) -> None:
        """Collector API: mirror an externally-accumulated monotone total
        (endpoint counters owned by the controller). Never regresses."""
        with self._lock:
            c.value = max(c.value, value)

    def set_total(self, value: float) -> None:
        self._set(self._default, value)

    def _observe(self, c, value):  # pragma: no cover
        raise TypeError(f"counter {self.name} has no observe()")

    @property
    def value(self) -> float:
        return self._default.value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        self._set(self._default, value)

    def inc(self, amount: float = 1.0) -> None:
        self._inc(self._default, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._inc(self._default, -amount)

    def _inc(self, c: _Child, amount: float) -> None:
        with self._lock:
            c.value += amount

    def _set(self, c: _Child, value: float) -> None:
        with self._lock:
            c.value = value

    def _observe(self, c, value):  # pragma: no cover
        raise TypeError(f"gauge {self.name} has no observe()")

    @property
    def value(self) -> float:
        return self._default.value


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str, labels: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labels)
        bounds = tuple(buckets) if buckets else default_latency_buckets()
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"{name}: bucket bounds must strictly increase")
        self.bounds = bounds

    def _new_child(self) -> _Child:
        return _Child(nbuckets=len(self.bounds) + 1)  # + overflow

    def observe(self, value: float) -> None:
        self._observe(self._default, value)

    def _observe(self, c: _Child, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            c.buckets[i] += 1
            c.sum += value
            c.count += 1

    def _inc(self, c, amount=1.0):  # pragma: no cover
        raise TypeError(f"histogram {self.name} has no inc()")

    def _set(self, c, value):  # pragma: no cover
        raise TypeError(f"histogram {self.name} has no set()")

    # -- host-side quantile estimate (bucket upper-bound interpolation) ----
    def quantile(self, q: float, labels: Tuple[str, ...] = ()) -> float:
        """Estimated q-quantile (0..1) from the bucket sketch: linear
        interpolation inside the containing bucket (log buckets make the
        relative error bounded by the bucket growth factor). Computed
        over a snapshot taken under the lock, like :meth:`samples` — a
        live child mid-observe() would yield a torn count/bucket pair."""
        with self._lock:
            c = self._children.get(labels)
            if c is not None:
                c = c.snapshot()
        return self.quantile_of(c, q)

    def quantile_of(self, c: Optional[_Child], q: float) -> float:
        """Quantile over one child/snapshot (export.py renders summaries
        from :meth:`samples` snapshots through this)."""
        if c is None or c.count == 0:
            return float("nan")
        rank = q * c.count
        seen = 0
        lo = 0.0
        for i, n in enumerate(c.buckets):
            if n == 0:
                if i < len(self.bounds):
                    lo = self.bounds[i]
                continue
            if seen + n >= rank:
                hi = self.bounds[i] if i < len(self.bounds) else lo * 2
                frac = (rank - seen) / n
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += n
            lo = self.bounds[i] if i < len(self.bounds) else lo
        return self.bounds[-1]


class Summary(Histogram):
    """Quantile summary over the same log-bucket sketch (the exposition
    differs: ``{quantile="0.5"}`` lines instead of cumulative buckets)."""

    kind = "summary"
    quantiles = (0.5, 0.95, 0.99)


class MetricsRegistry:
    """Owns metrics + scrape-time collectors; one per pipeline.

    ``counter``/``gauge``/``histogram``/``summary`` are get-or-create (same
    name must keep the same type and label names). ``register_collector``
    adds a zero-arg callable run before every exposition — the idiom for
    gauges mirroring engine state (spine residency, buffered rows) without
    per-tick bookkeeping."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._collectors: List[Callable[[], None]] = []
        self._lock = threading.Lock()
        _tsan_hook(self)

    def _get_or_create(self, cls, name, help, labels, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labels, **kw)
                # the construction chokepoint for every metric family:
                # instrumenting here (not in Metric.__init__) lets the
                # whole subclass __init__ chain finish first, so the
                # sanitizer never misreads construction as mutation
                _tsan_hook(m)
                return m
        # under tsan the stored instance's class is the traced subclass;
        # compare against the ORIGINAL class it instruments
        if getattr(type(m), "__tsan_base__", type(m)) is not cls or \
                m.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} re-registered as {cls.__name__}"
                f"{tuple(labels)} but exists as {type(m).__name__}"
                f"{m.label_names}")
        return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def summary(self, name: str, help: str = "",
                labels: Sequence[str] = (),
                buckets: Optional[Sequence[float]] = None) -> Summary:
        return self._get_or_create(Summary, name, help, labels,
                                   buckets=buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def collect(self) -> List[Metric]:
        """Run collectors, then return all metrics sorted by name."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            fn()
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    # -- test/introspection helpers -----------------------------------------
    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def value(self, name: str, **labels: str) -> float:
        """Current value of a counter/gauge child (tests). Goes through
        the metric's snapshotting :meth:`Metric.samples` instead of
        reaching into its private child dict — reading another object's
        lock-guarded state directly is exactly what the concurrency lint
        exists to stop."""
        m = self.get(name)
        if m is None:
            raise KeyError(name)
        key = tuple(str(labels[n]) for n in m.label_names)
        for k, c in m.samples():
            if k == key:
                return c.value
        return 0.0


def fmt_value(v: float) -> str:
    """Canonical Prometheus float formatting (ints render bare)."""
    if math.isnan(v):
        return "NaN"  # a quantile of an empty summary child; int(v) raises
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)
