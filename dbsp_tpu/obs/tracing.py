"""Span recorder: Chrome-trace-format JSON for a bounded window of steps.

Load the export in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
Spans nest step -> operator eval -> exchange on the host path (driven by
:class:`~dbsp_tpu.obs.instrument.CircuitInstrumentation` from the
scheduler-event stream) and tick -> compiled-step/validate/maintain on the
compiled path (driven by the compiled driver directly).

Format: the JSON-object flavor of the Trace Event Format — ``B``/``E``
duration events with microsecond timestamps, so nesting is explicit and a
consumer (or test) can check balance. The window is bounded: only the most
recent ``max_steps`` completed top-level spans are retained (a serving
pipeline runs forever; the trace buffer must not).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Deque, List, Optional


class SpanRecorder:
    """Accumulates B/E span events; ring-buffered per top-level span."""

    def __init__(self, max_steps: int = 64, pid: str = "dbsp_tpu"):
        self.pid = pid
        self._steps: Deque[List[dict]] = deque(maxlen=max_steps)
        self._open: List[dict] = []      # events of the in-flight step
        self._depth = 0
        self._lock = threading.Lock()
        self.dropped_steps = 0

    # -- recording ----------------------------------------------------------
    def begin(self, name: str, cat: str = "operator",
              ts_ns: Optional[int] = None) -> None:
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        with self._lock:
            self._open.append({"name": name, "cat": cat, "ph": "B",
                               "ts": ts, "pid": self.pid, "tid": 0})
            self._depth += 1

    def end(self, name: str, ts_ns: Optional[int] = None) -> None:
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        with self._lock:
            if self._depth == 0:
                return  # unbalanced end (attached mid-step): drop
            self._open.append({"name": name, "ph": "E", "ts": ts,
                               "pid": self.pid, "tid": 0})
            self._depth -= 1
            if self._depth == 0:
                if len(self._steps) == self._steps.maxlen:
                    self.dropped_steps += 1
                self._steps.append(self._open)
                self._open = []

    def instant(self, name: str, cat: str = "event",
                ts_ns: Optional[int] = None) -> None:
        """A zero-duration marker (overflow replays, re-traces, ...)."""
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        with self._lock:
            target = self._open if self._depth else None
            ev = {"name": name, "cat": cat, "ph": "i", "ts": ts,
                  "pid": self.pid, "tid": 0, "s": "t"}
            if target is not None:
                target.append(ev)
            else:
                self._steps.append([ev])

    class _Span:
        __slots__ = ("rec", "name", "cat")

        def __init__(self, rec, name, cat):
            self.rec, self.name, self.cat = rec, name, cat

        def __enter__(self):
            self.rec.begin(self.name, self.cat)
            return self

        def __exit__(self, *exc):
            self.rec.end(self.name)
            return False

    def span(self, name: str, cat: str = "operator") -> "_Span":
        """Context-manager convenience for host-driven span pairs."""
        return SpanRecorder._Span(self, name, cat)

    # -- export -------------------------------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return [ev for step in self._steps for ev in step]

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms",
                "otherData": {"dropped_steps": self.dropped_steps}}

    def to_json(self) -> str:
        return json.dumps(self.to_chrome_trace())

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._open = []
            self._depth = 0
